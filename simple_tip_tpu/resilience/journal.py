"""Crash-safe resume journal of completed (case study, phase, run) units.

The study phases were always *restartable* (file-granular idempotent
artifacts, the reference's contract) but never *resumable*: a restarted
``run_phase_parallel`` re-dispatched every id and relied on each phase's
own artifact checks — which the synthetic/chaos phases don't have, and
which still re-pays worker spawn + data load + cache probing per finished
run. The journal closes that gap at the scheduler layer: every run that
completes successfully appends one JSON line, and a restarted phase skips
journaled ids outright, riding the already-restart-safe SAFitCache and
artifact bus back to warm state.

Write discipline (the same crash-safety argument as the obs tracer):
append-only JSONL, one ``os.write`` per line on an ``O_APPEND`` fd with
fsync — a mid-append kill leaves at most one torn tail line, which the
reader skips and counts. No rewrite-in-place ever happens, so no kill can
eat *previous* completions.

Resolution (``journal_from_env``): ``TIP_JOURNAL`` = ``off``/``0``
disables; an explicit path is used verbatim; unset/``auto`` journals under
``$TIP_ASSETS/journal/runs.jsonl`` — but only when ``TIP_ASSETS`` itself
is pinned, because journaling into an implicit CWD-relative bus would leak
completion state between unrelated invocations (exactly the kind of
cross-test contamination the scheduler tests would hit). Semantics: a
journal entry means "this (case study, phase, id) finished once under this
bus"; delete the file (or the bus) to force a full re-run.

Stdlib-only; single-writer by construction (only the scheduler parent
appends; workers report over the done queue).
"""

import json
import logging
import os
import time
from typing import Optional, Set

from simple_tip_tpu import obs
from simple_tip_tpu.resilience import faults

logger = logging.getLogger(__name__)


class RunJournal:
    """Append-only completion ledger for one (case study, phase) pair."""

    def __init__(self, path: str, case_study: str, phase: str):
        self.path = path
        self.case_study = case_study
        self.phase = phase

    def completed(self) -> Set:
        """Model ids journaled as done for this (case study, phase).

        Torn tail lines (a kill mid-append) and foreign entries are
        skipped; a missing journal is simply the empty set.
        """
        done: Set = set()
        try:
            with open(self.path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail from a crash mid-append
                    if (
                        isinstance(rec, dict)
                        and rec.get("case_study") == self.case_study
                        and rec.get("phase") == self.phase
                        and "model_id" in rec
                    ):
                        done.add(rec["model_id"])
        except OSError:
            return set()
        return done

    def mark_done(self, model_id) -> None:
        """Append one completion line (fsync'd; failures warn, never raise
        — the journal accelerates restarts, it must not fail the phase)."""
        rec = {
            "case_study": self.case_study,
            "phase": self.phase,
            "model_id": model_id,
            "ts": time.time(),
            "pid": os.getpid(),
        }
        line = json.dumps(rec, sort_keys=True) + "\n"
        data = line.encode("utf-8")
        fault = faults.maybe_inject(
            "journal.append", phase=self.phase, model_id=model_id
        )
        if fault is not None and fault.kind == "torn":
            data = data[: max(1, len(data) // 2)]  # simulated mid-append kill
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            fd = os.open(self.path, os.O_APPEND | os.O_CREAT | os.O_RDWR, 0o644)
            try:
                # Heal a torn tail left by a previous kill mid-append: a
                # new line appended straight after half a line would merge
                # into one unparsable record, losing THIS completion too.
                if os.lseek(fd, 0, os.SEEK_END) > 0:
                    os.lseek(fd, -1, os.SEEK_END)
                    if os.read(fd, 1) != b"\n":
                        data = b"\n" + data
                os.write(fd, data)
                os.fsync(fd)
            finally:
                os.close(fd)
            obs.counter("journal.appends").inc()
        except OSError as e:
            logger.warning("resume journal append failed (%s): %s", self.path, e)


def journal_from_env(case_study: str, phase: str) -> Optional[RunJournal]:
    """The configured journal, or None when journaling is off (see module
    docstring for the ``TIP_JOURNAL`` / ``TIP_ASSETS`` resolution)."""
    raw = os.environ.get("TIP_JOURNAL", "").strip()
    if raw.lower() in ("off", "0"):
        return None
    if raw and raw.lower() not in ("auto", "1", "on"):
        return RunJournal(raw, case_study, phase)
    if not os.environ.get("TIP_ASSETS", "").strip():
        return None  # no pinned bus: journaling would leak across runs
    from simple_tip_tpu.config import output_folder

    path = os.path.join(output_folder(), "journal", "runs.jsonl")
    return RunJournal(path, case_study, phase)

"""Crash-safe resume journal of completed (case study, phase, run) units.

The study phases were always *restartable* (file-granular idempotent
artifacts, the reference's contract) but never *resumable*: a restarted
``run_phase_parallel`` re-dispatched every id and relied on each phase's
own artifact checks — which the synthetic/chaos phases don't have, and
which still re-pays worker spawn + data load + cache probing per finished
run. The journal closes that gap at the scheduler layer: every run that
completes successfully appends one JSON line, and a restarted phase skips
journaled ids outright, riding the already-restart-safe SAFitCache and
artifact bus back to warm state.

Write discipline (the same crash-safety argument as the obs tracer):
append-only JSONL, one ``os.write`` per line on an ``O_APPEND`` fd with
fsync — a mid-append kill leaves at most one torn tail line, which the
reader skips and counts. No rewrite-in-place ever happens, so no kill can
eat *previous* completions.

**Fencing (fleet mode).** The journal is the single commit point of the
fleet layer (resilience/lease.py): ``mark_done(fence=...)`` validates the
caller's lease token immediately before the append, under the journal
lock, and re-checks the unit is not already journaled — so a host whose
lease was stolen (preempted, wedged, clock-skewed) CANNOT append a stale
completion, and a stealer racing the original holder commits exactly
once. Fenced commits raise :class:`~..lease.LeaseLost` instead of
appending (the one deliberate exception to "the journal never raises":
fencing is correctness, not acceleration).

**Compaction.** Across a 400-run study with restarts the append-only file
grows without bound; with ``TIP_JOURNAL_MAX_BYTES`` set, an append that
pushes the file past the cap rewrites it as a deduplicated snapshot of
completed units (same JSONL schema, tmp + fsync + atomic rename — the
torn-tail rules are preserved because the snapshot is born whole). The
append and the compaction both hold the journal flock, so a concurrent
appender on another host can never land a line on the doomed inode.
Without the cap (and without a fence) the historical lock-free
single-writer append path is unchanged.

Resolution (``journal_from_env``): ``TIP_JOURNAL`` = ``off``/``0``
disables; an explicit path is used verbatim; unset/``auto`` journals under
``$TIP_ASSETS/journal/runs.jsonl`` — but only when ``TIP_ASSETS`` itself
is pinned, because journaling into an implicit CWD-relative bus would leak
completion state between unrelated invocations (exactly the kind of
cross-test contamination the scheduler tests would hit). Semantics: a
journal entry means "this (case study, phase, id) finished once under this
bus"; delete the file (or the bus) to force a full re-run. Opening the
journal also sweeps aged orphan ``*.tmp`` files in its directory (a kill
between an atomic writer's write and rename leaks them).

Stdlib-only. Single-writer by construction in the plain scheduler path;
multi-host appends (fleet mode) are safe because O_APPEND line writes are
atomic and fenced commits serialize on the journal lock.
"""

import json
import logging
import os
import time
from typing import Optional, Set

from simple_tip_tpu import obs
from simple_tip_tpu.resilience import faults

try:
    import fcntl
except ImportError:  # pragma: no cover — non-POSIX
    fcntl = None

logger = logging.getLogger(__name__)


def journal_max_bytes() -> int:
    """The ``TIP_JOURNAL_MAX_BYTES`` compaction trigger (0 = off)."""
    raw = os.environ.get("TIP_JOURNAL_MAX_BYTES", "").strip()
    if not raw:
        return 0
    try:
        return max(0, int(float(raw)))
    except ValueError:
        logger.warning("TIP_JOURNAL_MAX_BYTES=%r is not a number; ignoring", raw)
        return 0


class RunJournal:
    """Append-only completion ledger for one (case study, phase) pair."""

    def __init__(self, path: str, case_study: str, phase: str):
        self.path = path
        self.case_study = case_study
        self.phase = phase

    # -- reading -----------------------------------------------------------

    def _records(self) -> list:
        """Every parseable record in the journal, torn tails skipped."""
        out = []
        try:
            with open(self.path, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail from a crash mid-append
                    if isinstance(rec, dict):
                        out.append(rec)
        except OSError:
            return []
        return out

    def completed(self) -> Set:
        """Model ids journaled as done for this (case study, phase).

        Torn tail lines (a kill mid-append) and foreign entries are
        skipped; a missing journal is simply the empty set.
        """
        done: Set = set()
        for rec in self._records():
            if (
                rec.get("case_study") == self.case_study
                and rec.get("phase") == self.phase
                and "model_id" in rec
            ):
                done.add(rec["model_id"])
        return done

    # -- locking -----------------------------------------------------------

    def _locked(self):
        """Journal flock (sidecar ``.lock`` file): held by fenced commits
        and by compaction, so neither can race the other's rename."""
        path = self.path + ".lock"
        journal = self

        class _Lock:
            def __enter__(self):
                os.makedirs(os.path.dirname(journal.path) or ".", exist_ok=True)
                self.fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
                if fcntl is not None:
                    fcntl.flock(self.fd, fcntl.LOCK_EX)
                return self

            def __exit__(self, *exc):
                try:
                    if fcntl is not None:
                        fcntl.flock(self.fd, fcntl.LOCK_UN)
                finally:
                    os.close(self.fd)
                return False

        return _Lock()

    def wedged(self) -> bool:
        """Non-blocking probe: is the journal flock held right now?

        The health-plane input behind ``/healthz``'s "journal wedged"
        verdict. The flock is held only for the microseconds of a fenced
        append or a compaction rename, so one True is ordinary contention
        — but a holder that died or stalled with the fd open (the wedge
        failure mode this deployment actually sees) keeps the lock held
        across every probe. Publishers debounce: the scheduler flags the
        journal unhealthy only after several consecutive True polls.
        Never raises; no fcntl (non-POSIX) means never wedged.
        """
        if fcntl is None:
            return False
        try:
            fd = os.open(self.path + ".lock", os.O_CREAT | os.O_RDWR, 0o644)
        except OSError:
            return False
        try:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                return True
            fcntl.flock(fd, fcntl.LOCK_UN)
            return False
        finally:
            os.close(fd)

    # -- writing -----------------------------------------------------------

    def mark_done(self, model_id, fence=None) -> None:
        """Append one completion line (fsync'd).

        Plain appends warn and never raise — the journal accelerates
        restarts, it must not fail the phase. With ``fence`` (a lease
        :class:`~..lease.FenceToken`), this is the fleet commit point:
        under the journal lock the fence is validated (raising
        ``LeaseLost`` for a stolen lease — the stale host cannot commit)
        and an already-journaled unit is skipped, so every unit commits
        exactly once no matter how many hosts raced it.
        """
        if fence is not None:
            with self._locked():
                if model_id in self.completed():
                    # A stealer (or the original holder) already committed
                    # this unit; a second line would be a double completion.
                    obs.counter("journal.dup_skips").inc()
                    logger.info(
                        "journal: unit %s already committed; skipping duplicate",
                        model_id,
                    )
                    return
                fence.check()  # raises LeaseLost for a fenced-out holder
                self._append(model_id, epoch=fence.epoch)
                self._maybe_compact_locked()
            return
        if journal_max_bytes():
            with self._locked():
                self._append(model_id)
                self._maybe_compact_locked()
        else:
            self._append(model_id)

    def _append(self, model_id, epoch: Optional[int] = None) -> None:
        rec = {
            "case_study": self.case_study,
            "phase": self.phase,
            "model_id": model_id,
            "ts": time.time(),
            "pid": os.getpid(),
        }
        if epoch is not None:
            rec["epoch"] = int(epoch)
        line = json.dumps(rec, sort_keys=True) + "\n"
        data = line.encode("utf-8")
        fault = faults.maybe_inject(
            "journal.append", phase=self.phase, model_id=model_id
        )
        if fault is not None and fault.kind == "torn":
            data = data[: max(1, len(data) // 2)]  # simulated mid-append kill
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            fd = os.open(self.path, os.O_APPEND | os.O_CREAT | os.O_RDWR, 0o644)
            try:
                # Heal a torn tail left by a previous kill mid-append: a
                # new line appended straight after half a line would merge
                # into one unparsable record, losing THIS completion too.
                if os.lseek(fd, 0, os.SEEK_END) > 0:
                    os.lseek(fd, -1, os.SEEK_END)
                    if os.read(fd, 1) != b"\n":
                        data = b"\n" + data
                os.write(fd, data)
                os.fsync(fd)
            finally:
                os.close(fd)
            obs.counter("journal.appends").inc()
        except OSError as e:
            logger.warning("resume journal append failed (%s): %s", self.path, e)

    def _maybe_compact_locked(self) -> None:
        """Compact the journal if it outgrew ``TIP_JOURNAL_MAX_BYTES``.

        Caller holds the journal lock. The snapshot keeps ONE record per
        (case_study, phase, model_id) across ALL pairs sharing the file
        (first completion wins — later lines are restart duplicates), and
        lands via tmp + fsync + atomic rename, so a kill mid-compaction
        leaves the old journal intact.
        """
        cap = journal_max_bytes()
        if not cap:
            return
        try:
            size = os.stat(self.path).st_size
        except OSError:
            return
        if size <= cap:
            return
        try:
            seen, kept = set(), []
            for rec in self._records():
                key = (rec.get("case_study"), rec.get("phase"), rec.get("model_id"))
                if "model_id" not in rec or key in seen:
                    continue
                seen.add(key)
                kept.append(rec)
            tmp = f"{self.path}.{os.getpid()}.tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                for rec in kept:
                    f.write(json.dumps(rec, sort_keys=True) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            after = os.stat(self.path).st_size
            obs.counter("journal.compactions").inc()
            obs.event(
                "journal.compact", path=self.path, before_bytes=size,
                after_bytes=after, records=len(kept),
            )
            logger.info(
                "journal compacted: %s %d -> %d bytes (%d unique completions)",
                self.path, size, after, len(kept),
            )
        except OSError as e:
            logger.warning("journal compaction failed (%s): %s", self.path, e)


def journal_from_env(case_study: str, phase: str) -> Optional[RunJournal]:
    """The configured journal, or None when journaling is off (see module
    docstring for the ``TIP_JOURNAL`` / ``TIP_ASSETS`` resolution)."""
    raw = os.environ.get("TIP_JOURNAL", "").strip()
    if raw.lower() in ("off", "0"):
        return None
    if raw and raw.lower() not in ("auto", "1", "on"):
        return _opened(RunJournal(raw, case_study, phase))
    if not os.environ.get("TIP_ASSETS", "").strip():
        return None  # no pinned bus: journaling would leak across runs
    from simple_tip_tpu.config import output_folder

    path = os.path.join(output_folder(), "journal", "runs.jsonl")
    return _opened(RunJournal(path, case_study, phase))


def _opened(journal: RunJournal) -> RunJournal:
    """Open-path hygiene: sweep aged orphan tmp files next to the journal
    (an atomic writer killed between write and rename leaks them)."""
    from simple_tip_tpu.utils.artifacts_io import sweep_orphan_tmp

    sweep_orphan_tmp(os.path.dirname(journal.path) or ".")
    return journal

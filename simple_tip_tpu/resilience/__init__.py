"""Fault tolerance for study execution: inject, retry, journal, break.

The 4x100-run study is exactly the workload that dies to partial failures:
a TPU tunnel flap mid-phase wedges a worker, a kill mid-pickle tears a
cache entry, a restarted study refits and re-runs everything it had already
finished, and — worst of all — the BENCH_r01-r05 failure mode, where the
degraded CPU fallback was *silent* and five rounds of records quietly
replaced the real chip numbers. Podracer's lesson (PAPERS.md,
arxiv 2104.06272) is that staying saturated under preemption and worker
churn is an architecture concern; real TPU fleets run preemptible, so
failure is the normal path, not the exception.

Four pieces, all stdlib-only (this package is imported by the jax-free
scheduler workers, the bench parent and the tier-0 chaos smoke job):

- ``faults``   deterministic fault injection at named seams
  (``TIP_FAULT_PLAN``): worker kill/wedge, backend-probe timeout,
  SA-cache pickle corruption, artifact torn-writes — the chaos harness
  the scheduler's old ``_test_die``/``_test_wedge`` phases grew into;
- ``retry``    one retry policy (exponential backoff + jitter + monotonic
  deadline + transient/fatal classification, ``TIP_RETRY_*``) replacing
  the ad-hoc sleep/timeout logic scattered across the watchdog, the
  scheduler requeue path and the cache/bus readers;
- ``journal``  a crash-safe append-only journal of completed
  (case study, phase, run-id) work units under ``$TIP_ASSETS`` —
  a restarted ``run_phase_parallel`` skips finished runs and rides the
  already-restart-safe SAFitCache/artifact bus back to warm state;
- ``breaker``  a closed/open/half-open circuit breaker over the backend
  probe (``TIP_BREAKER_*``): an open breaker fails fast or *loudly*
  degrades to CPU, stamping the degradation into bench records and
  health counters at the source;
- ``lease``    file-backed work leases with monotonic fencing epochs and
  heartbeat membership — the host fault domain: a preempted host's
  expired claims are stealable by any member, a stolen lease's stale
  holder is fenced out at the journal commit, and the coordinator role
  itself is just one more lease any standby can take over.
"""

from simple_tip_tpu.resilience.breaker import (
    BackendUnavailable,
    CircuitBreaker,
)
from simple_tip_tpu.resilience.faults import (
    FaultPlan,
    InjectedFault,
    active_plan,
    corrupt_file,
    maybe_inject,
)
from simple_tip_tpu.resilience.journal import RunJournal, journal_from_env
from simple_tip_tpu.resilience.lease import (
    COORDINATOR_UNIT,
    FenceToken,
    LeaseLost,
    LeaseManager,
    Membership,
    fleet_now,
)
from simple_tip_tpu.resilience.retry import RetryGiveUp, RetryPolicy

__all__ = [
    "BackendUnavailable",
    "COORDINATOR_UNIT",
    "CircuitBreaker",
    "FaultPlan",
    "FenceToken",
    "InjectedFault",
    "LeaseLost",
    "LeaseManager",
    "Membership",
    "RetryGiveUp",
    "RetryPolicy",
    "RunJournal",
    "active_plan",
    "corrupt_file",
    "fleet_now",
    "journal_from_env",
    "maybe_inject",
]

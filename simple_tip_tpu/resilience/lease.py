"""File-backed work leases with fencing epochs, plus host membership.

The scheduler's failure domain used to be a *process*: a worker dies and
the parent requeues its id. At fleet scale (ROADMAP "Fleet-scale study
scheduler") the failing unit is a *host* — a preempted TPU VM takes its
whole worker pool, or the coordinator itself, and nothing requeues the
work it had claimed. Podracer's answer (PAPERS.md, arxiv 2104.06272) is to
group workers into independently failing units and keep the controller
stateless enough that any member can take over; this module is the claim
substrate that makes that safe over the existing filesystem bus:

- **Lease**: one JSON file per work unit under a shared directory. A
  claim creates it ``O_CREAT|O_EXCL`` (exactly one winner); the holder
  renews it on a heartbeat cadence; a lease whose ``expires_ts`` has
  passed is *stealable* by any host. Every steal (and every reclaim of a
  released lease) increments a **fencing epoch** that only ever grows.
- **Fencing**: a :class:`FenceToken` captures (unit, owner, epoch) at
  claim time. The journal — the single commit point — validates the
  token immediately before appending, so a preempted-then-resurrected
  (or wedged-but-alive) host whose lease was stolen CANNOT commit its
  stale unit: the epoch no longer matches and :class:`LeaseLost` is
  raised instead of a double completion.
- **Membership**: hosts register by heartbeating a per-host JSON file;
  ``alive()`` is the set beating within the TTL. Join/leave is elastic —
  a late joiner simply starts claiming (stealing expired leases), a
  clean leaver releases its claims so they requeue instantly.

Mutations (claim/steal/renew/release) are serialized per unit with an
``fcntl.flock`` on a sidecar lock file: a renewal racing a steal must not
resurrect the old holder's lease after the epoch was bumped. Expiry
timestamps are wall-clock by necessity (they cross hosts); comparisons
are written additively so an NTP step shifts a window rather than
corrupting a duration, and ``TIP_FLEET_CLOCK_SKEW_S`` lets the chaos
suite skew one host's clock deterministically — fencing, not clock
agreement, is what protects commits.

Chaos seams (resilience/faults.py): ``lease.steal`` fires on every steal
attempt (``fail`` denies it — a partitioned host that cannot take over;
``error`` raises), ``heartbeat.drop`` fires per beat (``fail`` skips the
write — the heartbeat-partition stand-in).

Stdlib-only, like the rest of resilience/: the CI chaos job runs this
with jax poisoned.
"""

import errno
import json
import logging
import os
import time
from typing import Dict, List, Optional

from simple_tip_tpu import obs
from simple_tip_tpu.resilience import faults

try:
    import fcntl
except ImportError:  # pragma: no cover — non-POSIX; leases need flock
    fcntl = None

logger = logging.getLogger(__name__)

#: Lease held by the member currently acting as coordinator (same
#: machinery as unit leases: kill the holder and a standby steals it).
COORDINATOR_UNIT = "__coordinator__"


def fleet_now() -> float:
    """Wall clock + ``TIP_FLEET_CLOCK_SKEW_S`` (chaos knob, default 0).

    Cross-host expiry decisions must ride the wall clock; the skew knob
    makes "this host's clock is wrong" a deterministic test input rather
    than an untestable deployment hazard.
    """
    raw = os.environ.get("TIP_FLEET_CLOCK_SKEW_S", "").strip()
    skew = 0.0
    if raw:
        try:
            skew = float(raw)
        except ValueError:
            logger.warning("TIP_FLEET_CLOCK_SKEW_S=%r is not a number", raw)
    return time.time() + skew


class LeaseLost(RuntimeError):
    """This holder's lease was stolen/released: its fence is invalid and
    any commit it attempts must be rejected."""


def _safe(unit: str) -> str:
    return "".join(c if c.isalnum() or c in "._-" else "_" for c in str(unit))


class FenceToken:
    """Proof of one claim: (unit, owner, epoch) at claim time.

    ``check()`` re-reads the lease and raises :class:`LeaseLost` unless
    this owner still holds this epoch — the journal calls it immediately
    before the commit append (RunJournal.mark_done(fence=...)).
    """

    def __init__(self, manager: "LeaseManager", unit: str, owner: str, epoch: int):
        self.manager = manager
        self.unit = unit
        self.owner = owner
        self.epoch = int(epoch)

    def check(self) -> None:
        """Raise :class:`LeaseLost` unless the lease is still ours."""
        self.manager.validate(self)

    def __repr__(self) -> str:  # diagnostics in scheduler logs
        return f"FenceToken({self.unit!r}, owner={self.owner!r}, epoch={self.epoch})"


class LeaseManager:
    """Claim/renew/steal/release leases for one fleet root directory."""

    def __init__(self, root: str, owner: str, ttl_s: float = 30.0):
        self.root = root
        self.owner = str(owner)
        self.ttl_s = float(ttl_s)

    # -- paths and serialization ------------------------------------------

    def _path(self, unit: str) -> str:
        return os.path.join(self.root, f"lease_{_safe(unit)}.json")

    def _lock_path(self, unit: str) -> str:
        return os.path.join(self.root, "locks", f"{_safe(unit)}.lock")

    def _read(self, unit: str) -> Optional[Dict]:
        try:
            with open(self._path(unit), encoding="utf-8") as f:
                rec = json.load(f)
            return rec if isinstance(rec, dict) else None
        except (OSError, ValueError):
            return None

    def _write(self, unit: str, rec: Dict) -> None:
        """Replace the lease file atomically (pid-unique tmp + rename)."""
        path = self._path(unit)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(rec, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _locked(self, unit: str):
        """Context manager: the per-unit mutation lock (flock).

        Serializes claim/steal/renew/release so a renewal racing a steal
        cannot resurrect a fenced-out lease. Advisory and per-unit, so
        unrelated units never contend.
        """
        mgr = self

        class _Lock:
            def __enter__(self):
                os.makedirs(os.path.dirname(mgr._lock_path(unit)), exist_ok=True)
                self.fd = os.open(mgr._lock_path(unit), os.O_CREAT | os.O_RDWR, 0o644)
                if fcntl is not None:
                    fcntl.flock(self.fd, fcntl.LOCK_EX)
                return self

            def __exit__(self, *exc):
                try:
                    if fcntl is not None:
                        fcntl.flock(self.fd, fcntl.LOCK_UN)
                finally:
                    os.close(self.fd)
                return False

        return _Lock()

    def _fresh(self, unit: str, epoch: int) -> Dict:
        now = fleet_now()
        return {
            "unit": str(unit),
            "owner": self.owner,
            "epoch": int(epoch),
            "claimed_ts": now,
            "renewed_ts": now,
            "expires_ts": now + self.ttl_s,
            "released": False,
        }

    # -- protocol ----------------------------------------------------------

    def claim(self, unit: str) -> Optional[FenceToken]:
        """Claim ``unit``: fresh (O_EXCL), reclaim of a released lease, or
        steal of an expired one. None when someone else validly holds it.
        """
        os.makedirs(self.root, exist_ok=True)
        with self._locked(unit):
            rec = self._read(unit)
            if rec is None:
                # First claim: O_CREAT|O_EXCL is the atomic winner-takes-it
                # even if a non-locking writer raced us.
                try:
                    fd = os.open(
                        self._path(unit), os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644
                    )
                    os.close(fd)
                except OSError as e:
                    if e.errno != errno.EEXIST:
                        raise
                    return None  # lost the creation race
                fresh = self._fresh(unit, epoch=1)
                self._write(unit, fresh)
                obs.counter("lease.claims").inc()
                return FenceToken(self, unit, self.owner, 1)
            if rec.get("released"):
                # Reclaim: the epoch keeps growing across release/claim
                # cycles so a fence from ANY earlier tenancy stays dead.
                return self._take(unit, rec, reason="reclaim")
            if fleet_now() >= float(rec.get("expires_ts", 0)):
                return self._take(unit, rec, reason="steal")
            if rec.get("owner") == self.owner:
                # Our own live lease (a restarted claim loop): hand the
                # current epoch back rather than treating it as foreign.
                return FenceToken(self, unit, self.owner, int(rec.get("epoch", 1)))
            return None

    def _take(self, unit: str, rec: Dict, reason: str) -> Optional[FenceToken]:
        """Take over an expired/released lease, bumping the fencing epoch.

        Caller holds the unit lock. ``lease.steal`` chaos seam: ``fail``
        denies the takeover (partitioned standby), ``error`` raises.
        """
        fault = faults.maybe_inject(
            "lease.steal", unit=str(unit), owner=self.owner,
            from_owner=str(rec.get("owner")), reason=reason,
        )
        if fault is not None and fault.kind in ("fail", "timeout"):
            return None
        epoch = int(rec.get("epoch", 1)) + 1
        fresh = self._fresh(unit, epoch=epoch)
        self._write(unit, fresh)
        if reason == "steal":
            obs.counter("lease.steals").inc()
            obs.event(
                "lease.steal", unit=str(unit), owner=self.owner,
                from_owner=str(rec.get("owner")), epoch=epoch,
            )
            logger.warning(
                "lease STOLEN: unit %s epoch %d (from %s, expired %.1fs ago)",
                unit, epoch, rec.get("owner"),
                fleet_now() - float(rec.get("expires_ts", 0)),
            )
        else:
            obs.counter("lease.claims").inc()
        return FenceToken(self, unit, self.owner, epoch)

    def renew(self, token: FenceToken) -> None:
        """Extend the expiry of a lease we still hold; :class:`LeaseLost`
        if it was stolen/released out from under us (fenced out)."""
        with self._locked(token.unit):
            rec = self._read(token.unit)
            self._validate_rec(token, rec)
            rec["renewed_ts"] = fleet_now()
            rec["expires_ts"] = rec["renewed_ts"] + self.ttl_s
            self._write(token.unit, rec)

    def release(self, token: FenceToken) -> None:
        """Mark our lease released (a tombstone keeping the epoch, so a
        later reclaim still bumps it). Losing the lease first is fine —
        release is how a clean leaver requeues its claims."""
        try:
            with self._locked(token.unit):
                rec = self._read(token.unit)
                try:
                    self._validate_rec(token, rec)
                except LeaseLost:
                    return  # already someone else's (or gone): nothing to release
                rec["released"] = True
                rec["expires_ts"] = fleet_now()
                self._write(token.unit, rec)
        except OSError as e:  # advisory cleanup, never fatal
            logger.warning("lease release failed for %s: %s", token.unit, e)

    def validate(self, token: FenceToken) -> None:
        """Raise :class:`LeaseLost` unless ``token`` matches the live lease."""
        self._validate_rec(token, self._read(token.unit))

    def _validate_rec(self, token: FenceToken, rec: Optional[Dict]) -> None:
        if rec is None:
            raise LeaseLost(f"lease file for {token.unit!r} is gone")
        if rec.get("released"):
            raise LeaseLost(f"lease for {token.unit!r} was released")
        if rec.get("owner") != token.owner or int(rec.get("epoch", -1)) != token.epoch:
            raise LeaseLost(
                f"lease for {token.unit!r} now owner={rec.get('owner')!r} "
                f"epoch={rec.get('epoch')} (ours: {token.owner!r}/{token.epoch})"
            )

    def expire_now(self, unit: str) -> bool:
        """Make ``unit``'s live lease immediately stealable (speculative
        re-lease of a straggler): expiry drops to now, owner/epoch stay —
        if the straggler is merely slow it may still commit first; the
        fencing epoch decides the race, never this hint."""
        try:
            with self._locked(unit):
                rec = self._read(unit)
                if rec is None or rec.get("released"):
                    return False
                rec["expires_ts"] = fleet_now()
                self._write(unit, rec)
                return True
        except OSError:
            return False

    def holder(self, unit: str) -> Optional[Dict]:
        """The live lease record for ``unit`` (tombstones included), or None."""
        return self._read(unit)

    def active(self) -> List[Dict]:
        """All unexpired, unreleased lease records under this root."""
        out: List[Dict] = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        now = fleet_now()
        for name in names:
            if not (name.startswith("lease_") and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.root, name), encoding="utf-8") as f:
                    rec = json.load(f)
            except (OSError, ValueError):
                continue
            if (
                isinstance(rec, dict)
                and not rec.get("released")
                and now < float(rec.get("expires_ts", 0))
            ):
                out.append(rec)
        return out


class Membership:
    """Heartbeat-file membership table for one fleet root."""

    def __init__(self, root: str, host_id: str, ttl_s: float = 10.0):
        self.root = root
        self.host_id = str(host_id)
        self.ttl_s = float(ttl_s)
        self._joined = False

    def _path(self, host_id: str) -> str:
        return os.path.join(self.root, f"member_{_safe(host_id)}.json")

    def beat(self, **info) -> bool:
        """Write this host's heartbeat (atomic replace). Returns False when
        the ``heartbeat.drop`` chaos seam ate the beat — the partition
        stand-in: the host is alive but the fleet stops seeing it."""
        fault = faults.maybe_inject("heartbeat.drop", host=self.host_id)
        if fault is not None and fault.kind in ("fail", "timeout"):
            obs.counter("fleet.heartbeats_dropped").inc()
            return False
        rec = {
            "host": self.host_id,
            "pid": os.getpid(),
            "ts": fleet_now(),
            **info,
        }
        try:
            os.makedirs(self.root, exist_ok=True)
            path = self._path(self.host_id)
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(rec, f)
            os.replace(tmp, path)
        except OSError as e:
            logger.warning("heartbeat write failed for %s: %s", self.host_id, e)
            return False
        if not self._joined:
            self._joined = True
            obs.counter("fleet.members").inc()
            obs.event("fleet.join", host=self.host_id, pid=os.getpid())
            logger.info("fleet member %s joined (pid %d)", self.host_id, os.getpid())
        return True

    def leave(self) -> None:
        """Clean departure: drop the heartbeat file (claims are requeued by
        the leaver releasing its leases — see the scheduler's fleet path)."""
        try:
            os.remove(self._path(self.host_id))
        except OSError:
            pass
        if self._joined:
            obs.event("fleet.leave", host=self.host_id)
            logger.info("fleet member %s left", self.host_id)
        self._joined = False

    def table(self) -> Dict[str, Dict]:
        """host_id -> last heartbeat record, stale hosts INCLUDED.

        ``alive()`` is the membership *decision* (TTL-filtered); this is
        the operator *view* behind the exporter's ``/fleet`` route — a
        host that stopped beating must show up with its heartbeat age so
        the coordinator can mark it ``stale=true``, not silently vanish
        from the table.
        """
        out: Dict[str, Dict] = {}
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in names:
            if not (name.startswith("member_") and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.root, name), encoding="utf-8") as f:
                    rec = json.load(f)
            except (OSError, ValueError):
                continue
            if isinstance(rec, dict) and rec.get("host") is not None:
                out[str(rec["host"])] = rec
        return out

    def alive(self) -> Dict[str, Dict]:
        """host_id -> heartbeat record, for hosts beating within the TTL."""
        now = fleet_now()
        return {
            host: rec
            for host, rec in self.table().items()
            if now - float(rec.get("ts", 0)) <= self.ttl_s
        }

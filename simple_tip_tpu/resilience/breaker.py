"""Backend circuit breaker: closed / open / half-open over the probe seam.

``ensure_responsive_backend`` probes the accelerator in a subprocess with
a ~90 s timeout. During a multi-hour tunnel outage every process — each
bench child, every scheduler dispatch, every capture script — re-paid that
probe, and the CPU fallback it chose was *silent* at the record level:
BENCH_r02-r05 are all ``degraded: true`` CPU numbers nobody alarmed on.
The breaker fixes both halves:

- **closed**     probes run normally; failures count up;
- **open**       after ``threshold`` consecutive failures, no probe runs
  until ``cooldown_s`` has passed — callers either fail fast
  (``mode=fail``) or degrade to CPU *loudly* (``mode=degrade``, default):
  the degradation lands in the ``breaker.short_circuit``/
  ``breaker.degraded`` health counters, a ``breaker.transition`` obs
  event, and (via the watchdog's ``degradation_reason``) the bench
  record itself — so ``obs regress`` fails against a healthy baseline
  and the silent-CPU failure mode is structurally impossible;
- **half-open**  after the cooldown one probe is allowed through; success
  closes the breaker, failure re-opens it for another cooldown.

State is a tiny JSON file (``TIP_BREAKER_STATE``, default
``$TIP_ASSETS/breaker_state.json`` when the bus is pinned) written
atomically, so the scheduler parent, its workers and the bench children
share one view of the outage instead of each rediscovering it at 90 s a
head. Timestamps are wall-clock by necessity (they cross processes); the
cooldown comparison is written additively so an NTP step can only shift
the window, never corrupt a duration. Without a pinned bus the breaker
still works process-locally (in-memory state).

Env knobs: ``TIP_BREAKER_THRESHOLD`` (consecutive failures to open,
default 2), ``TIP_BREAKER_COOLDOWN_S`` (default 900), ``TIP_BREAKER_MODE``
(``degrade``/``fail``), ``TIP_BREAKER_STATE`` (path; ``off`` disables the
breaker entirely — every call probes, the pre-breaker behavior).

Stdlib-only.
"""

import json
import logging
import os
import time
from typing import Dict, Optional

from simple_tip_tpu import obs

logger = logging.getLogger(__name__)

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class BackendUnavailable(RuntimeError):
    """Raised in ``mode=fail`` when the breaker short-circuits a probe."""


class CircuitBreaker:
    """File-backed (or process-local) circuit breaker for backend probes."""

    def __init__(
        self,
        state_path: Optional[str],
        threshold: int = 2,
        cooldown_s: float = 900.0,
        mode: str = "degrade",
        name: str = "backend",
    ):
        self.state_path = state_path
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self.mode = mode if mode in ("degrade", "fail") else "degrade"
        self.name = name
        self._local: Dict = {}  # in-memory state when no path is configured

    @classmethod
    def from_env(cls, name: str = "backend") -> Optional["CircuitBreaker"]:
        """Breaker per ``TIP_BREAKER_*`` policy; None when disabled."""
        raw = os.environ.get("TIP_BREAKER_STATE", "").strip()
        if raw.lower() in ("off", "0"):
            return None
        path: Optional[str] = None
        if raw:
            path = raw
        elif os.environ.get("TIP_ASSETS", "").strip():
            from simple_tip_tpu.config import output_folder

            path = os.path.join(output_folder(), "breaker_state.json")

        def _num(var, default):
            try:
                return float(os.environ.get(var, "") or default)
            except ValueError:
                return default

        return cls(
            state_path=path,
            threshold=int(_num("TIP_BREAKER_THRESHOLD", 2)),
            cooldown_s=_num("TIP_BREAKER_COOLDOWN_S", 900.0),
            mode=os.environ.get("TIP_BREAKER_MODE", "degrade").strip() or "degrade",
            name=name,
        )

    # -- state IO ------------------------------------------------------------

    def _load(self) -> Dict:
        if self.state_path is None:
            return dict(self._local)
        try:
            with open(self.state_path, encoding="utf-8") as f:
                st = json.load(f)
            return st if isinstance(st, dict) else {}
        except (OSError, ValueError):
            return {}

    def _store(self, st: Dict) -> None:
        if self.state_path is None:
            self._local = dict(st)
            return
        try:
            os.makedirs(os.path.dirname(self.state_path) or ".", exist_ok=True)
            tmp = f"{self.state_path}.{os.getpid()}.tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(st, f)
            os.replace(tmp, self.state_path)
        except OSError as e:  # breaker state is advisory, never fatal
            logger.warning("breaker state write failed (%s): %s", self.state_path, e)

    # -- protocol ------------------------------------------------------------

    def state(self) -> str:
        """Effective state now: closed, open, or half_open."""
        st = self._load()
        if st.get("state") != OPEN:
            return CLOSED
        if time.time() >= float(st.get("opened_ts", 0)) + self.cooldown_s:
            return HALF_OPEN
        return OPEN

    def allow(self) -> bool:
        """Whether a probe may run now (False = short-circuit).

        Half-open allows the probe through (one prober re-tests the
        backend; racers are tolerable — the probe is idempotent).
        Short-circuits count and emit, so the degradation is loud.
        """
        state = self.state()
        if state != OPEN:
            return True
        obs.counter("breaker.short_circuit").inc()
        obs.gauge("breaker.open").set(1)  # re-stamp the level each rejection
        st = self._load()
        now = time.time()  # cross-process timestamp, not a duration
        remaining = float(st.get("opened_ts", 0)) + self.cooldown_s - now
        obs.event(
            "breaker.short_circuit", breaker=self.name,
            cooldown_remaining_s=round(max(0.0, remaining), 1),
        )
        logger.error(
            "circuit breaker %r OPEN (%.0fs of cooldown left): backend probe "
            "short-circuited (mode=%s)",
            self.name, max(0.0, remaining), self.mode,
        )
        return False

    def record_success(self) -> None:
        """A probe succeeded: reset failures, close the breaker."""
        st = self._load()
        if st.get("state") == OPEN:
            obs.counter("breaker.closed").inc()
            obs.event("breaker.transition", breaker=self.name, to=CLOSED)
            logger.warning(
                "circuit breaker %r CLOSED: backend probe recovered", self.name
            )
        # Level gauge next to the transition counters: the SLO engine's
        # breaker-open rule samples state, not edges (obs/slo.py).
        obs.gauge("breaker.open").set(0)
        self._store({"state": CLOSED, "failures": 0})

    def record_failure(self) -> None:
        """A probe failed: count it; open the breaker at the threshold.

        A failure while half-open re-opens immediately (the one test
        probe burned; back to a full cooldown).
        """
        st = self._load()
        failures = int(st.get("failures", 0)) + 1
        was_open = st.get("state") == OPEN
        if failures >= self.threshold or was_open:
            if not was_open or self.state() == HALF_OPEN:
                obs.counter("breaker.opened").inc()
                obs.event(
                    "breaker.transition", breaker=self.name, to=OPEN,
                    failures=failures, cooldown_s=self.cooldown_s,
                )
                logger.error(
                    "circuit breaker %r OPEN after %d consecutive probe "
                    "failure(s): backend considered down for %.0fs "
                    "(mode=%s: %s)",
                    self.name, failures, self.cooldown_s, self.mode,
                    "callers fail fast" if self.mode == "fail"
                    else "callers degrade to CPU, stamped degraded",
                )
            # Level gauge for the SLO engine's breaker-open rule: 1 for
            # the whole open window, not just the transition edge.
            obs.gauge("breaker.open").set(1)
            self._store(
                {"state": OPEN, "failures": failures, "opened_ts": time.time()}
            )
        else:
            self._store({"state": CLOSED, "failures": failures})

    def healthy(self) -> bool:
        """Health-plane verdict (the exporter's ``/healthz`` input): False
        exactly while the breaker is OPEN in its cooldown window — the
        state where probes short-circuit and callers degrade. Half-open
        counts as healthy: a test probe is allowed through, which is the
        recovery path an operator wants 200 to reflect."""
        return self.state() != OPEN

    def snapshot(self) -> Dict:
        """JSON-safe view for bench records / diagnostics."""
        st = self._load()
        return {
            "name": self.name,
            "state": self.state(),
            "failures": int(st.get("failures", 0)),
            "threshold": self.threshold,
            "cooldown_s": self.cooldown_s,
            "mode": self.mode,
            **(
                {"opened_unix": round(float(st["opened_ts"]), 1)}
                if "opened_ts" in st
                else {}
            ),
        }

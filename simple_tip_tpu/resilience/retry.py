"""Unified retry policy: exponential backoff + jitter + monotonic deadline.

Before this module every component hand-rolled its own failure handling:
the watchdog gave up on the first spawn error, the scheduler hard-coded a
single requeue, cache/bus readers treated any OSError as a miss. One
policy object replaces those ad-hoc choices with a shared, env-tunable
contract and a shared failure taxonomy:

- **transient** — worth retrying with backoff (IO hiccups, timeouts,
  connection drops, a busy executor);
- **fatal**     — retrying cannot help (bad input, programming errors,
  interrupts); raised through immediately;
- **degrade**   — not this module's call: when retries are exhausted the
  *caller* decides whether to degrade (the breaker's job for the backend,
  a refit for the SA cache) — ``call`` surfaces exhaustion as
  ``RetryGiveUp`` so that decision is explicit, never accidental.

Env knobs (all optional), with per-scope overrides so one subsystem can be
tuned without touching the rest: ``TIP_RETRY_ATTEMPTS``,
``TIP_RETRY_BASE_S``, ``TIP_RETRY_FACTOR``, ``TIP_RETRY_MAX_S``,
``TIP_RETRY_DEADLINE_S``, ``TIP_RETRY_JITTER`` — and for a scope ``foo``
(``RetryPolicy.from_env(scope="foo")``), ``TIP_RETRY_FOO_ATTEMPTS`` etc.
take precedence. Deadlines ride ``time.monotonic`` (an NTP step must not
extend or fire a retry budget), which is also exactly the shape the
``naked-retry`` tiplint rule demands of every sleep loop in library code.

Counters: ``retry.attempts`` (each retry taken) and ``retry.giveups``
(budget exhausted) feed the health-counter comparison in ``obs regress``.

Stdlib-only; importable by jax-free workers and the tier-0 gate.
"""

import logging
import os
import random
import time
from typing import Callable, Iterator, Optional, Tuple

from simple_tip_tpu import obs

logger = logging.getLogger(__name__)

#: Exception types retried by default: environmental, not programming,
#: failures. Callers narrow or widen per site via ``transient=``.
DEFAULT_TRANSIENT = (OSError, TimeoutError, ConnectionError, EOFError)


class RetryGiveUp(RuntimeError):
    """Raised when the retry budget (attempts or deadline) is exhausted;
    ``__cause__`` carries the last underlying error."""


def _env_float(scope: str, name: str, default: float, inherit: bool = True) -> float:
    """``TIP_RETRY_<SCOPE>_<NAME>`` > ``TIP_RETRY_<NAME>`` > default.

    ``inherit=False`` skips the global fallback — for scopes whose retries
    are expensive enough (whole-run requeues) that a blanket retry bump
    must not silently multiply them.
    """
    names = [f"TIP_RETRY_{scope.upper()}_{name}"] if scope else []
    if inherit or not scope:
        names.append(f"TIP_RETRY_{name}")
    for var in names:
        raw = os.environ.get(var, "").strip()
        if raw:
            try:
                return float(raw)
            except ValueError:
                logger.warning("%s=%r is not a number; ignoring", var, raw)
    return default


class RetryPolicy:
    """One retry budget: attempt count, backoff curve, wall deadline.

    Immutable by convention; build via the constructor or ``from_env``.
    ``attempts`` counts TOTAL tries (1 = no retry); ``deadline_s`` bounds
    the whole call including sleeps (None = unbounded); ``jitter`` is the
    +/- fraction applied to each delay (seedable for deterministic tests).
    """

    def __init__(
        self,
        attempts: int = 3,
        base_s: float = 0.1,
        factor: float = 2.0,
        max_s: float = 30.0,
        deadline_s: Optional[float] = 120.0,
        jitter: float = 0.1,
        seed: Optional[int] = None,
    ):
        self.attempts = max(1, int(attempts))
        self.base_s = max(0.0, float(base_s))
        self.factor = max(1.0, float(factor))
        self.max_s = max(0.0, float(max_s))
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self.jitter = max(0.0, float(jitter))
        self.seed = seed

    @classmethod
    def from_env(cls, scope: str = "", inherit: bool = True, **defaults) -> "RetryPolicy":
        """Policy from ``TIP_RETRY_*`` (scoped names win; see module doc).

        ``defaults`` override the class defaults but still lose to env;
        ``inherit=False`` makes the scope ignore the unscoped globals.
        """
        base = cls(**defaults)
        deadline = _env_float(
            scope, "DEADLINE_S",
            -1.0 if base.deadline_s is None else base.deadline_s,
            inherit,
        )
        return cls(
            attempts=int(_env_float(scope, "ATTEMPTS", base.attempts, inherit)),
            base_s=_env_float(scope, "BASE_S", base.base_s, inherit),
            factor=_env_float(scope, "FACTOR", base.factor, inherit),
            max_s=_env_float(scope, "MAX_S", base.max_s, inherit),
            deadline_s=None if deadline < 0 else deadline,
            jitter=_env_float(scope, "JITTER", base.jitter, inherit),
            seed=base.seed,
        )

    def delays(self) -> Iterator[float]:
        """The backoff sequence: ``attempts - 1`` jittered delays."""
        rng = random.Random(self.seed) if self.seed is not None else random
        for i in range(self.attempts - 1):
            delay = min(self.max_s, self.base_s * (self.factor**i))
            if self.jitter:
                delay *= 1.0 + self.jitter * rng.uniform(-1.0, 1.0)
            yield max(0.0, delay)

    def call(
        self,
        fn: Callable,
        *args,
        transient: Tuple = DEFAULT_TRANSIENT,
        fatal: Tuple = (),
        describe: str = "",
        on_retry: Optional[Callable] = None,
        **kwargs,
    ):
        """``fn(*args, **kwargs)`` under this budget.

        Exceptions in ``fatal`` (checked first), interrupts, and anything
        NOT in ``transient`` propagate immediately. Transient failures
        back off and retry until attempts or the monotonic deadline run
        out, then raise ``RetryGiveUp`` from the last error.
        ``on_retry(attempt, exc, delay)`` observes each retry.
        """
        what = describe or getattr(fn, "__name__", "call")
        deadline = (
            None if self.deadline_s is None
            else time.monotonic() + self.deadline_s
        )
        last: Optional[BaseException] = None
        delays = list(self.delays()) + [None]  # None marks the final try
        for attempt, delay in enumerate(delays, start=1):
            try:
                return fn(*args, **kwargs)
            except (KeyboardInterrupt, SystemExit):
                raise
            except fatal:
                raise
            except transient as e:
                last = e
                if delay is None:
                    break  # budget spent
                if deadline is not None and time.monotonic() + delay > deadline:
                    logger.warning(
                        "%s: not retrying (%.1fs deadline would pass): %r",
                        what, self.deadline_s, e,
                    )
                    break
                obs.counter("retry.attempts").inc()
                logger.warning(
                    "%s failed (attempt %d/%d): %r — retrying in %.2fs",
                    what, attempt, self.attempts, e, delay,
                )
                if on_retry is not None:
                    on_retry(attempt, e, delay)
                time.sleep(delay)
        obs.counter("retry.giveups").inc()
        obs.event("retry.giveup", what=what, attempts=self.attempts)
        raise RetryGiveUp(
            f"{what}: gave up after {self.attempts} attempt(s): {last!r}"
        ) from last

"""Monotonic timing utilities.

Mirrors the semantics of the reference's timer (reference: src/core/timer.py:6-50):
re-entrant accumulation over start/stop segments, context-manager and decorator
forms, RuntimeError on misuse and a RuntimeWarning when read while running.

Two deliberate departures from the reference implementation (semantics kept):

- segments are measured with ``time.perf_counter()``, not ``time.time()``:
  wall-clock is not monotonic, so an NTP step or leap-second smear during a
  segment would corrupt the accumulated total (negative or wildly inflated
  phase records);
- a Timer constructed with ``name=`` optionally mirrors every completed
  segment into the obs span stream (simple_tip_tpu/obs), so the four-stage
  phase timings show up on the run flame chart without a second timing
  system. Mirroring is a no-op (and costs one attribute check) when
  ``TIP_OBS_DIR`` is unset.

Adds ``device_timed`` for accurate on-device timing: JAX dispatch is async, so a
naive wall-clock around a jitted call measures dispatch, not compute. We bracket
with ``jax.block_until_ready`` on the outputs.
"""

import time
import warnings


class Timer:
    """Accumulating monotonic timer (start/stop, context manager, decorator).

    ``name`` opts the timer into span mirroring: each completed start/stop
    segment is recorded as one obs span of that name (with ``attrs``
    attached), preserving the reference's accumulated-seconds contract
    while making the segments individually visible on the trace.
    """

    def __init__(self, start: bool = False, name: str = None, **attrs):
        self._start_time = None
        self._elapsed = 0.0
        self._name = name
        self._attrs = attrs
        self._wall_start = None
        if start:
            self.start()

    def start(self):
        """Start the timer; it must not already be running."""
        if self._start_time is not None:
            raise RuntimeError("Timer is already started")
        if self._name is not None:
            self._wall_start = time.time()
        self._start_time = time.perf_counter()

    def stop(self):
        """Stop the timer; it must be running."""
        if self._start_time is None:
            raise RuntimeError("Timer is not started")
        segment = time.perf_counter() - self._start_time
        self._elapsed += segment
        self._start_time = None
        if self._name is not None:
            from simple_tip_tpu import obs

            obs.record_span(self._name, self._wall_start, segment, **self._attrs)

    def timed(self, f):
        """Decorator: accumulate the wrapped function's wall-clock into this timer."""

        def wrapper(*args, **kwargs):
            with self:
                return f(*args, **kwargs)

        return wrapper

    def get(self) -> float:
        """Elapsed seconds over all completed segments (warns if still running)."""
        if self._start_time is not None:
            warnings.warn("Timer is not stopped", RuntimeWarning)
        return self._elapsed

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()


def device_timed(timer: Timer, fn, *args, **kwargs):
    """Run ``fn`` and accumulate its wall-clock into ``timer``, blocking on the
    returned JAX arrays so async dispatch does not fake the measurement."""
    import jax

    timer.start()
    try:
        out = fn(*args, **kwargs)
        out = jax.block_until_ready(out)
    finally:
        timer.stop()
    return out

"""Wall-clock timing utilities.

Mirrors the semantics of the reference's timer (reference: src/core/timer.py:6-50):
re-entrant accumulation over start/stop segments, context-manager and decorator
forms, RuntimeError on misuse and a RuntimeWarning when read while running.

Adds ``device_timed`` for accurate on-device timing: JAX dispatch is async, so a
naive wall-clock around a jitted call measures dispatch, not compute. We bracket
with ``jax.block_until_ready`` on the outputs.
"""

import time
import warnings


class Timer:
    """Accumulating wall-clock timer (start/stop, context manager, decorator)."""

    def __init__(self, start: bool = False):
        self._start_time = None
        self._elapsed = 0.0
        if start:
            self.start()

    def start(self):
        """Start the timer; it must not already be running."""
        if self._start_time is not None:
            raise RuntimeError("Timer is already started")
        self._start_time = time.time()

    def stop(self):
        """Stop the timer; it must be running."""
        if self._start_time is None:
            raise RuntimeError("Timer is not started")
        self._elapsed += time.time() - self._start_time
        self._start_time = None

    def timed(self, f):
        """Decorator: accumulate the wrapped function's wall-clock into this timer."""

        def wrapper(*args, **kwargs):
            with self:
                return f(*args, **kwargs)

        return wrapper

    def get(self) -> float:
        """Elapsed seconds over all completed segments (warns if still running)."""
        if self._start_time is not None:
            warnings.warn("Timer is not stopped", RuntimeWarning)
        return self._elapsed

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.stop()


def device_timed(timer: Timer, fn, *args, **kwargs):
    """Run ``fn`` and accumulate its wall-clock into ``timer``, blocking on the
    returned JAX arrays so async dispatch does not fake the measurement."""
    import jax

    timer.start()
    try:
        out = fn(*args, **kwargs)
        out = jax.block_until_ready(out)
    finally:
        timer.stop()
    return out

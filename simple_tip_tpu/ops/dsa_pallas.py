"""Pallas TPU kernel for DSA's masked nearest-neighbor search.

DSA needs, per test activation-trace: (a) the distance to + index of the
nearest *same-class* training AT, then (b) the distance from that neighbor to
the nearest *other-class* training AT (reference: src/core/surprise.py:615-651,
which materializes full (badge x train) difference tensors in RAM and
gc-collects between badges).

The XLA fallback (ops/surprise.DSA) computes a (chunk x N_train) distance
matrix in HBM per chunk. This kernel instead tiles the training set through
VMEM and keeps a running (min, argmin) accumulator per query row, so HBM
traffic is one pass over the training ATs per chunk and the distance matrix
never exists in HBM: the (chunk x tile) partial distances live in VMEM,
produced by one MXU matmul per tile.

Masking: class structure is applied by adding +inf to excluded entries before
the row-min. Train padding rows are excluded by setting their squared-norm
entries to +inf.
"""

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

CHUNK = 512  # query rows per kernel launch
TILE = 512  # training rows per grid step
MAX_FEATURES_VMEM = 2048  # above this, fall back to the XLA path

try:  # pallas import is deferred-failure: CPU-only setups keep working
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PALLAS = True
except ImportError:  # pragma: no cover
    HAVE_PALLAS = False


def _nearest_kernel(
    x_ref, xsq_ref, xlab_ref, t_ref, tsq_ref, tlab_ref, min_ref, arg_ref, *, want_same
):
    """One grid step: fold train tile i into the running (min, argmin)."""
    i = pl.program_id(0)

    x = x_ref[:]  # [C, D]
    t = t_ref[:]  # [T, D]
    # [C, T] squared distances via the MXU.
    d2 = (
        xsq_ref[:]  # [C, 1]
        + tsq_ref[:]  # [1, T] (+inf on padding rows)
        - 2.0 * jax.lax.dot_general(
            x, t, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    )
    d2 = jnp.maximum(d2, 0.0)
    same = xlab_ref[:] == tlab_ref[:]  # [C,1] == [1,T] -> [C, T]
    mask = same if want_same else jnp.logical_not(same)
    d2m = jnp.where(mask, d2, jnp.inf)

    tile_min = jnp.min(d2m, axis=1)  # [C]
    tile_arg = jnp.argmin(d2m, axis=1).astype(jnp.int32) + i * d2m.shape[1]

    @pl.when(i == 0)
    def _():
        min_ref[:] = tile_min
        arg_ref[:] = tile_arg

    @pl.when(i > 0)
    def _():
        better = tile_min < min_ref[:]
        min_ref[:] = jnp.where(better, tile_min, min_ref[:])
        arg_ref[:] = jnp.where(better, tile_arg, arg_ref[:])


@functools.partial(jax.jit, static_argnames=("want_same", "interpret"))
def _masked_nearest_call(x, x_labels, train, train_sq, train_labels, want_same, interpret=False):
    """(min_dist2[C], argmin[C]) of x against the masked training set."""
    c, d = x.shape
    n = train.shape[0]
    grid = n // TILE
    x_sq = jnp.sum(x * x, axis=1, keepdims=True)  # [C, 1]
    kernel = functools.partial(_nearest_kernel, want_same=want_same)
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((c, d), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((c, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((c, 1), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TILE), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TILE), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((c,), lambda i: (0,), memory_space=pltpu.VMEM),
            pl.BlockSpec((c,), lambda i: (0,), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((c,), jnp.float32),
            jax.ShapeDtypeStruct((c,), jnp.int32),
        ],
        interpret=interpret,
    )(
        x,
        x_sq,
        x_labels.astype(jnp.int32).reshape(c, 1),
        train,
        train_sq.reshape(1, n),
        train_labels.astype(jnp.int32).reshape(1, n),
    )


class PallasDSABackend:
    """Device state + scoring for DSA using the pallas kernel."""

    def __init__(self, train_activations: np.ndarray, train_predictions: np.ndarray):
        n, d = train_activations.shape
        # Pad the training set to a TILE multiple; padding rows excluded via
        # +inf squared norms.
        n_pad = math.ceil(n / TILE) * TILE
        train = np.zeros((n_pad, d), np.float32)
        train[:n] = train_activations
        tsq = np.full(n_pad, np.inf, np.float32)
        tsq[:n] = np.sum(train_activations.astype(np.float32) ** 2, axis=1)
        tlab = np.full(n_pad, -2, np.int32)
        tlab[:n] = train_predictions
        self.n_real = n
        self.train = jnp.asarray(train)
        self.train_sq = jnp.asarray(tsq)
        self.train_labels = jnp.asarray(tlab)

    def score(self, target_ats: np.ndarray, target_pred: np.ndarray, interpret=False) -> np.ndarray:
        """DSA = a_dist / b_dist per query row (chunked kernel launches)."""
        n_test = target_ats.shape[0]
        d = target_ats.shape[1]
        out = np.empty(n_test, np.float64)  # tiplint: disable=f64-on-tpu (host result buffer; DSA score dtype parity with ops/surprise.py)
        for start in range(0, n_test, CHUNK):
            xb = target_ats[start : start + CHUNK].astype(np.float32)
            lb = target_pred[start : start + CHUNK]
            c_real = xb.shape[0]
            if c_real < CHUNK:
                xb = np.concatenate([xb, np.zeros((CHUNK - c_real, d), np.float32)])
                lb = np.concatenate([lb, np.full(CHUNK - c_real, -1, lb.dtype)])
            xb_j = jnp.asarray(xb)
            lb_j = jnp.asarray(lb)
            a2, a_idx = _masked_nearest_call(
                xb_j, lb_j, self.train, self.train_sq, self.train_labels,
                want_same=True, interpret=interpret,
            )
            closest = jnp.take(self.train, a_idx, axis=0)
            b2, _ = _masked_nearest_call(
                closest, lb_j, self.train, self.train_sq, self.train_labels,
                want_same=False, interpret=interpret,
            )
            dsa = jnp.sqrt(a2) / jnp.sqrt(b2)
            out[start : start + c_real] = np.asarray(dsa)[:c_real]
        return out


def pallas_available_for(d_features: int) -> bool:
    """Whether the pallas DSA path applies (TPU backend, VMEM-fitting width)."""
    if not HAVE_PALLAS:
        return False
    if d_features > MAX_FEATURES_VMEM:
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False

"""Deterministic, severity-monotonic English text corruption (IMDB-C generator).

Behavioral contract matches the reference (reference: src/core/text_corruptor.py):

- Four corruption types — TYPO (random char replacement), SYNONYM (thesaurus
  lookup; falls back to TYPO when no synonyms), AUTOCOMPLETE (word sharing a
  3..5-char prefix; falls back to AUTOCORRECT), AUTOCORRECT (one of the 5
  Levenshtein-nearest dictionary words, probability ~ 1/distance).
- Per-sentence seed = md5(text) + seed, so corruption of a text is independent
  of the order/subset of the dataset; higher severity strictly adds
  corruptions on top of those applied at lower severity.
- Reference quirk preserved verbatim: the sampling weights vector is ordered
  [typo, autocomplete, autocorrect, synonym] while the enum numbers TYPO=0,
  SYNONYM=1, AUTOCOMPLETE=2, AUTOCORRECT=3 — so ``autocomplete_weight``
  effectively weights SYNONYM, ``autocorrect_weight`` weights AUTOCOMPLETE and
  ``synonym_weight`` weights AUTOCORRECT (reference: src/core/
  text_corruptor.py:128-146 vs :92-102). Changing this would change every
  IMDB-C corruption draw, so parity wins over readability.
- Dictionary = the ``dictionary_size`` most frequent words (len>4, not
  numeric) of a base dataset; pickle/npy caching keyed by dataset hash.

Differences by design:

- Levenshtein distances come from the in-repo C++ kernel
  (ops/native.lev_matrix) instead of the polyleven pip package; a pure-python
  fallback exists for toolchain-free environments.
- The reference downloads a wordnet thesaurus at runtime
  (text_corruptor.py:31-33,412-446); this build is zero-egress, so the
  thesaurus is read from ``TIP_DATA_DIR/en_thesaurus.jsonl`` if present and is
  otherwise empty — in which case every SYNONYM corruption degrades to TYPO,
  the reference's own documented fallback path.
"""

import collections
import dataclasses
import enum
import hashlib
import json
import logging
import os
import pickle
import re
import shutil
import warnings
from typing import Dict, List, Optional

import numpy as np

DEFAULT_CACHE_DIR = "./.text_corruption_cache/"

MAX_COMMON_START_FOR_AUTOCOMPLETE = 5
MIN_COMMON_START_FOR_AUTOCOMPLETE = 3

logger = logging.getLogger(__name__)


def split_by_whitespace(strings: List[str]) -> List[List[str]]:
    """Split strings into words (same regex as huggingface WhitespaceSplit)."""
    return [re.findall(r"\w+|[^\w\s]+", l) for l in strings]


def bad_autocompletes(
    word: str, start_bags: Dict[int, Dict[str, List[str]]], common_letters: int
) -> Optional[List[str]]:
    """Dictionary words sharing the first ``common_letters`` chars with
    ``word`` (recursively relaxing the prefix length down to 3)."""
    if common_letters < MIN_COMMON_START_FOR_AUTOCOMPLETE:
        return None
    common_letters = min(common_letters, len(word))
    start = word[:common_letters]
    bag = start_bags.get(common_letters, {}).get(start, [])
    bag = [w for w in bag if w != word]
    if len(bag) == 0:
        return bad_autocompletes(word, start_bags, common_letters=common_letters - 1)
    return bag


class CorruptionType(enum.Enum):
    """The four corruption types, imitating natural corruptions."""

    TYPO = 0
    SYNONYM = 1
    AUTOCOMPLETE = 2
    AUTOCORRECT = 3


def _get_rng(seed):
    return np.random.default_rng(seed)


@dataclasses.dataclass
class CorruptionWeights:
    """Probabilities of the different corruption types."""

    typo_weight: float = 0.05
    autocomplete_weight: float = 0.30
    autocorrect_weight: float = 0.30
    synonym_weight: float = 0.35


def _generate_corruption_types(
    seed: int, num_words: int, weights: CorruptionWeights
) -> List[CorruptionType]:
    w = np.array(
        [
            weights.typo_weight,
            weights.autocomplete_weight,
            weights.autocorrect_weight,
            weights.synonym_weight,
        ]
    )
    rng = _get_rng(seed)
    return [CorruptionType(rng.choice(4, p=w / w.sum())) for _ in range(num_words)]


def _hash_text_to_int(words: List[str]) -> int:
    return int(_hash_text_to_str(words), 16) % 1000000


def _hash_text_to_str(words: List[str]) -> str:
    return hashlib.md5(" ".join(words).encode("utf-8")).hexdigest()


def _pairwise_lev_matrix(words: List[str]) -> np.ndarray:
    """Pairwise Levenshtein distances: native C++ kernel, python fallback."""
    try:
        from simple_tip_tpu.ops.native import lev_matrix

        return lev_matrix(words)
    except ImportError:
        logger.warning("native levenshtein unavailable; using slow python fallback")
        n = len(words)
        out = np.zeros((n, n), dtype=np.uint8)
        for i in range(n):
            for j in range(i + 1, n):
                d = _py_lev(words[i], words[j])
                out[i, j] = out[j, i] = min(d, 255)
        return out


def _py_lev(a: str, b: str) -> int:
    if len(a) < len(b):
        a, b = b, a
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + (ca != cb)))
        prev = cur
    return prev[-1]


class TextCorruptor:
    """Corruptor for arbitrary English text datasets."""

    def __init__(
        self,
        base_dataset: List[str],
        cache_dir: Optional[str] = DEFAULT_CACHE_DIR,
        dictionary_size: int = 4000,
        clear_cache: bool = False,
        thesaurus_path: Optional[str] = None,
    ):
        if cache_dir is DEFAULT_CACHE_DIR:
            warnings.warn(
                "Using default cache directory, which is probably not what you "
                "want. Consider passing your own cache dir when creating a "
                "TextCorruptor instance. "
            )
        self.base_ds_hash = _hash_text_to_str(list(base_dataset) + [str(dictionary_size)])
        self.cache_dir: Optional[str] = None
        if cache_dir is not None:
            self.cache_dir = os.path.join(cache_dir, self.base_ds_hash)
            if not os.path.exists(self.cache_dir):
                os.makedirs(self.cache_dir)
            elif clear_cache:
                shutil.rmtree(self.cache_dir)
                os.makedirs(self.cache_dir)

        self.common_words = self._extract_common_words(base_dataset, dictionary_size)
        self._word_index = {w: i for i, w in enumerate(self.common_words)}
        self.start_bags = self._word_start_bags()
        self.lev_dist = self._calculate_distances()
        self.thesaurus = self.load_bad_translations(thesaurus_path)

    # -- dictionary construction --------------------------------------------

    def _extract_common_words(self, base_dataset: List[str], size: int) -> List[str]:
        """The ``size`` most common words (len>4, non-numeric, containing
        letters), sorted alphabetically; pickle-cached."""
        if self.cache_dir is not None:
            words_file = os.path.join(self.cache_dir, "common-words.pkl")
            if os.path.exists(words_file):
                with open(words_file, "rb") as f:
                    return pickle.load(f)
        words = split_by_whitespace(base_dataset)
        words = [w.lower() for l in words for w in l]
        words = [w for w in words if len(w) > 4]
        words = [w for w in words if not w.isdigit()]
        words = [w for w in words if any(c.isalpha() for c in w)]
        chosen_words = sorted(dict(collections.Counter(words).most_common(size)).keys())
        if self.cache_dir is not None:
            with open(words_file, "wb") as f:
                pickle.dump(chosen_words, f)
        return chosen_words

    def _word_start_bags(self) -> Dict[int, Dict[str, List[str]]]:
        """Bags of same-prefix dictionary words for prefix lengths 3..5."""
        assert self.common_words is not None, "Common words not extracted yet."
        if self.cache_dir is not None:
            bags_file = os.path.join(self.cache_dir, "word-start-bags.pkl")
            if os.path.exists(bags_file):
                with open(bags_file, "rb") as f:
                    return pickle.load(f)
        result: Dict[int, Dict[str, List[str]]] = {}
        for num_start_chars in range(
            MIN_COMMON_START_FOR_AUTOCOMPLETE, MAX_COMMON_START_FOR_AUTOCOMPLETE + 1
        ):
            bag: Dict[str, List[str]] = {}
            for word in self.common_words:
                if len(word) >= num_start_chars:
                    bag.setdefault(word[:num_start_chars], []).append(word)
            result[num_start_chars] = bag
        if self.cache_dir is not None:
            with open(bags_file, "wb") as f:
                pickle.dump(result, f)
        return result

    def _calculate_distances(self) -> np.ndarray:
        """Pairwise Levenshtein distances over the dictionary; npy-cached."""
        if self.cache_dir is not None:
            distances_file = os.path.join(self.cache_dir, "distances.npy")
            if os.path.exists(distances_file):
                return np.load(distances_file)
        distances = _pairwise_lev_matrix(self.common_words)
        if self.cache_dir is not None:
            np.save(os.path.join(self.cache_dir, "distances.npy"), distances)
        return distances

    def load_bad_translations(self, thesaurus_path: Optional[str] = None) -> Dict[str, List[str]]:
        """Load the synonym map from a jsonl thesaurus
        ({"word": ..., "synonyms": [...]} per line). Resolution order:
        explicit ``thesaurus_path`` > ``TIP_DATA_DIR/en_thesaurus.jsonl`` (a
        user-supplied wordnet export, matching the reference's downloaded one,
        reference: src/core/text_corruptor.py:412-446) > the bundled offline
        asset ``simple_tip_tpu/data/assets/en_thesaurus.jsonl`` (hand-curated,
        built by scripts/build_thesaurus.py — zero-egress default). Only if
        ALL are missing does the thesaurus come up empty, in which case
        SYNONYM corruptions degrade to TYPO (the reference's own no-synonym
        fallback)."""
        candidates = [thesaurus_path] if thesaurus_path else []
        from simple_tip_tpu.config import data_folder

        candidates.append(os.path.join(data_folder(), "en_thesaurus.jsonl"))
        candidates.append(
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "data",
                "assets",
                "en_thesaurus.jsonl",
            )
        )
        path = next((p for p in candidates if p and os.path.isfile(p)), None)
        if path is None:
            logger.warning(
                "No thesaurus file found (looked for %s); SYNONYM corruptions "
                "will degrade to TYPO.",
                candidates,
            )
            return {}
        with open(path) as f:
            data = [json.loads(line) for line in f]
        result: Dict[str, set] = {}
        for d in data:
            word, synonyms = d["word"], d["synonyms"]
            if len(synonyms) > 1:
                result.setdefault(word, set()).update(synonyms)
        return {w: list(s) for w, s in result.items()}

    # -- corruption ----------------------------------------------------------

    def corrupt(
        self,
        texts: List[str],
        severity: float,
        seed: int,
        weights: Optional[CorruptionWeights] = None,
        force_recalculate: bool = False,
    ) -> List[str]:
        """Corrupt a list of texts; deterministic per (text, seed, severity),
        order/subset independent, severity-monotonic (higher severity applies
        a superset of the lower-severity corruptions)."""
        assert 0 <= severity <= 1, "Severity must be between 0 and 1."
        cache_file = None
        if self.cache_dir is not None:
            ds_hash = _hash_text_to_str(texts)
            cache_file = os.path.join(
                self.cache_dir, "corrupted", f"{ds_hash}-{severity}-{seed}.pkl"
            )
            if os.path.exists(cache_file) and not force_recalculate:
                with open(cache_file, "rb") as f:
                    return pickle.load(f)
        if weights is None:
            weights = CorruptionWeights()

        def _corrupt_single_text(words: List[str]) -> str:
            new_text = []
            # Seed independent of dataset order/size.
            sentence_seed = _hash_text_to_int(words) + seed
            # Types chosen independently of severity; severity then selects a
            # prefix of a seeded shuffle -> monotonic corruption sets.
            corruption_types = _generate_corruption_types(
                sentence_seed, len(words), weights
            )
            corruption_indexes = np.arange(len(words))
            _get_rng(sentence_seed).shuffle(corruption_indexes)
            corruption_indexes = set(
                corruption_indexes[: round(len(words) * severity)].tolist()
            )
            for i, word in enumerate(words):
                if i not in corruption_indexes or len(word) < 2:
                    new_text.append(word)
                else:
                    new_text.append(
                        self._corrupt_word(word, sentence_seed + i, corruption_types[i])
                    )
            return " ".join(new_text)

        texts_as_words = split_by_whitespace(texts)
        corrupted_texts = [_corrupt_single_text(t) for t in texts_as_words]

        if cache_file is not None:
            os.makedirs(os.path.dirname(cache_file), exist_ok=True)
            with open(cache_file, "wb") as f:
                pickle.dump(corrupted_texts, f)
        return corrupted_texts

    @staticmethod
    def _corrupt_typo(text: str, seed: int) -> str:
        import string as _string

        letter_index = seed % len(text)
        candidate_letters = _string.ascii_lowercase.replace(text[letter_index], "")
        random_candidate_index = _hash_text_to_int([text, str(seed)]) % len(
            candidate_letters
        )
        typo = candidate_letters[random_candidate_index]
        return text[:letter_index] + typo + text[letter_index + 1 :]

    def _corrupt_autocomplete(self, word: str, seed: int) -> str:
        candidates = bad_autocompletes(word, self.start_bags, common_letters=5)
        if candidates is None or len(candidates) == 0:
            return self._corrupt_autocorrect(word, seed)
        random_candidate_index = _hash_text_to_int([word, str(seed)]) % len(candidates)
        return candidates[random_candidate_index]

    def _corrupt_autocorrect(self, word: str, seed: int) -> str:
        if word not in self._word_index:
            return word
        word_index = self._word_index[word]
        candidate_indices = np.argsort(self.lev_dist[word_index])[1:6]
        candidate_distances = 1 / self.lev_dist[word_index][candidate_indices]
        rng = _get_rng(seed)
        chosen_index = rng.choice(
            candidate_indices, p=candidate_distances / candidate_distances.sum()
        )
        return self.common_words[chosen_index]

    def _corrupt_synonym(self, word: str, seed: int) -> str:
        synonyms = self.thesaurus.get(word) or []
        if len(synonyms) == 0:
            return self._corrupt_typo(word, seed)
        method_salt = "_corrupt_synonym"
        random_candidate_index = _hash_text_to_int([word, str(seed), method_salt]) % len(
            synonyms
        )
        return synonyms[random_candidate_index]

    def _corrupt_word(self, w: str, seed: int, corruption_type: CorruptionType) -> str:
        if corruption_type == CorruptionType.TYPO:
            return self._corrupt_typo(w, seed)
        elif corruption_type == CorruptionType.AUTOCOMPLETE:
            return self._corrupt_autocomplete(w, seed)
        elif corruption_type == CorruptionType.AUTOCORRECT:
            return self._corrupt_autocorrect(w, seed)
        elif corruption_type == CorruptionType.SYNONYM:
            return self._corrupt_synonym(w, seed)
        else:
            raise ValueError(f"Unknown corruption type: {corruption_type}")

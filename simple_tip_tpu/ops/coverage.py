"""Neuron-coverage criteria (NAC, KMNC, NBC, SNAC, TKNC).

Behavioral contract matches the reference (reference: src/core/neuron_coverage.py):
each criterion maps a badge of per-layer activations to ``(scores, profiles)``
where ``profiles`` is a boolean coverage-bit array per sample and ``scores`` is
the per-sample count of set bits.

TPU-native design: all five criteria are pure elementwise/argsort programs over
the flattened activation matrix ``(batch, neurons)``; under jit they fuse into
the forward pass that produced the activations, so profile extraction is
HBM-bandwidth-bound rather than a host round-trip. The class wrappers keep the
reference's constructor surface (train-set mins/maxs/stds) so configuration and
tests carry over 1:1.
"""

import abc
from typing import List, Sequence, Tuple

import numpy as np

from simple_tip_tpu.ops._backend import xp_for


def sum_score(profiles) -> np.ndarray:
    """Reduce a boolean profile array to per-sample counts of covered sections.

    Chooses the smallest integer dtype that can hold the maximum possible
    score (reference: src/core/neuron_coverage.py:8-22).
    """
    assert profiles.dtype == bool
    xp = xp_for(profiles)
    maxval = int(np.prod(profiles.shape[1:]))
    if maxval <= np.iinfo(np.int16).max:
        dtype = xp.int16
    elif maxval <= np.iinfo(np.int32).max:
        dtype = xp.int32
    else:
        dtype = xp.int64
    score = xp.sum(profiles.reshape((profiles.shape[0], -1)), axis=1, dtype=dtype)
    return score


def flatten_layers(layers: Sequence) -> np.ndarray:
    """Flatten a list of per-layer activation arrays to (batch, neurons)."""
    xp = xp_for(layers[0])
    flat = [xp.reshape(layer, (layer.shape[0], -1)) for layer in layers]
    return xp.concatenate(flat, axis=1)


def _flatten_1d(arrays: Sequence) -> np.ndarray:
    """Concatenate per-layer statistics vectors into one flat neuron vector."""
    xp = xp_for(arrays[0])
    return xp.concatenate([xp.reshape(a, (-1,)) for a in arrays])


class CoverageMethod(abc.ABC):
    """Abstract neuron-coverage criterion: callable on a badge of activations."""

    @abc.abstractmethod
    def __call__(self, activations: List) -> Tuple[np.ndarray, np.ndarray]:
        """Return (scores, profiles) for a badge of per-layer activations."""


class NAC(CoverageMethod):
    """Neuron-Activation Coverage: bit set where activation > threshold."""

    def __init__(self, cov_threshold: float):
        self.cov_threshold = cov_threshold

    def __call__(self, activations: List) -> Tuple[np.ndarray, np.ndarray]:
        acts = flatten_layers(activations)
        profiles = acts > self.cov_threshold
        return sum_score(profiles), profiles


class KMNC(CoverageMethod):
    """K-Multisection Neuron Coverage: which of k train-range buckets each
    neuron's activation falls into (reference: src/core/neuron_coverage.py:65-94)."""

    def __init__(self, mins: List, maxs: List, sections: int):
        self.sections = sections
        min_arr = _flatten_1d(mins)
        max_arr = _flatten_1d(maxs)
        jumps = (max_arr - min_arr) / sections
        # Zero-width ranges (constant neurons, e.g. padded conv borders) simply
        # yield never-set bits; harmless for coverage counting.
        self.lo = min_arr
        self.jumps = jumps

    def __call__(self, activations: List) -> Tuple[np.ndarray, np.ndarray]:
        acts = flatten_layers(activations)
        xp = xp_for(acts)
        # profiles: (batch, neurons, sections); bucket i covers
        # [lo + i*jump, lo + (i+1)*jump)
        edges = self.lo[None, :, None] + self.jumps[None, :, None] * xp.arange(
            self.sections + 1
        )
        a = acts[:, :, None]
        profiles = (edges[..., :-1] <= a) & (a < edges[..., 1:])
        return sum_score(profiles), profiles


class NBC(CoverageMethod):
    """Neuron Boundary Coverage: activation outside [min - s*std, max + s*std]."""

    def __init__(self, mins: List, maxs: List, stds: List, scaler: float):
        min_arr = _flatten_1d(mins)
        max_arr = _flatten_1d(maxs)
        std_arr = _flatten_1d(stds)
        self.min_boundaries = min_arr - scaler * std_arr
        self.max_boundaries = max_arr + scaler * std_arr

    def __call__(self, activations: List) -> Tuple[np.ndarray, np.ndarray]:
        acts = flatten_layers(activations)
        xp = xp_for(acts)
        low = acts <= self.min_boundaries
        high = acts >= self.max_boundaries
        profiles = xp.stack([low, high], axis=-1)
        return sum_score(profiles), profiles


class SNAC(CoverageMethod):
    """Strong Neuron Activation Coverage: activation >= max + s*std."""

    def __init__(self, maxs: List, stds: List, scaler: float):
        max_arr = _flatten_1d(maxs)
        std_arr = _flatten_1d(stds)
        self.max_boundaries = max_arr + scaler * std_arr

    def __call__(self, activations: List) -> Tuple[np.ndarray, np.ndarray]:
        acts = flatten_layers(activations)
        profiles = acts >= self.max_boundaries
        return sum_score(profiles), profiles


def make_fused_profile_fn(metrics: dict):
    """Fuse all configured coverage metrics into ONE jitted device program.

    Returns ``(fn, bit_lens)`` where ``fn(activations) -> {metric_id:
    (scores, packed_profiles)}`` computes every metric's scores and
    bit-packed boolean profiles in a single dispatch (one XLA program per
    badge instead of one per metric — critical when device round-trips are
    expensive), and ``bit_lens[mid]`` is the unpacked per-sample bit count
    (packbits pads rows to a byte boundary).

    Profiles are packed MSB-first (numpy ``packbits`` layout), directly
    consumable by the packed C++ CAM kernel or ``np.unpackbits``.
    """
    import jax
    import jax.numpy as jnp

    bit_lens = {}

    @jax.jit
    def fused(activations):
        out = {}
        for mid, metric in metrics.items():
            s, p = metric(activations)
            flat = p.reshape((p.shape[0], -1))
            # static at trace time; records the unpadded bit width
            bit_lens[mid] = int(flat.shape[1])
            out[mid] = (s, jnp.packbits(flat, axis=1))
        return out

    def get_bit_len(mid: str) -> int:
        return bit_lens[mid]

    return fused, get_bit_len


class TKNC(CoverageMethod):
    """Top-K Neuron Coverage: per layer, bit set for the k highest-activated
    neurons of each sample (reference: src/core/neuron_coverage.py:147-167)."""

    def __init__(self, top_neurons: int):
        self.top_neurons = top_neurons

    def __call__(self, activations: List) -> Tuple[np.ndarray, np.ndarray]:
        xp = xp_for(activations[0])
        profiles = []
        for layer in activations:
            layer = xp.reshape(layer, (layer.shape[0], -1))
            n, d = layer.shape
            # Tie policy (exactly-equal activations at the top-k boundary):
            # the HIGHER neuron index deterministically wins, on both paths.
            # The reference's unstable introsort argsort leaves ties
            # unspecified (src/core/neuron_coverage.py:147-167); both paths
            # match it bit-exactly on tie-free inputs and refine it to a
            # deterministic choice on ties.
            if xp is np:
                # rank via double STABLE argsort: among equal values ranks
                # grow with index, so the top-k ranked are the highest
                # indices — the same ties policy as the device path below.
                order = xp.argsort(layer, axis=1, kind="stable")
                ranks = xp.argsort(order, axis=1, kind="stable")
                profiles.append(ranks >= d - self.top_neurons)
            else:
                # device path: top_k + scatter is O(n*d*k) instead of two
                # full sorts (measured 17s -> <1s for the 3 TKNC configs at
                # 10k x 3.5k neurons on XLA:CPU). lax.top_k prefers the
                # LOWER index among equal values; running it on the
                # column-reversed layer flips that preference to match the
                # stable-argsort policy above, ties included.
                import jax

                _, idx_rev = jax.lax.top_k(layer[:, ::-1], self.top_neurons)
                idx = d - 1 - idx_rev
                prof = xp.zeros((n, d), bool)
                prof = prof.at[xp.arange(n)[:, None], idx].set(True)
                profiles.append(prof)
        flat = flatten_layers(profiles)
        return sum_score(flat), flat

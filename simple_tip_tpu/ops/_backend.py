"""Numpy/jnp backend dispatch for the metric kernels.

Kernels accept either numpy arrays (host path — float64 exactness, used by the
oracle tests and small host-side computations) or jax arrays (device path —
used inside jitted pipelines). The array's own type picks the namespace.
"""

import numpy as np


def xp_for(a):
    """Return numpy or jax.numpy depending on the array type of ``a``."""
    try:
        import jax

        if isinstance(a, jax.Array):
            import jax.numpy as jnp

            return jnp
    except ImportError:  # pragma: no cover
        pass
    return np


def is_jax(a) -> bool:
    """True if ``a`` is a jax array."""
    try:
        import jax

        return isinstance(a, jax.Array)
    except ImportError:  # pragma: no cover
        return False

"""Softmax-based uncertainty quantifiers (point-prediction TIPs).

The reference registers these as uncertainty-wizard quantifiers (reference:
src/core/deepgini.py:12-40, src/dnn_test_prio/handler_model.py:106); here they
are pure array functions. Each returns ``(predictions, uncertainty)``.

Convention: all values are *uncertainties* (higher = more likely misclassified),
matching the reference's ``predict_quantified(as_confidence=False)``, which
negates confidence metrics (MaxSoftmax, PCS). All downstream consumers (APFD via
descending argsort, active-learning top-k) depend only on the ordering.

Functions dispatch on the input type: numpy in / numpy out (float64 exactness
for oracle tests), jax in / jax out (for use inside jit). Artifact-name keys
(matching the reference's file naming contract): ``softmax``, ``pcs``,
``softmax_entropy``, ``deep_gini``, ``VR``.
"""

from typing import Tuple

import numpy as np


def _xp(a):
    """Pick numpy or jax.numpy based on the input array's type."""
    try:
        import jax

        if isinstance(a, jax.Array):
            import jax.numpy as jnp

            return jnp
    except ImportError:  # pragma: no cover
        pass
    return np


def max_softmax(probs) -> Tuple[np.ndarray, np.ndarray]:
    """Vanilla softmax score: uncertainty = -max(softmax)."""
    xp = _xp(probs)
    pred = xp.argmax(probs, axis=1)
    conf = xp.max(probs, axis=1)
    return pred, -conf


def pcs(probs) -> Tuple[np.ndarray, np.ndarray]:
    """Prediction-confidence score: uncertainty = -(max - second_max)."""
    xp = _xp(probs)
    pred = xp.argmax(probs, axis=1)
    top2 = xp.sort(probs, axis=1)[:, -2:]
    conf = top2[:, 1] - top2[:, 0]
    return pred, -conf


def softmax_entropy(probs) -> Tuple[np.ndarray, np.ndarray]:
    """Softmax entropy: -sum p log2 p (0 log 0 := 0)."""
    xp = _xp(probs)
    pred = xp.argmax(probs, axis=1)
    logs = xp.where(probs > 0, xp.log2(xp.where(probs > 0, probs, 1.0)), 0.0)
    entropy = -xp.sum(probs * logs, axis=1)
    return pred, entropy


def deep_gini(probs) -> Tuple[np.ndarray, np.ndarray]:
    """DeepGini impurity: 1 - sum(softmax^2) (reference: src/core/deepgini.py:32-35)."""
    xp = _xp(probs)
    pred = xp.argmax(probs, axis=1)
    gini = 1 - xp.sum(probs * probs, axis=1)
    return pred, gini


def variation_ratio(sampled_probs) -> Tuple[np.ndarray, np.ndarray]:
    """MC-dropout variation ratio over stochastic forward samples.

    ``sampled_probs``: (num_samples, batch, classes) softmax outputs from
    stochastic forward passes. Per input: take each sample's argmax class,
    VR = 1 - (votes for majority class) / num_samples; prediction = majority
    class. Matches uncertainty-wizard's VariationRatio semantics
    (reference: src/dnn_test_prio/handler_model.py:151-166).
    """
    xp = _xp(sampled_probs)
    num_samples, _, num_classes = sampled_probs.shape
    votes = xp.argmax(sampled_probs, axis=2)  # (S, B)
    # One-hot count votes per class without data-dependent shapes.
    one_hot = votes[..., None] == xp.arange(num_classes)[None, None, :]
    counts = xp.sum(one_hot, axis=0)  # (B, C)
    majority = xp.argmax(counts, axis=1)
    majority_count = xp.max(counts, axis=1)
    vr = 1.0 - majority_count / num_samples
    return majority, vr


# Registry keyed by artifact name (the reference's `uncertainty_{key}.npy`
# naming, reference: src/dnn_test_prio/eval_prioritization.py:208-215).
POINT_PRED_QUANTIFIERS = {
    "softmax": max_softmax,
    "pcs": pcs,
    "softmax_entropy": softmax_entropy,
    "deep_gini": deep_gini,
}

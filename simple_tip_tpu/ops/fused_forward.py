"""Fully-fused Pallas forward for the MNIST/FMNIST convnet scoring path.

Why this kernel exists (SCALING.md "Where the 92% goes"): the flagship
TIP-scoring path is HBM-bound — its arithmetic intensity is 32.8 flop/byte
against the chip's 241 flop/byte balance point, because XLA materializes
every layer's activations to HBM at batch 32k (the analytic mandatory
traffic, `utils.flops.conv_net_forward_hbm_bytes`, is ~149 KB/input and
the measured rate already runs at 58% of HBM peak). This kernel runs the
ENTIRE forward — conv1 → pool → conv2 → pool → dense → softmax — for a
batch tile inside VMEM, so per-input HBM traffic collapses to the input
read + 10 probabilities out (~3.2 KB): intensity rises ~45×, moving the
path from the memory roofline onto the MXU one.

Kernel structure per batch tile (shapes for the 28×28×1 MNIST stack,
reference architecture src/dnn_test_prio/case_study_mnist.py:50-69,
mirrored from models/convnet.py MnistConvNet):

- conv1 (C_in=1) as 9 shifted broadcast FMAs — its FLOPs are 8% of the
  model; an im2col matmul with K=9 would waste the 128-wide MXU anyway.
- maxpool 2×2 via reshape-max (26 = 2·13 exactly).
- conv2 as ONE im2col matmul ``[TB·121, 288] @ [288, 64]`` — the FLOPs
  center of the model (58%); K=288 keeps the MXU's contraction dimension
  full, where the 9-shift formulation's K=32 would cap it at a quarter.
  The patch concatenation order (dy-major, then dx, then channel) matches
  ``w2.reshape(288, 64)`` row order.
- pool 2×2 on 11×11 floors to 5×5 (slice ``[:10, :10]`` then reshape-max,
  equal to flax ``max_pool`` window-2 stride-2 semantics).
- dense ``[TB, 1600] @ [1600, 10]`` (+bias) in one matmul; softmax f32.

``compute_dtype=bfloat16`` feeds the matmuls bf16 operands with f32
accumulation (``preferred_element_type``), the same contract as the flax
model's bf16 mode; f32 is exact-parity mode. Inference only (dropout
inactive), probabilities out — the scoring hot path of the reference's
``handler_model.py:102-173``; uncertainty quantifiers stay outside (they
are elementwise on [B, 10] — XLA fuses them into the consumer for free).

Correctness is pinned against the flax model in interpret mode on CPU
(tests/test_fused_forward.py); bench.py auto-validates numerics at runtime
before trusting the kernel on real hardware (TIP_BENCH_FUSED knob), so a
Mosaic lowering quirk on some TPU generation can never silently corrupt a
benchmark record.
"""

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

try:  # pallas is optional at import time (matches ops/flash_attention.py)
    from jax.experimental import pallas as pl

    HAVE_PALLAS = True
except ImportError:  # pragma: no cover
    pl = None
    HAVE_PALLAS = False


def _mnist_kernel(
    x_ref, w1_ref, b1_ref, w2_ref, b2_ref, wd_ref, bd_ref, out_ref, *, cdt
):
    f32 = jnp.float32
    x = x_ref[...].astype(cdt)  # [TB, 28, 28, 1]
    tb = x.shape[0]

    # conv1: C_in=1 -> 9 shifted broadcast FMAs, f32 accumulator
    w1 = w1_ref[...].astype(cdt)  # [3, 3, 1, 32]
    acc = jnp.zeros((tb, 26, 26, 32), f32)
    for dy in range(3):
        for dx in range(3):
            acc = acc + (
                x[:, dy : dy + 26, dx : dx + 26, :] * w1[dy, dx, 0, :]
            ).astype(f32)
    h = jax.nn.relu(acc + b1_ref[...].astype(f32))  # [TB, 26, 26, 32]
    # pool 2x2 (26 = 2*13)
    h = jnp.max(h.reshape(tb, 13, 2, 13, 2, 32), axis=(2, 4))  # [TB,13,13,32]

    # conv2: one im2col matmul [TB*121, 288] @ [288, 64]
    h = h.astype(cdt)
    patches = jnp.concatenate(
        [
            h[:, dy : dy + 11, dx : dx + 11, :]
            for dy in range(3)
            for dx in range(3)
        ],
        axis=-1,
    )  # [TB, 11, 11, 288] in (dy, dx, c) channel order
    # same (dy, dx, c) rows; [3, 3, 32, 64] -> [288, 64] derived from the
    # weight ref itself so a different channel stack can't silently mis-fold
    w2 = w2_ref[...].astype(cdt).reshape(-1, w2_ref.shape[-1])
    h2 = jax.lax.dot_general(
        patches.reshape(tb * 121, 288),
        w2,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=f32,
    ).reshape(tb, 11, 11, 64)
    h2 = jax.nn.relu(h2 + b2_ref[...].astype(f32))
    # pool 2x2 on 11x11 -> 5x5 (floor semantics == slice even region)
    h2 = jnp.max(
        h2[:, :10, :10, :].reshape(tb, 5, 2, 5, 2, 64), axis=(2, 4)
    )  # [TB, 5, 5, 64]

    # dense + softmax (f32)
    flat = h2.reshape(tb, 1600).astype(cdt)
    logits = (
        jax.lax.dot_general(
            flat,
            wd_ref[...].astype(cdt),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=f32,
        )
        + bd_ref[...].astype(f32)
    )
    out_ref[...] = jax.nn.softmax(logits, axis=-1)


def _im2col_conv(h, w, cdt, out_hw):
    """VALID 3×3 conv as ONE im2col matmul: [TB·out², 9·C_in] @ [9·C_in, C_out].

    Patch channel order is (dy, dx, c) — exactly ``w.reshape(9·C_in, C_out)``
    row order for a [3, 3, C_in, C_out] kernel. f32 accumulation via
    ``preferred_element_type``.
    """
    tb = h.shape[0]
    c_in, c_out = w.shape[2], w.shape[3]
    patches = jnp.concatenate(
        [
            h[:, dy : dy + out_hw, dx : dx + out_hw, :]
            for dy in range(3)
            for dx in range(3)
        ],
        axis=-1,
    )  # [TB, out, out, 9*C_in]
    out = jax.lax.dot_general(
        patches.reshape(tb * out_hw * out_hw, 9 * c_in),
        w.astype(cdt).reshape(9 * c_in, c_out),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return out.reshape(tb, out_hw, out_hw, c_out)


def _pool2(h, out_hw):
    """2×2 stride-2 maxpool with flax floor semantics."""
    tb, c = h.shape[0], h.shape[3]
    return jnp.max(
        h[:, : 2 * out_hw, : 2 * out_hw, :].reshape(tb, out_hw, 2, out_hw, 2, c),
        axis=(2, 4),
    )


def _cifar_kernel(
    x_ref, w1_ref, b1_ref, w2_ref, b2_ref, w3_ref, b3_ref,
    wd1_ref, bd1_ref, wd2_ref, bd2_ref, out_ref, *, cdt,
):
    """Cifar10ConvNet forward per batch tile (models/convnet.py layer
    order; reference src/dnn_test_prio/case_study_cifar10.py:33-57):
    conv32 → pool → conv64 → pool → conv64 → dense64 relu → dense10
    softmax, all three convs as im2col matmuls."""
    f32 = jnp.float32
    x = x_ref[...].astype(cdt)  # [TB, 32, 32, 3]
    tb = x.shape[0]
    h = jax.nn.relu(
        _im2col_conv(x, w1_ref[...], cdt, 30) + b1_ref[...].astype(f32)
    )
    h = _pool2(h, 15).astype(cdt)  # [TB, 15, 15, 32]
    h = jax.nn.relu(
        _im2col_conv(h, w2_ref[...], cdt, 13) + b2_ref[...].astype(f32)
    )
    h = _pool2(h, 6).astype(cdt)  # [TB, 6, 6, 64] (13 floors to 6)
    h = jax.nn.relu(
        _im2col_conv(h, w3_ref[...], cdt, 4) + b3_ref[...].astype(f32)
    )  # [TB, 4, 4, 64]
    flat = h.astype(cdt).reshape(tb, 1024)
    hd = jax.nn.relu(
        jax.lax.dot_general(
            flat, wd1_ref[...].astype(cdt),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=f32,
        )
        + bd1_ref[...].astype(f32)
    )
    logits = (
        jax.lax.dot_general(
            hd.astype(cdt), wd2_ref[...].astype(cdt),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=f32,
        )
        + bd2_ref[...].astype(f32)
    )
    out_ref[...] = jax.nn.softmax(logits, axis=-1)


def fused_cifar10_probs(
    params: dict,
    x: jnp.ndarray,
    compute_dtype: Optional[Any] = jnp.bfloat16,
    tile: int = 32,
    interpret: bool = False,
) -> jnp.ndarray:
    """Softmax probabilities [B, 10] for Cifar10ConvNet via the fused kernel.

    Default tile 32: the conv1 activation block [tile, 30, 30, 32] is the
    VMEM high-water mark (f32 accumulator), ~3.7 MB at 32.
    """
    if not HAVE_PALLAS:
        raise RuntimeError("jax.experimental.pallas unavailable in this build")
    cdt = jnp.dtype(compute_dtype) if compute_dtype is not None else jnp.dtype(
        jnp.float32
    )
    names = ("Conv_0", "Conv_1", "Conv_2", "Dense_0", "Dense_1")
    w = [params[n]["kernel"] for n in names]
    bias = [params[n]["bias"] for n in names]
    assert w[0].shape == (3, 3, 3, 32) and w[2].shape == (3, 3, 64, 64), (
        "fused kernel mirrors the CIFAR-10 architecture only"
    )
    b = x.shape[0]
    pad = (-b) % tile
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)], axis=0
        )
    full = lambda shape: pl.BlockSpec(shape, lambda i: (0,) * len(shape))
    operands = [x]
    specs = [pl.BlockSpec((tile, 32, 32, 3), lambda i: (i, 0, 0, 0))]
    for wk, bk in zip(w, bias):
        operands += [wk, bk]
        specs += [full(wk.shape), full(bk.shape)]
    out = pl.pallas_call(
        functools.partial(_cifar_kernel, cdt=cdt),
        grid=(x.shape[0] // tile,),
        in_specs=specs,
        out_specs=pl.BlockSpec((tile, 10), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((x.shape[0], 10), jnp.float32),
        interpret=interpret,
    )(*operands)
    return out[:b]


def fused_mnist_probs(
    params: dict,
    x: jnp.ndarray,
    compute_dtype: Optional[Any] = jnp.bfloat16,
    tile: int = 64,
    interpret: bool = False,
) -> jnp.ndarray:
    """Softmax probabilities [B, 10] for MnistConvNet via the fused kernel.

    ``params``: the flax param tree of ``MnistConvNet`` (``Conv_0``,
    ``Conv_1``, ``Dense_0``). Batch is padded to a multiple of ``tile``
    internally. Wrap in ``jax.jit`` at the call site.
    """
    if not HAVE_PALLAS:
        raise RuntimeError("jax.experimental.pallas unavailable in this build")
    cdt = jnp.dtype(compute_dtype) if compute_dtype is not None else jnp.dtype(
        jnp.float32
    )
    w1 = params["Conv_0"]["kernel"]
    b1 = params["Conv_0"]["bias"]
    w2 = params["Conv_1"]["kernel"]
    b2 = params["Conv_1"]["bias"]
    wd = params["Dense_0"]["kernel"]
    bd = params["Dense_0"]["bias"]
    assert w1.shape == (3, 3, 1, 32) and w2.shape == (3, 3, 32, 64), (
        "fused kernel mirrors the MNIST/FMNIST architecture only"
    )
    b = x.shape[0]
    pad = (-b) % tile
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)], axis=0
        )
    n_tiles = x.shape[0] // tile

    full = lambda shape: pl.BlockSpec(shape, lambda i: (0,) * len(shape))
    out = pl.pallas_call(
        functools.partial(_mnist_kernel, cdt=cdt),
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((tile, 28, 28, 1), lambda i: (i, 0, 0, 0)),
            full(w1.shape),
            full(b1.shape),
            full(w2.shape),
            full(b2.shape),
            full(wd.shape),
            full(bd.shape),
        ],
        out_specs=pl.BlockSpec((tile, 10), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((x.shape[0], 10), jnp.float32),
        interpret=interpret,
    )(x, w1, b1, w2, b2, wd, bd)
    return out[:b]


def fused_available() -> bool:
    """Whether the Pallas fused-forward kernels can run in this build."""
    return HAVE_PALLAS


def validate_against_model(
    params: dict,
    compute_dtype: Optional[Any] = jnp.bfloat16,
    n: int = 256,
    tile: int = 64,
    interpret: bool = False,
    seed: int = 0,
    family: str = "mnist",
) -> float:
    """Max |fused - flax| probability gap on random inputs (runtime gate).

    Callers refuse the fused path unless this is small; the flax model
    runs in the SAME compute dtype, so the gap measures kernel-vs-XLA
    numerics, not bf16-vs-f32 rounding. ``tile`` must be the tile the
    caller will MEASURE with — lowering is tile-dependent, so validating
    one tile says nothing about another. ``family`` selects the kernel
    ("mnist"/"fmnist" share one architecture; "cifar10" the other); each
    family must be gated separately before trust on a given TPU
    generation.
    """
    if family in ("mnist", "fmnist"):
        from simple_tip_tpu.models import MnistConvNet as Model

        shape, fused_fn = (28, 28, 1), fused_mnist_probs
    elif family == "cifar10":
        from simple_tip_tpu.models import Cifar10ConvNet as Model

        shape, fused_fn = (32, 32, 3), fused_cifar10_probs
    else:
        raise ValueError(f"no fused kernel for family {family!r}")
    x = jnp.asarray(
        np.random.default_rng(seed).normal(size=(n,) + shape).astype(np.float32)
    )
    model = Model(
        compute_dtype=None
        if compute_dtype is None or jnp.dtype(compute_dtype) == jnp.float32
        else compute_dtype
    )
    ref_probs, _ = model.apply({"params": params}, x, train=False)
    got = fused_fn(params, x, compute_dtype, tile=tile, interpret=interpret)
    return float(jnp.max(jnp.abs(got - ref_probs)))

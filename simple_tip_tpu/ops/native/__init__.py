"""ctypes loader for the native host kernels (see tip_native.cpp).

The library auto-builds on first import if a compiler is available; every
caller treats this module as optional and falls back to the numpy/python path
when the build fails (``from ... import cam_native`` raising ImportError).
"""

import ctypes
import logging
import os
import subprocess
from typing import List, Optional

import numpy as np

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "tip_native.cpp")
_LIB = os.path.join(_HERE, "libtipnative.so")

_lib: Optional[ctypes.CDLL] = None


def _build() -> None:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", _LIB]
    subprocess.run(cmd, check=True, capture_output=True)


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
        _build()
    lib = ctypes.CDLL(_LIB)
    lib.cam_greedy.restype = ctypes.c_int64
    lib.cam_greedy.argtypes = [
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.cam_greedy_packed.restype = ctypes.c_int64
    lib.cam_greedy_packed.argtypes = [
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.lev_matrix.restype = None
    lib.lev_matrix.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint8),
    ]
    lib.levenshtein.restype = ctypes.c_int64
    lib.levenshtein.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_char_p,
        ctypes.c_int64,
    ]
    _lib = lib
    return lib


try:
    _load()
except Exception as e:  # pragma: no cover - depends on toolchain
    logger.warning("native kernels unavailable (%s); using python fallbacks", e)
    raise ImportError(f"tip native library unavailable: {e}") from e


def cam_native(scores: np.ndarray, profiles: np.ndarray) -> np.ndarray:
    """Full CAM order: C++ greedy picks + numpy score-ordered remainder
    (identical semantics to the pure-python cam_order)."""
    lib = _load()
    prof = np.ascontiguousarray(profiles.reshape(profiles.shape[0], -1), dtype=np.uint8)
    n, m = prof.shape
    out = np.empty(n, dtype=np.int64)
    n_picked = lib.cam_greedy(
        prof.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n,
        m,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    picked = out[:n_picked]
    from simple_tip_tpu.ops.prioritizers import _with_score_tail

    return _with_score_tail(scores, picked)


def cam_order_packed(scores: np.ndarray, packed: np.ndarray, m_bits: int) -> np.ndarray:
    """Full CAM order from numpy-packbits profile rows (n x nbytes uint8):
    C++ popcount greedy picks + numpy score-ordered remainder."""
    lib = _load()
    packed = np.ascontiguousarray(packed, dtype=np.uint8)
    n, nbytes = packed.shape
    out = np.empty(n, dtype=np.int64)
    n_picked = lib.cam_greedy_packed(
        packed.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n,
        nbytes,
        int(m_bits),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
    )
    picked = out[:n_picked]
    from simple_tip_tpu.ops.prioritizers import _with_score_tail

    return _with_score_tail(scores, picked)


def lev_matrix(words: List[str]) -> np.ndarray:
    """Pairwise Levenshtein distance matrix (uint8) over a word list."""
    lib = _load()
    encoded = [w.encode("utf-8") for w in words]
    concat = b"".join(encoded)
    offsets = np.zeros(len(words) + 1, dtype=np.int64)
    np.cumsum([len(e) for e in encoded], out=offsets[1:])
    out = np.zeros((len(words), len(words)), dtype=np.uint8)
    lib.lev_matrix(
        concat,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(words),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return out


def levenshtein(a: str, b: str) -> int:
    """Levenshtein distance between two strings."""
    lib = _load()
    ea, eb = a.encode("utf-8"), b.encode("utf-8")
    return int(lib.levenshtein(ea, len(ea), eb, len(eb)))

// Native kernels for host-side sequential hot loops.
//
// The TPU (XLA) path owns all tensor math; these C++ kernels cover the two
// inherently-sequential host loops the interpreter would otherwise throttle:
//
// 1. cam_greedy: the greedy max-marginal-coverage loop of the CAM prioritizer
//    (behavioral contract: reference src/core/prioritizers.py:16-59). Called
//    on boolean profile matrices up to ~20k x 100k bits per (metric, dataset).
//
// 2. lev_matrix: the pairwise Levenshtein distance matrix of the text
//    corruptor's dictionary (reference src/core/text_corruptor.py:282-309,
//    which uses the polyleven C extension; this replaces it).
//
// Built as a plain shared library, loaded via ctypes (no pybind11 needed).

#include <cstdint>
#include <cstring>
#include <vector>
#include <algorithm>

extern "C" {

// Greedy CAM picks. profiles: row-major n x m uint8 (0/1). Returns the number
// of picked samples; picked indices (in pick order) written to out (size n).
// Stops when the best sample adds no new coverage or everything is covered.
int64_t cam_greedy(const uint8_t* profiles, int64_t n, int64_t m, int64_t* out) {
    std::vector<int64_t> num_coverable(n, 0);
    for (int64_t i = 0; i < n; ++i) {
        const uint8_t* row = profiles + i * m;
        int64_t s = 0;
        for (int64_t j = 0; j < m; ++j) s += row[j];
        num_coverable[i] = s;
    }
    std::vector<uint8_t> covered(m, 0);
    std::vector<int64_t> newly;
    newly.reserve(1024);
    int64_t remaining = m;
    int64_t n_picked = 0;
    while (true) {
        // argmax with lowest-index tie-break (matches np.argmax)
        int64_t best = 0;
        int64_t best_val = num_coverable[0];
        for (int64_t i = 1; i < n; ++i) {
            if (num_coverable[i] > best_val) {
                best_val = num_coverable[i];
                best = i;
            }
        }
        if (best_val == 0) break;
        out[n_picked++] = best;

        const uint8_t* row = profiles + best * m;
        newly.clear();
        for (int64_t j = 0; j < m; ++j) {
            if (row[j] && !covered[j]) newly.push_back(j);
        }
        for (int64_t i = 0; i < n; ++i) {
            const uint8_t* r = profiles + i * m;
            int64_t cnt = 0;
            for (int64_t j : newly) cnt += r[j];
            num_coverable[i] -= cnt;
        }
        for (int64_t j : newly) covered[j] = 1;
        remaining -= best_val;
        if (remaining == 0) break;
    }
    return n_picked;
}

// Packed-bit variant: profiles are row-major n x nbytes uint8 bitfields
// (MSB-first within a byte, numpy packbits layout; trailing pad bits zero).
// Same greedy semantics as cam_greedy, but membership counting is popcount
// over the bytes that gained new coverage — 8x denser memory traffic and
// ~8-64x fewer ops on the wide profile matrices of the real case studies.
int64_t cam_greedy_packed(const uint8_t* profiles, int64_t n, int64_t nbytes,
                          int64_t m_bits, int64_t* out) {
    static const auto popcount_row = [](const uint8_t* row, int64_t nbytes) {
        int64_t s = 0;
        int64_t i = 0;
        for (; i + 8 <= nbytes; i += 8) {
            uint64_t w;
            std::memcpy(&w, row + i, 8);
            s += __builtin_popcountll(w);
        }
        for (; i < nbytes; ++i) s += __builtin_popcount(row[i]);
        return s;
    };

    std::vector<int64_t> num_coverable(n);
    for (int64_t i = 0; i < n; ++i)
        num_coverable[i] = popcount_row(profiles + i * nbytes, nbytes);

    std::vector<uint8_t> covered(nbytes, 0);
    std::vector<uint8_t> newly(nbytes, 0);
    std::vector<int64_t> active;  // byte indices with new coverage this pick
    active.reserve(256);
    int64_t remaining = m_bits;
    int64_t n_picked = 0;
    while (true) {
        int64_t best = 0;
        int64_t best_val = num_coverable[0];
        for (int64_t i = 1; i < n; ++i) {
            if (num_coverable[i] > best_val) {
                best_val = num_coverable[i];
                best = i;
            }
        }
        if (best_val == 0) break;
        out[n_picked++] = best;

        const uint8_t* row = profiles + best * nbytes;
        active.clear();
        int64_t newly_bits = 0;
        for (int64_t b = 0; b < nbytes; ++b) {
            uint8_t nb = row[b] & static_cast<uint8_t>(~covered[b]);
            newly[b] = nb;
            if (nb) {
                active.push_back(b);
                newly_bits += __builtin_popcount(nb);
            }
        }
        for (int64_t i = 0; i < n; ++i) {
            const uint8_t* r = profiles + i * nbytes;
            int64_t cnt = 0;
            for (int64_t b : active) cnt += __builtin_popcount(r[b] & newly[b]);
            num_coverable[i] -= cnt;
        }
        for (int64_t b : active) covered[b] |= newly[b];
        remaining -= newly_bits;
        if (remaining <= 0) break;
    }
    return n_picked;
}

static inline int lev(const char* a, int la, const char* b, int lb,
                      std::vector<int>& dp) {
    // single-row DP
    if (la == 0) return lb;
    if (lb == 0) return la;
    dp.resize(lb + 1);
    for (int j = 0; j <= lb; ++j) dp[j] = j;
    for (int i = 1; i <= la; ++i) {
        int prev = dp[0];
        dp[0] = i;
        for (int j = 1; j <= lb; ++j) {
            int cur = dp[j];
            int cost = (a[i - 1] == b[j - 1]) ? 0 : 1;
            dp[j] = std::min(std::min(dp[j] + 1, dp[j - 1] + 1), prev + cost);
            prev = cur;
        }
    }
    return dp[lb];
}

// Full pairwise Levenshtein matrix over n words. words: concatenated bytes;
// offsets: n+1 prefix offsets. out: n*n uint8 (distances clipped to 255).
void lev_matrix(const char* words, const int64_t* offsets, int64_t n,
                uint8_t* out) {
    std::vector<int> dp;
    for (int64_t i = 0; i < n; ++i) {
        const char* wi = words + offsets[i];
        int li = static_cast<int>(offsets[i + 1] - offsets[i]);
        out[i * n + i] = 0;
        for (int64_t j = i + 1; j < n; ++j) {
            const char* wj = words + offsets[j];
            int lj = static_cast<int>(offsets[j + 1] - offsets[j]);
            int d = lev(wi, li, wj, lj, dp);
            uint8_t v = d > 255 ? 255 : static_cast<uint8_t>(d);
            out[i * n + j] = v;
            out[j * n + i] = v;
        }
    }
}

// Single-pair Levenshtein distance.
int64_t levenshtein(const char* a, int64_t la, const char* b, int64_t lb) {
    std::vector<int> dp;
    return lev(a, static_cast<int>(la), b, static_cast<int>(lb), dp);
}

}  // extern "C"

"""Whole-chain fused run program: predict -> quantify -> rank in ONE trace.

SCALING.md's roofline puts the flagship path at 7.9% MFU because every
per-run phase (predict, quantify, rank) is a separate Python-driven dispatch
whose intermediates round-trip through host memory. This module builds the
pure functions that collapse the chain: one traced program maps a badge of
inputs to predictions, every point uncertainty quantifier, and every
coverage metric's (scores, bit-packed profiles) — activations never leave
the device — and a second small program runs the greedy CAM phase over the
accumulated packed profiles. ``engine/run_program.py`` AOT-compiles and
caches these; this module stays jax-pure so it can be lowered, vmapped over
G-run ensemble groups, and tested in isolation.

Exact int8 profile coding (``ThresholdCodebook``): NAC/NBC/SNAC/KMNC are all
per-neuron threshold comparisons, so each neuron's activation can be recoded
as the COUNT of passed cutpoints — an int8 — from which every metric bit is
recovered by integer comparisons against precomputed ranks. The coding is
EXACT (each cut is the same float comparison the plain metrics perform, and
passing a higher cut implies passing all lower ones), so parity tests can
assert bit-identical scores and profiles with the codebook on; what changes
is the bytes: the 12-metric derivation reads a 1-byte code per neuron
instead of re-reading the f32 activation per metric family.
"""

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from simple_tip_tpu.ops.coverage import (
    KMNC,
    NAC,
    NBC,
    SNAC,
    flatten_layers,
    sum_score,
)
from simple_tip_tpu.ops.prioritizers import device_cam_greedy
from simple_tip_tpu.ops.uncertainty import POINT_PRED_QUANTIFIERS


def pack_bits_u32(flat):
    """Bit-pack a traced boolean [B, W] matrix into [B, ceil(W/32)] uint32.

    Same layout as ``prioritizers.pack_profiles`` (bit j of word k = section
    32*k + j), so the packed output feeds ``device_cam_greedy`` directly and
    cross-checks against the host packer in tests.
    """
    import jax.numpy as jnp

    b, w = flat.shape
    pad = (-w) % 32
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((b, pad), bool)], axis=1)
    bits = flat.reshape(b, -1, 32).astype(jnp.uint32)
    shifts = jnp.arange(32, dtype=jnp.uint32)
    return jnp.sum(bits << shifts[None, None, :], axis=2, dtype=jnp.uint32)


class ThresholdCodebook:
    """Exact int8 interval coding of the threshold-family coverage metrics.

    Build-time (host numpy): collect every cutpoint the configured
    NAC/NBC/SNAC/KMNC instances compare against as a (value, strict) pair
    per neuron, sorted so that *passing* is monotone — at equal values the
    inclusive ``>=`` cut sorts before the strict ``>`` cut, so an activation
    passing any cut passes all lower-ranked ones (the prefix property).

    Trace-time (``apply``): one comparison sweep yields the per-neuron pass
    count — the int8 code — and every metric's bits derive from it:

    - ``a > t`` / ``a >= t``  <=>  ``code > rank(cut)``
    - NBC low (``a <= min_b``) <=> ``code <= rank`` guarded by ``~isnan(a)``
      (the inverted comparison would otherwise fire on NaN activations)
    - KMNC bucket i (``e_i <= a < e_{i+1}``) <=>
      ``(code > rank(e_i)) & (code <= rank(e_{i+1}))``

    TKNC is rank-based (top-k), not threshold-based, and stays on its own
    formulation.
    """

    #: metric families the codebook can recode
    FAMILIES = (NAC, NBC, SNAC, KMNC)

    def __init__(self, metrics: Dict[str, object]):
        self._cuts: List[Tuple[object, bool]] = []  # (value scalar/[N], strict)
        self._specs: Dict[str, tuple] = {}
        for mid, m in metrics.items():
            if isinstance(m, NAC):
                self._specs[mid] = ("ge", self._cut(m.cov_threshold, True))
            elif isinstance(m, SNAC):
                self._specs[mid] = ("ge", self._cut(m.max_boundaries, False))
            elif isinstance(m, NBC):
                self._specs[mid] = (
                    "nbc",
                    self._cut(m.min_boundaries, True),
                    self._cut(m.max_boundaries, False),
                )
            elif isinstance(m, KMNC):
                edges = [m.lo + m.jumps * i for i in range(m.sections + 1)]
                self._specs[mid] = ("kmnc", [self._cut(e, False) for e in edges])
        if len(self._cuts) > 127:
            raise ValueError(
                f"{len(self._cuts)} cutpoints exceed the int8 code range"
            )
        self._finalized: Dict[int, tuple] = {}

    def _cut(self, value, strict: bool) -> int:
        self._cuts.append((value, strict))
        return len(self._cuts) - 1

    def covers(self, mid: str) -> bool:
        """True when this metric's bits derive from the code."""
        return mid in self._specs

    def spec_signature(self) -> tuple:
        """Hashable STRUCTURAL identity of the coding: metric ids, spec
        kinds, cut indices and per-cut strictness. Group members must agree
        on this (their cut VALUES differ — those ride the table)."""

        def _norm(spec):
            return tuple(
                tuple(x) if isinstance(x, list) else x for x in spec
            )

        return (
            tuple((mid, _norm(spec)) for mid, spec in sorted(self._specs.items())),
            tuple(s for _, s in self._cuts),
        )

    def _ensure(self, n_neurons: int):
        """Per-neuron sorted cut table + per-cut ranks (host numpy, cached
        per neuron count — one table per traced activation width)."""
        cached = self._finalized.get(n_neurons)
        if cached is not None:
            return cached
        vals = np.stack(
            [
                np.broadcast_to(np.asarray(v, np.float64).reshape(-1), (n_neurons,))  # tiplint: disable=f64-on-tpu (host cut-table build; exact lexsort of threshold values)
                if np.ndim(v)
                else np.full((n_neurons,), float(v))
                for v, _ in self._cuts
            ],
            axis=1,
        )  # [N, K]
        strict = np.array([s for _, s in self._cuts], dtype=bool)  # [K]
        strict_b = np.broadcast_to(strict, vals.shape)
        # primary key: cut value; secondary: strictness (inclusive first),
        # which is exactly the order that makes pass-sets prefix-closed
        order = np.lexsort((strict_b, vals), axis=1)
        rank = np.argsort(order, axis=1).astype(np.int32)  # [N, K]: cut j -> rank
        sorted_vals = np.take_along_axis(vals, order, axis=1)
        sorted_strict = np.take_along_axis(strict_b, order, axis=1)
        entry = (sorted_vals, sorted_strict, rank)
        self._finalized[n_neurons] = entry
        return entry

    def table(self, n_neurons: int):
        """The cut table as plain arrays: ``(sorted_vals f32 [N, K],
        sorted_strict bool [N, K], rank int32 [N, K])``.

        This is the per-member payload the grouped chain stacks over the G
        axis and passes as TRACED inputs: thresholds are per-member train
        statistics, so baking them as constants would need one compiled
        program per member — exactly the dispatch scaling grouping removes.
        f32 cast happens here (host, round-to-nearest) so the traced
        comparison is bit-identical to the baked-constant path, where jax
        performs the same narrowing on the f64 table at op time.
        """
        sorted_vals, sorted_strict, rank = self._ensure(n_neurons)
        return (
            np.asarray(sorted_vals, np.float32),
            np.asarray(sorted_strict, bool),
            np.asarray(rank, np.int32),
        )

    def apply(self, flat_acts) -> Dict[str, Tuple[np.ndarray, np.ndarray]]:
        """``{metric_id: (scores, bool profiles)}`` from one coded sweep.

        ``flat_acts``: traced [B, N] activation matrix (``flatten_layers``
        output). Profile shapes match the plain metrics' outputs exactly.
        """
        return self.apply_tables(flat_acts, self.table(flat_acts.shape[1]))

    def apply_tables(
        self, flat_acts, tables
    ) -> Dict[str, Tuple[np.ndarray, np.ndarray]]:
        """``apply`` with the cut table supplied as (possibly traced) arrays.

        ``tables`` is a ``table(...)``-shaped triple; the grouped chain
        vmaps this over a leading member axis so ONE program serves G
        members with G different threshold sets. The derivation is the same
        integer/compare arithmetic either way, so outputs are bit-identical
        to the baked-constant ``apply``.
        """
        import jax.numpy as jnp

        sorted_vals, sorted_strict, rank = tables
        a = flat_acts[:, :, None]
        passed = jnp.where(
            sorted_strict[None], a > sorted_vals[None], a >= sorted_vals[None]
        )
        # THE quantized representation: one byte per neuron carries every
        # threshold metric's information through the rest of the program.
        code = jnp.sum(passed, axis=2, dtype=jnp.int32).astype(jnp.int8)
        code = code.astype(jnp.int32)  # widen once for the rank comparisons
        nan = jnp.isnan(flat_acts)
        out = {}
        for mid, spec in self._specs.items():
            if spec[0] == "ge":
                prof = code > rank[:, spec[1]][None]
            elif spec[0] == "nbc":
                low = (code <= rank[:, spec[1]][None]) & ~nan
                high = code > rank[:, spec[2]][None]
                prof = jnp.stack([low, high], axis=-1)
            else:  # kmnc
                rs = [rank[:, i][None] for i in spec[1]]
                prof = jnp.stack(
                    [
                        (code > rs[i]) & (code <= rs[i + 1])
                        for i in range(len(rs) - 1)
                    ],
                    axis=-1,
                )
            out[mid] = (sum_score(prof), prof)
        return out


def make_chain_fn(
    model_def,
    layer_ids: Sequence,
    metrics: Dict[str, object],
    quantifiers: Optional[Dict] = None,
    int8_profiles: bool = False,
):
    """The whole-chain function ``(params, x, valid) -> (pred, unc, cov)``.

    One trace covers the forward pass, every point uncertainty quantifier
    on the softmax outputs, and every coverage metric's (scores, packed
    uint32 profiles) over the tapped activations. ``valid`` is a TRACED
    int32 scalar: rows at index >= valid are badge padding (the engine pads
    the final partial badge so ONE compiled shape serves the whole walk —
    no per-remainder retrace) and get all-zero packed profiles so they can
    never be picked by the CAM phase downstream. Their scores/uncertainties
    are garbage the caller slices off on host.

    Returns are raw device values: ``pred`` [B] argmax, ``unc`` a dict of
    [B] uncertainty arrays (same registry keys as the per-phase path), and
    ``cov`` a dict of ``(scores, packed)`` per metric id.
    """
    import jax.numpy as jnp

    quantifiers = dict(POINT_PRED_QUANTIFIERS if quantifiers is None else quantifiers)
    layer_ids = tuple(i for i in layer_ids if isinstance(i, int))
    codebook = ThresholdCodebook(metrics) if int8_profiles else None

    def chain(params, xb, valid):
        probs, taps = model_def.apply({"params": params}, xb, train=False)
        acts = [taps[i] for i in layer_ids]
        pred = jnp.argmax(probs, axis=1)
        unc = {name: fn(probs)[1] for name, fn in quantifiers.items()}
        mask = jnp.arange(xb.shape[0]) < valid
        coded = (
            codebook.apply(flatten_layers(acts)) if codebook is not None else {}
        )
        cov = {}
        for mid, metric in metrics.items():
            s, p = coded[mid] if mid in coded else metric(acts)
            packed = pack_bits_u32(p.reshape((p.shape[0], -1)))
            cov[mid] = (s, jnp.where(mask[:, None], packed, jnp.uint32(0)))
        return pred, unc, cov

    return chain


def make_member_chain_fn(
    model_def,
    layer_ids: Sequence,
    metrics: Dict[str, object],
    quantifiers: Optional[Dict] = None,
):
    """One group member's chain with its cut table as a TRACED input:
    ``(params, tables, xb, valid) -> (pred, unc, cov)``.

    The grouped executor scores G independently trained models in one
    dispatch, but the threshold-family metrics (NBC/SNAC/KMNC boundaries)
    are per-member TRAINING statistics — baked as constants they would
    force one compiled program per member, which is exactly the dispatch
    scaling grouping exists to remove. So here the threshold families
    always ride the int8 codebook with the cut table an argument
    (``ThresholdCodebook.table`` triple; ``make_group_chain_fn`` stacks one
    per member over the G axis), while config-only metrics (TKNC's top-k
    ranks, identical across members by construction) stay closed over.

    ``metrics`` supplies the coding STRUCTURE (families, spec layout) and
    must be structurally identical across members — callers assert with
    ``ThresholdCodebook.spec_signature``.
    """
    import jax.numpy as jnp

    quantifiers = dict(POINT_PRED_QUANTIFIERS if quantifiers is None else quantifiers)
    layer_ids = tuple(i for i in layer_ids if isinstance(i, int))
    codebook = ThresholdCodebook(metrics)

    def member_chain(params, tables, xb, valid):
        probs, taps = model_def.apply({"params": params}, xb, train=False)
        acts = [taps[i] for i in layer_ids]
        pred = jnp.argmax(probs, axis=1)
        unc = {name: fn(probs)[1] for name, fn in quantifiers.items()}
        mask = jnp.arange(xb.shape[0]) < valid
        coded = codebook.apply_tables(flatten_layers(acts), tables)
        cov = {}
        for mid, metric in metrics.items():
            s, p = coded[mid] if codebook.covers(mid) else metric(acts)
            packed = pack_bits_u32(p.reshape((p.shape[0], -1)))
            cov[mid] = (s, jnp.where(mask[:, None], packed, jnp.uint32(0)))
        return pred, unc, cov

    return member_chain


def make_group_chain_fn(
    model_def,
    layer_ids: Sequence,
    metrics: Dict[str, object],
    quantifiers: Optional[Dict] = None,
    int8_profiles: bool = False,
    member_tables: bool = False,
):
    """The chain vmapped over a leading G-run ensemble-group axis.

    Default (shared metrics): ``(stacked_params, x, valid) -> (pred [G,B],
    unc {name: [G,B]}, cov {mid: ([G,B], [G,B,W])})`` — one dispatch scores
    a whole device-resident run group against the same badge
    (parallel/ensemble.py's stacked-params layout). All members share the
    closed-over metric constants; right for ensembles that share train
    statistics.

    ``member_tables=True`` is the load-bearing study shape: members are
    INDEPENDENTLY trained runs whose threshold tables differ, so the
    signature grows two inputs — ``(stacked_params, tables, x, valid,
    members) -> ...`` where ``tables`` is a ``ThresholdCodebook.table``
    triple stacked to [G, N, K] per component, and ``members`` is a TRACED
    int32 member-valid scalar: when the run count is not a multiple of G
    the engine pads the stack (repeating member 0) and members at index >=
    ``members`` get all-zero packed profiles — inert to any downstream CAM
    consumer, same contract as badge-padding rows — so ONE compiled shape
    serves the ragged tail.
    """
    import jax
    import jax.numpy as jnp

    if not member_tables:
        chain = make_chain_fn(
            model_def,
            layer_ids,
            metrics,
            quantifiers=quantifiers,
            int8_profiles=int8_profiles,
        )
        return jax.vmap(chain, in_axes=(0, None, None))

    member = make_member_chain_fn(
        model_def, layer_ids, metrics, quantifiers=quantifiers
    )
    vmapped = jax.vmap(member, in_axes=(0, 0, None, None))

    def group_chain(stacked_params, tables, xb, valid, members):
        pred, unc, cov = vmapped(stacked_params, tables, xb, valid)
        alive = jnp.arange(pred.shape[0]) < members
        cov = {
            mid: (s, jnp.where(alive[:, None, None], packed, jnp.uint32(0)))
            for mid, (s, packed) in cov.items()
        }
        return pred, unc, cov

    return group_chain


def select_top_k(values, valid, k: int):
    """Traced AL top-k select: indices of the ``k`` largest valid values.

    The fused-chain counterpart of ``eval_active_learning``'s
    ``np.argsort(uncertainty)[-num_selected:]`` — the last host-side numpy
    step of the select loop, folded onto the device so the AL selection
    can ride the same AOT program pipeline as scoring (ROADMAP raw-speed
    item (b), the open remainder). ``values`` is a traced [N] vector,
    ``valid`` a traced int32 scalar masking badge padding (rows at index
    >= valid sort to the bottom and can never be selected while k <=
    valid), and ``k`` is STATIC (it shapes the output).

    Tie policy is pinned to jax's STABLE ascending argsort: among equal
    values the higher index wins a contested last slot — byte-identical to
    ``np.argsort(values, kind="stable")[-k:]``, which the parity tests
    assert. Output layout matches the numpy idiom: ascending by value,
    best-last.
    """
    import jax.numpy as jnp

    idx = jnp.arange(values.shape[0])
    masked = jnp.where(idx < valid, values.astype(jnp.float32), -jnp.inf)
    return jnp.argsort(masked)[-int(k):]


def make_select_fn(k: int):
    """``(values, valid) -> top-k indices`` with ``k`` closed over, in the
    AOT-lowerable shape ``engine/run_program.py`` compiles and caches."""

    def select(values, valid):
        return select_top_k(values, valid, k)

    return select


def make_group_select_fn(k: int):
    """``(values [G, N], valid) -> [G, k]`` — ``select_top_k`` vmapped over
    the group axis with ``k`` closed over. Members score the same badge, so
    the badge-padding ``valid`` scalar is shared; each member's row keeps
    the exact ``make_select_fn`` tie policy (stable ascending argsort,
    best-last)."""
    import jax

    def select(values, valid):
        return select_top_k(values, valid, k)

    return jax.vmap(select, in_axes=(0, None))


def rank_badges(badges):
    """Greedy CAM picks over a tuple of equally-shaped packed badges.

    Concatenating INSIDE the traced program (rather than dispatching a
    host-driven ``jnp.concatenate`` per metric) keeps the rank step at one
    compiled program per (badge count, word width) regardless of how many
    metrics share the shape. Returns ``(picked, count)`` as
    ``device_cam_greedy`` does; badge-padding rows are all-zero (see
    ``make_chain_fn``) so they are unpickable by construction.
    """
    import jax.numpy as jnp

    badges = list(badges)
    packed = badges[0] if len(badges) == 1 else jnp.concatenate(badges, axis=0)
    return device_cam_greedy(packed, packed.shape[0])


def rank_badges_grouped(badges):
    """``rank_badges`` vmapped over a leading G-group axis ([G, B, W] badges)."""
    import jax
    import jax.numpy as jnp

    badges = list(badges)
    packed = badges[0] if len(badges) == 1 else jnp.concatenate(badges, axis=1)
    return jax.vmap(lambda p: device_cam_greedy(p, p.shape[0]))(packed)

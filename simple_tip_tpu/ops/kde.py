"""Numerically-stabilized Gaussian kernel density estimation (host, float64).

Self-contained reimplementation of the math of scipy's ``gaussian_kde`` with the
reference's stabilization semantics (reference: src/core/stable_kde.py:26-101):

- Scott bandwidth factor ``n**(-1/(d+4))``.
- While the scaled covariance has a non-positive eigenvalue, the data
  covariance's *diagonal is replaced* by a doubling increment (1e-10, 2e-10,
  ...); past ``MAX_INCREMENT=1e-5`` preparation fails silently and all
  densities evaluate to 0. (The diagonal *replacement* — not addition — is a
  quirk of the reference, preserved for behavioral parity.)
- Cholesky of ``2*pi*covariance``; a failure surfaces the 1-based index of the
  offending leading minor so LSA can drop that feature and retry
  (reference: src/core/surprise.py:454-473).

float64 throughout on the host path: TPUs have no native f64, and KDE fitting
is a tiny (d<=300) host-side computation. The *evaluation* over many test
points is the bulk work; when the resolved cluster backend is ``jax`` it runs
as ONE jitted log-space dispatch over device-resident points (whiten, pairwise
quadform, logsumexp — the log-space form keeps f32 inside the dynamic range
that the normalization constant ``exp(-log_det/2)/n`` would overflow), with a
single final device→host transfer. The blocked float64 numpy quadform stays
the CPU/reference path — parity between the two is pinned by seeded tests
(tests/test_device_scoring.py); APFD depends on score ordering, which the
log-space device form preserves.

Module import stays jax-free on purpose: spawned SA fit-pool workers import
this module and must never pay (or wedge on) an accelerator-backend init.
"""

import warnings
from typing import Optional

import numpy as np
import scipy.linalg

_DEVICE_EVAL = None


def _use_device_backend() -> bool:
    """Whether KDE evaluation should run on the device (resolved cluster
    backend is ``jax``). Imported at call time: ops/surprise imports this
    module at its top level."""
    from simple_tip_tpu.ops.surprise import resolved_cluster_backend

    return resolved_cluster_backend() == "jax"


def _device_eval_fn():
    """Cached jitted log-space KDE evaluation kernel (lazy: module import
    must stay jax-free for the spawned fit-pool workers)."""
    global _DEVICE_EVAL
    if _DEVICE_EVAL is None:
        import jax
        import jax.numpy as jnp

        def _eval(chol, dataset, points, log_norm):
            white_data = jax.scipy.linalg.solve_triangular(chol, dataset, lower=True)
            white_points = jax.scipy.linalg.solve_triangular(chol, points, lower=True)
            # squared whitened distances: |x|^2 + |y|^2 - 2 x.y
            d2 = (
                jnp.sum(white_data**2, axis=0)[None, :]
                + jnp.sum(white_points**2, axis=0)[:, None]
                - 2.0 * (white_points.T @ white_data)
            )
            d2 = jnp.maximum(d2, 0.0)
            # log-space: exp(-log_det/2)/n over/underflows f32 where the f64
            # host path does not; logsumexp keeps the full dynamic range.
            return jnp.exp(
                jax.scipy.special.logsumexp(-0.5 * d2, axis=1) + log_norm
            )

        _DEVICE_EVAL = jax.jit(_eval)
    return _DEVICE_EVAL


class KDESingularError(np.linalg.LinAlgError):
    """Cholesky failure carrying the 0-based index of the offending feature
    (None if unknown)."""

    def __init__(self, message: str, problematic_dim: Optional[int]):
        super().__init__(message)
        self.problematic_dim = problematic_dim


class StableGaussianKDE:
    """Gaussian KDE over a ``(d, n)`` float dataset with covariance
    stabilization; mirrors scipy's gaussian_kde evaluation semantics."""

    MAX_INCREMENT = 1e-5

    def __init__(self, dataset: np.ndarray):
        self.dataset = np.atleast_2d(np.asarray(dataset, dtype=np.float64))
        self.d, self.n = self.dataset.shape
        self.factor = self.scotts_factor()
        self.prepare_failed = False
        self._compute_covariance()

    def scotts_factor(self) -> float:
        """Scott's rule bandwidth factor."""
        return np.power(self.n, -1.0 / (self.d + 4))

    def _compute_covariance(self):
        data_covariance = np.atleast_2d(np.cov(self.dataset, rowvar=1, bias=False))
        data_covariance = self._stabilize_covariance(data_covariance)
        if self.prepare_failed:
            return
        try:
            data_inv_cov = np.linalg.inv(data_covariance)
        except np.linalg.LinAlgError:
            self.prepare_failed = True
            return

        self.covariance = data_covariance * self.factor**2
        self.inv_cov = data_inv_cov / self.factor**2
        # Cholesky of 2*pi*cov: raises with the offending leading-minor index
        # (consumed by LSA's recursive feature drop).
        try:
            chol = scipy.linalg.cholesky(self.covariance * 2 * np.pi, lower=True)
        except scipy.linalg.LinAlgError as e:
            dim = None
            msg = str(e)
            if "leading minor" in msg:
                try:
                    dim = int(msg.split("-th")[0].strip().lstrip("(")) - 1
                except ValueError:
                    dim = None
            raise KDESingularError(msg, dim) from e
        self.cho_cov = chol
        self.log_det = 2 * np.log(np.diag(chol)).sum()

    def _stabilize_covariance(self, covariance: np.ndarray):
        """Replace the diagonal with a doubling increment until the scaled
        covariance is numerically positive definite, or fail silently."""
        if not np.isfinite(covariance).all():
            # e.g. a single-sample dataset: np.cov's n-1 divisor yields
            # NaN/inf, which would sail through the eigenvalue loop (NaN
            # comparisons are False) and explode in cholesky's finiteness
            # check. Same silent degraded mode as an unstabilizable matrix.
            warnings.warn(
                "Covariance matrix is not finite (too few samples?). "
                "Failing silently. All likelihoods will be reported as 0."
            )
            self.prepare_failed = True
            return None
        increment = 1e-10
        while np.any(np.linalg.eigh(covariance * self.factor**2)[0] <= 0):
            np.fill_diagonal(covariance, increment)
            if increment > self.MAX_INCREMENT:
                warnings.warn(
                    "Was not able to fix numerical imprecision in covariance "
                    "matrix. Failing silently. All likelihoods will be "
                    "reported as 0."
                )
                self.prepare_failed = True
                return None
            increment += increment
        self.prepare_failed = False
        return covariance

    def evaluate(self, points: np.ndarray) -> np.ndarray:
        """Densities at ``points`` of shape ``(d, m)``; zeros if preparation
        failed. Blocked whitened-distance evaluation, float64."""
        points = np.atleast_2d(np.asarray(points, dtype=np.float64))
        if self.prepare_failed:
            return np.zeros(points.shape[1])
        if points.shape[0] != self.d:
            raise ValueError(
                f"points have dimension {points.shape[0]}, dataset has {self.d}"
            )
        # Whiten with the cholesky of cov (not 2*pi*cov): solve L w = x.
        chol = self.cho_cov / np.sqrt(2 * np.pi)
        if _use_device_backend():
            log_norm = np.float32(-0.5 * self.log_det - np.log(self.n))
            densities = _device_eval_fn()(
                chol.astype(np.float32),
                self.dataset.astype(np.float32),
                points.astype(np.float32),
                log_norm,
            )
            return np.asarray(densities, dtype=np.float64)
        white_data = scipy.linalg.solve_triangular(chol, self.dataset, lower=True)
        white_points = scipy.linalg.solve_triangular(chol, points, lower=True)
        m = points.shape[1]
        out = np.empty(m)
        norm = np.exp(-0.5 * self.log_det) / self.n
        d2_data = np.sum(white_data**2, axis=0)
        block = max(1, int(2**22 // max(1, self.n)))
        for start in range(0, m, block):
            wp = white_points[:, start : start + block]
            # squared whitened distances: |x|^2 + |y|^2 - 2 x.y
            d2 = (
                d2_data[None, :]
                + np.sum(wp**2, axis=0)[:, None]
                - 2.0 * (wp.T @ white_data)
            )
            np.maximum(d2, 0.0, out=d2)
            out[start : start + block] = np.exp(-0.5 * d2).sum(axis=1) * norm
        return out

    __call__ = evaluate

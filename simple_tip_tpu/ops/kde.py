"""Numerically-stabilized Gaussian kernel density estimation (host, float64).

Self-contained reimplementation of the math of scipy's ``gaussian_kde`` with the
reference's stabilization semantics (reference: src/core/stable_kde.py:26-101):

- Scott bandwidth factor ``n**(-1/(d+4))``.
- While the scaled covariance has a non-positive eigenvalue, the data
  covariance's *diagonal is replaced* by a doubling increment (1e-10, 2e-10,
  ...); past ``MAX_INCREMENT=1e-5`` preparation fails silently and all
  densities evaluate to 0. (The diagonal *replacement* — not addition — is a
  quirk of the reference, preserved for behavioral parity.)
- Cholesky of ``2*pi*covariance``; a failure surfaces the 1-based index of the
  offending leading minor so LSA can drop that feature and retry
  (reference: src/core/surprise.py:454-473).

float64 throughout: TPUs have no native f64, and KDE fitting is a tiny
(d<=300) host-side computation; only the *evaluation* over many test points is
bulk work, implemented as a blocked float64 numpy quadform (still host — parity
with scipy's float64 results matters more than device speed here, and APFD
depends on score ordering which f32 exp underflow would distort).
"""

import warnings
from typing import Optional

import numpy as np
import scipy.linalg


class KDESingularError(np.linalg.LinAlgError):
    """Cholesky failure carrying the 0-based index of the offending feature
    (None if unknown)."""

    def __init__(self, message: str, problematic_dim: Optional[int]):
        super().__init__(message)
        self.problematic_dim = problematic_dim


class StableGaussianKDE:
    """Gaussian KDE over a ``(d, n)`` float dataset with covariance
    stabilization; mirrors scipy's gaussian_kde evaluation semantics."""

    MAX_INCREMENT = 1e-5

    def __init__(self, dataset: np.ndarray):
        self.dataset = np.atleast_2d(np.asarray(dataset, dtype=np.float64))
        self.d, self.n = self.dataset.shape
        self.factor = self.scotts_factor()
        self.prepare_failed = False
        self._compute_covariance()

    def scotts_factor(self) -> float:
        """Scott's rule bandwidth factor."""
        return np.power(self.n, -1.0 / (self.d + 4))

    def _compute_covariance(self):
        data_covariance = np.atleast_2d(np.cov(self.dataset, rowvar=1, bias=False))
        data_covariance = self._stabilize_covariance(data_covariance)
        if self.prepare_failed:
            return
        try:
            data_inv_cov = np.linalg.inv(data_covariance)
        except np.linalg.LinAlgError:
            self.prepare_failed = True
            return

        self.covariance = data_covariance * self.factor**2
        self.inv_cov = data_inv_cov / self.factor**2
        # Cholesky of 2*pi*cov: raises with the offending leading-minor index
        # (consumed by LSA's recursive feature drop).
        try:
            chol = scipy.linalg.cholesky(self.covariance * 2 * np.pi, lower=True)
        except scipy.linalg.LinAlgError as e:
            dim = None
            msg = str(e)
            if "leading minor" in msg:
                try:
                    dim = int(msg.split("-th")[0].strip().lstrip("(")) - 1
                except ValueError:
                    dim = None
            raise KDESingularError(msg, dim) from e
        self.cho_cov = chol
        self.log_det = 2 * np.log(np.diag(chol)).sum()

    def _stabilize_covariance(self, covariance: np.ndarray):
        """Replace the diagonal with a doubling increment until the scaled
        covariance is numerically positive definite, or fail silently."""
        if not np.isfinite(covariance).all():
            # e.g. a single-sample dataset: np.cov's n-1 divisor yields
            # NaN/inf, which would sail through the eigenvalue loop (NaN
            # comparisons are False) and explode in cholesky's finiteness
            # check. Same silent degraded mode as an unstabilizable matrix.
            warnings.warn(
                "Covariance matrix is not finite (too few samples?). "
                "Failing silently. All likelihoods will be reported as 0."
            )
            self.prepare_failed = True
            return None
        increment = 1e-10
        while np.any(np.linalg.eigh(covariance * self.factor**2)[0] <= 0):
            np.fill_diagonal(covariance, increment)
            if increment > self.MAX_INCREMENT:
                warnings.warn(
                    "Was not able to fix numerical imprecision in covariance "
                    "matrix. Failing silently. All likelihoods will be "
                    "reported as 0."
                )
                self.prepare_failed = True
                return None
            increment += increment
        self.prepare_failed = False
        return covariance

    def evaluate(self, points: np.ndarray) -> np.ndarray:
        """Densities at ``points`` of shape ``(d, m)``; zeros if preparation
        failed. Blocked whitened-distance evaluation, float64."""
        points = np.atleast_2d(np.asarray(points, dtype=np.float64))
        if self.prepare_failed:
            return np.zeros(points.shape[1])
        if points.shape[0] != self.d:
            raise ValueError(
                f"points have dimension {points.shape[0]}, dataset has {self.d}"
            )
        # Whiten with the cholesky of cov (not 2*pi*cov): solve L w = x.
        chol = self.cho_cov / np.sqrt(2 * np.pi)
        white_data = scipy.linalg.solve_triangular(chol, self.dataset, lower=True)
        white_points = scipy.linalg.solve_triangular(chol, points, lower=True)
        m = points.shape[1]
        out = np.empty(m)
        norm = np.exp(-0.5 * self.log_det) / self.n
        d2_data = np.sum(white_data**2, axis=0)
        block = max(1, int(2**22 // max(1, self.n)))
        for start in range(0, m, block):
            wp = white_points[:, start : start + block]
            # squared whitened distances: |x|^2 + |y|^2 - 2 x.y
            d2 = (
                d2_data[None, :]
                + np.sum(wp**2, axis=0)[:, None]
                - 2.0 * (wp.T @ white_data)
            )
            np.maximum(d2, 0.0, out=d2)
            out[start : start + block] = np.exp(-0.5 * d2).sum(axis=1) * norm
        return out

    __call__ = evaluate

"""Pallas TPU flash-attention kernel: the per-chip attention core.

Completes the long-context stack (SURVEY.md section 5 notes the reference has
none): across chips the sequence axis shards via ring or ulysses collectives
(parallel/ring_attention.py, parallel/ulysses_attention.py); within a chip
this kernel computes exact attention without ever materializing the
[seq_q, seq_kv] score matrix in HBM. K/V tiles stream through VMEM while
flash-style running (max, normalizer, output) accumulators live in VMEM
scratch; each tile contributes one MXU matmul for scores and one for the
weighted values.

Layout matches the other attention cores: q/k/v = [batch, seq, heads,
head_dim]. Sequence lengths are padded to the block size internally; padded
KEY positions are masked to -inf before the streaming softmax (padded query
rows compute garbage that is sliced off on return — they cannot contaminate
real rows).

The kernel runs on the TPU backend or anywhere under ``interpret=True``
(how the CPU test suite pins it against the dense oracle).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

try:  # pallas import is deferred-failure: CPU-only setups keep working
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    HAVE_PALLAS = True
except ImportError:  # pragma: no cover
    HAVE_PALLAS = False

# Per-tile row counts are adaptive: up to MAX_BLOCK (measured best on TPU v5e
# at long sequences: 43 TFLOP/s f32 at seq 32k vs 6.5 at block 128; 2048
# exceeds VMEM), rounded down to the actual padded sequence for short inputs
# so the 100-token parity models don't pay padded-row compute.
MAX_BLOCK = 1024
LANE = 128  # TPU lane granularity; block sizes are multiples of this

NEG_INF = -1e30  # large-finite: -inf breaks the m=-inf first-tile correction


def _block_for(t: int) -> int:
    """Tile size for a sequence of length ``t``: the smallest lane-multiple
    block that covers the lane-padded length in the minimum number of
    MAX_BLOCK-bounded tiles (avoids near-doubling the padding for lengths
    just above a block multiple, e.g. t=1100 -> block 640 x 2 tiles = 1280
    rows rather than 1024 x 2 = 2048)."""
    padded = -(-t // LANE) * LANE
    n_tiles = -(-padded // MAX_BLOCK)
    per_tile = -(-padded // n_tiles)
    return -(-per_tile // LANE) * LANE


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref, *, scale, kv_len, n_kv
):
    """One grid step: fold kv tile j into the streaming-softmax state."""
    j = pl.program_id(2)

    q = q_ref[0]  # [bq, dh]
    k = k_ref[0]  # [bk, dh]
    v = v_ref[0]  # [bk, dh]
    s = (
        jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        * scale
    )  # [bq, bk]
    # mask padded key positions
    col = j * k.shape[0] + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(col < kv_len, s, NEG_INF)

    @pl.when(j == 0)
    def _():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    m_prev = m_ref[:, 0]  # [bq]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
    alpha = jnp.exp(m_prev - m_new)  # [bq]
    p = jnp.exp(s - m_new[:, None])  # [bq, bk]
    l_new = l_ref[:, 0] * alpha + jnp.sum(p, axis=1)
    # p rides in the operand dtype (bf16 when the inputs are bf16 -> both
    # matmuls hit the MXU natively); accumulation stays f32 via preferred.
    acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
        p.astype(v.dtype), v, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[:] = m_new[:, None]
    l_ref[:] = l_new[:, None]

    @pl.when(j == n_kv - 1)
    def _():
        o_ref[0] = acc_ref[:] / l_ref[:]
        # logsumexp residual for the backward pass
        lse_ref[0, 0] = m_ref[:, 0] + jnp.log(l_ref[:, 0])


def _flash_fwd_call(q, k, v, kv_len: int, block_q: int, block_kv: int, interpret):
    """q [G, Tq, dh] x k/v [G, Tkv, dh] -> (out [G, Tq, dh], lse [G, 1, Tq]);
    T* are block multiples.

    The lse residual rides a singleton middle axis so its block's last two
    dims are (1, block_q) — legal under Mosaic's (8, 128) tiling rule, which
    a 2-D [G, Tq] layout with per-G blocks of 1 row is not."""
    g, t_q, dh = q.shape
    t_kv = k.shape[1]
    n_q, n_kv = t_q // block_q, t_kv // block_kv
    scale = np.float32(1.0 / np.sqrt(q.shape[-1]))
    kernel = functools.partial(
        _flash_kernel, scale=scale, kv_len=kv_len, n_kv=n_kv
    )
    # vma: inside shard_map (e.g. as ulysses' local core) outputs must
    # declare which mesh axes they vary over — inherit the query's.
    vma = getattr(jax.typeof(q), "vma", None)
    return pl.pallas_call(
        kernel,
        grid=(g, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, dh), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_kv, dh), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_kv, dh), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, block_q, dh), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, 1, block_q), lambda b, i, j: (b, 0, i), memory_space=pltpu.VMEM
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((g, t_q, dh), jnp.float32, vma=vma),
            jax.ShapeDtypeStruct((g, 1, t_q), jnp.float32, vma=vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),  # running max
            pltpu.VMEM((block_q, 1), jnp.float32),  # running normalizer
            pltpu.VMEM((block_q, dh), jnp.float32),  # running output
        ],
        interpret=interpret,
    )(q, k, v)


def _bwd_p_ds(q, k, v, do, lse, dvec, *, scale, kv_len, kv_tile):
    """Shared backward recompute for one (q block, kv block) pair:
    p = exp(s_masked - lse) and ds = p * (dO v^T - D). Both backward
    kernels derive their grads from exactly this pair."""
    s = (
        jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        * scale
    )
    col = kv_tile * k.shape[0] + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    s = jnp.where(col < kv_len, s, NEG_INF)
    p = jnp.exp(s - lse[:, None])  # [bq, bk]
    # do arrives pre-cast to the kv dtype (_flash_bwd_call), so this matmul
    # is MXU-native under bf16 like the forward's.
    dp = jax.lax.dot_general(
        do, v, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [bq, bk]
    return p, p * (dp - dvec[:, None])


def _flash_bwd_dq_kernel(
    k_ref, v_ref, q_ref, do_ref, lse_ref, dvec_ref, dq_ref, acc_ref,
    *, scale, kv_len, n_kv
):
    """dq for one q block: fold kv tile j into the accumulator.

    Standard flash backward: p = exp(s - lse); ds = p * (dO v^T - D);
    dq += ds k * scale, with D = rowsum(dO * O) precomputed on host/XLA."""
    j = pl.program_id(2)
    _, ds = _bwd_p_ds(
        q_ref[0], k_ref[0], v_ref[0], do_ref[0], lse_ref[0, 0], dvec_ref[0, 0],
        scale=scale, kv_len=kv_len, kv_tile=j,
    )
    k = k_ref[0]

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] = acc_ref[:] + scale * jax.lax.dot_general(
        ds.astype(k.dtype), k, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(j == n_kv - 1)
    def _():
        dq_ref[0] = acc_ref[:]


def _flash_bwd_dkv_kernel(
    k_ref, v_ref, q_ref, do_ref, lse_ref, dvec_ref, dk_ref, dv_ref,
    acc_dk_ref, acc_dv_ref, *, scale, kv_len, n_q
):
    """dk/dv for one kv block: fold q tile i into the accumulators."""
    i = pl.program_id(2)
    j = pl.program_id(1)
    q = q_ref[0]  # [bq, dh]
    do = do_ref[0]  # [bq, dh]
    p, ds = _bwd_p_ds(
        q, k_ref[0], v_ref[0], do, lse_ref[0, 0], dvec_ref[0, 0],
        scale=scale, kv_len=kv_len, kv_tile=j,
    )

    @pl.when(i == 0)
    def _():
        acc_dk_ref[:] = jnp.zeros_like(acc_dk_ref)
        acc_dv_ref[:] = jnp.zeros_like(acc_dv_ref)

    acc_dv_ref[:] = acc_dv_ref[:] + jax.lax.dot_general(
        p.astype(do.dtype), do, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [bk, dh]
    acc_dk_ref[:] = acc_dk_ref[:] + scale * jax.lax.dot_general(
        ds.astype(q.dtype), q, dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [bk, dh]

    @pl.when(i == n_q - 1)
    def _():
        dk_ref[0] = acc_dk_ref[:]
        dv_ref[0] = acc_dv_ref[:]


def _flash_bwd_call(q, k, v, out, lse, do, kv_len, block_q, block_kv, interpret):
    """(dq, dk, dv) via the two backward kernels."""
    g, t_q, dh = q.shape
    t_kv = k.shape[1]
    n_q, n_kv = t_q // block_q, t_kv // block_kv
    scale = np.float32(1.0 / np.sqrt(q.shape[-1]))
    # D in f32 (from the f32 out), then dO in the forward's compute dtype so
    # every backward matmul runs MXU-native when the forward did.
    dvec = jnp.sum(do * out, axis=-1)[:, None, :]  # [g, 1, t_q], like lse
    do = do.astype(q.dtype)
    vma = getattr(jax.typeof(q), "vma", None)

    q_spec = pl.BlockSpec(
        (1, block_q, dh), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM
    )
    kv_spec_dq = pl.BlockSpec(
        (1, block_kv, dh), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM
    )
    row_spec = pl.BlockSpec(
        (1, 1, block_q), lambda b, i, j: (b, 0, i), memory_space=pltpu.VMEM
    )
    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, scale=scale, kv_len=kv_len, n_kv=n_kv
        ),
        grid=(g, n_q, n_kv),
        in_specs=[kv_spec_dq, kv_spec_dq, q_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((g, t_q, dh), jnp.float32, vma=vma),
        scratch_shapes=[pltpu.VMEM((block_q, dh), jnp.float32)],
        interpret=interpret,
    )(k, v, q, do, lse, dvec)

    # grid (g, kv blocks, q blocks): q innermost so dk/dv accumulate per kv
    q_spec_kv = pl.BlockSpec(
        (1, block_q, dh), lambda b, j, i: (b, i, 0), memory_space=pltpu.VMEM
    )
    kv_spec_kv = pl.BlockSpec(
        (1, block_kv, dh), lambda b, j, i: (b, j, 0), memory_space=pltpu.VMEM
    )
    row_spec_kv = pl.BlockSpec(
        (1, 1, block_q), lambda b, j, i: (b, 0, i), memory_space=pltpu.VMEM
    )
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, scale=scale, kv_len=kv_len, n_q=n_q
        ),
        grid=(g, n_kv, n_q),
        in_specs=[kv_spec_kv, kv_spec_kv, q_spec_kv, q_spec_kv, row_spec_kv, row_spec_kv],
        out_specs=[kv_spec_kv, kv_spec_kv],
        out_shape=[
            jax.ShapeDtypeStruct((g, t_kv, dh), jnp.float32, vma=vma),
            jax.ShapeDtypeStruct((g, t_kv, dh), jnp.float32, vma=vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_kv, dh), jnp.float32),
            pltpu.VMEM((block_kv, dh), jnp.float32),
        ],
        interpret=interpret,
    )(k, v, q, do, lse, dvec)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_core(q, k, v, kv_len, block_q, block_kv, interpret):
    """Differentiable flash attention over folded padded [G, T, dh] arrays."""
    out, _ = _flash_fwd_call(q, k, v, kv_len, block_q, block_kv, interpret)
    return out


def _flash_core_fwd(q, k, v, kv_len, block_q, block_kv, interpret):
    out, lse = _flash_fwd_call(q, k, v, kv_len, block_q, block_kv, interpret)
    return out, (q, k, v, out, lse)


def _flash_core_bwd(kv_len, block_q, block_kv, interpret, res, do):
    q, k, v, out, lse = res
    return _flash_bwd_call(
        q, k, v, out, lse, do, kv_len, block_q, block_kv, interpret
    )


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(q, k, v, interpret: bool = False, compute_dtype=None):
    """Exact attention, [batch, seq, heads, head_dim] in and out.

    Same contract as ``ring_self_attention_reference`` (the dense oracle);
    score matrix is tiled through VMEM instead of materialized. Fully
    differentiable: a custom VJP runs the standard flash backward (dq and
    dk/dv as two more VMEM-tiled kernels over the saved logsumexp residual),
    so models can TRAIN with this core — gradients never materialize the
    [seq, seq] matrix either.

    ``compute_dtype=jnp.bfloat16`` feeds the kernels' matmuls bf16 operands
    (MXU-native, ~2x matmul throughput) while the streaming-softmax state,
    logsumexp residual and all accumulations stay f32 via
    ``preferred_element_type``; output returns in ``q.dtype``. Default
    ``None`` inherits the operands' dtype (bf16 in -> bf16 compute — this is
    how ulysses' local core picks the caller's precision up; anything other
    than bf16 computes in f32, matching the dense/ring cores' contract).
    """
    if not HAVE_PALLAS:
        raise RuntimeError(
            "jax.experimental.pallas is unavailable in this jax build; use "
            "the dense or ring attention cores instead"
        )
    if compute_dtype is not None:
        cdt = jnp.dtype(compute_dtype)
    elif q.dtype == jnp.bfloat16:
        cdt = jnp.dtype(jnp.bfloat16)
    else:
        cdt = jnp.dtype(jnp.float32)
    b, t_q, h, dh = q.shape
    t_kv = k.shape[1]
    block_q, block_kv = _block_for(t_q), _block_for(t_kv)

    def pad_to_block(x, block):
        t = x.shape[1]
        pad = (-t) % block
        if pad:
            x = jnp.pad(x, ((0, 0), (0, pad), (0, 0), (0, 0)))
        return x

    q_p = pad_to_block(q, block_q)
    k_p = pad_to_block(k, block_kv)
    v_p = pad_to_block(v, block_kv)
    # [b, T, h, dh] -> [b*h, T, dh]
    fold = lambda x: jnp.transpose(x, (0, 2, 1, 3)).reshape(
        b * h, x.shape[1], dh
    )
    out = _flash_core(
        fold(q_p).astype(cdt),
        fold(k_p).astype(cdt),
        fold(v_p).astype(cdt),
        t_kv,
        block_q,
        block_kv,
        interpret,
    )
    out = out.reshape(b, h, -1, dh).transpose(0, 2, 1, 3)[:, :t_q]
    return out.astype(q.dtype)


def flash_available() -> bool:
    """Whether the compiled (non-interpret) flash path applies here."""
    if not HAVE_PALLAS:
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False

"""Pure, framework-light metric kernels (the reusable library layer).

TPU-native counterpart of the reference's ``src/core/`` (see SURVEY.md section
2.1): every kernel is a pure function over arrays, usable from numpy on host or
jnp under jit/vmap on device.
"""

from simple_tip_tpu.ops.apfd import apfd_from_order, apfd_from_orders
from simple_tip_tpu.ops.timer import Timer

__all__ = ["apfd_from_order", "apfd_from_orders", "Timer"]

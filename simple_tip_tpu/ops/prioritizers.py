"""Test-input prioritizers: Coverage-Total Method (CTM) and
Coverage-Additional Method (CAM).

Behavioral contract matches the reference (reference: src/core/prioritizers.py):

- CTM: descending argsort of per-sample scores.
- CAM: greedy max-marginal-coverage over boolean profiles; once no sample adds
  new coverage, remaining samples follow in descending score order.

CAM is inherently sequential (each pick depends on the updated coverage state),
so it runs on host. The inner update is the hot loop; ``cam_order`` uses a
vectorized numpy formulation whose per-iteration cost is one masked matvec, and
a native C++ kernel (ops/native) is used when built, keeping the greedy loop out
of the Python interpreter for the large (20k x 100k-bit) profile matrices of
the real case studies.
"""

from typing import Generator

import numpy as np


def ctm(scores: np.ndarray) -> Generator[int, None, None]:
    """Yield sample indexes by descending score (Coverage-Total Method)."""
    scores = np.asarray(scores)
    assert len(scores.shape) == 1
    idxs = np.argsort(-scores)
    for x in idxs:
        yield x


def cam(scores: np.ndarray, profiles: np.ndarray) -> Generator[int, None, None]:
    """Yield sample indexes by greedy additional coverage (CAM), then by score.

    Semantics (reference: src/core/prioritizers.py:16-59): repeatedly pick the
    sample covering the most not-yet-covered sections (ties: lowest index, via
    argmax); stop when the best sample adds nothing new or everything is
    covered; remaining samples are yielded in descending original-score order.
    """
    order = cam_order(np.asarray(scores), np.asarray(profiles))
    for x in order:
        yield int(x)


def cam_order(scores: np.ndarray, profiles: np.ndarray) -> np.ndarray:
    """Full CAM order as an index array (vectorized host implementation)."""
    scores = np.asarray(scores).copy()
    profiles = np.asarray(profiles).reshape((profiles.shape[0], -1))

    native_order = _native_cam(scores, profiles)
    if native_order is not None:
        return native_order

    profiles = profiles.copy()
    num_coverable = profiles.sum(axis=1).astype(np.int64)
    remaining = int(profiles.shape[1])
    yielded = np.zeros(scores.shape[0], dtype=bool)
    picked = []
    while True:
        nxt = int(np.argmax(num_coverable))
        newly_covered = int(num_coverable[nxt])
        if newly_covered == 0:
            break
        picked.append(nxt)
        yielded[nxt] = True
        covering_columns = profiles[nxt].nonzero()[0]
        remaining -= newly_covered
        num_coverable -= profiles[:, covering_columns].sum(axis=1)
        profiles[:, covering_columns] = False
        if remaining == 0:
            break

    # Remaining samples by descending original score; already-picked samples
    # are pushed to the very end and cut off.
    min_score = scores.min() - 1
    scores[yielded] = min_score - 1
    rest = np.argsort(-scores)
    rest = rest[~ (scores[rest] < min_score)]
    order = np.concatenate([np.asarray(picked, dtype=np.int64), rest.astype(np.int64)])
    assert order.shape[0] == scores.shape[0]
    return order


def _native_cam(scores: np.ndarray, profiles: np.ndarray):
    """Run the C++ CAM kernel if the native extension is available, else None."""
    try:
        from simple_tip_tpu.ops.native import cam_native

        return cam_native(scores, profiles)
    except (ImportError, OSError):
        return None

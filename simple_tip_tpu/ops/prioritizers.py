"""Test-input prioritizers: Coverage-Total Method (CTM) and
Coverage-Additional Method (CAM).

Behavioral contract matches the reference (reference: src/core/prioritizers.py):

- CTM: descending argsort of per-sample scores.
- CAM: greedy max-marginal-coverage over boolean profiles; once no sample adds
  new coverage, remaining samples follow in descending score order.

CAM is inherently sequential (each pick depends on the updated coverage state),
so it runs on host. The inner update is the hot loop; ``cam_order`` uses a
vectorized numpy formulation whose per-iteration cost is one masked matvec, and
a native C++ kernel (ops/native) is used when built, keeping the greedy loop out
of the Python interpreter for the large (20k x 100k-bit) profile matrices of
the real case studies.
"""

from typing import Generator

import numpy as np


def ctm(scores: np.ndarray) -> Generator[int, None, None]:
    """Yield sample indexes by descending score (Coverage-Total Method)."""
    scores = np.asarray(scores)
    assert len(scores.shape) == 1
    idxs = np.argsort(-scores)
    for x in idxs:
        yield x


def cam(scores: np.ndarray, profiles: np.ndarray) -> Generator[int, None, None]:
    """Yield sample indexes by greedy additional coverage (CAM), then by score.

    Semantics (reference: src/core/prioritizers.py:16-59): repeatedly pick the
    sample covering the most not-yet-covered sections (ties: lowest index, via
    argmax); stop when the best sample adds nothing new or everything is
    covered; remaining samples are yielded in descending original-score order.
    """
    order = cam_order(np.asarray(scores), np.asarray(profiles))
    for x in order:
        yield int(x)


def cam_order(scores: np.ndarray, profiles: np.ndarray) -> np.ndarray:
    """Full CAM order as an index array (vectorized host implementation)."""
    scores = np.asarray(scores).copy()
    profiles = np.asarray(profiles).reshape((profiles.shape[0], -1))

    native_order = _native_cam(scores, profiles)
    if native_order is not None:
        return native_order

    profiles = profiles.copy()
    num_coverable = profiles.sum(axis=1).astype(np.int64)
    remaining = int(profiles.shape[1])
    picked = []
    while True:
        nxt = int(np.argmax(num_coverable))
        newly_covered = int(num_coverable[nxt])
        if newly_covered == 0:
            break
        picked.append(nxt)
        covering_columns = profiles[nxt].nonzero()[0]
        remaining -= newly_covered
        num_coverable -= profiles[:, covering_columns].sum(axis=1)
        profiles[:, covering_columns] = False
        if remaining == 0:
            break

    return _with_score_tail(scores, np.asarray(picked, dtype=np.int64))


def _with_score_tail(scores: np.ndarray, picked: np.ndarray) -> np.ndarray:
    """Append the non-picked samples in descending original-score order
    (shared by the host, native and device CAM paths).

    The argsort input uses the reference's sentinel trick (picked samples
    get min-1-1) so tie ordering matches it exactly, but the picked samples
    are then removed by an explicit index mask rather than the reference's
    ``< min_score`` comparison: with scores containing -inf (or magnitudes
    where ``min - 1 == min`` in float64) the sentinel is indistinguishable
    from a real score and the reference silently yields picked samples
    twice — the mask keeps the order well-formed on those inputs too."""
    scores = np.asarray(scores).copy()
    picked = np.asarray(picked, dtype=np.int64)
    scores[picked] = scores.min() - 2
    rest = np.argsort(-scores)
    is_picked = np.zeros(scores.shape[0], dtype=bool)
    is_picked[picked] = True
    rest = rest[~is_picked[rest]]
    order = np.concatenate([picked, rest.astype(np.int64)])
    assert order.shape[0] == scores.shape[0]
    return order


def _native_cam(scores: np.ndarray, profiles: np.ndarray):
    """Run the C++ CAM kernel if the native extension is available, else None."""
    try:
        from simple_tip_tpu.ops.native import cam_native

        return cam_native(scores, profiles)
    except (ImportError, OSError):
        return None


def device_cam_greedy(packed_profiles, num_samples: int):
    """Greedy CAM phase on device over bit-packed profiles.

    ``packed_profiles``: [n, words] uint32, bit j of word k = section 32*k+j.
    Returns ``(picked, count)``: an int32 [n] array whose first ``count``
    entries are the greedy picks in order (tie-break: lowest index, matching
    np.argmax), the rest -1.

    The loop is a ``lax.while_loop`` — each step recomputes every sample's
    marginal gain as one fused popcount/AND sweep (TPU vector units; no
    host round-trip per pick). Useful when profiles already live on device
    (the coverage engine computes them there): the greedy phase then runs
    where the data is, and only the small pick list crosses to host for the
    score tail of ``cam_order``.
    """
    import jax
    import jax.numpy as jnp

    p = jnp.asarray(packed_profiles, dtype=jnp.uint32)
    n = num_samples

    def cond(state):
        _, _, count, last_gain = state
        return jnp.logical_and(last_gain > 0, count < n)

    def body(state):
        covered, picked, count, _ = state
        # already-picked samples need no mask: once covered includes their
        # profile, their marginal gain is 0 forever, and a 0 max gain ends
        # the loop anyway
        gains = jnp.sum(
            jax.lax.population_count(p & ~covered[None, :]), axis=1
        ).astype(jnp.int32)
        nxt = jnp.argmax(gains).astype(jnp.int32)  # first max = lowest index
        gain = gains[nxt]
        do_pick = gain > 0
        covered = jnp.where(do_pick, covered | p[nxt], covered)
        picked = jnp.where(
            do_pick, picked.at[count].set(nxt), picked
        )
        count = jnp.where(do_pick, count + 1, count)
        return covered, picked, count, gain

    words = p.shape[1]
    init = (
        jnp.zeros((words,), jnp.uint32),
        jnp.full((n,), -1, jnp.int32),
        jnp.asarray(0, jnp.int32),
        jnp.asarray(1, jnp.int32),  # sentinel: enter the loop
    )
    covered, picked, count, _ = jax.lax.while_loop(cond, body, init)
    return picked, count


def pack_profiles(profiles: np.ndarray):
    """Bit-pack boolean [n, w] profiles into [n, ceil(w/32)] uint32 (bit j of
    word k = section 32*k+j, the layout device_cam_greedy expects)."""
    profiles = np.asarray(profiles, dtype=bool).reshape((profiles.shape[0], -1))
    n, w = profiles.shape
    pad = (-w) % 32
    if pad:
        profiles = np.concatenate(
            [profiles, np.zeros((n, pad), dtype=bool)], axis=1
        )
    bits = profiles.reshape(n, -1, 32).astype(np.uint32)
    shifts = np.arange(32, dtype=np.uint32)
    return (bits << shifts[None, None, :]).sum(axis=2, dtype=np.uint32)


def cam_order_device(scores: np.ndarray, profiles: np.ndarray) -> np.ndarray:
    """CAM order with the greedy phase on device (same result as cam_order).

    ``profiles`` may be boolean [n, w] (packed here) or already-packed uint32
    [n, words] — a device-resident packed array is passed through untouched
    (no host round-trip; only the small pick list crosses back).
    """
    if getattr(profiles, "dtype", None) == np.uint32:
        packed = profiles  # np or jnp; device arrays stay on device
    else:
        packed = pack_profiles(np.asarray(profiles))
    picked_dev, count_dev = device_cam_greedy(packed, packed.shape[0])
    count = int(count_dev)
    picked = np.asarray(picked_dev)[:count].astype(np.int64)
    return _with_score_tail(np.asarray(scores), picked)

"""TPU-native clustering/density primitives: KMeans (+ silhouette) and a
Gaussian mixture model.

The reference delegates these to sklearn on host CPU (reference:
src/core/surprise.py:102-133 KMeans+silhouette, surprise.py:498-520 GMM).
Here the iterative fits run as jitted XLA programs — assignment steps and
responsibilities are MXU matmuls — with sklearn-compatible APIs and defaults:

- ``KMeans(n_clusters, n_init=10, max_iter=300, tol=1e-4, random_state)``:
  k-means++ seeding per init, Lloyd iterations vmapped over all ``n_init``
  restarts simultaneously, best-inertia restart wins.
- ``silhouette_score``: mean silhouette over all samples (chunked pairwise
  distances).
- ``GaussianMixture(n_components, reg_covar=1e-6, max_iter=100, tol=1e-3,
  random_state)``: EM with full covariances, k-means-initialized
  responsibilities, ``score_samples`` = mixture log-likelihood.

Backend selection for the surprise-adequacy handlers is
``TIP_CLUSTER_BACKEND``: ``auto`` (default — sklearn's early-stopping C
implementations on CPU hosts, these jnp kernels on accelerator backends;
measured 91x on the paper-scale pc-mlsa fit, HOST_PHASE.json), or ``jax`` /
``sklearn`` to force one side. Unrecognized values raise.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _use_device_scoring() -> bool:
    """Whether fitted-estimator *scoring* (predict / score_samples) should
    run as jitted device dispatches rather than host numpy. Same backend
    switch as the fits (``resolved_cluster_backend``), imported at call
    time: ops/surprise must stay importable without jax."""
    from simple_tip_tpu.ops.surprise import resolved_cluster_backend

    return resolved_cluster_backend() == "jax"


@jax.jit
def _nearest_centroid(x, c):
    """Nearest-centroid labels on device (argmin of the expanded quadform)."""
    d2 = (
        jnp.sum(x * x, axis=1)[:, None]
        + jnp.sum(c * c, axis=1)[None, :]
        - 2.0 * (x @ c.T)
    )
    return jnp.argmin(d2, axis=1)


def _gmm_weighted_log_prob_impl(x, weights, means, cov):
    """Per-component weighted log-densities [n, k]; the scoring twin of
    ``_gmm_em``'s in-loop ``log_prob`` (same jitter as the host path's
    ``cov + eye*1e-12``; the weight floor is 1e-35 because the host's
    1e-300 underflows f32 to 0 and would turn the log into -inf)."""
    d = means.shape[1]
    chol = jnp.linalg.cholesky(cov + jnp.eye(d) * 1e-12)  # [k, d, d]
    diff = x[None, :, :] - means[:, None, :]  # [k, n, d]
    sol = jax.lax.linalg.triangular_solve(
        chol, jnp.swapaxes(diff, 1, 2), left_side=True, lower=True
    )  # [k, d, n]
    maha = jnp.sum(sol * sol, axis=1)  # [k, n]
    log_det = 2.0 * jnp.sum(jnp.log(jnp.diagonal(chol, axis1=1, axis2=2)), axis=1)
    log_gauss = -0.5 * (maha + d * jnp.log(2 * jnp.pi) + log_det[:, None])
    return log_gauss.T + jnp.log(jnp.maximum(weights, 1e-35))[None, :]


@jax.jit
def _gmm_score_samples_device(x, weights, means, cov):
    return jax.scipy.special.logsumexp(
        _gmm_weighted_log_prob_impl(x, weights, means, cov), axis=1
    )


@jax.jit
def _gmm_predict_device(x, weights, means, cov):
    return jnp.argmax(_gmm_weighted_log_prob_impl(x, weights, means, cov), axis=1)


def _kmeans_plus_plus(rng: np.random.RandomState, x: np.ndarray, k: int) -> np.ndarray:
    """Seeded k-means++ initial centroids (host, cheap)."""
    n = x.shape[0]
    centroids = [x[rng.randint(n)]]
    for _ in range(1, k):
        d2 = np.min(
            ((x[:, None, :] - np.asarray(centroids)[None, :, :]) ** 2).sum(-1), axis=1
        )
        probs = d2 / max(d2.sum(), 1e-12)
        centroids.append(x[rng.choice(n, p=probs)])
    return np.asarray(centroids, dtype=np.float32)


@functools.partial(jax.jit, static_argnames=("max_iter",))
def _lloyd(x, centroids, max_iter: int):
    """Lloyd iterations for one restart; returns (centroids, labels, inertia)."""
    x_sq = jnp.sum(x * x, axis=1)

    def assign(c):
        d2 = x_sq[:, None] + jnp.sum(c * c, axis=1)[None, :] - 2.0 * (x @ c.T)
        return jnp.argmin(d2, axis=1), jnp.maximum(jnp.min(d2, axis=1), 0.0)

    def body(_, c):
        labels, _ = assign(c)
        one_hot = jax.nn.one_hot(labels, c.shape[0], dtype=x.dtype)  # [n, k]
        counts = one_hot.sum(axis=0)  # [k]
        sums = one_hot.T @ x  # [k, d]
        new_c = sums / jnp.maximum(counts[:, None], 1.0)
        # keep old centroid for empty clusters
        return jnp.where(counts[:, None] > 0, new_c, c)

    centroids = jax.lax.fori_loop(0, max_iter, body, centroids)
    labels, d2 = assign(centroids)
    return centroids, labels, jnp.sum(d2)


class KMeans:
    """sklearn-compatible subset: fit / fit_predict / predict plus the
    fitted attributes ``cluster_centers_``, ``labels_``, ``inertia_``."""

    def __init__(
        self,
        n_clusters: int,
        n_init: int = 10,
        max_iter: int = 300,
        random_state: Optional[int] = 0,
    ):
        self.n_clusters = n_clusters
        self.n_init = n_init
        self.max_iter = max_iter
        self.random_state = random_state
        self.cluster_centers_: Optional[np.ndarray] = None
        self.labels_: Optional[np.ndarray] = None
        self.inertia_: Optional[float] = None

    def fit_predict(self, x: np.ndarray) -> np.ndarray:
        """Fit on x (best of n_init k-means++ restarts) and return labels."""
        x = np.asarray(x, dtype=np.float32)
        rng = np.random.RandomState(self.random_state)
        inits = np.stack(
            [_kmeans_plus_plus(rng, x, self.n_clusters) for _ in range(self.n_init)]
        )
        x_j = jnp.asarray(x)
        centroids, labels, inertia = jax.vmap(
            lambda c: _lloyd(x_j, c, max_iter=self.max_iter)
        )(jnp.asarray(inits))
        best = int(jnp.argmin(inertia))
        self.cluster_centers_ = np.asarray(centroids[best])
        self.labels_ = np.asarray(labels[best])
        self.inertia_ = float(inertia[best])
        return self.labels_

    def fit(self, x: np.ndarray) -> "KMeans":
        """Fit the estimator (sklearn-compatible); returns self."""
        self.fit_predict(x)
        return self

    def predict(self, x: np.ndarray) -> np.ndarray:
        """Nearest-centroid labels (one device dispatch + one transfer when
        the resolved backend is jax; host numpy is the reference path)."""
        assert self.cluster_centers_ is not None, "KMeans is not fitted"
        x = np.asarray(x, dtype=np.float32)
        c = self.cluster_centers_
        if _use_device_scoring():
            labels = _nearest_centroid(jnp.asarray(x), jnp.asarray(c))
            return np.asarray(labels)
        d2 = (
            (x * x).sum(1)[:, None]
            + (c * c).sum(1)[None, :]
            - 2.0 * (x @ c.T)
        )
        return np.argmin(d2, axis=1)


def silhouette_score(x: np.ndarray, labels: np.ndarray, chunk: int = 2048) -> float:
    """Mean silhouette coefficient over all samples (chunked distances)."""
    return silhouette_scores_multi(x, [labels], chunk=chunk)[0]


def silhouette_scores_multi(
    x: np.ndarray, labelings: "list[np.ndarray]", chunk: int = 2048
) -> "list[float]":
    """Mean silhouette for SEVERAL labelings of the same data in ONE
    distance pass.

    The O(n²·d) pairwise-distance work — the entirety of the cost at SA
    shapes (measured: 97 s of a 133 s pc-mmdsa fit at 18k×1600 on this
    host, ~24 s per candidate k under sklearn) — does not depend on the
    labels. The k-selection loop of the reference's silhouette-scored
    KMeans discriminator (/root/reference/src/core/surprise.py:102-133)
    therefore pays it once here, not once per candidate k: each chunk's
    distance block contracts against the horizontally-stacked one-hot
    matrices of ALL labelings in a single additional GEMM. f32 matmuls
    (MXU-native on device, sgemm on the cpu-pinned path); sklearn-parity
    within f32 tolerance is pinned by tests/test_cluster.py.
    """
    x = jnp.asarray(np.asarray(x, dtype=np.float32))
    n = x.shape[0]
    labs, counts, offsets, onehots = [], [], [], []
    off = 0
    for labels in labelings:
        labels_np = np.asarray(labels)
        uniq = np.unique(labels_np)
        k = len(uniq)
        assert k >= 2, "silhouette requires >= 2 clusters"
        remap = {int(l): i for i, l in enumerate(uniq)}
        lab = np.array([remap[int(l)] for l in labels_np])
        labs.append(lab)
        counts.append(np.bincount(lab, minlength=k).astype(np.float32))
        onehots.append(np.eye(k, dtype=np.float32)[lab])
        offsets.append((off, off + k))
        off += k
    big_onehot = jnp.asarray(np.concatenate(onehots, axis=1))  # [n, sum_k]
    x_sq = jnp.sum(x * x, axis=1)

    @jax.jit
    def chunk_cluster_sums(xc, xc_sq):
        d2 = xc_sq[:, None] + x_sq[None, :] - 2.0 * (xc @ x.T)
        d = jnp.sqrt(jnp.maximum(d2, 0.0))
        return d @ big_onehot  # [chunk, sum_k] distance sums per cluster

    sils: "list[list[np.ndarray]]" = [[] for _ in labelings]
    for start in range(0, n, chunk):
        xc = x[start : start + chunk]
        sums_all = np.asarray(chunk_cluster_sums(xc, x_sq[start : start + chunk]))
        for i, (lo, hi) in enumerate(offsets):
            sums = sums_all[:, lo:hi]
            lc = labs[i][start : start + chunk]
            own = counts[i][lc]
            # a: mean intra-cluster distance excluding self
            a = sums[np.arange(len(lc)), lc] / np.maximum(own - 1, 1)
            means = sums / np.maximum(counts[i][None, :], 1)
            means[np.arange(len(lc)), lc] = np.inf
            b = means.min(axis=1)
            s = (b - a) / np.maximum(a, b)
            s[own == 1] = 0.0  # sklearn: singleton clusters get 0
            sils[i].append(s)
    return [float(np.concatenate(parts).mean()) for parts in sils]


@functools.partial(jax.jit, static_argnames=("max_iter",))
def _gmm_em(x, resp, reg_covar, max_iter: int):
    """EM iterations from initial responsibilities; returns params + lls."""
    n, d = x.shape

    def m_step(resp):
        nk = resp.sum(axis=0) + 1e-10  # [k]
        means = (resp.T @ x) / nk[:, None]  # [k, d]
        diff = x[None, :, :] - means[:, None, :]  # [k, n, d]
        cov = jnp.einsum("kn,knd,kne->kde", resp.T, diff, diff) / nk[:, None, None]
        cov = cov + jnp.eye(d) * reg_covar
        weights = nk / n
        return weights, means, cov

    def log_prob(x, weights, means, cov):
        chol = jnp.linalg.cholesky(cov)  # [k, d, d]
        diff = x[None, :, :] - means[:, None, :]  # [k, n, d]
        sol = jax.lax.linalg.triangular_solve(
            chol, jnp.swapaxes(diff, 1, 2), left_side=True, lower=True
        )  # [k, d, n]
        maha = jnp.sum(sol * sol, axis=1)  # [k, n]
        log_det = 2.0 * jnp.sum(
            jnp.log(jnp.diagonal(chol, axis1=1, axis2=2)), axis=1
        )  # [k]
        log_gauss = -0.5 * (maha + d * jnp.log(2 * jnp.pi) + log_det[:, None])
        return log_gauss.T + jnp.log(weights)[None, :]  # [n, k]

    def body(carry, _):
        resp, _ = carry
        weights, means, cov = m_step(resp)
        weighted = log_prob(x, weights, means, cov)
        log_norm = jax.scipy.special.logsumexp(weighted, axis=1, keepdims=True)
        new_resp = jnp.exp(weighted - log_norm)
        return (new_resp, jnp.mean(log_norm)), None

    (resp, ll), _ = jax.lax.scan(body, (resp, jnp.float32(0.0)), None, length=max_iter)
    weights, means, cov = m_step(resp)
    return weights, means, cov, ll


class GaussianMixture:
    """sklearn-compatible subset: fit / predict / score_samples.

    Unlike sklearn's default (one EM run from one k-means init), ``fit``
    runs ``n_init`` EM restarts from diversified k-means inits as ONE
    vmapped XLA program and keeps the best final log-likelihood — restarts
    are nearly free on TPU, and a single unlucky init is the dominant
    failure mode of EM (observed: one seed landing 0.9 nats/sample below a
    restarted fit on anisotropic data)."""

    def __init__(
        self,
        n_components: int,
        reg_covar: float = 1e-6,
        max_iter: int = 100,
        random_state: Optional[int] = 0,
        n_init: int = 3,
    ):
        self.n_components = n_components
        self.reg_covar = reg_covar
        self.max_iter = max_iter
        self.random_state = random_state
        self.n_init = n_init
        self.weights_ = None
        self.means_ = None
        self.covariances_ = None

    def fit(self, x: np.ndarray) -> "GaussianMixture":
        """Fit by vmapped EM restarts from k-means-initialized
        responsibilities, keeping the best final log-likelihood."""
        x = np.asarray(x, dtype=np.float32)
        # random_state=None keeps sklearn's nondeterministic semantics
        base = (
            int(np.random.RandomState().randint(2**31 - self.n_init))
            if self.random_state is None
            else self.random_state
        )
        resps = []
        for s in range(self.n_init):
            # n_init=1 per restart: best-of-10 k-means would converge every
            # restart to the same labeling, de-diversifying the EM restarts
            km = KMeans(self.n_components, n_init=1, random_state=base + s)
            labels = km.fit_predict(x)
            resps.append(np.eye(self.n_components, dtype=np.float32)[labels])
        x_j = jnp.asarray(x)
        weights, means, cov, lls = jax.vmap(
            lambda r: _gmm_em(x_j, r, self.reg_covar, self.max_iter)
        )(jnp.asarray(np.stack(resps)))
        best = int(jnp.argmax(lls))
        self.weights_ = np.asarray(weights[best])
        self.means_ = np.asarray(means[best])
        self.covariances_ = np.asarray(cov[best])
        self._validate_fit()
        return self

    def _validate_fit(self) -> None:
        """Surface ill-defined components AT FIT TIME, like sklearn.

        sklearn's fit raises when the precision cholesky of any component
        fails (`GaussianMixture` docs: "increase reg_covar"); the jnp EM's
        fixed-iteration scan never raises — a near-singular component used
        to blow up only later, in ``score_samples``' cholesky, so callers
        running an escalation ladder (ops/surprise.py MLSA, matching
        /root/reference/src/core/surprise.py:498-520's fixed-default fit)
        saw the two backends fail at DIFFERENT points (round-4 verdict,
        weak #7). Criteria, aligned with sklearn's: any non-finite fit
        parameter (a mid-EM cholesky NaN is sticky through the scan and
        lands here), or a final covariance whose float64 cholesky fails
        with no jitter added.
        """
        finite = (
            np.all(np.isfinite(self.weights_))
            and np.all(np.isfinite(self.means_))
            and np.all(np.isfinite(self.covariances_))
        )
        if finite:
            try:
                np.linalg.cholesky(self.covariances_.astype(np.float64))  # tiplint: disable=f64-on-tpu (host sklearn-parity PSD probe)
            except np.linalg.LinAlgError:
                finite = False
        if not finite:
            raise ValueError(
                "Fitting the mixture model failed because some components "
                "have ill-defined empirical covariance (for instance caused "
                "by singleton or collapsed samples). Try to decrease the "
                "number of components, or increase reg_covar."
            )

    def _weighted_log_prob(self, x: np.ndarray) -> np.ndarray:
        import scipy.linalg

        x = np.asarray(x, dtype=np.float64)  # tiplint: disable=f64-on-tpu (host GMM scoring; sklearn numeric parity)
        n, d = x.shape
        out = np.empty((n, self.n_components))
        for k in range(self.n_components):
            cov = self.covariances_[k].astype(np.float64)  # tiplint: disable=f64-on-tpu (host cholesky: the numerically delicate step stays f64)
            chol = np.linalg.cholesky(cov + np.eye(d) * 1e-12)
            diff = (x - self.means_[k]).T  # [d, n]
            sol = scipy.linalg.solve_triangular(chol, diff, lower=True)
            maha = np.sum(sol * sol, axis=0)
            log_det = 2.0 * np.sum(np.log(np.diag(chol)))
            out[:, k] = -0.5 * (maha + d * np.log(2 * np.pi) + log_det) + np.log(
                max(self.weights_[k], 1e-300)
            )
        return out

    def _device_params(self, x: np.ndarray):
        return (
            jnp.asarray(np.asarray(x, dtype=np.float32)),
            jnp.asarray(self.weights_),
            jnp.asarray(self.means_),
            jnp.asarray(self.covariances_),
        )

    def score_samples(self, x: np.ndarray) -> np.ndarray:
        """Log-likelihood of each sample under the mixture (one jitted
        dispatch + one transfer on the jax backend; float64 host scipy is
        the reference path, parity pinned by tests/test_device_scoring.py)."""
        if _use_device_scoring():
            scores = _gmm_score_samples_device(*self._device_params(x))
            return np.asarray(scores, dtype=np.float64)  # tiplint: disable=f64-on-tpu (terminal host transfer; dtype parity with the scipy path)
        from scipy.special import logsumexp

        return logsumexp(self._weighted_log_prob(x), axis=1)

    def predict(self, x: np.ndarray) -> np.ndarray:
        """Most likely component per sample."""
        if _use_device_scoring():
            labels = _gmm_predict_device(*self._device_params(x))
            return np.asarray(labels)
        return np.argmax(self._weighted_log_prob(x), axis=1)

"""Surprise adequacy (SA) family: DSA, LSA, MDSA, MLSA and multimodal wrappers.

Behavioral contract matches the reference (reference: src/core/surprise.py):

- ``DSA``: ratio of (distance to nearest same-class train AT) over (distance
  from that nearest AT to the nearest other-class train AT). TPU-native: the
  reference's thread-pooled per-class badge loop (surprise.py:576-611) becomes
  chunked masked distance matrices on device — two MXU matmuls per chunk.
- ``LSA``: -log KDE density with variance-based feature pruning to
  ``max_features`` and recursive dropping of numerically-unstable features.
  Host float64 (see ops/kde.py).
- ``MDSA``: squared Mahalanobis distance under the empirical covariance.
- ``MLSA``: negative GMM log-likelihood.
- ``MultiModalSA``: discriminator (by predicted class, or silhouette-scored
  KMeans) routing samples to per-modal SA instances.
- ``SurpriseCoverageMapper``: SA values -> boolean bucket profiles for CAM.

Seeding: the reference leaves GMM/KMeans fits unseeded (a reproducibility
quirk); here every stochastic fit takes an explicit ``seed`` defaulting to 0.
"""

import abc
import logging
import math
import os
import warnings
from typing import Callable, Dict, Iterable, List, Optional, Tuple, Union

import numpy as np

from simple_tip_tpu.ops.kde import KDESingularError, StableGaussianKDE

Activations = Union[List[np.ndarray], np.ndarray]
Predictions = Union[List[Union[int, float]], np.ndarray]
Discriminator = Callable[[Activations, Predictions], np.ndarray]

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Helpers
# ---------------------------------------------------------------------------


def resolved_cluster_backend() -> str:
    """The concrete backend (``"sklearn"`` or ``"jax"``) that
    ``TIP_CLUSTER_BACKEND`` resolves to on this host right now.

    Exposed so callers that must pin the choice across process boundaries
    (the SA fit pool, engine/sa_prep.py — a spawned worker re-resolving
    ``auto`` would import jax itself) and cache fingerprints (the fitted
    estimators differ per backend) can record it explicitly.
    """
    choice = os.environ.get("TIP_CLUSTER_BACKEND", "auto").strip().lower()
    if choice not in ("auto", "jax", "sklearn"):
        raise ValueError(
            f"TIP_CLUSTER_BACKEND={choice!r} not recognized (auto, jax, sklearn)"
        )
    if choice == "auto":
        if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
            choice = "sklearn"
        else:
            import jax

            # By the time SA runs, the engine's forward passes have long
            # initialized the backend, so this does not first-touch a
            # potentially dead tunnel.
            choice = "sklearn" if jax.default_backend() == "cpu" else "jax"
    return choice


def _cluster_backend():
    """(KMeans, silhouette_score, GaussianMixture) from the configured backend.

    ``TIP_CLUSTER_BACKEND``: ``auto`` (default) picks sklearn's C
    implementations on CPU hosts and the TPU-native jnp ones
    (ops/cluster.py) when an accelerator backend is active; ``jax`` /
    ``sklearn`` force one side. Rationale (measured, HOST_PHASE.json): the
    jnp GMM's fixed-length vmapped EM restarts are built for the MXU —
    on one CPU core they cost ~110 min of a 121-min paper-scale prio phase,
    where sklearn's early-stopping C EM (what the reference itself runs,
    reference: src/core/surprise.py:509) fits in minutes. Same policy as
    the AL retrain path (device: vmapped ensemble; host: sequential).

    Known exception to the "sklearn on CPU hosts" contract: with ``auto``,
    the KMeans k-selection silhouette in ``_KmeansDiscriminator`` does NOT
    use the sklearn function returned here — it uses the jnp f32
    shared-distance pass (``ops/cluster.silhouette_scores_multi``), which
    pays the label-independent O(n²·d) pairwise work once for all candidate
    k instead of once per k. Only an EXPLICIT ``TIP_CLUSTER_BACKEND=sklearn``
    gets sklearn's own f64 per-k silhouette — the "force one side" contract
    outranks the speedup. Selection parity (same argmax, ties to the
    smaller k) is pinned by tests/test_cluster.py.
    """
    choice = resolved_cluster_backend()
    if choice == "sklearn":
        from sklearn.cluster import KMeans
        from sklearn.metrics import silhouette_score
        from sklearn.mixture import GaussianMixture

        return KMeans, silhouette_score, GaussianMixture
    from simple_tip_tpu.ops.cluster import GaussianMixture, KMeans, silhouette_score

    return KMeans, silhouette_score, GaussianMixture


def _subsample_array(subsampling, array: np.ndarray, seed: int) -> np.ndarray:
    """Subsample a single array (int = count, float in (0,1) = share)."""
    return _subsample_arrays(subsampling, (array,), seed=seed)[0]


def _resolve_subsample_count(subsampling, population: int) -> Optional[int]:
    """How many samples a ``subsampling`` spec keeps out of ``population``
    (None: keep everything). Spec semantics follow the reference's API
    (src/core/surprise.py:62-87): a float in (0, 1) is a share, a positive
    int an absolute cap."""
    if subsampling is None or subsampling == 1.0:
        return None
    if isinstance(subsampling, int) and subsampling > 0:
        return min(subsampling, population)
    if 0 < subsampling < 1:
        return int(subsampling * population)
    raise ValueError(
        "subsampling must be a float between 0 and 1 (share of training "
        "data), or a positive int declaring the number of samples"
    )


def _subsample_arrays(subsampling, arrays: Tuple[np.ndarray, ...], seed: int):
    """Apply one shared seeded index draw to every array in ``arrays``."""
    population = arrays[0].shape[0]
    mismatched = [a.shape[0] for a in arrays if a.shape[0] != population]
    assert not mismatched, "All arrays must have the same number of samples"
    keep = _resolve_subsample_count(subsampling, population)
    if keep is None:
        return arrays
    chosen = np.random.RandomState(seed).choice(population, keep, replace=False)
    return tuple(a[chosen] for a in arrays)


def _class_predictions(predictions: Predictions, num_classes: int = None) -> np.ndarray:
    """Validate and convert class predictions to a 1-D int array.

    The message fragments ("must be one-dimensional", "Predictions must be
    integers", ">= 0", "< num_classes") are API contract, pinned by
    tests/test_surprise.py."""
    predictions = np.asarray(predictions)
    assert predictions.ndim == 1, (
        "Class predictions must be one-dimensional. "
        "If your predictions are one_hot encoded, use "
        "eg `np.argmax(softmax_outputs, axis=1)`"
    )
    if not np.issubdtype(predictions.dtype, np.integer):
        truncated = predictions.astype(np.int64)
        drift = np.abs(predictions - truncated)
        # same band as np.testing.assert_almost_equal(decimal=5)
        assert float(drift.max(initial=0.0)) < 1.5 * 10.0**-5, (
            "Predictions must be integers"
        )
        predictions = truncated
    assert predictions.size == 0 or int(predictions.min()) >= 0, (
        "Class predictions must be >= 0"
    )
    assert (
        num_classes is None
        or predictions.size == 0
        or int(predictions.max()) < num_classes
    ), "Class predictions must be < num_classes"
    return predictions


def _flatten_layers(layers: Activations) -> np.ndarray:
    """Flatten per-layer activations (or a high-rank array) to (samples, neurons)."""
    if hasattr(layers, "ndim"):
        arr = np.asarray(layers)
        if arr.ndim == 2:
            return arr
        return arr.reshape((arr.shape[0], -1))
    flat = [np.reshape(np.asarray(layer), (layer.shape[0], -1)) for layer in layers]
    return np.concatenate(flat, axis=1)


def _flatten_predictions(predictions: Predictions) -> Optional[np.ndarray]:
    if predictions is None:
        return None
    return predictions if isinstance(predictions, np.ndarray) else np.array(predictions)


def _by_class_discriminator(
    activations: Activations, predictions: Predictions
) -> np.ndarray:
    """Discriminator assigning each sample to its predicted class."""
    return _class_predictions(predictions)


def _fit_candidate_kmeans(task):
    """Fit ONE candidate-k KMeans (runs in a fit-pool worker or inline).

    ``task`` = (k, n_init, max_iter, seed, training_data); returns
    (k, fitted KMeans, labels). Top-level so spawn can pickle it; the
    worker re-resolves the cluster backend from its (parent-pinned) env.
    """
    k, n_init, max_iter, seed, training_data = task
    KMeans, _, _ = _cluster_backend()
    kmeans = KMeans(n_clusters=k, n_init=n_init, max_iter=max_iter, random_state=seed)
    return k, kmeans, kmeans.fit_predict(training_data)


class _KmeansDiscriminator:
    """Silhouette-scored KMeans over candidate k values
    (reference: src/core/surprise.py:102-133).

    ``fit_map`` optionally fans the independent candidate-k fits over a
    caller-supplied order-preserving parallel map (the SA fit pool,
    engine/sa_prep.py); ``None`` keeps the serial in-process loop. Either
    way each fit is seeded, so the selected clusterer is identical.
    """

    def __init__(
        self,
        training_data: Activations,
        potential_k: Iterable[int],
        subsampling=1.0,
        subsampling_seed: int = 0,
        n_init: int = 10,
        max_iter: int = 300,
        seed: Optional[int] = 0,
        fit_map=None,
    ):
        _, backend_silhouette, _ = _cluster_backend()
        from simple_tip_tpu.ops.cluster import silhouette_scores_multi

        training_data = _flatten_layers(training_data)
        training_data = _subsample_array(
            subsampling, training_data, seed=subsampling_seed
        )
        # Fit every candidate k first, THEN score all labelings in one
        # shared-distance silhouette pass: the O(n²·d) pairwise work does
        # not depend on labels, so the reference's per-k silhouette loop
        # (src/core/surprise.py:102-133) pays it |potential_k| times for
        # nothing. Selection semantics are unchanged (same argmax, ties to
        # the smaller k); f32-silhouette parity vs sklearn is pinned by
        # tests/test_cluster.py. An EXPLICIT TIP_CLUSTER_BACKEND=sklearn
        # keeps sklearn's own f64 silhouette per k — the "force one side"
        # contract (_cluster_backend docstring) outranks the speedup.
        tasks = [
            (i, n_init, max_iter, seed, training_data) for i in potential_k
        ]
        if fit_map is None:
            fitted = [_fit_candidate_kmeans(t) for t in tasks]
        else:
            fitted = list(fit_map(_fit_candidate_kmeans, tasks))
        forced = os.environ.get("TIP_CLUSTER_BACKEND", "auto").strip().lower()
        if forced == "sklearn":
            scores = [
                backend_silhouette(training_data, labels)
                for _, _, labels in fitted
            ]
        else:
            scores = silhouette_scores_multi(
                training_data, [labels for _, _, labels in fitted]
            )
        self.best_score = -np.inf
        self.best_k = None
        self.best_clusterer = None
        for (i, kmeans, _), silhouette_avg in zip(fitted, scores):
            if silhouette_avg > self.best_score:
                self.best_score = silhouette_avg
                self.best_k = i
                self.best_clusterer = kmeans

    def __call__(
        self, activations: Activations, predictions: Predictions
    ) -> np.ndarray:
        return self.best_clusterer.predict(_flatten_layers(activations))


class SurpriseCoverageMapper:
    """SA values -> boolean bucket profiles (reference: src/core/surprise.py:186-209)."""

    def __init__(self, sections: int, upper_bound: float, overflow_bucket: bool = False):
        self.sections = sections
        self.upper_bound = upper_bound
        linspace_sections = sections if overflow_bucket else sections + 1
        self.thresholds = np.linspace(
            # tiplint: disable=f64-on-tpu (host bucketing; threshold parity with the reference's numpy)
            start=0, stop=upper_bound, num=linspace_sections, dtype=np.float64
        )
        if overflow_bucket:
            self.thresholds = np.concatenate((self.thresholds, [np.inf]))

    def get_coverage_profile(self, surprise_values: np.ndarray) -> np.ndarray:
        """Map SA values to (samples, sections) boolean bucket membership."""
        surprise_values = np.asarray(surprise_values)
        res = np.zeros(shape=(surprise_values.shape[0], self.sections), dtype=bool)
        for i in range(self.sections):
            res[..., i] = np.logical_and(
                self.thresholds[i] <= surprise_values,
                surprise_values < self.thresholds[i + 1],
            )
        return res


# ---------------------------------------------------------------------------
# SA base + multimodal wrapper
# ---------------------------------------------------------------------------


class SA(abc.ABC):
    """Abstract superclass of all surprise-adequacy variants."""

    @abc.abstractmethod
    def __call__(
        self, activations: Activations, predictions: Predictions, num_threads: int = 1
    ) -> np.ndarray:
        """Surprise adequacy of the provided activations/predictions."""


class MultiModalSA(SA):
    """Routes samples through a discriminator to per-modal SA instances.

    The reference fans modals out over a thread pool
    (src/core/surprise.py:339-361); here modal computations run sequentially on
    host — the heavy per-modal work (DSA distances) already saturates the
    device, so host threads would only add contention.
    """

    def __init__(self, discriminator: Discriminator, modal_sa: Dict[int, SA]):
        self.discriminator = discriminator
        self.modal_sa = modal_sa

    @staticmethod
    def build_by_class(
        activations: Activations,
        predictions: Predictions,
        sa_constructor: Callable[[Activations, Predictions], SA],
    ) -> "MultiModalSA":
        """Multi-modal SA discriminating by the predicted class."""
        return MultiModalSA.build(
            activations, predictions, _by_class_discriminator, sa_constructor
        )

    @staticmethod
    def build_with_kmeans(
        activations: Activations,
        predictions: Optional[Predictions],
        sa_constructor: Callable[[Activations, Predictions], SA],
        potential_k: Iterable[int],
        n_init: int = 10,
        max_iter: int = 300,
        subsampling=1.0,
        subsampling_seed: int = 0,
        seed: Optional[int] = 0,
    ) -> "MultiModalSA":
        """Multi-modal SA discriminating by silhouette-scored KMeans (MMDSA)."""
        discriminator = _KmeansDiscriminator(
            training_data=activations,
            potential_k=potential_k,
            n_init=n_init,
            max_iter=max_iter,
            subsampling=subsampling,
            subsampling_seed=subsampling_seed,
            seed=seed,
        )
        return MultiModalSA.build(activations, predictions, discriminator, sa_constructor)

    @staticmethod
    def build(
        activations: Activations,
        predictions: Optional[Predictions],
        discriminator: Discriminator,
        sa_constructor: Callable[[Activations, Predictions], SA],
    ) -> "MultiModalSA":
        """Fit one SA instance per modal id produced by the discriminator."""
        activations = _flatten_layers(activations)
        predictions = _flatten_predictions(predictions)
        modal_indexes = discriminator(activations, predictions)
        sa_s: Dict[int, SA] = {}
        for modal_id in np.unique(modal_indexes):
            modal_activations = activations[modal_indexes == modal_id]
            modal_predictions = (
                None if predictions is None else predictions[modal_indexes == modal_id]
            )
            sa_s[int(modal_id)] = sa_constructor(modal_activations, modal_predictions)
        return MultiModalSA(discriminator=discriminator, modal_sa=sa_s)

    def _get_sa_for_modal_id(self, modal_id: int) -> SA:
        try:
            return self.modal_sa[int(modal_id)]
        except KeyError:
            raise ValueError(
                f"No modal found for modal id {modal_id}. Check your discriminator"
            )

    def __call__(
        self,
        activations: Activations,
        predictions: Optional[Predictions],
        num_threads: int = 1,
    ) -> np.ndarray:
        discriminator_idxs = self.discriminator(activations, predictions)
        activations = _flatten_layers(activations)
        predictions = _flatten_predictions(predictions)
        assert len(discriminator_idxs) == activations.shape[0], (
            f"The discriminator returned an invalid number "
            f"({len(discriminator_idxs)}) of modal indexes."
            f"Expected: {activations.shape[0]} indexes."
        )
        if len(discriminator_idxs) == 0:
            return np.ndarray(shape=(0,))

        modals_in_this_set = np.unique(discriminator_idxs)
        per_modal_values = []
        for modal_id in modals_in_this_set:
            sa = self._get_sa_for_modal_id(modal_id)
            mask = discriminator_idxs == modal_id
            a = activations[mask]
            p = None if predictions is None else predictions[mask]
            per_modal_values.append(sa(a, p))

        res = np.full(
            fill_value=-np.inf,
            shape=discriminator_idxs.shape,
            dtype=per_modal_values[0].dtype,
        )
        for i, adequacies in enumerate(per_modal_values):
            res[discriminator_idxs == modals_in_this_set[i]] = adequacies
        return res


# ---------------------------------------------------------------------------
# Unimodal SA variants
# ---------------------------------------------------------------------------


_MDSA_DEVICE_SCORE = None


def _mdsa_device_score_fn():
    """Cached jitted MDSA quadform (lazy: module import stays jax-free for
    the spawned SA fit-pool workers)."""
    global _MDSA_DEVICE_SCORE
    if _MDSA_DEVICE_SCORE is None:
        import jax
        import jax.numpy as jnp

        def _score(activations, location, precision):
            centered = activations - location
            return jnp.sum((centered @ precision) * centered, axis=1)

        _MDSA_DEVICE_SCORE = jax.jit(_score)
    return _MDSA_DEVICE_SCORE


class MDSA(SA):
    """Mahalanobis-distance surprise adequacy (squared Mahalanobis distance to
    the training distribution; reference: src/core/surprise.py:374-393)."""

    def __init__(self, activations: Activations):
        import scipy.linalg

        # f32 accumulation for the O(n·d²) covariance GEMM (sgemm, 2× the
        # f64 rate on this host; MXU-native on device) — mean-centering
        # first keeps the f32 sums well-conditioned. The O(d³) pseudo-
        # inverse stays f64: it is the numerically delicate step and is
        # cheap relative to the GEMMs. Parity coverage: exact ordering +
        # rtol 2e-3 vs the reference's all-f64 sklearn path at small
        # shapes (tests/test_reference_oracle.py), and near-perfect rank
        # agreement vs a transcribed f64 oracle at thousands×hundreds
        # (tests/test_surprise.py::test_mdsa_f32_ordering_parity_at_scale)
        # — f32 can still swap scores tied within ~1e-4 relative.
        activations = _flatten_layers(activations).astype(np.float32)
        self.location = activations.mean(axis=0, dtype=np.float64).astype(  # tiplint: disable=f64-on-tpu (host mean accumulator; see block comment above)
            np.float32
        )
        # ML (biased) covariance — matches sklearn EmpiricalCovariance.
        centered = activations - self.location
        self.covariance = (centered.T @ centered).astype(np.float64) / activations.shape[0]  # tiplint: disable=f64-on-tpu (host covariance; pinvh is the numerically delicate step)
        self.precision = scipy.linalg.pinvh(np.atleast_2d(self.covariance)).astype(
            np.float32
        )

    def __call__(
        self,
        activations: Activations,
        predictions: Predictions = None,
        num_threads: int = None,
    ) -> np.ndarray:
        activations = _flatten_layers(activations).astype(np.float32)
        if resolved_cluster_backend() == "jax":
            # one jitted dispatch over device-resident ATs + one transfer;
            # host f64-reduction einsum below stays the reference path
            # (parity pinned by tests/test_device_scoring.py).
            scores = _mdsa_device_score_fn()(
                activations, self.location, self.precision
            )
            return np.asarray(scores, dtype=np.float64)  # tiplint: disable=f64-on-tpu (terminal host transfer; dtype parity with the host einsum path)
        centered = activations - self.location
        # one BLAS gemm + a row-wise dot; the 3-operand einsum form takes
        # numpy's unoptimized path and was ~5x slower. f64 row reduction
        # over f32 gemm outputs: the final dot's additions are where
        # cancellation could reorder near-ties.
        return np.einsum(
            # tiplint: disable=f64-on-tpu (host f64 row reduction over f32 gemm; see comment above)
            "ij,ij->i", (centered @ self.precision).astype(np.float64), centered
        )


class LSA(SA):
    """Likelihood surprise adequacy: -log KDE density over training ATs with
    variance-based feature pruning (reference: src/core/surprise.py:396-495)."""

    def __init__(
        self,
        activations: Activations,
        var_threshold: Optional[float] = None,
        max_features: Optional[Union[int, float]] = 300,
    ):
        activations = _flatten_layers(activations)
        assert var_threshold is None or max_features is None, (
            "Both var_threshold and max_features cannot be specified at the "
            "same time. We recommend using the max_features arg to dynamically "
            "keep the features with the highest variance."
        )
        self.removed_neurons: List[int] = []
        if var_threshold is not None and var_threshold > 0:
            self.removed_neurons = list(
                np.where(np.var(activations, axis=0) < var_threshold)[0]
            )
        if max_features is not None:
            if max_features < 1:
                num_features = int(
                    min(max_features * activations.shape[1], activations.shape[1])
                )
            else:
                num_features = min(max_features, activations.shape[1])
            dropped_columns = np.argsort(np.var(activations, axis=0))[:-num_features]
            self.removed_neurons = [int(x) for x in dropped_columns]

        self.kde = self._create_gaussian_kde(activations)
        logger.info("Done creating KDE")

    def _create_gaussian_kde(self, activations: np.ndarray):
        cleaned = self._remove_unused_columns(activations)
        if cleaned.shape[1] == 0:
            warnings.warn(
                "The removal of low-variance and/or numerically unstable "
                "features removed all ATs. This instance of LSA will thus "
                "always return density 0",
                UserWarning,
            )
            return None
        try:
            return StableGaussianKDE(cleaned.transpose())
        except KDESingularError as e:
            if e.problematic_dim is None:
                warnings.warn("Problem regarding KDE fitting", UserWarning)
                raise
            # Map the failing column of the cleaned matrix back to the original
            # feature index, drop it, and retry (recursive drop semantics).
            original_indexes = np.delete(
                np.arange(activations.shape[1]), self.removed_neurons
            )
            problematic_index = int(original_indexes[e.problematic_dim])
            warnings.warn(
                f"Dropping AT {problematic_index}, as leading to numerical error.",
                UserWarning,
            )
            self.removed_neurons.append(problematic_index)
            return self._create_gaussian_kde(activations)

    def _remove_unused_columns(self, tr_activations: np.ndarray) -> np.ndarray:
        if self.removed_neurons:
            return np.delete(tr_activations, self.removed_neurons, axis=1)
        return tr_activations

    def __call__(
        self,
        activations: Activations,
        predictions: Predictions = None,  # ignored in LSA
        num_threads: int = 0,  # ignored in LSA
    ) -> np.ndarray:
        activations = _flatten_layers(activations)
        activations = self._remove_unused_columns(activations)
        if self.kde is None:
            return np.zeros(shape=(activations.shape[0],))
        with np.errstate(divide="ignore"):
            density = self.kde.evaluate(activations.transpose())
            return -np.log(density)


class MLSA(SA):
    """Multimodal likelihood SA: negative GMM log-likelihood
    (reference: src/core/surprise.py:498-520)."""

    def __init__(
        self,
        activations: Activations,
        num_components: int = 2,
        seed: Optional[int] = 0,
    ):
        _, _, GaussianMixture = _cluster_backend()

        activations = _flatten_layers(activations)
        if activations.shape[0] < num_components:
            # Tiny modal: per-class/per-cluster MLSA can receive fewer
            # samples than mixture components (seen in practice: a weak
            # small-data model predicting a class only twice). sklearn
            # requires n_samples >= n_components and NO reg_covar fixes
            # that, so the escalation ladder would exhaust and abort the
            # whole run — the reference's fixed-default fit would crash
            # identically (src/core/surprise.py:498-520); it just never
            # meets per-class counts this small. Clamp with a loud warning:
            # a k-point GMM over k points is degenerate-but-defined, and
            # the resulting scores keep their role (such samples are
            # maximally surprising to everything else anyway).
            warnings.warn(
                f"MLSA modal has only {activations.shape[0]} samples for "
                f"{num_components} mixture components; clamping components "
                "to the sample count"
            )
            num_components = max(1, activations.shape[0])
            if activations.shape[0] == 1:
                # sklearn additionally requires n_samples >= 2 outright; a
                # duplicated row fits a point-mass Gaussian of reg_covar
                # width at the sample — defined, and maximally surprising
                # to everything away from it (same spirit as LSA's
                # documented single-sample degraded mode)
                activations = np.repeat(activations, 2, axis=0)
        logger.info("Fitting Gaussian Mixture with %d components", num_components)
        # Degenerate activation sets (collapsed features / near-singleton
        # components at small scale) can make the default reg_covar=1e-6 fit
        # raise; escalating the covariance regularization is sklearn's own
        # documented remedy and keeps the metric defined where the
        # reference's fixed-default fit would abort the whole run.
        last_error = None
        ladder = (1e-6, 1e-4, 1e-2)
        for reg_covar in ladder:
            try:
                self.gmm = GaussianMixture(
                    n_components=num_components,
                    random_state=seed,
                    reg_covar=reg_covar,
                )
                self.gmm.fit(activations)
                # Backstop probe. Both backends now surface degeneracy at
                # fit time (the jnp backend validates its final covariances
                # sklearn-style — ops/cluster.py _validate_fit, with a
                # parity test pinning identical rung selection), but a one
                # -row probe here still catches anything that slips to the
                # scoring path, keeping the ladder airtight.
                self.gmm.score_samples(activations[:1])
                break
            except ValueError as e:  # includes LinAlgError
                last_error = e
                if reg_covar != ladder[-1]:
                    warnings.warn(
                        f"GMM fit failed at reg_covar={reg_covar:g} ({e}); "
                        "retrying with stronger covariance regularization"
                    )
        else:
            raise last_error

    def __call__(
        self,
        activations: Activations,
        predictions: Predictions = None,  # ignored
        num_threads: int = 0,  # ignored
    ) -> np.ndarray:
        activations = _flatten_layers(activations)
        return -self.gmm.score_samples(activations)


def estimate_dsa_memory_bytes(
    num_train: int, chunk_size: int, num_features: int
) -> int:
    """Estimated peak device bytes for one chunked DSA dispatch.

    TPU analog of the reference's host-RAM estimator for the full DSA pass
    (reference: src/core/surprise.py:653-703). There the concern is the
    per-badge (badge x train) float distance matrices held across a thread
    pool; here it is the HBM footprint of one jitted chunk: the resident
    train matrix, the (chunk x train) squared-distance matrix plus its
    same/other-class masked variants (counted separately — XLA usually fuses
    the masks but we stay conservative), and the chunk's row operands.
    """
    f32 = 4
    train_resident = num_train * num_features * f32
    chunk_matrices = 3 * chunk_size * num_train * f32
    chunk_rows = 2 * chunk_size * num_features * f32
    return train_resident + chunk_matrices + chunk_rows


def _available_accelerator_bytes() -> Optional[int]:
    """Free bytes on the default device (HBM via ``memory_stats``), or host
    RAM via psutil on backends without stats. ``None`` if neither works."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats() or {}
        if "bytes_limit" in stats:
            return int(stats["bytes_limit"]) - int(stats.get("bytes_in_use", 0))
    except Exception:  # noqa: BLE001 - any backend failure degrades to psutil
        pass
    try:
        import psutil

        return int(psutil.virtual_memory().available)
    except Exception:  # noqa: BLE001
        return None


class DSA(SA):
    """Distance-based surprise adequacy.

    Based on `Weiss et al., A Review and Refinement of Surprise Adequacy,
    ICSE-W 2021` (as is the reference, src/core/surprise.py:523-651).

    TPU-native formulation: for a chunk of test ATs with predicted classes,
    squared distances to *all* training ATs are one ``|x|^2+|y|^2-2xy`` matmul
    on the MXU; the same/other-class structure is applied as additive masks
    (+inf on excluded entries) before the row-min. A second masked matmul from
    the nearest same-class neighbors yields the denominator. The reference's
    per-class thread pool and badge splitting disappear; ``badge_size`` remains
    as the device chunk size to bound the (chunk x train) matrix in HBM.
    """

    def __init__(
        self,
        activations: Activations,
        predictions: Predictions,
        badge_size: int = 10,
        subsampling=1.0,
        subsampling_seed: int = 0,
    ):
        self.train_activations = _flatten_layers(activations).astype(np.float32)
        self.train_predictions = _class_predictions(predictions)
        self.train_activations, self.train_predictions = _subsample_arrays(
            subsampling,
            (self.train_activations, self.train_predictions),
            subsampling_seed,
        )
        self.num_classes = int(np.max(self.train_predictions)) + 1
        self.badge_size = badge_size
        self._device_state = None
        self._pallas_backend = None
        self.use_pallas: Optional[bool] = None  # None = auto-detect

    def __getstate__(self):
        """Pickle support (SA fit cache / fit pool, engine/sa_prep.py): the
        jitted chunk closure and the pallas backend are process-local device
        handles — dropped here and rebuilt lazily on the first score."""
        state = self.__dict__.copy()
        state["_device_state"] = None
        state["_pallas_backend"] = None
        return state

    def _prepare_device(self):
        import jax
        import jax.numpy as jnp

        train = jnp.asarray(self.train_activations)
        labels = jnp.asarray(self.train_predictions)
        train_sq = jnp.sum(train * train, axis=1)

        @jax.jit
        def dsa_chunk(x, x_labels):
            x_sq = jnp.sum(x * x, axis=1)
            d2 = x_sq[:, None] + train_sq[None, :] - 2.0 * (x @ train.T)
            d2 = jnp.maximum(d2, 0.0)
            same = x_labels[:, None] == labels[None, :]
            inf = jnp.inf
            d2_same = jnp.where(same, d2, inf)
            a_idx = jnp.argmin(d2_same, axis=1)
            a_dist = jnp.sqrt(jnp.min(d2_same, axis=1))
            closest = train[a_idx]
            c_sq = jnp.sum(closest * closest, axis=1)
            d2b = c_sq[:, None] + train_sq[None, :] - 2.0 * (closest @ train.T)
            d2b = jnp.maximum(d2b, 0.0)
            d2_other = jnp.where(same, inf, d2b)
            b_dist = jnp.sqrt(jnp.min(d2_other, axis=1))
            return a_dist / b_dist

        self._device_state = (train, labels, train_sq, dsa_chunk)

    def _fit_chunk_to_memory(self, chunk: int, num_features: int) -> int:
        """Shrink the device chunk until its estimated footprint fits free
        device memory, warning like the reference's OOM predictor
        (src/core/surprise.py:694-703) when even the minimum chunk may not."""
        available = _available_accelerator_bytes()
        if available is None:
            return chunk
        budget = int(available * 0.8)
        n_train = self.train_activations.shape[0]
        floor = max(1, min(chunk, self.badge_size))
        while (
            chunk > floor
            and estimate_dsa_memory_bytes(n_train, chunk, num_features) > budget
        ):
            chunk = max(floor, chunk // 2)
        if estimate_dsa_memory_bytes(n_train, chunk, num_features) > budget:
            warnings.warn(
                "DSA will likely run out of device memory: one chunk of "
                f"{chunk} test ATs against {n_train} train ATs needs about "
                f"{estimate_dsa_memory_bytes(n_train, chunk, num_features) / 2**30:.2f} "
                f"GiB but only {budget / 2**30:.2f} GiB fit the memory budget "
                "(80% of free). Consider "
                "a smaller badge_size or stronger train subsampling.",
                UserWarning,
            )
        return chunk

    def __call__(
        self,
        activations: Activations,
        predictions: Predictions,
        num_threads: int = None,  # accepted for API parity; device path ignores it
    ) -> np.ndarray:
        import jax.numpy as jnp

        target_pred = _class_predictions(predictions)
        target_ats = _flatten_layers(activations).astype(np.float32)

        # Prefer the pallas kernel on TPU (no HBM-resident distance matrix);
        # fall back to the chunked XLA formulation elsewhere.
        use_pallas = self.use_pallas
        if use_pallas is None:
            from simple_tip_tpu.ops.dsa_pallas import pallas_available_for

            use_pallas = pallas_available_for(target_ats.shape[1])
        if use_pallas:
            if self._pallas_backend is None:
                from simple_tip_tpu.ops.dsa_pallas import PallasDSABackend

                self._pallas_backend = PallasDSABackend(
                    self.train_activations, self.train_predictions
                )
            return self._pallas_backend.score(target_ats, target_pred)

        if self._device_state is None:
            self._prepare_device()
        _, _, _, dsa_chunk = self._device_state

        n_test = target_ats.shape[0]
        # Device chunk: at least badge_size, at most a few thousand rows so the
        # (chunk x train) distance matrix stays comfortably in HBM.
        chunk = int(min(max(self.badge_size, 256), 4096, max(1, n_test)))
        chunk = self._fit_chunk_to_memory(chunk, target_ats.shape[1])
        n_chunks = math.ceil(n_test / chunk)
        padded = n_chunks * chunk
        if padded != n_test:
            target_ats = np.concatenate(
                [target_ats, np.zeros((padded - n_test, target_ats.shape[1]), np.float32)]
            )
            target_pred = np.concatenate(
                [target_pred, np.zeros(padded - n_test, target_pred.dtype)]
            )

        out = np.empty(padded, dtype=np.float32)
        for i in range(n_chunks):
            sl = slice(i * chunk, (i + 1) * chunk)
            # tiplint: disable=host-sync (bounded-memory streaming: each chunk lands in a preallocated host buffer)
            out[sl] = np.asarray(
                dsa_chunk(jnp.asarray(target_ats[sl]), jnp.asarray(target_pred[sl]))
            )
        return out[:n_test].astype(np.float64)  # tiplint: disable=f64-on-tpu (host output dtype parity with the reference's DSA)

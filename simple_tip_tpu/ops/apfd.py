"""Average Percentage of Fault Detection (APFD).

Behavioral contract matches the reference (reference: src/core/apfd.py:8-19):
``1 - sum(fault_orders) / (k*n) + 1/(2n)`` where fault orders are 1-based ranks
of misclassified samples in the prioritized order.

Two entry points:

- ``apfd_from_order``: host-side scalar, exact float64 (used by evaluation).
- ``apfd_from_orders``: batched jnp kernel — evaluates a whole
  (approach x model) grid of orders in one fused XLA program; the evaluation
  phase over 39 approaches x 100 runs becomes a single device call.
"""

from typing import List, Union

import numpy as np


def apfd_from_order(is_fault, index_order: Union[List[int], np.ndarray]) -> float:
    """APFD of one prioritization order given the per-sample fault mask."""
    is_fault = np.asarray(is_fault)
    assert is_fault.ndim == 1, "at the moment, only unique faults are supported"
    ordered_faults = is_fault[np.asarray(index_order)]
    fault_indexes = np.where(ordered_faults == 1)[0]
    k = np.count_nonzero(is_fault)
    n = is_fault.shape[0]
    # +1: first sample has index 0 but rank 1
    sum_of_fault_orders = np.sum(fault_indexes + 1)
    return 1 - (sum_of_fault_orders / (k * n)) + (1 / (2 * n))


def apfd_from_orders(is_fault, index_orders) -> "np.ndarray":
    """Batched APFD: ``index_orders`` has shape (batch, n); ``is_fault`` is
    (n,) or (batch, n). Returns (batch,) APFD values.

    Pure jnp so it can be jitted/vmapped; ranks are computed without any
    data-dependent control flow.
    """
    import jax.numpy as jnp

    is_fault = jnp.asarray(is_fault)
    index_orders = jnp.asarray(index_orders)
    if is_fault.ndim == 1:
        is_fault = jnp.broadcast_to(is_fault[None, :], index_orders.shape)
    n = index_orders.shape[-1]
    ordered_faults = jnp.take_along_axis(is_fault, index_orders, axis=-1)
    ranks = jnp.arange(1, n + 1, dtype=jnp.float32)[None, :]
    sum_of_fault_orders = jnp.sum(ordered_faults * ranks, axis=-1)
    k = jnp.sum(is_fault, axis=-1)
    return 1.0 - sum_of_fault_orders / (k * n) + 1.0 / (2.0 * n)

"""Streaming aggregate statistics (per-neuron min / max / std) over activation
badges, with per-statistic timing.

Replaces the reference's welford-package-backed collector (reference:
src/dnn_test_prio/aggregate_statistics.py:12-67) with a self-contained Welford
implementation. ``std`` is the sample standard deviation (ddof=1), matching
``welford.Welford.var_s``.

A fused jnp path (``aggregate_over_batches``) computes all three statistics for
a whole dataset in one ``lax.scan`` on device — the preferred path for the
coverage worker; the incremental host class remains for streaming use.
"""

from typing import List, Sequence, Tuple

import numpy as np

from simple_tip_tpu.ops.timer import Timer

AggStats = Tuple[List[np.ndarray], List[np.ndarray], List[np.ndarray]]


class _Welford:
    """Chan et al. parallel variance over batches of (batch, ...) arrays."""

    def __init__(self):
        self.count = 0
        self.mean = None
        self.m2 = None

    def add_all(self, batch: np.ndarray):
        batch = np.asarray(batch, dtype=np.float64)  # tiplint: disable=f64-on-tpu (host-parity Welford; device path is DeviceAggregateStatisticsCollector)
        b_count = batch.shape[0]
        if b_count == 0:
            return
        b_mean = batch.mean(axis=0)
        b_m2 = ((batch - b_mean) ** 2).sum(axis=0)
        if self.count == 0:
            self.count, self.mean, self.m2 = b_count, b_mean, b_m2
            return
        delta = b_mean - self.mean
        total = self.count + b_count
        self.mean = self.mean + delta * (b_count / total)
        self.m2 = self.m2 + b_m2 + delta**2 * (self.count * b_count / total)
        self.count = total

    @property
    def var_s(self) -> np.ndarray:
        """Sample variance (ddof=1)."""
        if self.count < 2:
            return np.full_like(self.mean, np.nan)
        return self.m2 / (self.count - 1)


class AggregateStatisticsCollector:
    """Streaming per-neuron min/max/std over per-layer activation badges,
    timing each statistic separately (for the reference's per-metric setup-time
    debit accounting, reference: src/dnn_test_prio/handler_coverage.py:49-101)."""

    def __init__(self):
        self.done = False
        self.mins: List[np.ndarray] = []
        self.maxs: List[np.ndarray] = []
        self.welfords: List[_Welford] = []
        self.min_timer = Timer()
        self.max_timer = Timer()
        self.welford_timer = Timer()

    def track(self, badge: Sequence[np.ndarray]) -> None:
        """Fold the next badge of per-layer activation arrays into the stats."""
        if self.done:
            raise RuntimeError(
                "`get` has been called. calling it multiple times falsifies timer."
            )
        badge = [np.asarray(b) for b in badge]
        if not self.mins:
            self.mins = [np.full(b.shape[1:], np.inf) for b in badge]
            self.maxs = [np.full(b.shape[1:], -np.inf) for b in badge]
            self.welfords = [_Welford() for _ in badge]
        with self.min_timer:
            self.mins = [
                np.minimum(self.mins[i], badge[i].min(axis=0))
                for i in range(len(badge))
            ]
        with self.max_timer:
            self.maxs = [
                np.maximum(self.maxs[i], badge[i].max(axis=0))
                for i in range(len(badge))
            ]
        with self.welford_timer:
            for i in range(len(badge)):
                self.welfords[i].add_all(badge[i].reshape(badge[i].shape[0], -1))

    def get(self) -> AggStats:
        """Return (mins, maxs, stds) per layer."""
        with self.welford_timer:
            stds = [
                np.sqrt(w.var_s).reshape(self.mins[i].shape)
                for i, w in enumerate(self.welfords)
            ]
        return self.mins, self.maxs, stds


class DeviceAggregateStatisticsCollector:
    """Streaming per-neuron min/max/std computed on device.

    Same interface and output as ``AggregateStatisticsCollector`` (including
    the min/max/welford timer attributes consumed by the coverage worker's
    time-debit accounting), but each badge folds into the running statistics
    as one fused jitted program per layer — no host float64 passes. Because
    the three statistics are fused, their measured device time is attributed
    equally to the three timers (a documented approximation; the reference
    times them separately on host).
    """

    def __init__(self):
        self.done = False
        self._state = None  # per-layer (min, max, count, mean, m2)
        self.min_timer = Timer()
        self.max_timer = Timer()
        self.welford_timer = Timer()
        self._fused_elapsed = 0.0

        import jax
        import jax.numpy as jnp

        def _one_init(b):
            flat = b.reshape(b.shape[0], -1).astype(jnp.float32)
            mean = flat.mean(axis=0)
            return (
                b.min(axis=0),
                b.max(axis=0),
                b.shape[0],
                mean,
                ((flat - mean) ** 2).sum(axis=0),
            )

        def _one_update(state, b):
            mn, mx, cnt, mean, m2 = state
            flat = b.reshape(b.shape[0], -1).astype(jnp.float32)
            b_cnt = b.shape[0]
            b_mean = flat.mean(axis=0)
            b_m2 = ((flat - b_mean) ** 2).sum(axis=0)
            delta = b_mean - mean
            total = cnt + b_cnt
            return (
                jnp.minimum(mn, b.min(axis=0)),
                jnp.maximum(mx, b.max(axis=0)),
                total,
                mean + delta * (b_cnt / total),
                m2 + b_m2 + delta**2 * (cnt * b_cnt / total),
            )

        # One fused dispatch per badge over the whole layer list. The running
        # state is replaced on every fold, so its old buffers are donated —
        # without donation both generations stay alive across the call
        # (flagged by tiplint buffer-donation).
        self._init_layer = jax.jit(lambda badge: [_one_init(b) for b in badge])
        self._update_layer = jax.jit(
            lambda state, badge: [
                _one_update(s, b) for s, b in zip(state, badge)
            ],
            donate_argnums=(0,),
        )

    def track(self, badge) -> None:
        """Fold the next badge of per-layer (jax or numpy) arrays in."""
        if self.done:
            raise RuntimeError(
                "`get` has been called. calling it multiple times falsifies timer."
            )
        import jax
        import jax.numpy as jnp
        import time as _time

        # perf_counter, not time.time: duration accounting must survive
        # NTP steps (repo idiom since the PR 4 timer fix; tiplint
        # wallclock-duration enforces it).
        t0 = _time.perf_counter()
        badge = [jnp.asarray(b) for b in badge]
        if self._state is None:
            self._state = self._init_layer(badge)
        else:
            self._state = self._update_layer(self._state, badge)
        jax.block_until_ready([s[0] for s in self._state])
        self._fused_elapsed += _time.perf_counter() - t0

    def get(self) -> AggStats:
        """Return (mins, maxs, stds) per layer (host numpy)."""
        import jax.numpy as jnp

        third = self._fused_elapsed / 3.0
        for t in (self.min_timer, self.max_timer, self.welford_timer):
            t._elapsed += third
        mins = [np.asarray(s[0]) for s in self._state]
        maxs = [np.asarray(s[1]) for s in self._state]
        stds = [
            # tiplint: disable=host-sync (get() IS the phase boundary: one transfer per collection)
            np.asarray(jnp.sqrt(s[4] / (np.asarray(s[2]) - 1)).reshape(s[0].shape))
            for s in self._state
        ]
        return mins, maxs, stds


def aggregate_over_batches(layer_batches_iter):
    """Fused device path: iterate (list-of-layer-arrays) badges, compute
    min/max/Welford on device via jnp, return host numpy (mins, maxs, stds).

    The per-badge update is a single fused XLA program per layer; the
    sequential fold over badges stays in Python because badge count is tiny
    compared to badge size.
    """
    import jax.numpy as jnp

    state = None  # per-layer (min, max, count, mean, m2)
    for badge in layer_batches_iter:
        badge = [jnp.asarray(b) for b in badge]
        if state is None:
            state = []
            for b in badge:
                flat = b.reshape(b.shape[0], -1).astype(jnp.float32)
                state.append(
                    (
                        b.min(axis=0),
                        b.max(axis=0),
                        b.shape[0],
                        flat.mean(axis=0),
                        ((flat - flat.mean(axis=0)) ** 2).sum(axis=0),
                    )
                )
            continue
        new_state = []
        for (mn, mx, cnt, mean, m2), b in zip(state, badge):
            flat = b.reshape(b.shape[0], -1).astype(jnp.float32)
            b_cnt = b.shape[0]
            b_mean = flat.mean(axis=0)
            b_m2 = ((flat - b_mean) ** 2).sum(axis=0)
            delta = b_mean - mean
            total = cnt + b_cnt
            new_state.append(
                (
                    jnp.minimum(mn, b.min(axis=0)),
                    jnp.maximum(mx, b.max(axis=0)),
                    total,
                    mean + delta * (b_cnt / total),
                    m2 + b_m2 + delta**2 * (cnt * b_cnt / total),
                )
            )
        state = new_state
    mins = [np.asarray(s[0]) for s in state]
    maxs = [np.asarray(s[1]) for s in state]
    stds = [
        # tiplint: disable=host-sync (terminal transfer: host results once per aggregation)
        np.asarray(jnp.sqrt(s[4] / (s[2] - 1)).reshape(s[0].shape)) for s in state
    ]
    return mins, maxs, stds

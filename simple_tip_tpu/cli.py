"""Reproduction CLI: the entrypoint for running experiment phases.

Equivalent of the reference's interactive typer app
(reference: reproduction.py:184-204) with the same phases
(training, test_prio, active_learning, evaluation, at_collection) — argparse
based (non-interactive flags first, prompts only when flags are missing),
which suits batch TPU jobs better than the reference's confirm-gates.

Usage:
    python -m simple_tip_tpu.cli --phase training --case-study mnist --runs 0-4
    python -m simple_tip_tpu.cli --phase test_prio --case-study mnist --runs 0
    python -m simple_tip_tpu.cli --phase evaluation --eval test_prio
"""

import argparse
import logging
import os
import sys
from typing import List

PHASES = ["training", "test_prio", "active_learning", "evaluation", "at_collection", "check"]
CASE_STUDIES = ["mnist", "cifar10", "fmnist", "imdb"]
EVALS = ["test_prio", "active_learning", "test_prio_statistics", "active_learning_statistics"]


def _parse_runs(spec: str) -> List[int]:
    """Parse '0', '0-4', '0,3,7' or '-1' (= all 100) into run-id lists."""
    spec = spec.strip()
    if spec == "-1":
        return list(range(100))
    runs: List[int] = []
    for part in spec.split(","):
        if "-" in part and not part.startswith("-"):
            lo, hi = part.split("-")
            if int(hi) < int(lo):
                raise SystemExit(
                    f"--runs: inverted range {part!r} selects nothing "
                    f"(did you mean {hi}-{lo}?)"
                )
            runs.extend(range(int(lo), int(hi) + 1))
        else:
            runs.append(int(part))
    if not runs:
        raise SystemExit(f"--runs: {spec!r} selects no run ids")
    return runs


ALL_CASE_STUDIES = ("mnist", "fmnist", "cifar10", "imdb")


def _run_eval(which: str, case_studies=ALL_CASE_STUDIES):
    if which == "test_prio":
        from simple_tip_tpu.plotters import eval_apfd_table

        eval_apfd_table.run(case_studies=case_studies)
    elif which == "active_learning":
        from simple_tip_tpu.plotters import eval_active_learning_table

        eval_active_learning_table.run(case_studies=case_studies)
    elif which == "test_prio_statistics":
        from simple_tip_tpu.plotters import eval_apfd_correlation

        eval_apfd_correlation.run(case_studies=case_studies)
    elif which == "active_learning_statistics":
        from simple_tip_tpu.plotters import eval_active_correlation

        eval_active_correlation.run(case_studies=case_studies)
    else:
        raise ValueError(f"Unknown eval type: {which}")


def dispatch_phase(cs, phase: str, runs, num_workers: int = 1):
    """Run one non-evaluation phase on a CaseStudy (shared by the CLI and
    scripts/full_study.py so the phase->method mapping lives in one place).

    ``num_workers`` fans per-run host work out over worker processes
    (parallel/run_scheduler.py); training ignores it — its parallel axis is
    the vmapped ensemble sharded over the device mesh."""
    if phase == "training":
        cs.train(runs)
    elif phase == "test_prio":
        cs.run_prio_eval(runs, num_workers=num_workers)
    elif phase == "active_learning":
        cs.run_active_learning_eval(runs, num_workers=num_workers)
    elif phase == "at_collection":
        cs.collect_activations(runs, num_workers=num_workers)
    else:
        raise ValueError(f"Unknown phase: {phase}")


def main(argv=None) -> int:
    """CLI entrypoint."""
    parser = argparse.ArgumentParser(
        description="TPU-native reproduction of the simple-tip experiments."
    )
    parser.add_argument("--phase", choices=PHASES, required=True)
    parser.add_argument("--case-study", choices=CASE_STUDIES)
    parser.add_argument(
        "--runs",
        default="0",
        help="run ids: '0', '0-4', '0,3,7', or -1 for all 100",
    )
    parser.add_argument("--eval", choices=EVALS, help="evaluation to run (phase=evaluation)")
    parser.add_argument(
        "--workers",
        type=int,
        default=int(os.environ.get("TIP_NUM_WORKERS", "1")),
        help="worker processes for per-run host work in the test_prio/"
        "active_learning/at_collection phases (default TIP_NUM_WORKERS or 1)",
    )
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.INFO if args.verbose else logging.WARNING)

    if args.phase == "check":
        from simple_tip_tpu.utils.artifact_check import report

        for cs_name in [args.case_study] if args.case_study else CASE_STUDIES:
            print(report(cs_name, has_dropout=cs_name != "cifar10"))
        return 0

    if args.phase == "evaluation":
        which = args.eval or "test_prio"
        _run_eval(which)
        print("Done. Check your assets results folder for the reproduced result files.")
        return 0

    if not args.case_study:
        parser.error("--case-study is required for non-evaluation phases")
    runs = _parse_runs(args.runs)

    # jax-using phases only (check/evaluation above stay jax-free and fast)
    from simple_tip_tpu.config import enable_compilation_cache
    from simple_tip_tpu.utils.device_watchdog import ensure_responsive_backend

    enable_compilation_cache()
    # Degrade loudly to CPU when the accelerator is wedged or its transport
    # is down (observed: multi-hour tunnel outages hang every device op, or
    # fail backend init mid-phase) instead of dying partway through a run.
    intended_cpu = os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu"
    platform = ensure_responsive_backend()
    if platform == "cpu":
        logging.getLogger(__name__).warning("running on the CPU backend")
    if platform == "cpu" and not intended_cpu:
        # Unintended degradation. For the multi-hour phases, silently
        # converting an accelerator study into a vastly slower CPU run is
        # worse than stopping: require an explicit opt-in, and say so on
        # stdout (not just the log).
        print(
            "WARNING: accelerator unresponsive — degraded to the CPU backend",
            flush=True,
        )
        if args.phase in ("training", "active_learning", "at_collection") and (
            os.environ.get("TIP_ALLOW_CPU_FALLBACK") != "1"
        ):
            print(
                f"Refusing to run the long '{args.phase}' phase on the CPU "
                f"fallback (it would be slower by orders of magnitude). "
                f"Set TIP_ALLOW_CPU_FALLBACK=1 to allow, or retry when the "
                f"accelerator is back.",
                flush=True,
            )
            return 2

    from simple_tip_tpu.casestudies import get_case_study

    cs = get_case_study(args.case_study)
    dispatch_phase(cs, args.phase, runs, num_workers=max(1, args.workers))
    print("Done.")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Metrics registry: counters / gauges / histograms, flushed into the stream.

The registry is process-local and ALWAYS live (a counter increment is a dict
lookup plus an add — cheap enough to leave unconditional), so callers like
``bench.py`` can embed ``snapshot()`` in their records even when the JSONL
stream is disabled. ``flush()`` writes the snapshot as one ``metrics`` event
into the span stream when ``TIP_OBS_DIR`` is set, and is called automatically
at process exit by the tracer's atexit hook.

Standing instruments (populated by the instrumented seams):

- ``sa_fit_cache.{hit,miss,stale,corrupt,store}``   engine/sa_prep.py
- ``scheduler.{requeues,timeouts,worker_deaths}``   parallel/run_scheduler.py
- ``scheduler.journal_skips`` / ``journal.appends`` resilience/journal.py
- ``watchdog.{probe_ok,probe_fail,probe_timeout}``  utils/device_watchdog.py
- ``breaker.{opened,closed,short_circuit,degraded}`` resilience/breaker.py
- ``retry.{attempts,giveups}``                      resilience/retry.py
- ``faults.injected[.<site>]``                      resilience/faults.py
- ``jax.compiles`` / ``jax.compile_seconds``        ``install_jax_hooks``
- ``device.<id>.peak_bytes_in_use``                 ``record_device_memory``

``install_jax_hooks`` / ``record_device_memory`` are the only functions here
that touch jax, both behind an explicit call + try/except: the registry
itself must stay importable in jax-free processes (fit-pool workers, the
tier-0 CLI).
"""

import threading
import time

_lock = threading.RLock()
_counters = {}
_gauges = {}
_hists = {}
_quantiles = {}
# Per-family descriptions for the exporter's `# HELP` lines: explicit
# registrations via describe() win, then the standing-instrument table
# below, then the family name itself (HELP must never be empty).
_help = {}
_jax_hooks_installed = False
# json.dumps of the last snapshot this process flushed into the stream:
# periodic pollers (the scheduler's device-memory poll) call flush() on a
# timer, and an unchanged registry must not spam identical metrics events.
_last_flushed = None


class Counter:
    """Monotonic counter (``inc``); snapshots as a number."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n=1):
        """Add ``n`` (default 1) to the counter."""
        with _lock:
            self.value += n
        return self


class Gauge:
    """Last-value gauge with a ``set_max`` high-water helper."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = None

    def set(self, v):
        """Set the gauge to ``v``."""
        self.value = v
        return self

    def set_max(self, v):
        """Raise the gauge to ``v`` if higher (high-water semantics)."""
        with _lock:
            if self.value is None or v > self.value:
                self.value = v
        return self


class Histogram:
    """Streaming summary histogram: count / sum / min / max."""

    __slots__ = ("count", "sum", "min", "max")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def observe(self, v):
        """Record one observation."""
        v = float(v)
        with _lock:
            self.count += 1
            self.sum += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
        return self


def _nearest_rank(window, q: float):
    """Nearest-rank percentile ``q`` (0..100) of a pre-sorted window."""
    if not window:
        return None
    rank = max(1, -(-int(q) * len(window) // 100))  # ceil(q*n/100)
    return window[min(rank, len(window)) - 1]


class Quantile:
    """Sliding-window percentile estimator (SLO p50/p95/p99).

    A bounded ring of the last ``cap`` observations — deterministic (no
    reservoir randomness), O(cap) memory by construction, and windowed the
    way SLO dashboards read latency: recent behavior, not the process's
    whole lifetime. ``percentile`` uses the nearest-rank definition, so
    p50 of [1, 2, 3] is 2, never an interpolated value no request actually
    saw.

    ``summary`` copies and sorts the ring ONCE under the registry lock, so
    its three percentiles describe a single consistent window even while
    writer threads (the serving dispatch pool) observe concurrently —
    p50 <= p95 <= p99 holds by construction, which three independent
    ``percentile`` calls could not guarantee mid-mutation.
    """

    __slots__ = ("count", "cap", "_ring", "_idx")

    def __init__(self, cap: int = 512):
        self.count = 0
        self.cap = max(1, int(cap))
        self._ring = []
        self._idx = 0

    def observe(self, v):
        """Record one observation into the window."""
        v = float(v)
        with _lock:
            self.count += 1
            if len(self._ring) < self.cap:
                self._ring.append(v)
            else:
                self._ring[self._idx] = v
                self._idx = (self._idx + 1) % self.cap
        return self

    def percentile(self, q: float):
        """Nearest-rank percentile ``q`` (0..100) of the window, or None."""
        with _lock:
            window = sorted(self._ring)
        return _nearest_rank(window, q)

    def summary(self) -> dict:
        """JSON-safe p50/p95/p99 + total observation count (one atomic
        copy-under-lock capture of the window; see the class docstring)."""
        with _lock:
            count = self.count
            window = sorted(self._ring)
        return {
            "count": count,
            "p50": _nearest_rank(window, 50),
            "p95": _nearest_rank(window, 95),
            "p99": _nearest_rank(window, 99),
        }


#: Descriptions for the standing instruments (module docstring table) —
#: the /metrics HELP default when no seam registered its own text.
_STANDING_HELP = {
    "scheduler.requeues": "scheduler work units requeued after a worker loss",
    "scheduler.timeouts": "scheduler work units that hit the run timeout",
    "scheduler.worker_deaths": "scheduler worker processes that died mid-run",
    "scheduler.journal_skips": "journal entries that skipped re-dispatch",
    "scheduler.in_flight": "work units currently dispatched to workers",
    "scheduler.outstanding": "work units not yet completed",
    "journal.appends": "resilience journal records appended",
    "breaker.opened": "circuit breaker transitions into OPEN",
    "breaker.closed": "circuit breaker transitions back to CLOSED",
    "breaker.short_circuit": "calls rejected while the breaker was OPEN",
    "breaker.degraded": "calls served by the degraded fallback path",
    "breaker.open": "1 while the circuit breaker is OPEN, else 0",
    "retry.attempts": "retry-policy attempts across all scopes",
    "retry.giveups": "retry-policy exhaustions (budget spent)",
    "faults.injected": "chaos faults injected by the active fault plan",
    "jax.compiles": "XLA backend compiles observed via jax.monitoring",
    "jax.compile_seconds": "XLA backend compile wall time",
    "serving.request_ms": "serving request latency window (SLO quantiles)",
    "serving.rows": "rows admitted into serving badges",
    "serving.shed": "rows shed by serving admission control",
    "serving.scheduler_crashes": "serving engine scheduler-task deaths",
    "serving.backend_errors": "serving backend dispatch errors",
    "fleet.members_alive": "fleet members with a fresh heartbeat",
}


def describe(name: str, text: str) -> None:
    """Register the ``# HELP`` description for metric family ``name``.

    Owning seams call this once next to the instrument they create; the
    exporter falls back to the standing table, then the name itself.
    """
    if text:
        with _lock:
            _help[name] = " ".join(str(text).split())


def help_text(name: str) -> str:
    """The HELP description for ``name`` (never empty)."""
    with _lock:
        text = _help.get(name)
    return text or _STANDING_HELP.get(name) or str(name)


def counter(name: str) -> Counter:
    """Get-or-create the counter ``name``."""
    with _lock:
        c = _counters.get(name)
        if c is None:
            c = _counters[name] = Counter()
        return c


def gauge(name: str) -> Gauge:
    """Get-or-create the gauge ``name``."""
    with _lock:
        g = _gauges.get(name)
        if g is None:
            g = _gauges[name] = Gauge()
        return g


def histogram(name: str) -> Histogram:
    """Get-or-create the histogram ``name``."""
    with _lock:
        h = _hists.get(name)
        if h is None:
            h = _hists[name] = Histogram()
        return h


def quantile(name: str, cap: int = 512) -> Quantile:
    """Get-or-create the sliding-window quantile ``name``."""
    with _lock:
        q = _quantiles.get(name)
        if q is None:
            q = _quantiles[name] = Quantile(cap=cap)
        return q


def snapshot() -> dict:
    """Point-in-time registry state as plain JSON-safe dicts.

    The whole snapshot — quantile summaries included — is built under the
    (re-entrant) registry lock, so readers like ``slo_snapshot()`` and the
    exporter's ``/metrics`` see one coherent view while the batcher's
    dispatch threads mutate the windows: a quantile summary can never mix
    two windows, and counters/quantiles never disagree about which badges
    have landed.

    The ``quantiles`` key is additive next to the original three — the
    metrics event schema (obs/cli.py REQUIRED_KEYS) only pins presence of
    counters/gauges/histograms, so older readers keep parsing.
    """
    with _lock:
        snap = {
            "counters": {k: c.value for k, c in sorted(_counters.items())},
            "gauges": {k: g.value for k, g in sorted(_gauges.items())},
            "histograms": {
                k: {"count": h.count, "sum": h.sum, "min": h.min, "max": h.max}
                for k, h in sorted(_hists.items())
            },
        }
        if _quantiles:
            snap["quantiles"] = {
                k: q.summary() for k, q in sorted(_quantiles.items())
            }
    return snap


def flush() -> None:
    """Write one ``metrics`` event with the current snapshot (if non-empty).

    No-op when the stream is disabled, nothing was ever recorded, or the
    snapshot is byte-identical to the last one this process flushed (so
    periodic pollers do not spam duplicate events); safe to call
    repeatedly (phase boundaries, poll timers, atexit).
    """
    global _last_flushed
    from simple_tip_tpu.obs import tracer

    if not tracer.enabled():
        return
    snap = snapshot()
    if not (
        snap["counters"]
        or snap["gauges"]
        or snap["histograms"]
        or snap.get("quantiles")
    ):
        return
    import json

    encoded = json.dumps(snap, sort_keys=True, default=repr)
    with _lock:
        if encoded == _last_flushed:
            return
        _last_flushed = encoded
    import os

    tracer.write(
        {"type": "metrics", "ts": time.time(), "pid": os.getpid(), **snap}
    )


def reset() -> None:
    """Drop every registered instrument (test hook)."""
    global _jax_hooks_installed, _last_flushed
    with _lock:
        _counters.clear()
        _gauges.clear()
        _hists.clear()
        _quantiles.clear()
        _help.clear()
        _jax_hooks_installed = False
        _last_flushed = None


def install_jax_hooks() -> None:
    """Count XLA compiles via ``jax.monitoring`` (idempotent, failure-safe).

    Registers a duration listener on jax's monitoring bus: every
    ``backend_compile`` event increments ``jax.compiles`` and accumulates
    into the ``jax.compile_seconds`` histogram, so the CLI summary shows
    recompile storms per process. Requires jax to be importable; callers
    that may run jax-free (fit-pool workers) simply never call this.
    """
    global _jax_hooks_installed
    with _lock:
        if _jax_hooks_installed:
            return
        _jax_hooks_installed = True
    try:
        import jax.monitoring

        def _on_duration(name, dur, **kw):
            if name.endswith("/backend_compile_duration"):
                counter("jax.compiles").inc()
                histogram("jax.compile_seconds").observe(dur)

        jax.monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:  # noqa: BLE001 — telemetry never takes the host down
        pass


def record_device_memory() -> None:
    """High-water device memory per local device, where the backend reports it.

    ``memory_stats()`` returns None on backends without allocator telemetry
    (CPU); TPU/GPU report ``peak_bytes_in_use``, recorded as a per-device
    high-water gauge. Failure-safe and cheap enough for phase boundaries.
    """
    try:
        import jax

        for d in jax.local_devices():
            stats = d.memory_stats()
            if stats and "peak_bytes_in_use" in stats:
                gauge(f"device.{d.id}.peak_bytes_in_use").set_max(
                    int(stats["peak_bytes_in_use"])
                )
    except Exception:  # noqa: BLE001 — telemetry never takes the host down
        pass


def poll_device_memory() -> None:
    """One device-memory poll tick: sample the gauges, flush if changed.

    The scheduler's per-run loop calls this on a timer
    (``TIP_OBS_MEMPOLL_S``), so the exported flame chart carries the
    memory high-water as a counter track that moves over the run instead
    of a single end-of-phase value. ``flush``'s duplicate suppression
    keeps an idle poll from writing anything.
    """
    record_device_memory()
    flush()

"""Splice XLA profiler timelines under their host obs spans (obs v2).

``utils/profiling.maybe_trace`` already records, on each phase span, the
``xla_trace_dir`` the ``jax.profiler`` capture went to (and, since obs v2,
``xla_started_ts`` — the wall-clock instant the profiler actually started,
which is a tighter anchor than the span start). But the two timelines lived
in two files an operator had to eyeball side by side. This module reads the
profiler's trace-event JSON (``*.trace.json[.gz]`` under the TensorBoard
``plugins/profile/<capture>/`` layout), shifts its (arbitrary-origin,
microsecond) clock onto the span clock, remaps its process ids into a
reserved range so device tracks cannot collide with host pids, and returns
Chrome ``trace_event`` entries ready to merge into the host export — ONE
Perfetto file where each device timeline sits under the host span that
captured it.

Alignment is by construction approximate: the XLA trace's internal clock
origin is unknown, so its earliest event is pinned to the host span's
``xla_started_ts`` (fallback: span start). That is exact enough to read
"which kernels ran inside this phase", which is the question the flame
chart answers.

Stdlib-only (gzip/json/os): the CLI that calls this is part of the tier-0
gate.
"""

import gzip
import json
import os

#: Synthetic pid base for spliced device tracks (host pids are real OS
#: pids, far below this).
XLA_PID_BASE = 9_000_000

#: Per-spliced-capture pid stride (one capture's internal pids stay
#: grouped and ordered).
XLA_PID_STRIDE = 1_000


def find_trace_files(trace_dir):
    """Every ``*.trace.json[.gz]`` under ``trace_dir``, sorted, recursive."""
    found = []
    for root, _dirs, files in os.walk(trace_dir):
        for name in files:
            if name.endswith(".trace.json") or name.endswith(".trace.json.gz"):
                found.append(os.path.join(root, name))
    return sorted(found)


def load_trace_events(path):
    """The ``traceEvents`` list of one profiler JSON (gz or plain).

    Returns ``[]`` on unreadable/unparsable files — a torn capture must
    not take the whole export down.
    """
    try:
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rt", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return []
    if isinstance(doc, dict):
        evs = doc.get("traceEvents", [])
    elif isinstance(doc, list):  # bare-array trace_event files are legal
        evs = doc
    else:
        return []
    return [e for e in evs if isinstance(e, dict)]


def _xla_spans(events):
    """Host spans carrying an existing ``xla_trace_dir``, ts-ordered."""
    spans = []
    for rec in events:
        if rec.get("type") != "span":
            continue
        attrs = rec.get("attrs") or {}
        d = attrs.get("xla_trace_dir")
        if isinstance(d, str) and os.path.isdir(d):
            spans.append(rec)
    spans.sort(key=lambda r: r.get("ts") or 0)
    return spans


def splice(events, t0):
    """Spliced device trace events for the merged host ``events``.

    ``t0`` is the host export's epoch (earliest host event ts, seconds);
    returned events use the same relative-microsecond clock the host
    export emits. Returns ``(trace_events, report)`` where ``report`` is a
    list of human-readable lines (one per spliced or skipped capture).
    """
    out, report = [], []
    capture_idx = 0
    seen_files = set()
    for span_rec in _xla_spans(events):
        attrs = span_rec.get("attrs") or {}
        trace_dir = attrs["xla_trace_dir"]
        files = [
            f for f in find_trace_files(trace_dir) if f not in seen_files
        ]
        seen_files.update(files)
        if not files:
            report.append(
                f"skip {span_rec.get('name')!r}: no *.trace.json under {trace_dir}"
            )
            continue
        anchor_s = attrs.get("xla_started_ts")
        if not isinstance(anchor_s, (int, float)):
            anchor_s = span_rec.get("ts") or t0
        anchor_us = int(round((anchor_s - t0) * 1e6))
        for path in files:
            xla_events = load_trace_events(path)
            timed = [
                e for e in xla_events if isinstance(e.get("ts"), (int, float))
            ]
            if not timed:
                report.append(f"skip {os.path.basename(path)}: no timed events")
                continue
            offset = anchor_us - min(e["ts"] for e in timed)
            pid_base = XLA_PID_BASE + capture_idx * XLA_PID_STRIDE
            capture_idx += 1
            pid_map, names = {}, {}
            for e in xla_events:
                if e.get("ph") == "M" and e.get("name") == "process_name":
                    names[e.get("pid")] = (e.get("args") or {}).get("name", "")
            label = str(span_rec.get("name", "xla"))
            for e in xla_events:
                pid = e.get("pid", 0)
                new_pid = pid_map.setdefault(pid, pid_base + len(pid_map))
                if e.get("ph") == "M":
                    if e.get("name") == "process_name":
                        orig = names.get(pid) or f"pid {pid}"
                        out.append(
                            {
                                "ph": "M",
                                "name": "process_name",
                                "pid": new_pid,
                                "tid": e.get("tid", 0),
                                "args": {"name": f"xla:{label} · {orig}"},
                            }
                        )
                    else:  # thread names etc. pass through, re-pidded
                        moved = dict(e)
                        moved["pid"] = new_pid
                        out.append(moved)
                    continue
                ts = e.get("ts")
                if not isinstance(ts, (int, float)):
                    continue
                moved = dict(e)
                moved["pid"] = new_pid
                moved["ts"] = max(0, int(round(ts + offset)))
                moved.setdefault("cat", "xla")
                out.append(moved)
            report.append(
                f"spliced {os.path.basename(path)} under span "
                f"{label!r} ({len(xla_events)} events, pid base {pid_base})"
            )
    return out, report

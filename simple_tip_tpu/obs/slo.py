"""SLO rule documents + burn-rate math (obs v5's declarative half).

The telemetry plane records everything (spans, /metrics, trend gates, MFU
floors) but nothing *watches* it live — a serving p99 blowout or a fleet
losing members is only caught when a human runs ``obs trend`` after the
fact. This module is the declarative half of the alerting layer: a
schema-stamped rule document declaring objectives over the metric
families the last four obs PRs already emit, each with an error budget
and multi-window multi-burn-rate thresholds (Google SRE style: a
fast-burn page on a short window, a slow-burn warn on a long one). The
procedural half — the state machine, persistence, sinks and incidents —
lives in ``obs/alerts.py``.

Rule document resolution (``load_rules``):

- ``TIP_ALERT_RULES`` unset/empty: ``$TIP_ASSETS/obs/slo_rules.json`` if
  it exists, else alerting is OFF (the TIP_OBS_DIR no-op contract);
- ``TIP_ALERT_RULES=0|off``: explicitly OFF;
- ``TIP_ALERT_RULES=builtin``: the bundled :data:`DEFAULT_RULES` covering
  serving p99 / shed rate / fleet members-alive / breaker state /
  scheduler churn / MFU floors / cost-model drift;
- ``TIP_ALERT_RULES={...}`` inline JSON, or ``@/path`` / ``/path`` a file.

A document must carry ``"schema": 1`` (the stamp every obs JSONL writer
carries); individual rules that fail validation are dropped loudly, never
fatally — a typo'd rule must not take down the host it is watching.

Objective kinds (each states the GOOD condition; a tick's sample is
``bad`` when it fails):

- ``quantile``       a registry Quantile percentile vs a bound
                     (``serving.request_ms`` p99 <= 500 ms);
- ``gauge``          a registry gauge vs a bound (``breaker.open`` <= 0,
                     ``fleet.members_alive`` >= 1, ``mfu.*`` floors);
- ``ratio``          an error-rate between counter deltas (shed rate =
                     d(serving.shed) / d(serving.rows + serving.shed)) —
                     the sample's bad fraction IS the rate;
- ``counter_delta``  counters that must not move (scheduler.requeues +
                     scheduler.worker_deaths);
- ``index``          a cross-process feature-store aggregate (``audit.*``
                     prediction error, ``mfu.*`` rows) — the evaluator
                     feeds rows from ``obs/store.py``.

Burn rate (:func:`burn_rate`) = (mean bad fraction over a window) /
(error budget): burn 1.0 spends the budget exactly; the fast window pages
at a high multiple (default 14.4, the SRE 2%-of-monthly-budget-in-an-hour
rate), the slow window warns at a low one. The fast window doubles as the
Google short-window: recovery drains it quickly, so pages stop soon after
the condition clears.

Stdlib-only, like the rest of obs: this module is imported by the tier-0
alert smoke lane (no jax/numpy installed).
"""

import json
import logging
import os
from typing import Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

#: Stamp on every rule document (and on the alert-state/transition records
#: downstream): readers skip documents whose stamp they do not understand.
SCHEMA = 1

#: Env override for the rule document (see module docstring for grammar).
RULES_ENV = "TIP_ALERT_RULES"

OPS = ("<=", ">=", "<", ">")
KINDS = ("quantile", "gauge", "ratio", "counter_delta", "index")
SEVERITIES = ("page", "warn")

#: Default multi-window thresholds (Google SRE table 6-2 shape): the fast
#: pair pages, the slow pair warns.
_DEFAULT_WINDOWS = {
    "fast": {"window_s": 300.0, "burn": 14.4},
    "slow": {"window_s": 3600.0, "burn": 3.0},
}


def default_rules_path() -> str:
    """The standing rule document: ``$TIP_ASSETS/obs/slo_rules.json``."""
    assets = os.environ.get("TIP_ASSETS", os.path.join(os.getcwd(), "assets"))
    return os.path.join(os.path.abspath(assets), "obs", "slo_rules.json")


#: The bundled rule set (``TIP_ALERT_RULES=builtin``): one objective per
#: metric family the ROADMAP's SLO item names. Budgets/thresholds are
#: deliberately loose defaults — a deployment pins its own document.
DEFAULT_RULES = {
    "schema": SCHEMA,
    "rules": [
        {
            "name": "serving-p99-latency",
            "severity": "page",
            "objective": {
                "kind": "quantile", "metric": "serving.request_ms",
                "field": "p99", "op": "<=", "threshold": 500.0,
            },
            "budget": 0.02,
            "for_s": 60.0,
        },
        {
            "name": "serving-shed-rate",
            "severity": "page",
            "objective": {
                "kind": "ratio", "num": "serving.shed",
                "den": ["serving.rows", "serving.shed"],
            },
            "budget": 0.05,
            "for_s": 60.0,
        },
        {
            "name": "fleet-members-alive",
            "severity": "page",
            "objective": {
                "kind": "gauge", "metric": "fleet.members_alive",
                "op": ">=", "threshold": 1.0,
            },
            "budget": 0.05,
            "for_s": 30.0,
        },
        {
            "name": "breaker-open",
            "severity": "page",
            "objective": {
                "kind": "gauge", "metric": "breaker.open",
                "op": "<=", "threshold": 0.0,
            },
            "budget": 0.05,
            "for_s": 30.0,
        },
        {
            "name": "scheduler-churn",
            "severity": "warn",
            "objective": {
                "kind": "counter_delta",
                "metrics": ["scheduler.requeues", "scheduler.worker_deaths"],
                "threshold": 0.0,
            },
            "budget": 0.1,
        },
        {
            "name": "mfu-floor",
            "severity": "warn",
            "objective": {
                "kind": "index", "phase_prefix": "mfu.",
                "op": ">=", "threshold": 0.02, "agg": "mean",
            },
            "budget": 0.25,
        },
        {
            "name": "cost-model-drift",
            "severity": "warn",
            "objective": {
                "kind": "index", "phase_prefix": "audit.",
                "op": "<=", "threshold": 60.0, "agg": "mean",
            },
            "budget": 0.25,
        },
    ],
}


def rules_configured() -> bool:
    """Whether alerting is ON for this process (the no-op contract gate).

    True when ``TIP_ALERT_RULES`` names a source, or the standing
    ``$TIP_ASSETS/obs/slo_rules.json`` exists. One env read and at most
    one stat — cheap enough for every owner-loop tick.
    """
    raw = os.environ.get(RULES_ENV, "").strip()
    if raw.lower() in ("0", "off"):
        return False
    if raw:
        return True
    return os.path.isfile(default_rules_path())


def load_rules(raw: Optional[str] = None) -> Optional[dict]:
    """Resolve + validate the rule document; None when alerting is off.

    Failure-safe end to end: an unreadable file, corrupt JSON, a missing
    schema stamp, or a document with zero valid rules all log a warning
    and return None — a bad rule document must never crash the process
    it is supposed to watch.
    """
    if raw is None:
        raw = os.environ.get(RULES_ENV, "").strip()
    source = None
    if raw.lower() in ("0", "off"):
        return None
    if not raw:
        path = default_rules_path()
        if not os.path.isfile(path):
            return None
        raw, source = "@" + path, path
    if raw.lower() in ("builtin", "default"):
        doc, source = DEFAULT_RULES, "builtin"
    elif raw.lstrip().startswith("{"):
        source = "inline"
        try:
            doc = json.loads(raw)
        except ValueError as e:
            logger.warning("%s: inline rules are not JSON: %s", RULES_ENV, e)
            return None
    else:
        path = raw[1:] if raw.startswith("@") else raw
        source = source or path
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            logger.warning("%s: cannot read rules %s: %s", RULES_ENV, path, e)
            return None
    if not isinstance(doc, dict) or doc.get("schema") != SCHEMA:
        logger.warning(
            "%s (%s): rule document must carry \"schema\": %d",
            RULES_ENV, source, SCHEMA,
        )
        return None
    rules, problems = validate(doc.get("rules"))
    for p in problems:
        logger.warning("%s (%s): %s", RULES_ENV, source, p)
    if not rules:
        logger.warning("%s (%s): no valid rules; alerting off", RULES_ENV, source)
        return None
    return {"schema": SCHEMA, "source": str(source), "rules": rules}


def _num(v, default=None) -> Optional[float]:
    """``v`` as a float, or ``default`` (bools are not numbers here)."""
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return default
    return float(v)


def _norm_windows(spec) -> Optional[dict]:
    """Normalize a rule's window pair; None on an invalid spec."""
    spec = spec if isinstance(spec, dict) else {}
    out = {}
    for key in ("fast", "slow"):
        w = spec.get(key)
        w = w if isinstance(w, dict) else {}
        window_s = _num(w.get("window_s"), _DEFAULT_WINDOWS[key]["window_s"])
        burn = _num(w.get("burn"), _DEFAULT_WINDOWS[key]["burn"])
        if window_s is None or window_s <= 0 or burn is None or burn <= 0:
            return None
        out[key] = {"window_s": float(window_s), "burn": float(burn)}
    return out


def _norm_objective(obj) -> Tuple[Optional[dict], str]:
    """Normalize one objective dict; ``(None, reason)`` when invalid."""
    if not isinstance(obj, dict):
        return None, "objective must be a dict"
    kind = obj.get("kind")
    if kind not in KINDS:
        return None, f"unknown objective kind {kind!r} (known: {KINDS})"
    if kind in ("quantile", "gauge"):
        metric = obj.get("metric")
        threshold = _num(obj.get("threshold"))
        op = obj.get("op", "<=")
        if not metric or threshold is None or op not in OPS:
            return None, f"{kind} objective needs metric/op/threshold"
        out = {"kind": kind, "metric": str(metric), "op": op,
               "threshold": threshold}
        if kind == "quantile":
            field = obj.get("field", "p99")
            if field not in ("p50", "p95", "p99"):
                return None, f"quantile field must be p50/p95/p99, got {field!r}"
            out["field"] = field
        return out, ""
    if kind == "ratio":
        num = obj.get("num")
        den = obj.get("den") or ([num] if num else None)
        if not num or not isinstance(den, (list, tuple)) or not den:
            return None, "ratio objective needs num + den counter names"
        return {"kind": kind, "num": str(num),
                "den": [str(d) for d in den]}, ""
    if kind == "counter_delta":
        metrics = obj.get("metrics") or obj.get("metric")
        if isinstance(metrics, str):
            metrics = [metrics]
        if not isinstance(metrics, (list, tuple)) or not metrics:
            return None, "counter_delta objective needs metrics"
        return {"kind": kind, "metrics": [str(m) for m in metrics],
                "threshold": _num(obj.get("threshold"), 0.0)}, ""
    # index: a cross-process feature-store aggregate
    prefix = obj.get("phase_prefix")
    threshold = _num(obj.get("threshold"))
    op = obj.get("op", "<=")
    agg = obj.get("agg", "mean")
    if not prefix or threshold is None or op not in OPS:
        return None, "index objective needs phase_prefix/op/threshold"
    if agg not in ("mean", "max", "min", "last"):
        return None, f"index agg must be mean/max/min/last, got {agg!r}"
    return {"kind": kind, "phase_prefix": str(prefix), "op": op,
            "threshold": threshold, "agg": agg}, ""


def validate(rules) -> Tuple[List[dict], List[str]]:
    """Normalize a rule list; ``(valid_rules, problem_strings)``.

    Bad rules are dropped and described, valid siblings survive — the
    partial-tolerance contract every obs reader follows.
    """
    out: List[dict] = []
    problems: List[str] = []
    seen = set()
    for i, rule in enumerate(rules if isinstance(rules, list) else []):
        label = f"rule[{i}]"
        if not isinstance(rule, dict):
            problems.append(f"{label}: not a dict")
            continue
        name = rule.get("name")
        if not name or not isinstance(name, str):
            problems.append(f"{label}: missing name")
            continue
        label = f"rule {name!r}"
        if name in seen:
            problems.append(f"{label}: duplicate name")
            continue
        obj, reason = _norm_objective(rule.get("objective"))
        if obj is None:
            problems.append(f"{label}: {reason}")
            continue
        budget = _num(rule.get("budget"))
        if budget is None or not 0.0 < budget <= 1.0:
            problems.append(f"{label}: budget must be in (0, 1]")
            continue
        windows = _norm_windows(rule.get("windows"))
        if windows is None:
            problems.append(f"{label}: windows need positive window_s + burn")
            continue
        severity = rule.get("severity", "page")
        if severity not in SEVERITIES:
            problems.append(f"{label}: severity must be page|warn")
            continue
        for_s = _num(rule.get("for_s"), 0.0)
        seen.add(name)
        out.append(
            {
                "name": name,
                "severity": severity,
                "objective": obj,
                "budget": budget,
                "windows": windows,
                "for_s": max(0.0, for_s),
            }
        )
    return out, problems


# -- sampling + burn math --------------------------------------------------


def _good(value: float, op: str, threshold: float) -> bool:
    """Whether ``value`` satisfies the objective's good condition."""
    if op == "<=":
        return value <= threshold
    if op == ">=":
        return value >= threshold
    if op == "<":
        return value < threshold
    return value > threshold


def sample_rule(
    rule: dict,
    snap: dict,
    prev_counters: Optional[dict] = None,
    index_rows: Optional[Sequence[dict]] = None,
) -> Optional[dict]:
    """One evaluation tick of ``rule`` against a metrics snapshot.

    Returns ``{"value": float, "bad": 0.0..1.0}`` — ``bad`` is the tick's
    error fraction (a hard breach is 1.0; a ``ratio`` objective's bad IS
    the observed rate) — or None when the rule has no data this tick (a
    quantile never observed, a counter pair that didn't move, an empty
    index): no sample, no budget spent, no alert.
    """
    obj = rule["objective"]
    kind = obj["kind"]
    if kind == "quantile":
        fam = (snap.get("quantiles") or {}).get(obj["metric"])
        v = _num(fam.get(obj["field"])) if isinstance(fam, dict) else None
        if v is None:
            return None
        return {"value": v,
                "bad": 0.0 if _good(v, obj["op"], obj["threshold"]) else 1.0}
    if kind == "gauge":
        v = _num((snap.get("gauges") or {}).get(obj["metric"]))
        if v is None:
            return None
        return {"value": v,
                "bad": 0.0 if _good(v, obj["op"], obj["threshold"]) else 1.0}
    cur = snap.get("counters") or {}
    if kind == "ratio":
        if prev_counters is None:
            return None  # first tick: no delta window yet
        num_d = max(0.0, _num(cur.get(obj["num"]), 0.0)
                    - _num(prev_counters.get(obj["num"]), 0.0))
        den_d = sum(
            max(0.0, _num(cur.get(d), 0.0) - _num(prev_counters.get(d), 0.0))
            for d in obj["den"]
        )
        if den_d <= 0:
            return None  # no traffic between ticks: nothing to grade
        frac = max(0.0, min(1.0, num_d / den_d))
        return {"value": frac, "bad": frac}
    if kind == "counter_delta":
        if prev_counters is None:
            return None
        delta = sum(
            max(0.0, _num(cur.get(m), 0.0) - _num(prev_counters.get(m), 0.0))
            for m in obj["metrics"]
        )
        return {"value": delta,
                "bad": 0.0 if delta <= obj["threshold"] else 1.0}
    # index: newest cross-process rows under the phase prefix
    vals = []
    for row in index_rows or []:
        phase = str(row.get("phase") or "")
        if not phase.startswith(obj["phase_prefix"]):
            continue
        v = _num(row.get("value"))
        if v is None:
            v = _num(row.get("seconds"))
        if v is not None:
            vals.append(v)
    if not vals:
        return None
    if obj["agg"] == "mean":
        v = sum(vals) / len(vals)
    elif obj["agg"] == "max":
        v = max(vals)
    elif obj["agg"] == "min":
        v = min(vals)
    else:
        v = vals[-1]
    return {"value": v,
            "bad": 0.0 if _good(v, obj["op"], obj["threshold"]) else 1.0}


def burn_rate(
    samples: Sequence[Sequence[float]],
    now: float,
    window_s: float,
    budget: float,
) -> Optional[float]:
    """Budget burn over the trailing window: mean(bad) / budget.

    ``samples`` is the rule's ``[ts, bad]`` ring (ts-ascending). None when
    the window holds no samples — an idle rule burns nothing. Burn 1.0
    spends the error budget exactly as fast as it accrues; the thresholds
    in the rule's window pair are multiples of that.
    """
    lo = now - window_s
    window = [s[1] for s in samples if s[0] > lo and s[0] <= now]
    if not window:
        return None
    return (sum(window) / len(window)) / budget


def prune_samples(
    samples: List, now: float, keep_s: float, cap: int = 2048
) -> List:
    """Drop samples older than ``keep_s`` (and hard-cap the ring size)."""
    lo = now - keep_s
    out = [s for s in samples if s[0] > lo]
    return out[-cap:]


def write_default_rules(path: Optional[str] = None) -> str:
    """Materialize :data:`DEFAULT_RULES` at ``path`` (atomic); the path.

    The operator bootstrap (RUNBOOK §11): write the bundled document to
    ``$TIP_ASSETS/obs/slo_rules.json``, edit budgets/thresholds in place.
    """
    path = path or default_rules_path()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(DEFAULT_RULES, f, indent=2, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path

"""Telemetry feature store: every run's cost features in one queryable index.

obs v2 made the pipeline emit exactly the features a learned performance
model wants — per-phase durations, ``jax.compiles`` counts, device-memory
high-water, health counters, degraded/breaker state — but they were
write-only: each study's trace sat in its own ``$TIP_ASSETS/obs/<run_ts>``
directory, each bench round in its own ``BENCH_r*.json``, and nothing could
query "what did test_prio cost per run, historically". This module is the
read side: it walks those sources, normalizes each into **one
schema-versioned feature row per (run, phase)**, and persists the rows as
an append-only JSONL index under ``$TIP_ASSETS/obs/index/`` (override with
``TIP_OBS_INDEX``) with incremental refresh — a source whose (mtime, size)
already matches its manifest entry is skipped, so re-indexing after a study
only pays for the new run.

Row schema (``schema`` is the version stamp; the ``unversioned-schema``
tiplint rule enforces that every obs JSONL writer carries one):

- identity: ``schema``, ``kind`` (``obs_run`` | ``bench`` | ``host_phase``
  | ``multichip`` | ``mfu_breakdown``), ``source`` (path), ``seq``
  (append batch, newest wins),
  ``run`` (model id / round / capture label; None for aggregates),
  ``phase`` (span name / bench metric);
- target: ``seconds`` (what the cost model fits) or ``value`` (bench
  throughput, higher-is-better);
- features: ``count``, ``platform``, ``degraded``, ``batch``, ``workers``,
  ``group`` (cross-run dispatch-fusion group size; None on ungrouped
  sources — ``costmodel._features`` treats it as 1),
  ``compiles``, ``device_peak_bytes``, ``health`` (summed health counters),
  ``case_study``, ``captured`` (epoch seconds when the source states one),
  ``plan`` (the ExecutionPlan id the run executed under, ``"unplanned"``
  when a record says so explicitly, None for sources predating the stamp).

Consumers: ``obs runs`` (the table/JSON reporter in ``obs/cli.py``),
``obs/costmodel.py`` (features → phase seconds), and ``obs trend`` when
gating from the index. Stdlib-only: the index is built and queried in the
tier-0 CI gate with no jax/numpy installed.
"""

import json
import os
import time

from simple_tip_tpu.obs import regress as _regress

#: Feature-row schema version. Bump when a row's field semantics change;
#: readers skip rows whose stamp they do not understand.
SCHEMA = 1

#: Env var overriding the index directory (default ``$TIP_ASSETS/obs/index``).
INDEX_ENV = "TIP_OBS_INDEX"

#: Span names that are per-run work units: their ``attrs.phase`` is the
#: phase identity and ``attrs.model_id`` the run identity.
_RUN_SPAN = "run"

#: Repo-root record files swept by source discovery, by prefix.
_RECORD_PREFIXES = (
    ("BENCH_r", "bench"),
    ("MULTICHIP_r", "multichip"),
    ("MFU_BREAKDOWN", "mfu_breakdown"),
)


def default_index_dir() -> str:
    """The index directory: ``TIP_OBS_INDEX`` or ``$TIP_ASSETS/obs/index``."""
    raw = os.environ.get(INDEX_ENV, "").strip()
    if raw:
        return os.path.abspath(raw)
    assets = os.environ.get("TIP_ASSETS", os.path.join(os.getcwd(), "assets"))
    return os.path.join(os.path.abspath(assets), "obs", "index")


def _is_obs_run_dir(path: str) -> bool:
    """Whether ``path`` holds at least one ``events-*.jsonl`` stream."""
    try:
        return any(
            n.startswith("events-") and n.endswith(".jsonl")
            for n in os.listdir(path)
        )
    except OSError:
        return False


def _classify_file(path: str):
    """Source kind of a ``.json``/``.jsonl`` file path, or None."""
    name = os.path.basename(path)
    if name.startswith("events-") and name.endswith(".jsonl"):
        return "obs_run"  # a bare stream file: treat its parent as the run
    if not name.endswith(".json"):
        return None
    for prefix, kind in _RECORD_PREFIXES:
        if name.startswith(prefix):
            return kind
    if name == "HOST_PHASE.json":
        return "host_phase"
    # Unprefixed fixture/bench records (tests/fixtures/obs_trend/t01.json,
    # a bare bench.py line saved to disk) classify by content.
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if isinstance(doc, dict):
        if isinstance(doc.get("parsed"), dict):
            doc = doc["parsed"]
        if doc.get("kind") == "mfu_breakdown":
            return "mfu_breakdown"
        if "metric" in doc and "value" in doc:
            return "bench"
        # Renamed HOST_PHASE captures (trend fixtures, archived
        # trajectories) classify by the same keys regress.load_snapshot
        # dispatches on.
        if "test_prio_s" in doc or "sa_setup" in doc:
            return "host_phase"
    return None


def discover_sources(roots) -> list:
    """``roots`` (dirs/files) -> sorted [(kind, abspath)] of indexable sources.

    A directory is scanned one level deep: obs run dirs (any subdirectory
    holding ``events-*.jsonl``, including the root itself), plus
    ``BENCH_r*.json`` / ``HOST_PHASE.json`` / ``MULTICHIP_r*.json`` /
    ``MFU_BREAKDOWN*.json`` / recognizable bench-record files directly
    inside it. The index directory
    itself is never a source (the store must not eat its own output).
    """
    found = {}
    index_dir = os.path.abspath(default_index_dir())
    for root in roots:
        root = os.path.abspath(root)
        if not os.path.exists(root):
            continue
        if os.path.isfile(root):
            kind = _classify_file(root)
            if kind == "obs_run":
                found[os.path.dirname(root)] = "obs_run"
            elif kind:
                found[root] = kind
            continue
        if _is_obs_run_dir(root):
            found[root] = "obs_run"
        try:
            entries = sorted(os.listdir(root))
        except OSError:
            continue
        for name in entries:
            path = os.path.join(root, name)
            if path == index_dir:
                continue
            if os.path.isdir(path):
                if _is_obs_run_dir(path):
                    found[path] = "obs_run"
                continue
            kind = _classify_file(path)
            if kind and kind != "obs_run":
                found[path] = kind
    return sorted((kind, path) for path, kind in found.items())


def _blank_row(kind: str, source: str, seq: int) -> dict:
    """A feature-row skeleton with every schema field present."""
    return {
        "schema": SCHEMA,
        "kind": kind,
        "source": source,
        "seq": seq,
        "run": None,
        "phase": None,
        "seconds": None,
        "value": None,
        "count": 1,
        "platform": None,
        "degraded": None,
        "batch": None,
        "workers": None,
        "group": None,
        "compiles": None,
        "device_peak_bytes": None,
        "health": None,
        "case_study": None,
        "captured": None,
        "plan": None,
    }


def _health_sum(counters: dict) -> float:
    """Summed health-counter value of a counters dict (regress's list)."""
    return float(
        sum(
            v
            for k, v in (counters or {}).items()
            if isinstance(v, (int, float)) and _regress._is_health_counter(k)
        )
    )


def _rows_from_obs_run(path: str, seq: int) -> list:
    """Feature rows of one obs run directory (span streams)."""
    from simple_tip_tpu.obs.cli import _summed_counters, load_events

    events, files, _bad = load_events(path)
    if not files:
        return []
    counters = _summed_counters(events)
    compiles = counters.get("jax.compiles")
    health = _health_sum(counters)
    degraded = bool(counters.get("breaker.degraded", 0))
    platform_by_pid = {}
    peak = None
    for rec in events:
        if rec.get("type") == "meta" and rec.get("platform"):
            platform_by_pid[rec.get("pid")] = str(rec["platform"])
        if rec.get("type") == "metrics":
            for name, v in (rec.get("gauges") or {}).items():
                if name.endswith(".peak_bytes_in_use") and isinstance(
                    v, (int, float)
                ):
                    peak = max(peak or 0, int(v))

    def stamp(row, ts=None):
        row["compiles"] = compiles
        row["health"] = health
        row["device_peak_bytes"] = peak
        row["captured"] = ts
        if row["degraded"] is None:
            row["degraded"] = degraded
        return row

    rows = []
    agg = {}  # span name -> [count, total] for non-run, non-phase spans
    for rec in events:
        if rec.get("type") != "span":
            continue
        name = str(rec.get("name", "?"))
        dur = float(rec.get("dur", 0) or 0)
        attrs = rec.get("attrs") or {}
        if name == _RUN_SPAN and attrs.get("phase"):
            row = _blank_row("obs_run", path, seq)
            row["run"] = attrs.get("model_id")
            row["phase"] = str(attrs["phase"])
            row["seconds"] = round(dur, 6)
            row["platform"] = platform_by_pid.get(rec.get("pid"))
            row["case_study"] = attrs.get("case_study")
            rows.append(stamp(row, rec.get("ts")))
        elif name == "scheduler.phase" and attrs.get("phase"):
            row = _blank_row("obs_run", path, seq)
            row["phase"] = f"scheduler.{attrs['phase']}"
            row["seconds"] = round(dur, 6)
            row["count"] = attrs.get("runs", 1)
            row["workers"] = attrs.get("workers")
            row["case_study"] = attrs.get("case_study")
            row["plan"] = attrs.get("plan")
            rows.append(stamp(row, rec.get("ts")))
            # Plan-vs-actual audit row (obs v4): when the scheduler stamped
            # a cost-model prediction next to the measured duration, the
            # grading error becomes its own feature — ``seconds`` is the
            # absolute error, ``value`` the signed relative error — so
            # `obs audit`/`obs trend` can gate cost-model drift from the
            # same index that feeds the model.
            pred = attrs.get("predicted_s")
            act = attrs.get("actual_s")
            if isinstance(pred, (int, float)) and isinstance(act, (int, float)):
                arow = _blank_row("obs_run", path, seq)
                arow["phase"] = f"audit.{attrs['phase']}"
                arow["seconds"] = round(abs(float(act) - float(pred)), 6)
                arow["value"] = (
                    round((float(act) - float(pred)) / float(pred), 6)
                    if pred
                    else None
                )
                arow["count"] = attrs.get("runs", 1)
                arow["workers"] = attrs.get("workers")
                arow["case_study"] = attrs.get("case_study")
                rows.append(stamp(arow, rec.get("ts")))
        else:
            # Prio-scoring spans carry a variant attr: split them into
            # per-variant features (sa_score.pc-mlsa, ...) so `obs predict`
            # learns the post-device-pipeline test_prio cost per variant
            # instead of one blended aggregate. Everything else aggregates
            # by bare span name as before.
            if name in ("sa_fit", "sa_score", "sa_cam") and attrs.get("variant"):
                name = f"{name}.{attrs['variant']}"
            cnt, tot = agg.get(name, (0, 0.0))
            agg[name] = (cnt + 1, tot + dur)
    for name, (cnt, tot) in sorted(agg.items()):
        row = _blank_row("obs_run", path, seq)
        row["phase"] = name
        row["seconds"] = round(tot, 6)
        row["count"] = cnt
        rows.append(stamp(row))
    return rows


def _rows_from_bench(path: str, seq: int) -> list:
    """Feature rows of one bench record / ``BENCH_r*.json`` wrapper."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return []
    if not isinstance(doc, dict):
        return []
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    if not isinstance(doc, dict) or "value" not in doc:
        return []
    run = os.path.splitext(os.path.basename(path))[0]
    counters = (doc.get("obs_metrics") or {}).get("counters") or {}

    # Device-memory high-water: bench.py calls record_device_memory()
    # before snapshotting metrics, so the gauges carry the same
    # ``.peak_bytes_in_use`` series obs run dirs do — parsed here so
    # committed bench records can train the planner's memory model.
    peak = None
    for name, v in ((doc.get("obs_metrics") or {}).get("gauges") or {}).items():
        if name.endswith(".peak_bytes_in_use") and isinstance(v, (int, float)):
            peak = max(peak or 0, int(v))

    def base():
        row = _blank_row("bench", path, seq)
        row["run"] = run
        row["platform"] = doc.get("platform")
        row["degraded"] = bool(doc.get("degraded", False))
        row["batch"] = doc.get("batch")
        row["compiles"] = counters.get("jax.compiles")
        row["health"] = _health_sum(counters)
        row["captured"] = doc.get("captured_unix")
        row["device_peak_bytes"] = peak
        row["plan"] = doc.get("plan")
        return row

    rows = []
    row = base()
    row["phase"] = str(doc.get("metric", "bench.value"))
    try:
        row["value"] = float(doc.get("value") or 0)
    except (TypeError, ValueError):
        row["value"] = 0.0
    rows.append(row)
    sa = doc.get("sa_fit_seconds") or {}
    if isinstance(sa.get("total"), (int, float)):
        row = base()
        row["phase"] = "sa_fit.total"
        row["seconds"] = float(sa["total"])
        rows.append(row)
    for variant, secs in sorted((sa.get("by_variant") or {}).items()):
        if isinstance(secs, (int, float)):
            row = base()
            row["phase"] = f"sa_fit.{variant}"
            row["seconds"] = float(secs)
            rows.append(row)
    if isinstance(doc.get("obs_overhead_seconds"), (int, float)):
        row = base()
        row["phase"] = "obs.overhead_per_1k_spans"
        row["seconds"] = float(doc["obs_overhead_seconds"])
        rows.append(row)
    # Grouped-chain companion: the G sweep becomes group-featured rows —
    # the walk seconds train the cost model's log(group) coefficient
    # (count = G x inputs, so seconds/count is per MODEL-input and the
    # planner's coordinate descent can rank G), and the analytic host
    # bytes/input rides as a value row the trend gate watches (the
    # 68 B/input claim for the 12-metric chain).
    grouped = doc.get("grouped_chain") or {}
    if isinstance(grouped, dict) and "error" not in grouped:
        if isinstance(grouped.get("host_bytes_per_input"), (int, float)):
            row = base()
            row["phase"] = "grouped_chain.host_bytes_per_input"
            row["value"] = float(grouped["host_bytes_per_input"])
            rows.append(row)
        n_inputs = grouped.get("n_inputs")
        for g_label, entry in sorted((grouped.get("sweep") or {}).items()):
            if not isinstance(entry, dict):
                continue
            try:
                g = int(g_label)
            except ValueError:
                continue
            if isinstance(entry.get("walk_seconds"), (int, float)) and \
                    isinstance(n_inputs, (int, float)) and n_inputs > 0:
                row = base()
                row["phase"] = "grouped_chain.walk"
                row["seconds"] = float(entry["walk_seconds"])
                row["count"] = int(g * n_inputs)
                row["group"] = g
                row["batch"] = grouped.get("badge_size") or row["batch"]
                rows.append(row)
            for field in ("inputs_per_sec", "dispatches_per_badge"):
                if isinstance(entry.get(field), (int, float)):
                    row = base()
                    row["phase"] = f"grouped_chain.{field}"
                    row["value"] = float(entry[field])
                    row["group"] = g
                    rows.append(row)
    # Devicemeter companion: the record's headline MFU plus any per-program
    # grades ride as ``mfu.*`` value rows — the cost-analysis features the
    # costmodel corpus can learn utilization terms from.
    if isinstance(doc.get("mfu"), (int, float)) and doc["mfu"] > 0:
        row = base()
        row["phase"] = "mfu"
        row["value"] = float(doc["mfu"])
        rows.append(row)
    for section in ("fused_chain", "grouped_chain"):
        programs = (doc.get(section) or {}).get("device_cost") or {}
        if not isinstance(programs, dict):
            continue
        for prog, graded in sorted(programs.items()):
            if isinstance(graded, dict) and isinstance(
                graded.get("mfu"), (int, float)
            ):
                row = base()
                row["phase"] = f"mfu.{prog}"
                row["value"] = float(graded["mfu"])
                rows.append(row)
    # Serving companion (schema 1): per-arrival-rate SLO features so the
    # learned cost model and the trend gate see the online path.
    serving = doc.get("serving") or {}
    for label, rate in sorted((serving.get("rates") or {}).items()):
        if not isinstance(rate, dict):
            continue
        for field, phase, as_seconds in (
            ("p99_ms", "p99", True),  # ms -> seconds, like every phase row
            ("sustained_inputs_per_s", "sustained_inputs_per_s", False),
            ("badge_fill", "badge_fill", False),
        ):
            v = rate.get(field)
            if not isinstance(v, (int, float)):
                continue
            row = base()
            row["phase"] = f"serving.{phase}.{label}"
            if as_seconds:
                row["seconds"] = float(v) / 1000.0
            else:
                row["value"] = float(v)
            rows.append(row)
    return rows


def _rows_from_host_phase(path: str, seq: int) -> list:
    """Feature rows of a ``HOST_PHASE.json`` capture (plus its history)."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return []
    if not isinstance(doc, dict):
        return []
    rows = []

    def add(run, phase, seconds):
        if not isinstance(seconds, (int, float)):
            return
        row = _blank_row("host_phase", path, seq)
        row["run"] = run
        row["phase"] = phase
        row["seconds"] = float(seconds)
        row["platform"] = doc.get("platform")
        rows.append(row)

    add("current", "test_prio", doc.get("test_prio_s"))
    add("current", "train_1epoch", doc.get("train_1epoch_s"))
    for label, hist in sorted((doc.get("history") or {}).items()):
        if not isinstance(hist, dict):
            continue
        tp = hist.get("test_prio_s")
        if isinstance(tp, dict):  # oldest capture nests per-backend numbers
            tp = tp.get("auto_backend_sklearn_on_cpu")
        add(label, "test_prio", tp)
        add(label, "train_1epoch", hist.get("train_1epoch_s"))
    return rows


def _multichip_stamp(doc: dict) -> dict:
    """The ``MULTICHIP_STAMP`` payload the dryrun printed, if any.

    MULTICHIP records are composed by the external driver from the dryrun
    process's exit code and stdout tail, so the degradation/breaker state
    travels as a ``MULTICHIP_STAMP: {json}`` line inside ``tail`` (the
    same at-the-source stamping bench records get directly). ``tail`` may
    be one string or a list of lines; the last parseable stamp wins.
    """
    tail = doc.get("tail")
    lines = []
    if isinstance(tail, str):
        lines = tail.splitlines()
    elif isinstance(tail, (list, tuple)):
        lines = [line for line in tail if isinstance(line, str)]
    stamp = {}
    for line in lines:
        marker = line.find("MULTICHIP_STAMP:")
        if marker < 0:
            continue
        try:
            parsed = json.loads(line[marker + len("MULTICHIP_STAMP:"):])
        except ValueError:
            continue
        if isinstance(parsed, dict):
            stamp = parsed
    return stamp


def _rows_from_multichip(path: str, seq: int) -> list:
    """One summary row per ``MULTICHIP_r*.json`` capture."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return []
    if not isinstance(doc, dict):
        return []
    row = _blank_row("multichip", path, seq)
    row["run"] = os.path.splitext(os.path.basename(path))[0]
    row["phase"] = "multichip.capture"
    row["count"] = doc.get("n_devices", 1)
    # Degraded iff the capture failed OR the dryrun stamped a degradation
    # (CPU fallback, open breaker) — stamps ride ``tail`` (see above), but
    # explicit top-level keys from a newer driver win over the parse.
    stamp = _multichip_stamp(doc)
    reason = doc.get("degraded_reason", stamp.get("degraded_reason"))
    breaker = doc.get("breaker", stamp.get("breaker"))
    breaker_open = isinstance(breaker, dict) and breaker.get("state") == "open"
    row["degraded"] = (
        not bool(doc.get("ok", False))
        or bool(stamp.get("degraded"))
        or bool(reason)
        or breaker_open
    )
    return [row]


def _rows_from_mfu_breakdown(path: str, seq: int) -> list:
    """Feature rows of one ``MFU_BREAKDOWN.json`` device-cost capture:
    one ``mfu.<program>`` value row (the trend-gated floor feature) plus
    one ``dispatch.<program>`` seconds row per graded program. Grouped
    G-sweep entries carry their ``models_per_dispatch`` as ``group``."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return []
    if not isinstance(doc, dict) or doc.get("kind") != "mfu_breakdown":
        return []
    run = os.path.splitext(os.path.basename(path))[0]
    rows = []

    def base():
        row = _blank_row("mfu_breakdown", path, seq)
        row["run"] = run
        row["platform"] = doc.get("platform")
        row["degraded"] = bool(doc.get("degraded", False))
        row["captured"] = doc.get("captured_unix")
        return row

    for prog, entry in sorted((doc.get("programs") or {}).items()):
        if not isinstance(entry, dict):
            continue
        graded = entry.get("grade") or {}
        cost = entry.get("cost") or {}
        group = entry.get("models_per_dispatch")
        dispatch = entry.get("dispatch_s") or {}
        if isinstance(graded.get("mfu"), (int, float)):
            row = base()
            row["phase"] = f"mfu.{prog}"
            row["value"] = float(graded["mfu"])
            row["group"] = group
            if isinstance(cost.get("peak_memory_bytes"), (int, float)):
                row["device_peak_bytes"] = int(cost["peak_memory_bytes"])
            rows.append(row)
        p50 = dispatch.get("p50", dispatch.get("mean"))
        if isinstance(p50, (int, float)):
            row = base()
            row["phase"] = f"dispatch.{prog}"
            row["seconds"] = float(p50)
            row["count"] = dispatch.get("count", 1)
            row["group"] = group
            rows.append(row)
    return rows


_NORMALIZERS = {
    "obs_run": _rows_from_obs_run,
    "bench": _rows_from_bench,
    "host_phase": _rows_from_host_phase,
    "multichip": _rows_from_multichip,
    "mfu_breakdown": _rows_from_mfu_breakdown,
}


def _source_stat(kind: str, path: str):
    """Change-detection fingerprint of a source: (mtime, size).

    For run directories the newest stream's mtime and the summed stream
    size stand in, so an appended event re-triggers normalization.
    """
    try:
        if kind == "obs_run":
            mtime, size = 0.0, 0
            for n in os.listdir(path):
                if n.startswith("events-") and n.endswith(".jsonl"):
                    st = os.stat(os.path.join(path, n))
                    mtime = max(mtime, st.st_mtime)
                    size += st.st_size
            return round(mtime, 6), size
        st = os.stat(path)
        return round(st.st_mtime, 6), st.st_size
    except OSError:
        return None


def _index_paths(index_dir: str):
    """(rows JSONL path, manifest path) of ``index_dir``."""
    return (
        os.path.join(index_dir, "index.jsonl"),
        os.path.join(index_dir, "manifest.json"),
    )


def _load_manifest(manifest_path: str) -> dict:
    """The manifest document, or a fresh skeleton when absent/corrupt."""
    try:
        with open(manifest_path, encoding="utf-8") as f:
            doc = json.load(f)
        if isinstance(doc, dict) and isinstance(doc.get("sources"), dict):
            return doc
    except (OSError, ValueError):
        pass
    return {"schema": SCHEMA, "next_seq": 1, "sources": {}}


def refresh(roots, index_dir=None) -> dict:
    """Incrementally (re)index ``roots`` into ``index_dir``.

    Appends one batch of rows per new-or-changed source (the JSONL is
    append-only: a changed source gets fresh rows under a higher ``seq``
    and readers keep only the newest batch per source). Returns the
    refresh report: ``{index, sources, indexed, skipped, rows_appended,
    rows_total}``.
    """
    index_dir = os.path.abspath(index_dir or default_index_dir())
    rows_path, manifest_path = _index_paths(index_dir)
    os.makedirs(index_dir, exist_ok=True)
    manifest = _load_manifest(manifest_path)
    sources = discover_sources(roots)
    indexed, skipped, appended = [], 0, 0
    with open(rows_path, "a", encoding="utf-8") as f:
        for kind, path in sources:
            stat = _source_stat(kind, path)
            if stat is None:
                continue
            entry = manifest["sources"].get(path)
            if entry and entry.get("stat") == list(stat):
                skipped += 1
                continue
            seq = int(manifest.get("next_seq", 1))
            rows = _NORMALIZERS[kind](path, seq)
            for row in rows:
                f.write(json.dumps(row, sort_keys=True) + "\n")
            f.flush()
            appended += len(rows)
            manifest["next_seq"] = seq + 1
            manifest["sources"][path] = {
                "kind": kind,
                "stat": list(stat),
                "rows": len(rows),
                "seq": seq,
                "indexed_unix": round(time.time(), 1),
            }
            indexed.append(path)
    # pid-unique tmp + fsync before the replace: two indexers racing on a
    # shared ".tmp" would publish each other's torn manifest.
    tmp = f"{manifest_path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, manifest_path)
    return {
        "index": rows_path,
        "sources": len(sources),
        "indexed": indexed,
        "skipped": skipped,
        "rows_appended": appended,
        "rows_total": len(load_rows(index_dir)),
    }


def load_rows(index_dir=None) -> list:
    """The index's live feature rows (newest batch per source, seq-ordered).

    Torn tail lines (a kill mid-append) are skipped, never fatal; rows
    with an unknown ``schema`` stamp are skipped too.
    """
    index_dir = os.path.abspath(index_dir or default_index_dir())
    rows_path, _ = _index_paths(index_dir)
    rows = []
    try:
        with open(rows_path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if isinstance(row, dict) and row.get("schema") == SCHEMA:
                    rows.append(row)
    except OSError:
        return []
    latest_seq = {}
    for row in rows:
        src = row.get("source")
        latest_seq[src] = max(latest_seq.get(src, 0), int(row.get("seq", 0)))
    live = [r for r in rows if int(r.get("seq", 0)) == latest_seq[r.get("source")]]
    live.sort(key=lambda r: (int(r.get("seq", 0)), str(r.get("phase")), str(r.get("run"))))
    return live


#: ``rows_path -> ((path, mtime_ns, size), rows)``: one cached corpus per
#: index file, invalidated by stat. The planner scores hundreds of
#: candidates and the obs CLI predicts in the same process — both read
#: through here instead of re-walking the JSONL per call.
_corpus_cache: dict = {}


def load_corpus(index_dir=None) -> list:
    """``load_rows`` with a stat-keyed cache (treat the result read-only).

    The planner (``plan/search.py``), ``obs predict`` and
    ``costmodel.quick_phase_estimate`` all share one parse of the index
    per (mtime, size); a ``refresh`` that appends rows changes the stat
    and invalidates naturally. Callers must not mutate the returned list.
    """
    index_dir = os.path.abspath(index_dir or default_index_dir())
    rows_path, _ = _index_paths(index_dir)
    try:
        st = os.stat(rows_path)
    except OSError:
        _corpus_cache.pop(rows_path, None)
        return []
    key = (rows_path, st.st_mtime_ns, st.st_size)
    cached = _corpus_cache.get(rows_path)
    if cached is not None and cached[0] == key:
        return cached[1]
    rows = load_rows(index_dir)
    _corpus_cache[rows_path] = (key, rows)
    return rows


def render_rows(rows, limit=None) -> str:
    """The index as a deterministic text table (the ``obs runs`` reporter)."""
    out = [
        f"  {'kind':<10} {'source':<34} {'run':<10} {'phase':<28} "
        f"{'seconds':>10} {'value':>12} {'platform':>8}  degraded"
    ]
    shown = rows if limit is None else rows[-limit:]
    for r in shown:
        src = os.path.basename(str(r.get("source", "")))[:34]
        secs = r.get("seconds")
        val = r.get("value")
        out.append(
            f"  {str(r.get('kind', '')):<10} {src:<34} "
            f"{str(r.get('run', '-'))[:10]:<10} "
            f"{str(r.get('phase', '-'))[:28]:<28} "
            f"{(f'{secs:.3f}' if isinstance(secs, (int, float)) else '-'):>10} "
            f"{(f'{val:.1f}' if isinstance(val, (int, float)) else '-'):>12} "
            f"{str(r.get('platform') or '-'):>8}  "
            f"{r.get('degraded')}"
        )
    if limit is not None and len(rows) > limit:
        out.append(f"  ... ({len(rows) - limit} earlier rows not shown)")
    out.append(f"  rows: {len(rows)}")
    return "\n".join(out)

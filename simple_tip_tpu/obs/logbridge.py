"""Worker log routing: spawned children's ``logger.*`` must land somewhere.

``run_scheduler`` workers are fresh spawned interpreters with NO logging
configuration, so every ``logger.info/warning/error`` in the phase code
(scheduler claims, SA cache hits, watchdog fallbacks) was silently dropped.
``install_worker_logging`` gives each worker two sinks:

1. stderr, with a ``[pid/worker-idx]`` prefix so interleaved worker output
   stays attributable when the parent's console multiplexes children;
2. the obs event stream (``type: "log"`` records), when ``TIP_OBS_DIR`` is
   set — so the run-inspection CLI can show each worker's log tail next to
   its spans.

Idempotent per process (re-install is a no-op), and the obs sink guards
against recursion (a log record emitted while writing a log record is
dropped, not looped).
"""

import logging
import os
import threading
import time

from simple_tip_tpu.obs import tracer

_installed = False
_in_emit = threading.local()


class ObsLogHandler(logging.Handler):
    """Route log records into the obs JSONL stream as ``log`` events."""

    def emit(self, record):
        """Write one ``log`` event; recursion- and failure-safe."""
        if getattr(_in_emit, "on", False) or not tracer.enabled():
            return
        _in_emit.on = True
        try:
            tracer.write(
                {
                    "type": "log",
                    "ts": time.time(),
                    "pid": os.getpid(),
                    "level": record.levelname,
                    "logger": record.name,
                    "msg": self.format(record),
                }
            )
        except Exception:  # noqa: BLE001 — logging must never raise
            pass
        finally:
            _in_emit.on = False


def install_worker_logging(worker: str = "", level=logging.INFO) -> None:
    """Install the worker log sinks on the root logger (idempotent).

    ``worker`` is the scheduler's worker index (defaults to the
    ``TIP_OBS_WORKER`` env var the scheduler stamps on spawn); it appears in
    the stderr prefix as ``[pid/worker-idx]``. Existing root handlers are
    left alone — in the parent process (which usually has its own logging
    config) this only ADDS the obs sink, it never reformats the console.
    """
    global _installed
    root = logging.getLogger()
    # Idempotence is decided by INSPECTING the root logger, not only the
    # module flag: a scheduler phase that requeues after a worker death (or
    # a test's reset_all()) may re-enter here in a process whose logger
    # already carries the bridge — adding a second ObsLogHandler would
    # duplicate every record in the event stream from then on.
    has_bridge = any(isinstance(h, ObsLogHandler) for h in root.handlers)
    if _installed or has_bridge:
        _installed = True
        return
    _installed = True
    worker = worker or os.environ.get("TIP_OBS_WORKER", "").strip()
    tag = f"[{os.getpid()}/{worker}]" if worker else f"[{os.getpid()}]"
    if root.level > level or root.level == logging.NOTSET:
        root.setLevel(level)
    if not root.handlers:
        stderr_handler = logging.StreamHandler()
        stderr_handler.setFormatter(
            logging.Formatter(f"{tag} %(levelname)s %(name)s: %(message)s")
        )
        root.addHandler(stderr_handler)
    if tracer.enabled():
        obs_handler = ObsLogHandler()
        obs_handler.setFormatter(logging.Formatter("%(message)s"))
        root.addHandler(obs_handler)


def reset() -> None:
    """Forget the installed state (test hook; handlers are not removed)."""
    global _installed
    _installed = False

"""Live telemetry plane: /healthz /metrics /slo /fleet /alerts (stdlib HTTP).

The rest of the obs stack is post-hoc — spans, the feature store and the
trend gates all read JSONL after a run finishes. But the fleet (leases,
heartbeats, coordinator handoff) and the serving engine (SLO quantiles,
breaker state, admission backlog) are long-lived processes whose state is
invisible exactly when an operator needs it: mid-study. This module is the
missing live surface — a daemon ``http.server`` thread any long-lived
process mounts via :func:`start`:

- ``/healthz``  process liveness + pushed component health (breaker /
  journal / scheduler / serving): HTTP 200 when every component is ok,
  503 otherwise — curlable by a load balancer or a watch loop;
- ``/metrics``  the in-memory metrics registry (counters, gauges,
  histograms, and the serving Quantile windows) rendered as Prometheus
  text exposition format — also the first network surface in front of
  the serving engine (the ROADMAP serving item's open boundary);
- ``/slo``      the serving engine's ``slo_snapshot()`` (JSON), when an
  engine registered itself;
- ``/fleet``    the coordinator-aggregated membership view (per-host
  heartbeat age + stale flag, lease epochs, in-flight units, straggler
  verdicts), when a fleet mounted it;
- ``/alerts``   the SLO evaluator's cached per-rule alert states, burn
  rates and open incidents (obs/alerts.py), when an evaluator mounted
  itself in this process.

Knob contract mirrors ``TIP_OBS_DIR`` (see tracer): ``TIP_OBS_HTTP``
unset / empty / ``0`` / ``off`` means NO-OP — no socket, no thread, no
overhead (pinned by tests/test_obs.py). ``TIP_OBS_HTTP=<port>`` binds
that port on 127.0.0.1; ``TIP_OBS_HTTP=auto`` binds an ephemeral port
(CI smoke). A bind failure (port taken by a sibling process) logs a
warning and disables the exporter — telemetry never takes the host down.

Design invariant, enforced by the ``blocking-endpoint`` tiplint rule:
HTTP handler bodies read ONLY in-memory state. Health components are
PUSHED by their owning loops (:func:`set_health`); ``/slo`` and
``/fleet`` serve provider callables (:func:`set_provider`) that must
return cached in-memory views — the filesystem walks behind the fleet
view happen on the scheduler's beat cadence, never in a request thread.

Stdlib-only, zero third-party dependencies, like the rest of obs.
"""

import json
import logging
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from simple_tip_tpu.obs import metrics

# Version stamp on the /healthz JSON body: scrapers archive health
# snapshots next to obs stream rows, so the doc outlives this writer.
SCHEMA = 1

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_server: Optional[ThreadingHTTPServer] = None
_thread: Optional[threading.Thread] = None
_pid: Optional[int] = None  # owner pid: a spawned child must not reuse it
_started_monotonic: Optional[float] = None
# Route providers ("slo", "fleet") and pushed health components. Plain
# dicts mutated under the GIL: handler threads only .get()/iterate copies.
_providers: Dict[str, Callable[[], dict]] = {}
_health: Dict[str, Dict] = {}

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")
ROUTES = ("/healthz", "/metrics", "/slo", "/fleet", "/alerts")


def _resolve_port() -> Optional[int]:
    """``TIP_OBS_HTTP`` as a bindable port, or None (disabled).

    Unset / empty / ``0`` / ``off`` disable the plane (the TIP_OBS_DIR
    no-op contract); ``auto`` means an ephemeral port (socket port 0);
    anything else must be an integer port. Invalid values warn and
    disable — a typo must not crash a study.
    """
    raw = os.environ.get("TIP_OBS_HTTP", "").strip().lower()
    if raw in ("", "0", "off"):
        return None
    if raw == "auto":
        return 0
    try:
        port = int(raw)
    except ValueError:
        logger.warning("TIP_OBS_HTTP=%r is not a port; exporter disabled", raw)
        return None
    if not 0 < port < 65536:
        logger.warning("TIP_OBS_HTTP=%r out of range; exporter disabled", raw)
        return None
    return port


def enabled() -> bool:
    """Whether the live plane is configured on (knob set to a port)."""
    return _resolve_port() is not None


def bound_port() -> Optional[int]:
    """The actually-bound port of this process's running exporter, or None."""
    with _lock:
        if _server is not None and _pid == os.getpid():
            return _server.server_address[1]
    return None


# -- rendering (module functions so handler bodies stay thin) --------------


def _san(name: str) -> str:
    """A metric name as a valid Prometheus identifier, ``tip_``-prefixed."""
    clean = _NAME_BAD.sub("_", str(name))
    if not clean or clean[0].isdigit():
        clean = "_" + clean
    return "tip_" + clean


def _fmt(v) -> str:
    """A sample value in Prometheus text format."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


def _help_line(fam: str, name: str) -> str:
    """One ``# HELP`` line for family ``fam`` (description from the
    registry; HELP text is single-line by the format's grammar)."""
    text = " ".join(metrics.help_text(name).split()) or name
    return f"# HELP {fam} {text}"


def render_metrics(snap: Optional[dict] = None) -> str:
    """The registry snapshot as Prometheus text exposition format.

    Counters become ``tip_<name>_total`` counter families; gauges map
    1:1; histograms (count/sum/min/max summaries) become a summary family
    plus ``_min``/``_max`` gauges; Quantile windows become summary
    families with ``quantile="0.5|0.95|0.99"`` labels. Non-numeric gauge
    values are skipped — the text format has no string samples. Every
    ``# TYPE`` is preceded by a ``# HELP`` with the family's registry
    description (``metrics.describe``/``help_text``), pinned by
    scripts/exporter_smoke.py's HELP/TYPE-pair check.
    """
    if snap is None:
        snap = metrics.snapshot()
    lines = [
        "# HELP tip_up exporter liveness (always 1 while serving)",
        "# TYPE tip_up gauge",
        "tip_up 1",
    ]
    for name, v in (snap.get("counters") or {}).items():
        if not isinstance(v, (int, float)):
            continue
        fam = _san(name) + "_total"
        lines.append(_help_line(fam, name))
        lines.append(f"# TYPE {fam} counter")
        lines.append(f"{fam} {_fmt(v)}")
    for name, v in (snap.get("gauges") or {}).items():
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        fam = _san(name)
        lines.append(_help_line(fam, name))
        lines.append(f"# TYPE {fam} gauge")
        lines.append(f"{fam} {_fmt(v)}")
    for name, h in (snap.get("histograms") or {}).items():
        if not isinstance(h, dict):
            continue
        fam = _san(name)
        lines.append(_help_line(fam, name))
        lines.append(f"# TYPE {fam} summary")
        lines.append(f"{fam}_count {_fmt(int(h.get('count') or 0))}")
        lines.append(f"{fam}_sum {_fmt(float(h.get('sum') or 0.0))}")
        for bound in ("min", "max"):
            if isinstance(h.get(bound), (int, float)):
                lines.append(
                    f"# HELP {fam}_{bound} {bound} observed by "
                    f"{metrics.help_text(name)}"
                )
                lines.append(f"# TYPE {fam}_{bound} gauge")
                lines.append(f"{fam}_{bound} {_fmt(h[bound])}")
    for name, q in (snap.get("quantiles") or {}).items():
        if not isinstance(q, dict):
            continue
        fam = _san(name)
        lines.append(_help_line(fam, name))
        lines.append(f"# TYPE {fam} summary")
        for label, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
            if isinstance(q.get(key), (int, float)):
                lines.append(f'{fam}{{quantile="{label}"}} {_fmt(q[key])}')
        lines.append(f"{fam}_count {_fmt(int(q.get('count') or 0))}")
    if _health:
        lines.append(
            "# HELP tip_health_ok pushed component health (1 ok, 0 failing)"
        )
        lines.append("# TYPE tip_health_ok gauge")
    for component, rec in sorted(_health.items()):
        lines.append(
            f'tip_health_ok{{component="{_NAME_BAD.sub("_", component)}"}} '
            f"{_fmt(bool(rec.get('ok')))}"
        )
    return "\n".join(lines) + "\n"


def render_healthz() -> dict:
    """The ``/healthz`` JSON body: overall verdict + pushed components."""
    components = {k: dict(v) for k, v in _health.items()}
    ok = all(bool(c.get("ok")) for c in components.values())
    uptime = (
        time.monotonic() - _started_monotonic
        if _started_monotonic is not None
        else None
    )
    return {
        "schema": SCHEMA,
        "ok": ok,
        "pid": os.getpid(),
        "uptime_s": round(uptime, 3) if uptime is not None else None,
        "components": components,
    }


# -- the HTTP surface ------------------------------------------------------


class _Handler(BaseHTTPRequestHandler):
    """Request handler for the live routes.

    Reads ONLY in-memory state (the pushed health dict, the metrics
    registry snapshot, provider-cached views) — the blocking-endpoint
    tiplint rule holds every handler body to that contract, because a
    filesystem walk or a jax call here would block the operator's curl
    behind exactly the wedge they are diagnosing.
    """

    server_version = "tip-obs-exporter/1"

    def _reply(self, status: int, body: str, ctype: str) -> None:
        """Send one complete response."""
        data = body.encode("utf-8")
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _reply_json(self, status: int, doc: dict) -> None:
        """Send one JSON response."""
        self._reply(
            status,
            json.dumps(doc, indent=2, sort_keys=True, default=repr) + "\n",
            "application/json",
        )

    def do_GET(self) -> None:  # noqa: N802 — http.server's casing
        """Serve one of the live routes from in-memory state."""
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        if path == "/healthz":
            doc = render_healthz()
            self._reply_json(200 if doc["ok"] else 503, doc)
        elif path == "/metrics":
            self._reply(200, render_metrics(), "text/plain; version=0.0.4")
        elif path in ("/slo", "/fleet", "/alerts"):
            provider = _providers.get(path[1:])
            if provider is None:
                self._reply_json(
                    404, {"error": f"no {path[1:]} provider mounted here"}
                )
                return
            try:
                doc = provider()
            except Exception as e:  # noqa: BLE001 — a bad provider must not kill the thread
                self._reply_json(500, {"error": repr(e)[:200]})
                return
            self._reply_json(200, doc if isinstance(doc, dict) else {"value": doc})
        else:
            self._reply_json(
                404, {"error": "unknown route", "routes": list(ROUTES)}
            )

    def log_message(self, fmt: str, *args) -> None:
        """Route http.server's per-request chatter to the debug log."""
        logger.debug("exporter: " + fmt, *args)


def start() -> Optional[int]:
    """Mount the live plane in this process (idempotent); the bound port.

    Returns None when ``TIP_OBS_HTTP`` is unset/off (the no-op contract),
    or when the bind fails (a sibling process already owns the port) —
    in both cases the caller proceeds exactly as before. A stale handle
    inherited across a fork is discarded, never reused: the server thread
    did not survive into the child.
    """
    global _server, _thread, _pid, _started_monotonic
    port = _resolve_port()
    if port is None:
        return None
    with _lock:
        if _server is not None and _pid == os.getpid():
            return _server.server_address[1]
        _server = None
        _thread = None
        try:
            server = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        except OSError as e:
            logger.warning(
                "TIP_OBS_HTTP=%s: bind failed (%s); exporter disabled in "
                "pid %d", os.environ.get("TIP_OBS_HTTP"), e, os.getpid(),
            )
            return None
        server.daemon_threads = True
        thread = threading.Thread(
            target=server.serve_forever,
            kwargs={"poll_interval": 0.2},
            name="tip-obs-exporter",
            daemon=True,
        )
        thread.start()
        _server, _thread, _pid = server, thread, os.getpid()
        _started_monotonic = time.monotonic()
        bound = server.server_address[1]
    logger.info(
        "obs exporter serving http://127.0.0.1:%d%s (pid %d)",
        bound, "|".join(ROUTES), os.getpid(),
    )
    return bound


def stop() -> None:
    """Shut the exporter down (idempotent; only the owning pid's server)."""
    global _server, _thread, _started_monotonic
    with _lock:
        server, thread = _server, _thread
        _server = _thread = None
        _started_monotonic = None
        owned = _pid == os.getpid()
    if server is not None and owned:
        try:
            server.shutdown()
            server.server_close()
        except Exception:  # noqa: BLE001 — teardown is best-effort
            pass
        if thread is not None:
            thread.join(timeout=5)


def set_provider(name: str, fn: Callable[[], dict]) -> None:
    """Register the ``/slo``, ``/fleet`` or ``/alerts`` body source.

    ``fn`` runs on a request thread and MUST be an in-memory read (a
    cached view, the metrics registry) — never filesystem or device work.
    """
    _providers[name] = fn


def clear_provider(name: str) -> None:
    """Drop a route provider (no-op when absent)."""
    _providers.pop(name, None)


def set_health(component: str, ok: bool, **details) -> None:
    """Push one component's health verdict into ``/healthz``.

    Owning loops (scheduler tick, fleet beat, serving scheduler) call
    this on their own cadence; the handler only reads the stored dict.
    Any component with ``ok=False`` turns ``/healthz`` into a 503.
    """
    _health[component] = {"ok": bool(ok), **details}


def clear_health(component: str) -> None:
    """Drop a pushed health component (no-op when absent)."""
    _health.pop(component, None)


def reset() -> None:
    """Test hook: stop the server and drop providers + health state."""
    stop()
    _providers.clear()
    _health.clear()

"""Device cost observatory: analytic FLOPs/bytes accounting per program.

The hot path is a handful of AOT-compiled programs (fused chain, grouped
G-chain, rank/select); the host-side telemetry plane times them but has
no idea how much *arithmetic* each dispatch represents. This module
closes that gap with three pieces, all stdlib-only so the meter math is
testable (and CI-smokable) without jax:

- **cost extraction** — ``extract_cost(compiled)`` pulls XLA's
  ``cost_analysis()`` (flops, bytes accessed) plus ``memory_analysis()``
  (peak memory) off a compiled executable, tolerating every historical
  shape of that API (dict, list-of-dicts, missing keys, hard failure on
  deserialized executables → ``None``). ``normalize_cost`` is the pure
  half, unit-tested on synthetic dicts.
- **peak tables + grading** — ``resolve_peaks`` maps (platform,
  device_kind) to peak FLOP/s and HBM bytes/s: a ``TIP_DEVICE_PEAKS``
  JSON env override first, then bundled defaults for TPU v4 and CPU.
  Unknown chips resolve to ``analytic_only=True`` — achieved FLOP/s and
  bytes/s are still reported (they need no peak), but MFU and the
  roofline verdict are withheld rather than silently graded against the
  wrong chip. ``grade(cost, dt_s, ...)`` turns one measured dispatch
  into achieved-FLOPs/s, achieved-HBM-GB/s, MFU, HBM fraction, and a
  compute-bound vs HBM-bound verdict (whichever roofline ceiling is
  closer).
- **live attribution** — ``record_program_cost`` keeps an in-process
  registry of per-program costs (stamped at AOT compile time by
  ``engine/run_program.py``, recovered from ProgramCache metadata on
  cache hits); ``observe_dispatch`` feeds per-program dispatch-latency
  Quantile windows plus MFU / bandwidth gauges into the metrics
  registry, so they flow to ``/metrics`` via the exporter and to
  ``obs roofline`` / ``obs trend`` via the stream.

``build_breakdown`` composes the schema-stamped ``MFU_BREAKDOWN.json``
document (per-program cost analysis × measured dispatch time) that
``scripts/healthy_window.py`` captures and ``obs/store.py`` /
``obs/regress.py`` consume.
"""

import json
import os
import threading
from typing import Dict, List, Optional, Tuple

from simple_tip_tpu import obs

SCHEMA = 1
KIND = "mfu_breakdown"

# Bundled peak table. Deliberately small: TPU v4 (the chip the study
# targets; bf16 matmul peak + HBM2 bandwidth) and a nominal CPU core
# (f32 FMA peak per core, single-socket DDR bandwidth). Anything else
# must come in through TIP_DEVICE_PEAKS or be graded analytic_only —
# a wrong peak table produces confidently-wrong MFU, which is worse
# than none.
_BUILTIN_PEAKS = {
    "v4": {
        "flops_per_s": 275e12,
        "hbm_bytes_per_s": 1228e9,
        "label": "tpu-v4-bf16",
    },
    "cpu": {
        "flops_per_s": 96e9,  # per core; scaled by ``cores``
        "hbm_bytes_per_s": 25.6e9,
        "label": "cpu-core-f32-nominal",
        "per_core_flops": True,
    },
}

_COST_KEY_ALIASES = {
    "flops": "flops",
    "bytes accessed": "bytes_accessed",
    "bytes_accessed": "bytes_accessed",
    "peak memory": "peak_memory_bytes",
    "peak_memory_bytes": "peak_memory_bytes",
    "optimal seconds": "optimal_seconds",
    "optimal_seconds": "optimal_seconds",
}

_lock = threading.Lock()
_program_costs: Dict[str, dict] = {}


# -- cost extraction ---------------------------------------------------------


def normalize_cost(raw) -> Optional[dict]:
    """Normalize one ``cost_analysis()`` result to canonical keys.

    Tolerates every shape the API has had: a dict, a list of per-device
    dicts (first entry wins), missing keys (→ absent, never KeyError),
    and junk values (non-numeric entries are dropped). Returns None when
    nothing usable survives.
    """
    if isinstance(raw, (list, tuple)):
        raw = raw[0] if raw else None
    if not isinstance(raw, dict):
        return None
    out = {}
    for key, value in raw.items():
        name = _COST_KEY_ALIASES.get(str(key).lower())
        if name is None:
            continue
        try:
            value = float(value)
        except (TypeError, ValueError):
            continue
        if value >= 0:
            out[name] = value
    return out or None


def extract_cost(compiled) -> Optional[dict]:
    """Best-effort analytic cost of one compiled executable.

    ``cost_analysis()`` can raise on deserialized executables (the
    ProgramCache-hit path recovers the cost from the entry's metadata
    instead) and ``memory_analysis()`` is optional everywhere — both are
    advisory, so every failure collapses to None/absent.
    """
    cost = None
    try:
        cost = normalize_cost(compiled.cost_analysis())
    except Exception:  # noqa: BLE001 — advisory, never load-bearing
        cost = None
    try:
        mem = compiled.memory_analysis()
        peak = 0.0
        for attr in (
            "argument_size_in_bytes",
            "output_size_in_bytes",
            "temp_size_in_bytes",
            "generated_code_size_in_bytes",
        ):
            val = getattr(mem, attr, None)
            if isinstance(val, (int, float)) and val > 0:
                peak += float(val)
        if peak > 0:
            cost = dict(cost or {})
            cost.setdefault("peak_memory_bytes", peak)
    except Exception:  # noqa: BLE001
        pass
    return cost


# -- peak tables -------------------------------------------------------------


def _env_peaks() -> Dict[str, dict]:
    """The ``TIP_DEVICE_PEAKS`` override table: a JSON object mapping a
    lowercase device-kind substring (or platform name) to
    ``{"flops_per_s": ..., "hbm_bytes_per_s": ..., "label": ...}``.
    Malformed JSON or entries are ignored (the bundled table still
    applies) — a typo'd override must not take the meter down."""
    raw = os.environ.get("TIP_DEVICE_PEAKS", "")
    if not raw.strip():
        return {}
    try:
        table = json.loads(raw)
    except (ValueError, TypeError):
        return {}
    if not isinstance(table, dict):
        return {}
    out = {}
    for key, entry in table.items():
        if not isinstance(entry, dict):
            continue
        peaks = {}
        for field in ("flops_per_s", "hbm_bytes_per_s"):
            try:
                peaks[field] = float(entry[field])
            except (KeyError, TypeError, ValueError):
                continue
        if not peaks:
            continue
        peaks["label"] = str(entry.get("label", f"env:{key}"))
        out[str(key).lower()] = peaks
    return out


def resolve_peaks(
    platform: Optional[str],
    device_kind: Optional[str],
    cores: int = 1,
) -> dict:
    """Peak FLOP/s + HBM bytes/s for one device, or an analytic_only stub.

    Resolution order: longest-matching ``TIP_DEVICE_PEAKS`` key (matched
    as a substring of the lowercased device kind, falling back to the
    platform name), then the bundled v4/CPU defaults. An unrecognized
    chip returns ``{"analytic_only": True}`` with no peaks — loud by
    design, so a new chip gets an explicit table entry rather than a
    silently-wrong MFU.
    """
    platform = (platform or "").lower()
    kind = (device_kind or "").lower()
    haystack = kind or platform
    env = _env_peaks()
    for key in sorted(env, key=len, reverse=True):
        if key and (key in haystack or key == platform):
            entry = dict(env[key])
            entry.setdefault("analytic_only", False)
            return entry
    if "v4" in haystack:
        return dict(_BUILTIN_PEAKS["v4"], analytic_only=False)
    if platform == "cpu" or "cpu" in haystack:
        entry = dict(_BUILTIN_PEAKS["cpu"], analytic_only=False)
        entry["flops_per_s"] *= max(1, int(cores))
        entry.pop("per_core_flops", None)
        return entry
    return {
        "analytic_only": True,
        "label": f"unknown:{device_kind or platform or 'device'}",
    }


# -- grading -----------------------------------------------------------------


def grade(
    cost: Optional[dict],
    dt_s: Optional[float],
    platform: Optional[str] = None,
    device_kind: Optional[str] = None,
    cores: int = 1,
    peaks: Optional[dict] = None,
) -> dict:
    """Grade one measured dispatch against the device roofline.

    Returns a JSON-safe dict: achieved FLOP/s and HBM bytes/s (whenever
    the cost and a positive dt are known), MFU and HBM fraction
    (additionally requiring peaks), and ``bound`` — ``"compute"`` or
    ``"hbm"`` by whichever roofline ceiling the dispatch sits closer to,
    ``"unknown"`` when the verdict cannot be computed. ``analytic_only``
    is True whenever the peak table did not recognize the chip.
    """
    if peaks is None:
        peaks = resolve_peaks(platform, device_kind, cores=cores)
    cost = cost or {}
    out = {
        "flops": cost.get("flops"),
        "bytes_accessed": cost.get("bytes_accessed"),
        "peak_memory_bytes": cost.get("peak_memory_bytes"),
        "dispatch_s": dt_s,
        "achieved_flops_per_s": None,
        "achieved_hbm_bytes_per_s": None,
        "mfu": None,
        "hbm_frac": None,
        "bound": "unknown",
        "analytic_only": bool(peaks.get("analytic_only", False)),
        "peak_label": peaks.get("label"),
        "peak_flops_per_s": peaks.get("flops_per_s"),
        "peak_hbm_bytes_per_s": peaks.get("hbm_bytes_per_s"),
    }
    if not dt_s or dt_s <= 0:
        return out
    flops = cost.get("flops")
    bytes_accessed = cost.get("bytes_accessed")
    if flops is not None:
        out["achieved_flops_per_s"] = flops / dt_s
        if peaks.get("flops_per_s"):
            out["mfu"] = out["achieved_flops_per_s"] / peaks["flops_per_s"]
    if bytes_accessed is not None:
        out["achieved_hbm_bytes_per_s"] = bytes_accessed / dt_s
        if peaks.get("hbm_bytes_per_s"):
            out["hbm_frac"] = (
                out["achieved_hbm_bytes_per_s"] / peaks["hbm_bytes_per_s"]
            )
    if out["mfu"] is not None and out["hbm_frac"] is not None:
        out["bound"] = "compute" if out["mfu"] >= out["hbm_frac"] else "hbm"
    elif out["mfu"] is not None:
        out["bound"] = "compute"
    elif out["hbm_frac"] is not None:
        out["bound"] = "hbm"
    return out


# -- the in-process cost registry -------------------------------------------


def record_program_cost(
    program: str, cost: Optional[dict], fingerprint: Optional[str] = None
) -> None:
    """Remember one program's analytic cost (compile-time stamp or
    ProgramCache-hit recovery). A None cost is remembered as absent so a
    later hit cannot resurrect a stale entry from a previous program."""
    cost = normalize_cost(cost) if cost else None
    with _lock:
        if cost is None:
            _program_costs.pop(str(program), None)
        else:
            _program_costs[str(program)] = {
                "cost": cost,
                "fingerprint": fingerprint,
            }


def program_cost(program: str) -> Optional[dict]:
    """The registered analytic cost for ``program``, or None."""
    with _lock:
        entry = _program_costs.get(str(program))
        return dict(entry["cost"]) if entry else None


def program_costs() -> Dict[str, dict]:
    """Snapshot of every registered program cost (JSON-safe copy)."""
    with _lock:
        return {
            name: {"cost": dict(e["cost"]), "fingerprint": e["fingerprint"]}
            for name, e in _program_costs.items()
        }


def reset() -> None:
    """Forget every registered program cost (test isolation)."""
    with _lock:
        _program_costs.clear()


def observe_dispatch(
    program: str,
    dt_s: float,
    platform: Optional[str] = None,
    device_kind: Optional[str] = None,
    cores: int = 1,
) -> None:
    """Feed one measured dispatch into the live metrics registry.

    Always lands the dispatch-latency quantile; when the program's cost
    is registered and the chip is recognized, also sets the per-program
    MFU / bandwidth / HBM-fraction gauges (last-dispatch values — the
    quantile window carries the distribution). Never raises: dispatch
    paths must not fail on telemetry.
    """
    try:
        program = str(program)
        obs.quantile(f"run_program.dispatch_s.{program}").observe(float(dt_s))
        cost = program_cost(program)
        if cost is None:
            return
        graded = grade(
            cost, dt_s, platform=platform, device_kind=device_kind, cores=cores
        )
        if graded["mfu"] is not None:
            obs.gauge(f"run_program.mfu.{program}").set(round(graded["mfu"], 6))
        if graded["hbm_frac"] is not None:
            obs.gauge(f"run_program.hbm_frac.{program}").set(
                round(graded["hbm_frac"], 6)
            )
        if graded["achieved_hbm_bytes_per_s"] is not None:
            obs.gauge(f"run_program.hbm_gbps.{program}").set(
                round(graded["achieved_hbm_bytes_per_s"] / 1e9, 3)
            )
    except Exception:  # noqa: BLE001 — telemetry must not fail a dispatch
        pass


def detect_device() -> Tuple[str, str, int]:
    """(platform, device_kind, core/chip count) — jax when importable,
    a CPU fallback otherwise (the meter itself stays stdlib-only)."""
    try:
        import jax

        devices = jax.devices()
        return (
            devices[0].platform,
            getattr(devices[0], "device_kind", devices[0].platform),
            len(devices),
        )
    except Exception:  # noqa: BLE001 — no jax / no backend → host CPU
        return ("cpu", "cpu", os.cpu_count() or 1)


# -- MFU_BREAKDOWN documents -------------------------------------------------


def build_breakdown(
    programs: Dict[str, dict],
    platform: str,
    device_kind: str,
    cores: int = 1,
    degraded: bool = False,
    captured_unix: Optional[float] = None,
    extra: Optional[dict] = None,
) -> dict:
    """Compose the schema-stamped MFU_BREAKDOWN document.

    ``programs`` maps a program name (free-form; grouped-chain G-sweep
    entries use e.g. ``group_chain@g4``) to ``{"cost": <normalized cost
    dict>, "dispatch_s": <seconds | quantile summary dict>}`` plus any
    extra fields (``models_per_dispatch``, ``n_dispatches``...). Each
    entry is graded here against one shared peak resolution, so the doc
    is self-contained for ``obs roofline`` / store / regress.
    """
    peaks = resolve_peaks(platform, device_kind, cores=cores)
    doc = {
        "schema": SCHEMA,
        "kind": KIND,
        "platform": platform,
        "device_kind": device_kind,
        "cores": int(cores),
        "degraded": bool(degraded),
        "peaks": peaks,
        "programs": {},
    }
    if captured_unix is not None:
        doc["captured_unix"] = float(captured_unix)
    for name, entry in sorted(programs.items()):
        entry = dict(entry or {})
        cost = normalize_cost(entry.get("cost"))
        dispatch = entry.get("dispatch_s")
        summary = None
        if isinstance(dispatch, dict):
            summary = dispatch
            dt_s = dispatch.get("p50") or dispatch.get("mean")
        else:
            dt_s = dispatch
        graded = grade(cost, dt_s, peaks=peaks)
        row = {
            "cost": cost,
            "grade": graded,
        }
        if summary is not None:
            row["dispatch_s"] = summary
        elif dt_s is not None:
            row["dispatch_s"] = {"mean": float(dt_s)}
        for key, value in entry.items():
            if key not in ("cost", "dispatch_s"):
                row[key] = value
        doc["programs"][str(name)] = row
    if extra:
        for key, value in extra.items():
            doc.setdefault(key, value)
    return doc


# -- roofline rows + rendering ----------------------------------------------


def rows_from_breakdown(doc: dict) -> List[dict]:
    """Flatten one MFU_BREAKDOWN document into roofline table rows."""
    rows = []
    programs = doc.get("programs")
    if not isinstance(programs, dict):
        return rows
    for name, entry in sorted(programs.items()):
        graded = (entry or {}).get("grade") or {}
        dispatch = (entry or {}).get("dispatch_s") or {}
        rows.append(
            {
                "program": str(name),
                "mfu": graded.get("mfu"),
                "hbm_frac": graded.get("hbm_frac"),
                "hbm_gbps": (
                    graded["achieved_hbm_bytes_per_s"] / 1e9
                    if graded.get("achieved_hbm_bytes_per_s") is not None
                    else None
                ),
                "gflops_per_s": (
                    graded["achieved_flops_per_s"] / 1e9
                    if graded.get("achieved_flops_per_s") is not None
                    else None
                ),
                "p50_ms": (
                    dispatch["p50"] * 1e3 if dispatch.get("p50") is not None
                    else (
                        dispatch["mean"] * 1e3
                        if dispatch.get("mean") is not None
                        else None
                    )
                ),
                "p99_ms": (
                    dispatch["p99"] * 1e3
                    if dispatch.get("p99") is not None
                    else None
                ),
                "count": dispatch.get("count"),
                "bound": graded.get("bound", "unknown"),
                "analytic_only": bool(graded.get("analytic_only", False)),
                "models_per_dispatch": (entry or {}).get("models_per_dispatch"),
            }
        )
    return rows


def rows_from_metrics(snapshot: dict) -> List[dict]:
    """Roofline rows from one live metrics snapshot (gauges + quantiles
    as ``observe_dispatch`` lands them) — the run-directory path of
    ``obs roofline``, where no MFU_BREAKDOWN document exists yet."""
    gauges = snapshot.get("gauges") or {}
    quantiles = snapshot.get("quantiles") or {}
    programs = set()
    for key in gauges:
        for prefix in (
            "run_program.mfu.", "run_program.hbm_frac.", "run_program.hbm_gbps."
        ):
            if key.startswith(prefix):
                programs.add(key[len(prefix):])
    for key in quantiles:
        if key.startswith("run_program.dispatch_s."):
            programs.add(key[len("run_program.dispatch_s."):])
    rows = []
    for name in sorted(programs):
        summary = quantiles.get(f"run_program.dispatch_s.{name}") or {}
        mfu = gauges.get(f"run_program.mfu.{name}")
        hbm_frac = gauges.get(f"run_program.hbm_frac.{name}")
        if mfu is not None and hbm_frac is not None:
            bound = "compute" if mfu >= hbm_frac else "hbm"
        elif mfu is not None:
            bound = "compute"
        elif hbm_frac is not None:
            bound = "hbm"
        else:
            bound = "unknown"
        rows.append(
            {
                "program": name,
                "mfu": mfu,
                "hbm_frac": hbm_frac,
                "hbm_gbps": gauges.get(f"run_program.hbm_gbps.{name}"),
                "gflops_per_s": None,
                "p50_ms": (
                    summary["p50"] * 1e3
                    if summary.get("p50") is not None
                    else None
                ),
                "p99_ms": (
                    summary["p99"] * 1e3
                    if summary.get("p99") is not None
                    else None
                ),
                "count": summary.get("count"),
                "bound": bound,
                "analytic_only": mfu is None and hbm_frac is None,
                "models_per_dispatch": None,
            }
        )
    return rows


def _fmt(value, spec: str = ".3f", none: str = "-") -> str:
    if value is None:
        return none
    try:
        return format(value, spec)
    except (TypeError, ValueError):
        return str(value)


def render_roofline(rows: List[dict], header: str = "") -> str:
    """The ``obs roofline`` table: one line per program, verdict last."""
    lines = []
    if header:
        lines.append(header)
    lines.append(
        f"{'program':<24} {'mfu':>8} {'hbm%':>8} {'GB/s':>9} "
        f"{'p50 ms':>9} {'p99 ms':>9} {'n':>6}  verdict"
    )
    for row in rows:
        verdict = row.get("bound", "unknown")
        if verdict == "compute":
            verdict = "compute-bound"
        elif verdict == "hbm":
            verdict = "HBM-bound"
        if row.get("analytic_only"):
            verdict += " [analytic_only]"
        mpd = row.get("models_per_dispatch")
        if mpd:
            verdict += f" (G={mpd})"
        lines.append(
            f"{row.get('program', '?'):<24} "
            f"{_fmt(row.get('mfu'), '.4f'):>8} "
            f"{_fmt(row.get('hbm_frac'), '.4f'):>8} "
            f"{_fmt(row.get('hbm_gbps'), '.2f'):>9} "
            f"{_fmt(row.get('p50_ms'), '.3f'):>9} "
            f"{_fmt(row.get('p99_ms'), '.3f'):>9} "
            f"{_fmt(row.get('count'), 'd'):>6}  {verdict}"
        )
    return "\n".join(lines)

"""Live study inspection: merged tail, refreshing top table, plan audit.

Three operator workflows over the same events-JSONL plumbing the rest of
obs reads post-hoc, but built to run WHILE the study runs:

- :func:`tail` (``obs tail [--follow]``) — one merged, start-aligned tail
  of every process's event stream in a run directory. Incremental byte
  cursors with torn-tail tolerance: a line a writer is mid-appending is
  carried until its newline lands, never dropped and never mis-parsed,
  and files that appear late (a worker spawning mid-phase) join the
  merge on the next poll.
- :func:`top` (``obs top``) — a refreshing phase-progress / queue-depth /
  badge-fill table: announce/start/done/requeue lifecycle counts per
  phase plus the latest registry gauges, recomputed per refresh.
- :func:`audit` (``obs audit``) — grades every completed
  ``scheduler.phase`` span's ``predicted_s`` against its ``actual_s``
  (the pairs run_scheduler stamps; obs v3 collected them but never
  closed the loop), prints per-phase error distributions, and emits them
  as feature-store rows (``--index``) and trend-gateable snapshots
  (``--json`` + ``obs trend``) so cost-model drift fails CI like any
  other regression.

Stdlib-only; output goes through a writable ``out`` stream (default
stdout) so library callers and tests capture it without touching the
process's fds.
"""

import json
import os
import sys
import time
from typing import Dict, List, Optional

# A follow that nobody stops is still bounded: every poll loop carries a
# monotonic deadline (default one day) per the naked-retry contract — on
# this deployment dependencies wedge rather than error, and an unbounded
# poll against a dead study would be a hang.
DEFAULT_FOLLOW_S = 86400.0
_POLL_S = 0.5
# Idle-backoff ceiling for follow mode: each poll that yields no bytes
# doubles the interval up to this cap (reset to the base on activity), so
# a quiet study doesn't busy-rescan its run directory twice a second.
_POLL_CAP_S = 8.0


def _next_poll_s(cur_s: float, base_s: float, active: bool) -> float:
    """The next follow-mode poll interval: base while the streams are
    producing, exponential backoff to ``_POLL_CAP_S`` while idle."""
    if active:
        return base_s
    return min(max(base_s, cur_s) * 2.0, max(base_s, _POLL_CAP_S))

# Version stamp on every emitted audit document: `obs audit --json` output
# is a trend snapshot (regress.load_snapshot consumes it), so the docs
# outlive this writer like any other obs stream row.
SCHEMA = 1


class StreamCursor:
    """Incremental reader of one JSONL stream with torn-tail tolerance.

    Keeps a byte offset plus a carry buffer: each :meth:`poll` reads only
    the bytes appended since the last, and the trailing partial line (a
    writer caught mid-append — the torn tail) is carried until its
    newline arrives instead of being parsed short or dropped.
    """

    def __init__(self, path: str):
        self.path = path
        self.offset = 0
        self._carry = b""
        self.bad_lines = 0

    def poll(self) -> List[dict]:
        """Parse and return the records appended since the last poll."""
        try:
            with open(self.path, "rb") as f:
                f.seek(self.offset)
                chunk = f.read()
        except OSError:
            return []
        if not chunk:
            return []
        self.offset += len(chunk)
        data = self._carry + chunk
        lines = data.split(b"\n")
        self._carry = lines.pop()  # torn tail: kept for the next poll
        out = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                self.bad_lines += 1
                continue
            if isinstance(rec, dict):
                rec["_file"] = os.path.basename(self.path)
                out.append(rec)
        return out


def _err(msg: str) -> None:
    """CLI diagnostic to stderr: this module is the obs CLI's live
    surface, so stderr is its diagnostic contract while stdout (the
    ``out`` stream) carries the payload — same split as ``obs predict``.
    """
    sys.stderr.write(msg + "\n")
    sys.stderr.flush()


def _stream_paths(target) -> List[str]:
    """The events-JSONL files of ``target`` (dir(s) or explicit files).

    An explicit ``events-*.jsonl`` file operand also pulls in its sibling
    segments: the tracer rotates to a fresh ``events-<pid>-<n>.jsonl``
    after compaction (the ``obs.evicted`` marker), and a follow pinned to
    the pre-rotation segment alone would go silent mid-study. Rescanning
    the parent directory each poll is what lets ``tail --follow`` ride
    through rotation.
    """
    targets = target if isinstance(target, (list, tuple)) else [target]
    dirs = []
    explicit = []
    for t in targets:
        if os.path.isdir(t):
            dirs.append(t)
        else:
            explicit.append(t)
            base = os.path.basename(t)
            if base.startswith("events-") and base.endswith(".jsonl"):
                dirs.append(os.path.dirname(t) or ".")
    paths = list(explicit)
    for d in dirs:
        try:
            names = sorted(os.listdir(d))
        except OSError:
            continue
        paths.extend(
            os.path.join(d, n)
            for n in names
            if n.startswith("events-") and n.endswith(".jsonl")
        )
    seen = set()
    unique = []
    for p in paths:
        if p not in seen:
            seen.add(p)
            unique.append(p)
    return unique


def iter_tail(
    target,
    follow: bool = False,
    poll_s: float = _POLL_S,
    duration_s: Optional[float] = None,
    max_events: Optional[int] = None,
):
    """Yield merged events from ``target``'s streams, oldest-ts first.

    Non-follow mode drains whatever is on disk once. Follow mode keeps
    polling (rediscovering new stream files each pass, so late-spawning
    workers join the merge) until ``duration_s`` passes or ``max_events``
    have been yielded; within one poll batch events are ts-sorted —
    cross-poll order is arrival order, the live-tail contract. Idle polls
    back the interval off exponentially to ``_POLL_CAP_S`` (reset to
    ``poll_s`` the moment a stream produces events) so a quiet study
    isn't rescanned at full cadence.
    """
    cursors: Dict[str, StreamCursor] = {}
    deadline = time.monotonic() + (
        duration_s if duration_s is not None else DEFAULT_FOLLOW_S
    )
    yielded = 0
    cur_poll = poll_s
    while True:
        live = set(_stream_paths(target))
        for path in live:
            if path not in cursors:
                cursors[path] = StreamCursor(path)
        # Segments the tracer compacted away (no longer listed, gone from
        # disk) leave dead cursors behind; prune them so a long follow
        # over many rotations doesn't poll an unbounded stale set.
        for path in [p for p in cursors if p not in live]:
            if not os.path.exists(path):
                del cursors[path]
        batch = []
        for cursor in cursors.values():
            batch.extend(cursor.poll())
        batch.sort(key=lambda r: (r.get("ts") or 0, r.get("pid") or 0))
        for rec in batch:
            yield rec
            yielded += 1
            if max_events is not None and yielded >= max_events:
                return
        if not follow:
            return
        now = time.monotonic()
        if now >= deadline:
            return
        cur_poll = _next_poll_s(cur_poll, poll_s, active=bool(batch))
        time.sleep(min(cur_poll, deadline - now))


def format_event(rec: dict, t0: Optional[float]) -> str:
    """One tail line: start-aligned offset, pid, type, name, attrs."""
    ts = rec.get("ts")
    if isinstance(ts, (int, float)) and t0 is not None:
        clock = f"+{max(0.0, ts - t0):9.3f}s"
    else:
        clock = " " * 10 + "-"
    kind = str(rec.get("type", "?"))
    name = str(rec.get("name", "")) if kind != "metrics" else "(registry)"
    if kind == "log":
        name = f"[{rec.get('level', '?')}] {str(rec.get('msg', ''))[:120]}"
    attrs = rec.get("attrs")
    detail = ""
    if isinstance(attrs, dict) and attrs:
        detail = " " + json.dumps(attrs, sort_keys=True, default=repr)[:160]
    dur = rec.get("dur")
    if kind == "span" and isinstance(dur, (int, float)):
        detail = f" dur={dur:.3f}s" + detail
    return f"{clock} pid={rec.get('pid', '?'):<7} {kind:<7} {name}{detail}"


def tail(
    target,
    follow: bool = False,
    poll_s: float = _POLL_S,
    duration_s: Optional[float] = None,
    max_events: Optional[int] = None,
    out=None,
) -> int:
    """``obs tail`` entry: stream formatted events to ``out``; exit code.

    The alignment origin is the earliest ts seen (the study's first meta
    line in practice), so every process's events print on one clock.
    """
    out = out or sys.stdout
    t0: Optional[float] = None
    n = 0
    for rec in iter_tail(
        target, follow=follow, poll_s=poll_s,
        duration_s=duration_s, max_events=max_events,
    ):
        ts = rec.get("ts")
        if t0 is None and isinstance(ts, (int, float)):
            t0 = ts
        out.write(format_event(rec, t0) + "\n")
        out.flush()
        n += 1
    if n == 0 and not follow:
        _err(f"obs tail: no events under {target}")
        return 3
    return 0


# -- top -------------------------------------------------------------------


def top_snapshot(events) -> dict:
    """Aggregate a study's live progress from its event stream.

    Per phase: announced / started / done / failed / requeued lifecycle
    counts and the derived queue depth (announced but not yet resolved).
    Plus the newest registry gauges and badge-fill/queue metrics from
    ``metrics`` flush events — the serving liveness columns.
    """
    phases: Dict[str, Dict[str, int]] = {}
    gauges: Dict[str, float] = {}
    counters: Dict[str, float] = {}

    def bucket(phase) -> Dict[str, int]:
        return phases.setdefault(
            str(phase or "?"),
            {"announced": 0, "started": 0, "done": 0, "failed": 0,
             "requeued": 0, "expected": 0},
        )

    for rec in events:
        kind = rec.get("type")
        if kind == "event":
            name = rec.get("name", "")
            attrs = rec.get("attrs") or {}
            short = {
                "scheduler.announce": "announced",
                "scheduler.start": "started",
                "scheduler.done": "done",
                "scheduler.fail": "failed",
                "scheduler.requeue": "requeued",
            }.get(name)
            if short:
                bucket(attrs.get("phase"))[short] += 1
        elif kind == "span" and rec.get("name") == "scheduler.phase":
            attrs = rec.get("attrs") or {}
            b = bucket(attrs.get("phase"))
            runs = attrs.get("runs")
            if isinstance(runs, (int, float)):
                b["expected"] = max(b["expected"], int(runs))
        elif kind == "metrics":
            for k, v in (rec.get("gauges") or {}).items():
                if isinstance(v, (int, float)):
                    gauges[k] = v
            for k, v in (rec.get("counters") or {}).items():
                if isinstance(v, (int, float)):
                    counters[k] = max(counters.get(k, 0), v)
    for b in phases.values():
        b["queue"] = max(0, b["announced"] - b["done"] - b["failed"])
    return {"phases": phases, "gauges": gauges, "counters": counters}


def render_top(snap: dict) -> str:
    """The :func:`top_snapshot` dict as a fixed-width progress table."""
    lines = [
        f"{'phase':<24} {'done':>6} {'fail':>6} {'queue':>6} "
        f"{'requeue':>8} {'announced':>10}"
    ]
    for phase, b in sorted(snap.get("phases", {}).items()):
        expected = f"/{b['expected']}" if b.get("expected") else ""
        lines.append(
            f"{phase:<24} {b['done']:>6} {b['failed']:>6} {b['queue']:>6} "
            f"{b['requeued']:>8} {str(b['announced']) + expected:>10}"
        )
    gauges = snap.get("gauges", {})
    interesting = {
        k: v
        for k, v in sorted(gauges.items())
        if k.startswith(("serving.", "scheduler.")) or "badge" in k
    }
    if interesting:
        lines.append("")
        for k, v in interesting.items():
            lines.append(f"  {k:<40} {v}")
    # Dispatch counters are the grouped-path liveness signal: a G-sweep
    # that stopped incrementing group_chain_dispatches is wedged even
    # while its gauges hold their last value.
    counters = snap.get("counters", {})
    dispatch = {
        k: v
        for k, v in sorted(counters.items())
        if k.startswith("run_program.") or k.startswith("serving.")
    }
    if dispatch:
        lines.append("")
        for k, v in dispatch.items():
            shown = int(v) if float(v).is_integer() else v
            lines.append(f"  {k:<40} {shown}")
    return "\n".join(lines)


def top(
    target,
    refresh_s: float = 2.0,
    iterations: Optional[int] = None,
    out=None,
) -> int:
    """``obs top`` entry: render the progress table every ``refresh_s``.

    ``iterations=1`` is the one-shot mode (CI / tests); otherwise the
    loop runs until its day-long deadline or Ctrl-C, re-reading the run
    directory each pass (re-reads are cheap at study scale, and a full
    re-read is what makes late files and compactions harmless).
    """
    from simple_tip_tpu.obs.cli import load_events

    out = out or sys.stdout
    deadline = time.monotonic() + DEFAULT_FOLLOW_S
    n = 0
    while True:
        events, files, _bad = load_events(target)
        if not files and n == 0:
            _err(f"obs top: no events under {target}")
            return 3
        n += 1
        if n > 1:
            out.write("\x1b[2J\x1b[H")  # clear + home between refreshes
        out.write(render_top(top_snapshot(events)) + "\n")
        out.flush()
        if iterations is not None and n >= iterations:
            return 0
        if time.monotonic() >= deadline:
            return 0
        time.sleep(max(0.1, refresh_s))


# -- audit -----------------------------------------------------------------


def audit_events(events, source: str = "") -> dict:
    """Grade every predicted-vs-actual pair in one run's events.

    Returns a trend-gateable snapshot document::

        {"kind": "audit", "source": ..., "spans": [per-span grades],
         "by_phase": {phase: {count, mean_abs_error_s, mean_rel_err,
                              bias_s}},
         "phases": {"audit.<phase>": mean_abs_error_s}}

    ``phases`` carries mean ABSOLUTE error seconds per phase — the shape
    ``obs trend`` gates, so a drifted cost model (errors jumping out of
    the historical band) fails CI exactly like a runtime regression.
    """
    spans = []
    for rec in events:
        if rec.get("type") != "span":
            continue
        attrs = rec.get("attrs") or {}
        pred, act = attrs.get("predicted_s"), attrs.get("actual_s")
        if not (
            isinstance(pred, (int, float)) and isinstance(act, (int, float))
        ):
            continue
        err = float(act) - float(pred)
        spans.append(
            {
                "span": str(rec.get("name", "?")),
                "phase": str(attrs.get("phase") or rec.get("name", "?")),
                "case_study": attrs.get("case_study"),
                "predicted_s": round(float(pred), 6),
                "actual_s": round(float(act), 6),
                "error_s": round(err, 6),
                "rel_err": round(err / float(pred), 6) if pred else None,
            }
        )
    by_phase: Dict[str, dict] = {}
    for s in spans:
        agg = by_phase.setdefault(
            s["phase"], {"count": 0, "_abs": 0.0, "_signed": 0.0, "_rel": 0.0}
        )
        agg["count"] += 1
        agg["_abs"] += abs(s["error_s"])
        agg["_signed"] += s["error_s"]
        agg["_rel"] += abs(s["rel_err"] or 0.0)
    for phase, agg in by_phase.items():
        n = agg.pop("count")
        by_phase[phase] = {
            "count": n,
            "mean_abs_error_s": round(agg.pop("_abs") / n, 6),
            "bias_s": round(agg.pop("_signed") / n, 6),
            "mean_rel_err": round(agg.pop("_rel") / n, 6),
        }
    return {
        "schema": SCHEMA,
        "kind": "audit",
        "source": str(source),
        "spans": spans,
        "by_phase": by_phase,
        "phases": {
            f"audit.{phase}": agg["mean_abs_error_s"]
            for phase, agg in by_phase.items()
        },
        "degraded": False,
        "counters": {},
    }


def render_audit(doc: dict) -> str:
    """The audit document as a per-phase plan-vs-actual table."""
    lines = [
        f"{'phase':<24} {'n':>4} {'mean|err|':>10} {'bias':>10} "
        f"{'mean rel':>9}"
    ]
    for phase, agg in sorted(doc.get("by_phase", {}).items()):
        lines.append(
            f"{phase:<24} {agg['count']:>4} {agg['mean_abs_error_s']:>9.3f}s "
            f"{agg['bias_s']:>+9.3f}s {agg['mean_rel_err']:>8.1%}"
        )
    for s in doc.get("spans", []):
        rel = f"{s['rel_err']:+.1%}" if s["rel_err"] is not None else "-"
        lines.append(
            f"  {s['phase']:<22} predicted {s['predicted_s']:>8.3f}s  "
            f"actual {s['actual_s']:>8.3f}s  ({rel})"
        )
    return "\n".join(lines)


def audit(
    targets,
    index: Optional[str] = None,
    as_json: bool = False,
    out=None,
) -> int:
    """``obs audit`` entry: grade run dirs, print/emit; exit code.

    Exit 0 with grades on stdout (``--json``: the snapshot document —
    feed a chronological series of them to ``obs trend`` to gate model
    drift); exit 3 when no span in the targets carries a
    predicted_s/actual_s pair (nothing to audit — same contract as
    ``obs predict``'s insufficient corpus); diagnostics on stderr. With
    ``index``, the targets are also refreshed into the feature store,
    whose obs-run normalizer emits the per-phase ``audit.*`` error rows.
    """
    from simple_tip_tpu.obs.cli import load_events

    out = out or sys.stdout
    events, files, bad = load_events(targets)
    # load_events lists a missing operand as an (unreadable) candidate
    # file; "no streams" means nothing on disk actually backed the merge.
    if not any(os.path.exists(f) for f in files):
        if as_json:
            out.write(
                json.dumps(
                    {"schema": SCHEMA, "kind": "audit", "error": "no_streams"}
                )
                + "\n"
            )
        _err(f"obs audit: no events-*.jsonl streams under {targets}")
        return 2
    doc = audit_events(
        events, source=targets[0] if len(targets) == 1 else ";".join(targets)
    )
    if bad:
        _err(f"obs audit: skipped {bad} torn line(s)")
    if index:
        from simple_tip_tpu.obs import store

        report = store.refresh(targets, index)
        _err(
            f"obs audit: indexed {len(report['indexed'])} source(s) "
            f"(+{report['rows_appended']} rows) into {report['index']}"
        )
    if as_json:
        out.write(json.dumps(doc, indent=2, sort_keys=True) + "\n")
    else:
        out.write(render_audit(doc) + "\n")
    if not doc["spans"]:
        _err(
            "obs audit: no span carries both predicted_s and actual_s — "
            "nothing to grade (exit 3; run with the feature-store index "
            "populated so the scheduler stamps predictions)"
        )
        return 3
    return 0

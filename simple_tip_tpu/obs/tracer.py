"""Span tracer: nested, attributed spans written as append-only JSONL.

One event stream per process, under ``TIP_OBS_DIR``:

- unset / ``0`` / ``off``  -> telemetry fully disabled: ``span()`` returns a
  shared no-op context manager, no directory is created, zero files are
  written (overhead is pinned by tests/test_obs.py);
- ``1`` / ``auto``         -> ``$TIP_ASSETS/obs/<run_ts>/``, resolved ONCE in
  the first process that emits and re-exported into ``os.environ`` so every
  spawned child (run_scheduler workers, SA fit pool, bench subprocesses)
  appends into the SAME run directory;
- any other value          -> that directory, verbatim.

Each process owns one stream (``events-<pid>-<token>.jsonl``; the token
keeps restarts from interleaving two boots in one file) opened lazily on
the first real event. The first line of every file is a ``meta`` event
stamping pid / worker index / platform (``TIP_OBS_WORKER`` /
``TIP_OBS_PLATFORM``, set by the scheduler when it spawns workers), which
is how the CLI merges streams across the spawn boundary. Every write is one
``json.dumps`` line plus flush — a crashed process leaves a file whose
complete lines all still parse (the reader skips at most the torn tail
line).

Trace lifecycle (obs v2) — a 100-run study with per-badge spans would
otherwise grow GB-class run directories:

- ``TIP_OBS_MAX_BYTES`` caps this process's on-disk footprint (default
  64 MiB; suffixes ``k``/``m``/``g``; ``0``/``off``/``unlimited`` disables
  the cap). The stream rotates into fixed-count segments
  (``events-<pid>-<token>-<seq>.jsonl``, each opening with its own ``meta``
  stamp); past the cap the OLDEST segment is deleted and an
  ``obs.evicted`` marker event records how many segments/bytes are gone,
  so a truncated trace is always self-describing.
- ``TIP_OBS_SAMPLE`` (``name=N[,name=N...]``) keeps 1-in-N spans of each
  named hot span (per process, deterministic from the per-name counter);
  kept spans carry ``sample_1_in: N`` so readers know each one stands for
  N. Sampled-out spans are full no-ops — their children attach to the
  nearest kept ancestor. This is what makes per-badge loops instrumentable.
- ``study_root`` opens a study-level root span and pins its id into
  ``os.environ["TIP_OBS_ROOT"]`` (the same spawn-boundary trick as the
  resolved TIP_OBS_DIR): a span opened at stack depth 0 in ANY process of
  the study — scheduler.phase, a worker's ``run``, an engine phase —
  parents onto the root, so the merged trace is one tree.

Span semantics: context manager (``with span("fit", variant="dsa"):``) or
decorator (``@traced()``); nesting is tracked per thread, each span records
its wall-clock start (``time.time``, cross-process alignable), a monotonic
duration (``time.perf_counter``), its parent span id and depth, and
arbitrary JSON-safe attributes. Spans are written on EXIT only: an event
that never closed (crash mid-span) is absent rather than half-written.

Everything here is stdlib-only (json/os/time/threading): the tracer is
imported by pool workers and the tier-0 CLI, neither of which may pay (or
wedge on) a jax import.
"""

import atexit
import json
import os
import secrets
import sys
import threading
import time

_lock = threading.RLock()
_local = threading.local()

#: Event-stream schema version, stamped into every stream's ``meta`` head
#: line. Readers (``obs check``, the feature store) use it to reject rows
#: they do not understand; the ``unversioned-schema`` tiplint rule enforces
#: that every obs JSONL writer carries such a stamp.
SCHEMA = 1

# Resolved lazily on first use; _State.pid lets a forked child detect that it
# inherited the parent's handle and must re-resolve (spawn re-imports anyway).
_state = None

#: Default per-process on-disk cap (64 MiB). Chosen for 100-run studies:
#: one scheduler parent + a handful of workers stays comfortably under a
#: GB even with per-badge spans sampled in; RUNBOOK 5b documents the math.
DEFAULT_MAX_BYTES = 64 * 1024 * 1024

#: Rotation granularity: the cap is split across this many segments, so
#: eviction drops at most 1/Nth of the history at a time.
SEGMENTS = 8

#: Env var carrying the study root span id across the spawn boundary.
ROOT_ENV = "TIP_OBS_ROOT"


def _parse_max_bytes(raw: str):
    """``TIP_OBS_MAX_BYTES`` -> byte count or None (uncapped)."""
    raw = (raw or "").strip().lower()
    if not raw:
        return DEFAULT_MAX_BYTES
    if raw in ("0", "off", "unlimited", "none"):
        return None
    mult = 1
    if raw[-1] in "kmg":
        mult = {"k": 1024, "m": 1024**2, "g": 1024**3}[raw[-1]]
        raw = raw[:-1]
    try:
        n = int(float(raw) * mult)
    except ValueError:
        return DEFAULT_MAX_BYTES
    return n if n > 0 else None


def _parse_sample(raw: str) -> dict:
    """``TIP_OBS_SAMPLE`` (``name=N,name2=M``) -> {span name: keep-1-in-N}."""
    out = {}
    for part in (raw or "").split(","):
        part = part.strip()
        if not part or "=" not in part:
            continue
        name, _, n = part.rpartition("=")
        try:
            n = int(n)
        except ValueError:
            continue
        if name.strip() and n > 1:
            out[name.strip()] = n
    return out


class _State:
    """Per-process tracer state: resolved directory, lazy rotating stream."""

    __slots__ = (
        "enabled", "dir", "path", "fh", "pid", "next_id", "meta_written",
        "token", "seq", "cur_bytes", "segments", "max_bytes", "seg_bytes",
        "sample", "sample_counts", "evicted_segments", "evicted_bytes",
    )

    def __init__(self, enabled, directory):
        self.enabled = enabled
        self.dir = directory
        self.path = None
        self.fh = None
        self.pid = os.getpid()
        self.next_id = 0
        self.meta_written = False
        self.token = secrets.token_hex(4) if enabled else ""
        self.seq = 0
        self.cur_bytes = 0
        self.segments = []  # this process's live segment paths, oldest first
        self.max_bytes = _parse_max_bytes(os.environ.get("TIP_OBS_MAX_BYTES", "")) if enabled else None
        # Floor keeps a tiny cap from rotating on every line; the cap still
        # holds because eviction runs on segment COUNT, not byte totals.
        self.seg_bytes = (
            max(1024, self.max_bytes // SEGMENTS) if self.max_bytes else None
        )
        self.sample = _parse_sample(os.environ.get("TIP_OBS_SAMPLE", "")) if enabled else {}
        self.sample_counts = {}
        self.evicted_segments = 0
        self.evicted_bytes = 0


def _resolve():
    """Build this process's ``_State`` from ``TIP_OBS_DIR`` (see module doc)."""
    raw = os.environ.get("TIP_OBS_DIR", "").strip()
    if not raw or raw.lower() in ("0", "off"):
        return _State(False, None)
    if raw.lower() in ("1", "auto"):
        assets = os.environ.get("TIP_ASSETS", os.path.join(os.getcwd(), "assets"))
        raw = os.path.join(assets, "obs", time.strftime("%Y%m%d-%H%M%S"))
        # Children (spawned workers / pools) inherit os.environ: pinning the
        # resolved path here is what merges the whole study into one run dir.
        os.environ["TIP_OBS_DIR"] = raw
    return _State(True, os.path.abspath(raw))


def _get_state():
    """The process-wide tracer state, (re)resolved on first use or after fork."""
    global _state
    st = _state
    if st is None or st.pid != os.getpid():
        with _lock:
            st = _state
            if st is None or st.pid != os.getpid():
                st = _resolve()
                _state = st
    return st


def enabled() -> bool:
    """Whether telemetry is active for this process (``TIP_OBS_DIR`` set)."""
    return _get_state().enabled


def obs_dir():
    """The resolved event-stream directory, or None when disabled."""
    return _get_state().dir


def reset() -> None:
    """Close the stream and drop cached state so the env is re-read.

    Test/tooling hook: production processes resolve once and never reset.
    """
    global _state
    with _lock:
        if _state is not None and _state.fh is not None:
            try:
                _state.fh.close()
            except OSError:
                pass
        _state = None
        _local.__dict__.clear()


def _close_at_exit() -> None:
    """atexit hook: flush the metrics registry, then close the stream."""
    from simple_tip_tpu.obs import metrics

    metrics.flush()
    st = _state
    if st is not None and st.fh is not None:
        try:
            st.fh.close()
        except OSError:
            pass
        st.fh = None


def _meta_event() -> dict:
    """The stream-head ``meta`` event stamping this process's identity."""
    worker = os.environ.get("TIP_OBS_WORKER", "").strip()
    platform = os.environ.get("TIP_OBS_PLATFORM", "").strip()
    rec = {
        "type": "meta",
        "schema": SCHEMA,
        "ts": time.time(),
        "pid": os.getpid(),
        "argv": list(sys.argv),
    }
    if worker:
        rec["worker"] = worker
    if platform:
        rec["platform"] = platform
    return rec


def _segment_name(st) -> str:
    """Filename of segment ``st.seq`` (the first keeps the legacy name)."""
    base = f"events-{st.pid}-{st.token}"
    return f"{base}.jsonl" if st.seq == 0 else f"{base}-{st.seq:03d}.jsonl"


def _open_segment(st) -> None:
    """Open the current segment file and stamp its ``meta`` head line."""
    os.makedirs(st.dir, exist_ok=True)
    st.path = os.path.join(st.dir, _segment_name(st))
    st.fh = open(st.path, "a", encoding="utf-8")
    st.segments.append(st.path)
    st.cur_bytes = 0
    line = json.dumps(_meta_event(), default=repr) + "\n"
    st.fh.write(line)
    st.cur_bytes += len(line.encode("utf-8"))
    st.meta_written = True


def _rotate(st) -> None:
    """Close the full segment, evict past the cap, open the next one."""
    try:
        st.fh.close()
    except OSError:
        pass
    st.fh = None
    st.seq += 1
    # Evict oldest segments until the live count fits the cap again. The
    # about-to-open segment counts toward the budget, hence >= SEGMENTS.
    while len(st.segments) >= SEGMENTS:
        victim = st.segments.pop(0)
        try:
            st.evicted_bytes += os.path.getsize(victim)
            os.remove(victim)
            st.evicted_segments += 1
        except OSError:
            break  # cannot evict (already gone / perms): stop trying
    _open_segment(st)
    if st.evicted_segments:
        # Self-describing truncation: the first real line after the meta
        # stamp says what the retention policy has dropped so far.
        marker = {
            "type": "event",
            "name": "obs.evicted",
            "ts": time.time(),
            "pid": st.pid,
            "tid": threading.get_ident(),
            "attrs": {
                "segments": st.evicted_segments,
                "bytes": st.evicted_bytes,
                "max_bytes": st.max_bytes,
            },
        }
        line = json.dumps(marker, default=repr) + "\n"
        st.fh.write(line)
        st.cur_bytes += len(line.encode("utf-8"))


def write(rec: dict) -> None:
    """Append one event line to this process's stream (no-op when disabled).

    Rotates into a fresh segment when the current one would exceed its
    share of ``TIP_OBS_MAX_BYTES``. Never raises: a full disk or revoked
    directory degrades telemetry to silence, not the pipeline to failure.
    """
    st = _get_state()
    if not st.enabled:
        return
    with _lock:
        try:
            if st.fh is None:
                _open_segment(st)
                atexit.register(_close_at_exit)
            line = json.dumps(rec, default=repr) + "\n"
            nbytes = len(line.encode("utf-8"))
            if st.seg_bytes is not None and st.cur_bytes + nbytes > st.seg_bytes:
                _rotate(st)
            st.fh.write(line)
            st.cur_bytes += nbytes
            st.fh.flush()
        except OSError:
            # Telemetry must never take the instrumented pipeline down.
            st.enabled = False


def _span_stack():
    """This thread's open-span stack (span ids)."""
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    return stack


def _new_span_id(st) -> str:
    """Process-unique span id (``pid:n``)."""
    with _lock:
        st.next_id += 1
        return f"{st.pid}:{st.next_id}"


class _NoopSpan:
    """Shared do-nothing span for the disabled path (near-zero overhead)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        """Ignore attribute updates on the disabled path."""
        return self


_NOOP = _NoopSpan()


class Span:
    """One live span: records wall start, monotonic duration, nesting."""

    __slots__ = ("name", "attrs", "_id", "_parent", "_depth", "_t0", "_wall")

    def __init__(self, name, attrs):
        self.name = name
        self.attrs = attrs

    def set(self, **attrs):
        """Attach/overwrite attributes while the span is open."""
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        st = _get_state()
        stack = _span_stack()
        self._id = _new_span_id(st)
        if stack:
            self._parent = stack[-1]
            self._depth = len(stack)
        else:
            # Stack-root span: attach under the study root pinned into the
            # environment (by study_root, possibly in ANOTHER process — the
            # spawn boundary inherits os.environ), so scheduler/worker/
            # engine top spans merge into one study tree.
            root = os.environ.get(ROOT_ENV, "").strip() or None
            self._parent = root if root != self._id else None
            self._depth = 1 if self._parent else 0
        stack.append(self._id)
        self._wall = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        dur = time.perf_counter() - self._t0
        stack = _span_stack()
        if stack and stack[-1] == self._id:
            stack.pop()
        rec = {
            "type": "span",
            "name": self.name,
            "ts": self._wall,
            "dur": dur,
            "pid": os.getpid(),
            "tid": threading.get_ident(),
            "id": self._id,
            "depth": self._depth,
        }
        if self._parent is not None:
            rec["parent"] = self._parent
        if exc_type is not None:
            rec["error"] = repr(exc_val)
        if self.attrs:
            rec["attrs"] = self.attrs
        write(rec)
        return False


def span(name: str, **attrs):
    """A context-manager span; the shared no-op when telemetry is disabled.

    With ``TIP_OBS_SAMPLE`` naming this span, only 1-in-N occurrences are
    recorded (kept spans carry ``sample_1_in: N``); the rest are full
    no-ops whose children attach to the nearest kept ancestor.
    """
    st = _get_state()
    if not st.enabled:
        return _NOOP
    rate = st.sample.get(name)
    if rate is not None:
        with _lock:
            count = st.sample_counts.get(name, 0)
            st.sample_counts[name] = count + 1
        if count % rate:
            return _NOOP
        attrs.setdefault("sample_1_in", rate)
    return Span(name, attrs)


class _RootSpan(Span):
    """The study root span: pins its id into the env for every child process."""

    __slots__ = ("_prev_root",)

    def __enter__(self):
        self._prev_root = os.environ.get(ROOT_ENV)
        super().__enter__()
        os.environ[ROOT_ENV] = self._id
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        # Un-pin only our own id: a crashed inner study must not clear an
        # outer root's pin.
        if os.environ.get(ROOT_ENV) == self._id:
            if self._prev_root is None:
                os.environ.pop(ROOT_ENV, None)
            else:
                os.environ[ROOT_ENV] = self._prev_root
        return super().__exit__(exc_type, exc_val, exc_tb)


def study_root(name: str = "study", **attrs):
    """Open the study-level root span and export its id to child processes.

    Every span later opened at stack depth 0 — in this process or any
    spawned child that inherits the environment — parents onto this span,
    so a whole multi-phase, multi-worker study merges into ONE tree (and
    one nested Perfetto flame chart). No-op when telemetry is disabled.
    """
    if not _get_state().enabled:
        return _NOOP
    attrs.setdefault("kind", "study_root")
    return _RootSpan(name, attrs)


def traced(name=None, **attrs):
    """Decorator form of ``span`` (span name defaults to the qualname)."""

    def deco(fn):
        label = name or fn.__qualname__

        def wrapper(*args, **kwargs):
            with span(label, **attrs):
                return fn(*args, **kwargs)

        wrapper.__name__ = fn.__name__
        wrapper.__doc__ = fn.__doc__
        wrapper.__qualname__ = fn.__qualname__
        wrapper.__wrapped__ = fn
        return wrapper

    return deco


def event(name: str, **attrs) -> None:
    """One instantaneous lifecycle event (scheduler announce/done/requeue...)."""
    if not _get_state().enabled:
        return
    rec = {
        "type": "event",
        "name": name,
        "ts": time.time(),
        "pid": os.getpid(),
        "tid": threading.get_ident(),
    }
    if attrs:
        rec["attrs"] = attrs
    write(rec)


def record_span(name: str, wall_start: float, dur: float, **attrs) -> None:
    """Record an externally-timed span (the ``Timer`` mirror path).

    The caller owns the measurement (``wall_start`` from ``time.time``,
    ``dur`` in seconds); nesting attaches to this thread's current open span.
    """
    st = _get_state()
    if not st.enabled:
        return
    stack = _span_stack()
    span_id = _new_span_id(st)
    parent = stack[-1] if stack else (
        os.environ.get(ROOT_ENV, "").strip() or None
    )
    rec = {
        "type": "span",
        "name": name,
        "ts": wall_start,
        "dur": dur,
        "pid": os.getpid(),
        "tid": threading.get_ident(),
        "id": span_id,
        "depth": len(stack) if stack else (1 if parent else 0),
    }
    if parent is not None and parent != span_id:
        rec["parent"] = parent
    if attrs:
        rec["attrs"] = attrs
    write(rec)

"""Span tracer: nested, attributed spans written as append-only JSONL.

One event stream per process, under ``TIP_OBS_DIR``:

- unset / ``0`` / ``off``  -> telemetry fully disabled: ``span()`` returns a
  shared no-op context manager, no directory is created, zero files are
  written (overhead is pinned by tests/test_obs.py);
- ``1`` / ``auto``         -> ``$TIP_ASSETS/obs/<run_ts>/``, resolved ONCE in
  the first process that emits and re-exported into ``os.environ`` so every
  spawned child (run_scheduler workers, SA fit pool, bench subprocesses)
  appends into the SAME run directory;
- any other value          -> that directory, verbatim.

Each process owns exactly one file (``events-<pid>-<token>.jsonl``; the
token keeps restarts from interleaving two boots in one file) and opens it
lazily on the first real event. The first line is a ``meta`` event stamping
pid / worker index / platform (``TIP_OBS_WORKER`` / ``TIP_OBS_PLATFORM``,
set by the scheduler when it spawns workers), which is how the CLI merges
streams across the spawn boundary. Every write is one ``json.dumps`` line
plus flush — a crashed process leaves a file whose complete lines all still
parse (the reader skips at most the torn tail line).

Span semantics: context manager (``with span("fit", variant="dsa"):``) or
decorator (``@traced()``); nesting is tracked per thread, each span records
its wall-clock start (``time.time``, cross-process alignable), a monotonic
duration (``time.perf_counter``), its parent span id and depth, and
arbitrary JSON-safe attributes. Spans are written on EXIT only: an event
that never closed (crash mid-span) is absent rather than half-written.

Everything here is stdlib-only (json/os/time/threading): the tracer is
imported by pool workers and the tier-0 CLI, neither of which may pay (or
wedge on) a jax import.
"""

import atexit
import json
import os
import secrets
import sys
import threading
import time

_lock = threading.RLock()
_local = threading.local()

# Resolved lazily on first use; _State.pid lets a forked child detect that it
# inherited the parent's handle and must re-resolve (spawn re-imports anyway).
_state = None


class _State:
    """Per-process tracer state: resolved directory, lazy file handle."""

    __slots__ = ("enabled", "dir", "path", "fh", "pid", "next_id", "meta_written")

    def __init__(self, enabled, directory):
        self.enabled = enabled
        self.dir = directory
        self.path = None
        self.fh = None
        self.pid = os.getpid()
        self.next_id = 0
        self.meta_written = False


def _resolve():
    """Build this process's ``_State`` from ``TIP_OBS_DIR`` (see module doc)."""
    raw = os.environ.get("TIP_OBS_DIR", "").strip()
    if not raw or raw.lower() in ("0", "off"):
        return _State(False, None)
    if raw.lower() in ("1", "auto"):
        assets = os.environ.get("TIP_ASSETS", os.path.join(os.getcwd(), "assets"))
        raw = os.path.join(assets, "obs", time.strftime("%Y%m%d-%H%M%S"))
        # Children (spawned workers / pools) inherit os.environ: pinning the
        # resolved path here is what merges the whole study into one run dir.
        os.environ["TIP_OBS_DIR"] = raw
    return _State(True, os.path.abspath(raw))


def _get_state():
    """The process-wide tracer state, (re)resolved on first use or after fork."""
    global _state
    st = _state
    if st is None or st.pid != os.getpid():
        with _lock:
            st = _state
            if st is None or st.pid != os.getpid():
                st = _resolve()
                _state = st
    return st


def enabled() -> bool:
    """Whether telemetry is active for this process (``TIP_OBS_DIR`` set)."""
    return _get_state().enabled


def obs_dir():
    """The resolved event-stream directory, or None when disabled."""
    return _get_state().dir


def reset() -> None:
    """Close the stream and drop cached state so the env is re-read.

    Test/tooling hook: production processes resolve once and never reset.
    """
    global _state
    with _lock:
        if _state is not None and _state.fh is not None:
            try:
                _state.fh.close()
            except OSError:
                pass
        _state = None
        _local.__dict__.clear()


def _close_at_exit() -> None:
    """atexit hook: flush the metrics registry, then close the stream."""
    from simple_tip_tpu.obs import metrics

    metrics.flush()
    st = _state
    if st is not None and st.fh is not None:
        try:
            st.fh.close()
        except OSError:
            pass
        st.fh = None


def _meta_event() -> dict:
    """The stream-head ``meta`` event stamping this process's identity."""
    worker = os.environ.get("TIP_OBS_WORKER", "").strip()
    platform = os.environ.get("TIP_OBS_PLATFORM", "").strip()
    rec = {
        "type": "meta",
        "ts": time.time(),
        "pid": os.getpid(),
        "argv": list(sys.argv),
    }
    if worker:
        rec["worker"] = worker
    if platform:
        rec["platform"] = platform
    return rec


def write(rec: dict) -> None:
    """Append one event line to this process's stream (no-op when disabled).

    Never raises: a full disk or revoked directory degrades telemetry to
    silence, not the pipeline to failure.
    """
    st = _get_state()
    if not st.enabled:
        return
    with _lock:
        try:
            if st.fh is None:
                os.makedirs(st.dir, exist_ok=True)
                st.path = os.path.join(
                    st.dir,
                    f"events-{os.getpid()}-{secrets.token_hex(4)}.jsonl",
                )
                st.fh = open(st.path, "a", encoding="utf-8")
                atexit.register(_close_at_exit)
            if not st.meta_written:
                st.meta_written = True
                st.fh.write(json.dumps(_meta_event(), default=repr) + "\n")
            st.fh.write(json.dumps(rec, default=repr) + "\n")
            st.fh.flush()
        except OSError:
            # Telemetry must never take the instrumented pipeline down.
            st.enabled = False


def _span_stack():
    """This thread's open-span stack (span ids)."""
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    return stack


def _new_span_id(st) -> str:
    """Process-unique span id (``pid:n``)."""
    with _lock:
        st.next_id += 1
        return f"{st.pid}:{st.next_id}"


class _NoopSpan:
    """Shared do-nothing span for the disabled path (near-zero overhead)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        """Ignore attribute updates on the disabled path."""
        return self


_NOOP = _NoopSpan()


class Span:
    """One live span: records wall start, monotonic duration, nesting."""

    __slots__ = ("name", "attrs", "_id", "_parent", "_depth", "_t0", "_wall")

    def __init__(self, name, attrs):
        self.name = name
        self.attrs = attrs

    def set(self, **attrs):
        """Attach/overwrite attributes while the span is open."""
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        st = _get_state()
        stack = _span_stack()
        self._id = _new_span_id(st)
        self._parent = stack[-1] if stack else None
        self._depth = len(stack)
        stack.append(self._id)
        self._wall = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        dur = time.perf_counter() - self._t0
        stack = _span_stack()
        if stack and stack[-1] == self._id:
            stack.pop()
        rec = {
            "type": "span",
            "name": self.name,
            "ts": self._wall,
            "dur": dur,
            "pid": os.getpid(),
            "tid": threading.get_ident(),
            "id": self._id,
            "depth": self._depth,
        }
        if self._parent is not None:
            rec["parent"] = self._parent
        if exc_type is not None:
            rec["error"] = repr(exc_val)
        if self.attrs:
            rec["attrs"] = self.attrs
        write(rec)
        return False


def span(name: str, **attrs):
    """A context-manager span; the shared no-op when telemetry is disabled."""
    if not _get_state().enabled:
        return _NOOP
    return Span(name, attrs)


def traced(name=None, **attrs):
    """Decorator form of ``span`` (span name defaults to the qualname)."""

    def deco(fn):
        label = name or fn.__qualname__

        def wrapper(*args, **kwargs):
            with span(label, **attrs):
                return fn(*args, **kwargs)

        wrapper.__name__ = fn.__name__
        wrapper.__doc__ = fn.__doc__
        wrapper.__qualname__ = fn.__qualname__
        wrapper.__wrapped__ = fn
        return wrapper

    return deco


def event(name: str, **attrs) -> None:
    """One instantaneous lifecycle event (scheduler announce/done/requeue...)."""
    if not _get_state().enabled:
        return
    rec = {
        "type": "event",
        "name": name,
        "ts": time.time(),
        "pid": os.getpid(),
        "tid": threading.get_ident(),
    }
    if attrs:
        rec["attrs"] = attrs
    write(rec)


def record_span(name: str, wall_start: float, dur: float, **attrs) -> None:
    """Record an externally-timed span (the ``Timer`` mirror path).

    The caller owns the measurement (``wall_start`` from ``time.time``,
    ``dur`` in seconds); nesting attaches to this thread's current open span.
    """
    st = _get_state()
    if not st.enabled:
        return
    stack = _span_stack()
    rec = {
        "type": "span",
        "name": name,
        "ts": wall_start,
        "dur": dur,
        "pid": os.getpid(),
        "tid": threading.get_ident(),
        "id": _new_span_id(st),
        "depth": len(stack),
    }
    if stack:
        rec["parent"] = stack[-1]
    if attrs:
        rec["attrs"] = attrs
    write(rec)

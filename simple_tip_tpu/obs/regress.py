"""Cross-run regression detection over traces and bench records (obs v2).

A 100-run study emits per-phase wall-clock (obs spans), health counters
(worker deaths, requeues, cache corruption) and bench records — but until
this module nothing COMPARED one study against the last: BENCH_r05.json
silently replaced a TPU record with a ``"degraded": true`` CPU one and no
alarm fired. ``obs regress BASELINE CURRENT`` diffs two snapshots per
phase/metric and exits nonzero when the current one regressed.

Accepted snapshot forms (auto-detected, mixable):

- an obs run directory (or ``events-*.jsonl`` files): phases are the span
  table's per-name totals, counters the summed metrics counters;
- a ``summary --json`` document (``{"spans": ..., "counters": ...}``);
- a bench record — either ``bench.py``'s raw JSON line or the round
  driver's ``BENCH_r0*.json`` wrapper (the record under ``"parsed"``).

Regression rules (thresholds configurable from the CLI):

- a phase whose duration grew more than ``max_growth`` (default 25%) over
  a baseline of at least ``min_seconds`` (noise floor, default 0.05 s);
- a bench headline value that DROPPED more than ``max_growth`` (throughput
  metrics: higher is better);
- any ``degraded`` flip false -> true (the BENCH_r05 failure mode);
- any growth in a health counter (worker deaths, timeouts, requeues,
  watchdog failures, cache corruption).

Stdlib-only: this runs in the tier-0 CI gate.
"""

import json
import os

#: Counters whose INCREASE between runs is a health regression. Matched as
#: name prefixes so per-device / per-phase suffixes participate. The
#: breaker entries are the anti-BENCH_r05 guarantee: an open circuit (the
#: loud form of the CPU degradation) fails regress against a healthy
#: baseline even if the headline value happens to survive.
HEALTH_COUNTERS = (
    "scheduler.worker_deaths",
    "scheduler.timeouts",
    "scheduler.requeues",
    "watchdog.probe_fail",
    "watchdog.probe_timeout",
    "sa_fit_cache.corrupt",
    "breaker.opened",
    "breaker.short_circuit",
    "breaker.degraded",
    "retry.giveups",
)

#: Default growth threshold (fraction) past which a phase regressed.
DEFAULT_MAX_GROWTH = 0.25

#: Phases shorter than this (seconds) in the baseline are noise, not signal.
DEFAULT_MIN_SECONDS = 0.05


def _is_health_counter(name: str) -> bool:
    """Whether counter ``name`` participates in the health comparison."""
    return any(name.startswith(p) for p in HEALTH_COUNTERS)


def _blank_snapshot(kind: str, source: str) -> dict:
    """A zeroed snapshot skeleton."""
    return {
        "kind": kind,
        "source": source,
        "phases": {},
        "counters": {},
        "degraded": None,
        "value": None,
    }


def _normalize_bench(doc: dict, source: str) -> dict:
    """A bench record (raw ``bench.py`` JSON) as a snapshot."""
    snap = _blank_snapshot("bench", source)
    try:
        snap["value"] = float(doc.get("value") or 0)
    except (TypeError, ValueError):
        snap["value"] = 0.0
    snap["degraded"] = bool(doc.get("degraded", False))
    counters = (doc.get("obs_metrics") or {}).get("counters") or {}
    snap["counters"] = {
        k: v for k, v in counters.items() if isinstance(v, (int, float))
    }
    sa = doc.get("sa_fit_seconds") or {}
    for variant, secs in (sa.get("by_variant") or {}).items():
        if isinstance(secs, (int, float)):
            snap["phases"][f"sa_fit.{variant}"] = float(secs)
    if isinstance(sa.get("total"), (int, float)):
        snap["phases"]["sa_fit.total"] = float(sa["total"])
    return snap


def load_snapshot(target) -> dict:
    """Normalize ``target`` into ``{kind, phases, counters, degraded, value}``.

    ``target`` is a path: an obs run dir / ``.jsonl`` file (trace mode), or
    a JSON document (bench record, ``BENCH_r0*.json`` wrapper, or
    ``summary --json`` output). Raises ``ValueError`` on unrecognizable
    input — regress must fail loudly, not compare garbage.
    """
    snap = _blank_snapshot("trace", str(target))
    if os.path.isdir(target) or str(target).endswith(".jsonl"):
        from simple_tip_tpu.obs.cli import (
            _span_table,
            _summed_counters,
            load_events,
        )

        events, files, _bad = load_events(target)
        if not files:
            raise ValueError(f"{target}: no events-*.jsonl streams found")
        snap["phases"] = {
            name: round(total, 6)
            for name, (_cnt, total, _mx) in _span_table(events).items()
        }
        snap["counters"] = _summed_counters(events)
        return snap

    try:
        with open(target, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise ValueError(f"{target}: not a readable JSON document ({e})")
    if not isinstance(doc, dict):
        raise ValueError(f"{target}: expected a JSON object")
    if isinstance(doc.get("parsed"), dict):  # BENCH_r0*.json driver wrapper
        doc = doc["parsed"]

    if "metric" in doc and "value" in doc:  # bench record
        return _normalize_bench(doc, str(target))

    if isinstance(doc.get("spans"), dict):  # summary --json document
        snap["phases"] = {
            name: float(info.get("total_s", 0) or 0)
            for name, info in doc["spans"].items()
            if isinstance(info, dict)
        }
        counters = doc.get("counters") or {}
        snap["counters"] = {
            k: v for k, v in counters.items() if isinstance(v, (int, float))
        }
        return snap

    raise ValueError(
        f"{target}: unrecognized snapshot (need an obs run dir, a bench "
        "record / BENCH_r0*.json, or `obs summary --json` output)"
    )


def compare(
    baseline: dict,
    current: dict,
    max_growth: float = DEFAULT_MAX_GROWTH,
    min_seconds: float = DEFAULT_MIN_SECONDS,
) -> dict:
    """Diff two snapshots; returns ``{rows, regressions, ok}``.

    ``rows`` is every compared (kind, name, base, cur, delta) tuple-dict —
    the printable table; ``regressions`` the failing subset.
    """
    rows = []

    def row(kind, name, base, cur, regressed, note=""):
        delta = None
        if isinstance(base, (int, float)) and isinstance(cur, (int, float)) and base:
            delta = (cur - base) / abs(base)
        rows.append(
            {
                "kind": kind,
                "name": name,
                "baseline": base,
                "current": cur,
                "delta": delta,
                "regressed": bool(regressed),
                "note": note,
            }
        )

    for name in sorted(set(baseline["phases"]) | set(current["phases"])):
        base = baseline["phases"].get(name)
        cur = current["phases"].get(name)
        if base is None or cur is None:
            row("phase", name, base, cur, False, "only in one snapshot")
            continue
        if base < min_seconds:
            row("phase", name, base, cur, False, "below noise floor")
            continue
        grew = cur > base * (1.0 + max_growth)
        row(
            "phase", name, base, cur, grew,
            f"> +{max_growth:.0%} growth" if grew else "",
        )

    if baseline["value"] is not None and current["value"] is not None:
        dropped = (
            baseline["value"] > 0
            and current["value"] < baseline["value"] * (1.0 - max_growth)
        )
        row(
            "bench", "value", baseline["value"], current["value"], dropped,
            f"> -{max_growth:.0%} drop" if dropped else "",
        )

    if baseline["degraded"] is not None or current["degraded"] is not None:
        flip = baseline["degraded"] is False and current["degraded"] is True
        row(
            "bench", "degraded", baseline["degraded"], current["degraded"],
            flip, "false -> true flip" if flip else "",
        )

    for name in sorted(set(baseline["counters"]) | set(current["counters"])):
        if not _is_health_counter(name):
            continue
        base = baseline["counters"].get(name, 0)
        cur = current["counters"].get(name, 0)
        row(
            "counter", name, base, cur, cur > base,
            "health counter grew" if cur > base else "",
        )

    regressions = [r for r in rows if r["regressed"]]
    return {"rows": rows, "regressions": regressions, "ok": not regressions}


def render(result: dict, baseline: dict, current: dict) -> str:
    """The comparison as a deterministic text table."""
    out = [
        f"baseline: {baseline['source']} ({baseline['kind']})",
        f"current:  {current['source']} ({current['kind']})",
        "",
        f"  {'kind':<8} {'name':<40} {'baseline':>12} {'current':>12} "
        f"{'delta':>8}  verdict",
    ]

    def fmt(v):
        if isinstance(v, bool) or v is None:
            return str(v)
        if isinstance(v, float):
            return f"{v:.3f}"
        return str(v)

    for r in result["rows"]:
        delta = f"{r['delta']:+.0%}" if r["delta"] is not None else "-"
        verdict = "REGRESSED" if r["regressed"] else "ok"
        if r["note"] and not r["regressed"]:
            verdict = f"ok ({r['note']})"
        elif r["note"]:
            verdict = f"REGRESSED ({r['note']})"
        out.append(
            f"  {r['kind']:<8} {r['name']:<40} {fmt(r['baseline']):>12} "
            f"{fmt(r['current']):>12} {delta:>8}  {verdict}"
        )
    out.append("")
    n = len(result["regressions"])
    out.append(
        "regress OK: no regressions"
        if not n
        else f"regress FAILED: {n} regression(s)"
    )
    return "\n".join(out)


def bench_delta(current_record: dict, previous_path: str) -> dict:
    """``bench.py`` hook: the current record's delta vs a previous BENCH file.

    Returns a JSON-safe summary to embed in the record (never raises —
    bench's one-JSON-line contract outranks the companion).
    """
    try:
        baseline = load_snapshot(previous_path)
        current = _normalize_bench(current_record, "<current run>")
        result = compare(baseline, current)
        return {
            "against": os.path.basename(previous_path),
            "ok": result["ok"],
            "regressions": [
                {k: r[k] for k in ("kind", "name", "baseline", "current", "note")}
                for r in result["regressions"]
            ],
            "value_ratio": (
                round(current["value"] / baseline["value"], 3)
                if baseline["value"]
                else None
            ),
        }
    except Exception as e:  # noqa: BLE001 — companion data, never fatal
        return {"against": os.path.basename(str(previous_path)), "error": repr(e)[:200]}

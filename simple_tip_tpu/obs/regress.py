"""Cross-run regression detection over traces and bench records (obs v2).

A 100-run study emits per-phase wall-clock (obs spans), health counters
(worker deaths, requeues, cache corruption) and bench records — but until
this module nothing COMPARED one study against the last: BENCH_r05.json
silently replaced a TPU record with a ``"degraded": true`` CPU one and no
alarm fired. ``obs regress BASELINE CURRENT`` diffs two snapshots per
phase/metric and exits nonzero when the current one regressed.

Accepted snapshot forms (auto-detected, mixable):

- an obs run directory (or ``events-*.jsonl`` files): phases are the span
  table's per-name totals, counters the summed metrics counters;
- a ``summary --json`` document (``{"spans": ..., "counters": ...}``);
- a bench record — either ``bench.py``'s raw JSON line or the round
  driver's ``BENCH_r0*.json`` wrapper (the record under ``"parsed"``);
- an ``MFU_BREAKDOWN.json`` device-cost capture (obs/devicemeter):
  per-program MFUs gate as *floors* (drop-is-bad), per-program p50
  dispatch seconds as phases (growth-is-bad).

Regression rules (thresholds configurable from the CLI):

- a phase whose duration grew more than ``max_growth`` (default 25%) over
  a baseline of at least ``min_seconds`` (noise floor, default 0.05 s);
- a bench headline value that DROPPED more than ``max_growth`` (throughput
  metrics: higher is better);
- any ``degraded`` flip false -> true (the BENCH_r05 failure mode);
- any growth in a health counter (worker deaths, timeouts, requeues,
  watchdog failures, cache corruption).

obs v3 adds N-run **trend gating** (``trend`` + ``obs trend``): instead of
one noisy pairwise diff, the current snapshot is gated against robust
median/MAD bands computed over the last K comparable — non-degraded —
predecessors. Degraded snapshots never enter a baseline (BENCH r02–r05
would otherwise normalize the CPU fallback into "expected"), but a current
degraded flip still regresses. ``select_bench_baseline`` applies the same
policy to single-baseline selection: newest non-degraded ``BENCH_r*.json``,
else the newest embedded ``last_good_tpu`` record, else an explicit
``no_comparable_baseline`` verdict.

Stdlib-only: this runs in the tier-0 CI gate.
"""

import json
import os
import statistics

#: Counters whose INCREASE between runs is a health regression. Matched as
#: name prefixes so per-device / per-phase suffixes participate. The
#: breaker entries are the anti-BENCH_r05 guarantee: an open circuit (the
#: loud form of the CPU degradation) fails regress against a healthy
#: baseline even if the headline value happens to survive.
HEALTH_COUNTERS = (
    "scheduler.worker_deaths",
    "scheduler.timeouts",
    "scheduler.requeues",
    "watchdog.probe_fail",
    "watchdog.probe_timeout",
    "sa_fit_cache.corrupt",
    "cov_stats_cache.corrupt",
    "breaker.opened",
    "breaker.short_circuit",
    "breaker.degraded",
    "retry.giveups",
)

#: Default growth threshold (fraction) past which a phase regressed.
DEFAULT_MAX_GROWTH = 0.25

#: Phases shorter than this (seconds) in the baseline are noise, not signal.
DEFAULT_MIN_SECONDS = 0.05

#: Trend gate: how many comparable predecessors form the baseline window.
DEFAULT_TREND_WINDOW = 5

#: Trend gate: band half-width in robust sigmas (MAD x 1.4826).
DEFAULT_TREND_BAND = 3.0

#: Trend gate: minimum band half-width as a fraction of the median, so a
#: perfectly-flat fixture history (MAD = 0) does not flag ppm-level jitter.
DEFAULT_TREND_REL_FLOOR = 0.10

#: Trend gate: fewer comparable predecessors than this is not a trend —
#: the verdict is ``no_comparable_baseline`` (exit 3), not a pass/fail.
DEFAULT_MIN_BASELINE = 3


def _is_health_counter(name: str) -> bool:
    """Whether counter ``name`` participates in the health comparison."""
    return any(name.startswith(p) for p in HEALTH_COUNTERS)


def _blank_snapshot(kind: str, source: str) -> dict:
    """A zeroed snapshot skeleton.

    ``phases`` gate growth-is-bad (durations, bytes); ``floors`` is the
    mirror for higher-is-better metrics (MFU, utilization fractions):
    a floor regresses when the current value DROPS below the band.
    """
    return {
        "kind": kind,
        "source": source,
        "phases": {},
        "floors": {},
        "counters": {},
        "degraded": None,
        "value": None,
        "plan": None,
    }


def _normalize_bench(doc: dict, source: str) -> dict:
    """A bench record (raw ``bench.py`` JSON) as a snapshot."""
    snap = _blank_snapshot("bench", source)
    try:
        snap["value"] = float(doc.get("value") or 0)
    except (TypeError, ValueError):
        snap["value"] = 0.0
    snap["degraded"] = bool(doc.get("degraded", False))
    # ExecutionPlan stamp (simple_tip_tpu.plan): records predating the
    # stamp normalize to "unplanned" — the same value bench.py writes when
    # no plan is active — so the trend gate's like-for-like filter keeps
    # the committed history comparable instead of orphaning it.
    snap["plan"] = str(doc.get("plan") or "unplanned")
    counters = (doc.get("obs_metrics") or {}).get("counters") or {}
    snap["counters"] = {
        k: v for k, v in counters.items() if isinstance(v, (int, float))
    }
    sa = doc.get("sa_fit_seconds") or {}
    for variant, secs in (sa.get("by_variant") or {}).items():
        if isinstance(secs, (int, float)):
            snap["phases"][f"sa_fit.{variant}"] = float(secs)
    if isinstance(sa.get("total"), (int, float)):
        snap["phases"]["sa_fit.total"] = float(sa["total"])
    # Fused/grouped-chain host-transfer claim: the analytic bytes/input
    # the chain drains to host (68 B for the 12-metric chain) becomes a
    # gated "phase" — growth past the band means someone widened the
    # device->host fan-out, which is exactly the regression the fused
    # chain exists to prevent. Units are bytes, not seconds; the growth
    # gate is unit-agnostic.
    fc = doc.get("fused_chain") or {}
    if isinstance(fc, dict) and isinstance(
        fc.get("host_transfer_bytes_per_input"), (int, float)
    ):
        snap["phases"]["fused_chain.host_bytes_per_input"] = float(
            fc["host_transfer_bytes_per_input"]
        )
    grouped = doc.get("grouped_chain") or {}
    if isinstance(grouped, dict) and isinstance(
        grouped.get("host_bytes_per_input"), (int, float)
    ):
        snap["phases"]["grouped_chain.host_bytes_per_input"] = float(
            grouped["host_bytes_per_input"]
        )
    # Serving companion: p99 per arrival rate becomes a gated phase so a
    # latency regression on the online path fails `obs trend` exactly like
    # a batch-phase slowdown.
    for label, rate in ((doc.get("serving") or {}).get("rates") or {}).items():
        if isinstance(rate, dict) and isinstance(rate.get("p99_ms"), (int, float)):
            snap["phases"][f"serving.p99.{label}"] = float(rate["p99_ms"]) / 1000.0
    # Device-cost observatory: the record's headline MFU (and any
    # per-program MFUs the devicemeter companion graded) gate as FLOORS —
    # a chip-utilization drop fails trend exactly like a p99 growth.
    if isinstance(doc.get("mfu"), (int, float)) and doc["mfu"] > 0:
        snap["floors"]["mfu"] = float(doc["mfu"])
    for section in ("fused_chain", "grouped_chain"):
        programs = (doc.get(section) or {}).get("device_cost") or {}
        if not isinstance(programs, dict):
            continue
        for prog, graded in programs.items():
            if isinstance(graded, dict) and isinstance(
                graded.get("mfu"), (int, float)
            ):
                snap["floors"][f"mfu.{prog}"] = float(graded["mfu"])
    return snap


def _normalize_mfu_breakdown(doc: dict, source: str) -> dict:
    """An ``MFU_BREAKDOWN.json`` capture (obs/devicemeter) as a snapshot:
    per-program MFUs become floors (drop-is-bad), per-program p50 dispatch
    seconds become phases (growth-is-bad), so one healthy-window capture
    series is trend-gated on both axes."""
    snap = _blank_snapshot("mfu_breakdown", source)
    snap["degraded"] = bool(doc.get("degraded", False))
    for name, entry in sorted((doc.get("programs") or {}).items()):
        if not isinstance(entry, dict):
            continue
        graded = entry.get("grade") or {}
        if isinstance(graded.get("mfu"), (int, float)):
            snap["floors"][f"mfu.{name}"] = float(graded["mfu"])
        dispatch = entry.get("dispatch_s") or {}
        p50 = dispatch.get("p50", dispatch.get("mean"))
        if isinstance(p50, (int, float)):
            snap["phases"][f"dispatch.{name}"] = float(p50)
    return snap


def _normalize_host_phase(doc: dict, source: str) -> dict:
    """A ``HOST_PHASE.json`` capture (scripts/measure_host_phase.py) as a
    snapshot: the headline host-phase durations become phases so `obs
    trend` can gate the test-prio trajectory the same way it gates bench
    fixtures."""
    snap = _blank_snapshot("host_phase", source)
    for key, phase in (
        ("test_prio_s", "test_prio"),
        ("train_1epoch_s", "train_1epoch"),
    ):
        if isinstance(doc.get(key), (int, float)):
            snap["phases"][phase] = float(doc[key])
    for label, stage in (doc.get("sa_setup") or {}).items():
        if isinstance(stage, dict) and isinstance(
            stage.get("setup_total_s"), (int, float)
        ):
            snap["phases"][f"sa_setup.{label}"] = float(stage["setup_total_s"])
    for label, stage in (doc.get("cov_stats") or {}).items():
        if isinstance(stage, dict) and isinstance(
            stage.get("debit_s"), (int, float)
        ):
            snap["phases"][f"cov_stats.{label}"] = float(stage["debit_s"])
    if "degraded" in doc:
        snap["degraded"] = bool(doc.get("degraded"))
    counters = (doc.get("obs_metrics") or {}).get("counters") or {}
    snap["counters"] = {
        k: v for k, v in counters.items() if isinstance(v, (int, float))
    }
    return snap


def load_snapshot(target) -> dict:
    """Normalize ``target`` into ``{kind, phases, counters, degraded, value}``.

    ``target`` is a path: an obs run dir / ``.jsonl`` file (trace mode), or
    a JSON document (bench record, ``BENCH_r0*.json`` wrapper,
    ``HOST_PHASE.json`` capture, or ``summary --json`` output). Raises
    ``ValueError`` on unrecognizable input — regress must fail loudly, not
    compare garbage.
    """
    snap = _blank_snapshot("trace", str(target))
    if os.path.isdir(target) or str(target).endswith(".jsonl"):
        from simple_tip_tpu.obs.cli import (
            _span_table,
            _summed_counters,
            load_events,
        )

        events, files, _bad = load_events(target)
        if not files:
            raise ValueError(f"{target}: no events-*.jsonl streams found")
        snap["phases"] = {
            name: round(total, 6)
            for name, (_cnt, total, _mx) in _span_table(events).items()
        }
        snap["counters"] = _summed_counters(events)
        return snap

    try:
        with open(target, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise ValueError(f"{target}: not a readable JSON document ({e})")
    if not isinstance(doc, dict):
        raise ValueError(f"{target}: expected a JSON object")
    if isinstance(doc.get("parsed"), dict):  # BENCH_r0*.json driver wrapper
        doc = doc["parsed"]

    if doc.get("kind") == "audit" and isinstance(doc.get("phases"), dict):
        # `obs audit --json` document (obs v4): per-phase mean absolute
        # prediction error in seconds, pre-shaped for trend gating — a
        # chronological series of audits fails `obs trend` when the cost
        # model drifts out of its historical error band.
        snap = _blank_snapshot("audit", str(target))
        snap["phases"] = {
            str(k): float(v)
            for k, v in doc["phases"].items()
            if isinstance(v, (int, float))
        }
        counters = doc.get("counters") or {}
        snap["counters"] = {
            k: v for k, v in counters.items() if isinstance(v, (int, float))
        }
        if "degraded" in doc:
            snap["degraded"] = bool(doc.get("degraded"))
        return snap

    if doc.get("kind") == "mfu_breakdown":  # MFU_BREAKDOWN.json capture
        return _normalize_mfu_breakdown(doc, str(target))

    if "metric" in doc and "value" in doc:  # bench record
        return _normalize_bench(doc, str(target))

    if "test_prio_s" in doc or "sa_setup" in doc:  # HOST_PHASE.json capture
        return _normalize_host_phase(doc, str(target))

    if isinstance(doc.get("spans"), dict):  # summary --json document
        snap["phases"] = {
            name: float(info.get("total_s", 0) or 0)
            for name, info in doc["spans"].items()
            if isinstance(info, dict)
        }
        counters = doc.get("counters") or {}
        snap["counters"] = {
            k: v for k, v in counters.items() if isinstance(v, (int, float))
        }
        return snap

    raise ValueError(
        f"{target}: unrecognized snapshot (need an obs run dir, a bench "
        "record / BENCH_r0*.json, or `obs summary --json` output)"
    )


def compare(
    baseline: dict,
    current: dict,
    max_growth: float = DEFAULT_MAX_GROWTH,
    min_seconds: float = DEFAULT_MIN_SECONDS,
) -> dict:
    """Diff two snapshots; returns ``{rows, regressions, ok}``.

    ``rows`` is every compared (kind, name, base, cur, delta) tuple-dict —
    the printable table; ``regressions`` the failing subset.
    """
    rows = []

    def row(kind, name, base, cur, regressed, note=""):
        delta = None
        if isinstance(base, (int, float)) and isinstance(cur, (int, float)) and base:
            delta = (cur - base) / abs(base)
        rows.append(
            {
                "kind": kind,
                "name": name,
                "baseline": base,
                "current": cur,
                "delta": delta,
                "regressed": bool(regressed),
                "note": note,
            }
        )

    for name in sorted(set(baseline["phases"]) | set(current["phases"])):
        base = baseline["phases"].get(name)
        cur = current["phases"].get(name)
        if base is None or cur is None:
            row("phase", name, base, cur, False, "only in one snapshot")
            continue
        if base < min_seconds:
            row("phase", name, base, cur, False, "below noise floor")
            continue
        grew = cur > base * (1.0 + max_growth)
        row(
            "phase", name, base, cur, grew,
            f"> +{max_growth:.0%} growth" if grew else "",
        )

    if baseline["value"] is not None and current["value"] is not None:
        dropped = (
            baseline["value"] > 0
            and current["value"] < baseline["value"] * (1.0 - max_growth)
        )
        row(
            "bench", "value", baseline["value"], current["value"], dropped,
            f"> -{max_growth:.0%} drop" if dropped else "",
        )

    base_floors = baseline.get("floors") or {}
    cur_floors = current.get("floors") or {}
    for name in sorted(set(base_floors) | set(cur_floors)):
        base = base_floors.get(name)
        cur = cur_floors.get(name)
        if base is None or cur is None:
            row("floor", name, base, cur, False, "only in one snapshot")
            continue
        dropped = base > 0 and cur < base * (1.0 - max_growth)
        row(
            "floor", name, base, cur, dropped,
            f"> -{max_growth:.0%} drop" if dropped else "",
        )

    if baseline["degraded"] is not None or current["degraded"] is not None:
        flip = baseline["degraded"] is False and current["degraded"] is True
        row(
            "bench", "degraded", baseline["degraded"], current["degraded"],
            flip, "false -> true flip" if flip else "",
        )

    for name in sorted(set(baseline["counters"]) | set(current["counters"])):
        if not _is_health_counter(name):
            continue
        base = baseline["counters"].get(name, 0)
        cur = current["counters"].get(name, 0)
        row(
            "counter", name, base, cur, cur > base,
            "health counter grew" if cur > base else "",
        )

    regressions = [r for r in rows if r["regressed"]]
    return {"rows": rows, "regressions": regressions, "ok": not regressions}


def render(result: dict, baseline: dict, current: dict) -> str:
    """The comparison as a deterministic text table."""
    out = [
        f"baseline: {baseline['source']} ({baseline['kind']})",
        f"current:  {current['source']} ({current['kind']})",
        "",
        f"  {'kind':<8} {'name':<40} {'baseline':>12} {'current':>12} "
        f"{'delta':>8}  verdict",
    ]

    def fmt(v):
        if isinstance(v, bool) or v is None:
            return str(v)
        if isinstance(v, float):
            return f"{v:.3f}"
        return str(v)

    for r in result["rows"]:
        delta = f"{r['delta']:+.0%}" if r["delta"] is not None else "-"
        verdict = "REGRESSED" if r["regressed"] else "ok"
        if r["note"] and not r["regressed"]:
            verdict = f"ok ({r['note']})"
        elif r["note"]:
            verdict = f"REGRESSED ({r['note']})"
        out.append(
            f"  {r['kind']:<8} {r['name']:<40} {fmt(r['baseline']):>12} "
            f"{fmt(r['current']):>12} {delta:>8}  {verdict}"
        )
    out.append("")
    n = len(result["regressions"])
    out.append(
        "regress OK: no regressions"
        if not n
        else f"regress FAILED: {n} regression(s)"
    )
    return "\n".join(out)


def bench_delta(
    current_record: dict, previous_path: str, baseline_snapshot=None
) -> dict:
    """``bench.py`` hook: the current record's delta vs a previous BENCH file.

    ``baseline_snapshot`` (from ``select_bench_baseline``) skips re-loading
    ``previous_path``; the path then only labels the comparison. Returns a
    JSON-safe summary to embed in the record (never raises — bench's
    one-JSON-line contract outranks the companion).
    """
    try:
        baseline = baseline_snapshot or load_snapshot(previous_path)
        current = _normalize_bench(current_record, "<current run>")
        result = compare(baseline, current)
        return {
            "against": os.path.basename(previous_path),
            "ok": result["ok"],
            "regressions": [
                {k: r[k] for k in ("kind", "name", "baseline", "current", "note")}
                for r in result["regressions"]
            ],
            "value_ratio": (
                round(current["value"] / baseline["value"], 3)
                if baseline["value"]
                else None
            ),
        }
    except Exception as e:  # noqa: BLE001 — companion data, never fatal
        return {"against": os.path.basename(str(previous_path)), "error": repr(e)[:200]}


def select_bench_baseline(dirpath: str):
    """The newest COMPARABLE bench baseline in ``dirpath``: ``(snap, note)``.

    Scans ``BENCH_r*.json`` newest-first. The first non-degraded record
    wins; failing that, the newest embedded ``last_good_tpu`` record (a
    degraded wrapper carrying the pre-outage chip measurement) is promoted
    to baseline with a note saying so; failing that, ``(None,
    "no_comparable_baseline")``. A ``degraded: true`` record itself is
    NEVER returned — comparing against the CPU fallback is how BENCH r05
    passed review.
    """
    try:
        rounds = sorted(
            (
                n
                for n in os.listdir(dirpath)
                if n.startswith("BENCH_r") and n.endswith(".json")
            ),
            reverse=True,
        )
    except OSError:
        return None, "no_comparable_baseline"
    last_good = None  # newest (doc, note) fallback seen so far
    for name in rounds:
        path = os.path.join(dirpath, name)
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
            doc = doc["parsed"]
        if not isinstance(doc, dict) or "value" not in doc:
            continue
        if not doc.get("degraded", False):
            return _normalize_bench(doc, path), name
        lg = doc.get("last_good_tpu")
        if (
            last_good is None
            and isinstance(lg, dict)
            and isinstance(lg.get("value"), (int, float))
            and not lg.get("degraded", False)
        ):
            last_good = (lg, f"last_good_tpu of {name}")
    if last_good is not None:
        doc, note = last_good
        return _normalize_bench(doc, note), note
    return None, "no_comparable_baseline"


def _band(values, band: float, rel_floor: float):
    """Robust ``(median, half_width)`` of a sample: MAD-sigma band.

    The half-width is ``max(band * 1.4826 * MAD, rel_floor * |median|)`` —
    the relative floor keeps a zero-variance history from flagging noise.
    """
    med = statistics.median(values)
    mad = statistics.median(abs(v - med) for v in values)
    return med, max(band * 1.4826 * mad, rel_floor * abs(med))


def trend(
    snapshots,
    window: int = DEFAULT_TREND_WINDOW,
    band: float = DEFAULT_TREND_BAND,
    rel_floor: float = DEFAULT_TREND_REL_FLOOR,
    min_seconds: float = DEFAULT_MIN_SECONDS,
    min_baseline: int = DEFAULT_MIN_BASELINE,
) -> dict:
    """Gate the LAST snapshot against a trend over its predecessors.

    ``snapshots`` is a chronological list (oldest first) of
    ``load_snapshot`` dicts; the last one is under test. The baseline is
    the last ``window`` NON-DEGRADED predecessors — degraded rows never
    enter a baseline, but a current ``degraded: true`` still regresses
    (flip gate). Per phase/metric the current value is compared against
    robust median/MAD bands (``_band``): durations regress above the upper
    band (if the median clears ``min_seconds``), the bench ``value``
    regresses below the lower band (throughput: higher is better), and a
    health counter regresses above the baseline window's max.

    Returns ``{verdict, ok, rows, regressions, n_baseline, current}`` with
    ``verdict`` one of ``ok`` / ``regression`` / ``no_comparable_baseline``
    (fewer than ``min_baseline`` comparable predecessors — CI exit 3, a
    skip, not a failure).
    """
    if not snapshots:
        return {
            "verdict": "no_comparable_baseline",
            "ok": False,
            "rows": [],
            "regressions": [],
            "n_baseline": 0,
            "current": None,
        }
    current = snapshots[-1]
    comparable = [s for s in snapshots[:-1] if s.get("degraded") is not True]
    # Like-for-like plans only: a record measured under ExecutionPlan A is
    # not a baseline for one measured under plan B (different knob
    # assignments measure different configurations, not drift). Snapshot
    # kinds without a plan stamp (host_phase, audit, obs runs) keep the
    # unfiltered window — their current["plan"] is None.
    if current.get("plan") is not None:
        comparable = [
            s for s in comparable
            if (s.get("plan") or "unplanned") == current["plan"]
        ]
    baseline = comparable[-window:]
    if len(baseline) < min_baseline:
        return {
            "verdict": "no_comparable_baseline",
            "ok": False,
            "rows": [],
            "regressions": [],
            "n_baseline": len(baseline),
            "current": current["source"],
        }

    rows = []

    def row(kind, name, med, half, cur, regressed, note=""):
        rows.append(
            {
                "kind": kind,
                "name": name,
                "median": med,
                "band": half,
                "current": cur,
                "regressed": bool(regressed),
                "note": note,
            }
        )

    for name in sorted(current["phases"]):
        cur = current["phases"][name]
        history = [
            s["phases"][name] for s in baseline if name in s["phases"]
        ]
        if len(history) < min_baseline:
            row("phase", name, None, None, cur, False, "not enough history")
            continue
        med, half = _band(history, band, rel_floor)
        if med < min_seconds:
            row("phase", name, med, half, cur, False, "below noise floor")
            continue
        grew = cur > med + half
        row(
            "phase", name, med, half, cur, grew,
            "above trend band" if grew else "",
        )

    if current["value"] is not None:
        history = [
            s["value"] for s in baseline if isinstance(s["value"], (int, float))
        ]
        if len(history) >= min_baseline:
            med, half = _band(history, band, rel_floor)
            dropped = current["value"] < med - half
            row(
                "bench", "value", med, half, current["value"], dropped,
                "below trend band" if dropped else "",
            )
        else:
            row(
                "bench", "value", None, None, current["value"], False,
                "not enough history",
            )

    # Floors (MFU and friends): drop-is-bad, the mirror of the bench value
    # gate — a utilization collapse on an otherwise-fast run still fails.
    for name in sorted(current.get("floors") or {}):
        cur = current["floors"][name]
        history = [
            (s.get("floors") or {}).get(name) for s in baseline
        ]
        history = [v for v in history if isinstance(v, (int, float))]
        if len(history) < min_baseline:
            row("floor", name, None, None, cur, False, "not enough history")
            continue
        med, half = _band(history, band, rel_floor)
        dropped = cur < med - half
        row(
            "floor", name, med, half, cur, dropped,
            "below trend band" if dropped else "",
        )

    if current["degraded"] is not None:
        flip = current["degraded"] is True
        row(
            "bench", "degraded", False, None, current["degraded"], flip,
            "degraded flip vs non-degraded baseline" if flip else "",
        )

    names = set(current["counters"])
    for s in baseline:
        names |= set(s["counters"])
    for name in sorted(names):
        if not _is_health_counter(name):
            continue
        cur = current["counters"].get(name, 0)
        peak = max((s["counters"].get(name, 0) for s in baseline), default=0)
        row(
            "counter", name, peak, None, cur, cur > peak,
            "above baseline-window max" if cur > peak else "",
        )

    regressions = [r for r in rows if r["regressed"]]
    return {
        "verdict": "regression" if regressions else "ok",
        "ok": not regressions,
        "rows": rows,
        "regressions": regressions,
        "n_baseline": len(baseline),
        "current": current["source"],
    }


def render_trend(result: dict) -> str:
    """A trend verdict as a deterministic text table."""
    if result["verdict"] == "no_comparable_baseline":
        return (
            f"trend SKIPPED: no comparable baseline "
            f"({result['n_baseline']} non-degraded predecessor(s), "
            f"need {DEFAULT_MIN_BASELINE})"
        )
    out = [
        f"current: {result['current']}  "
        f"(baseline: {result['n_baseline']} non-degraded run(s))",
        "",
        f"  {'kind':<8} {'name':<40} {'median':>12} {'band':>10} "
        f"{'current':>12}  verdict",
    ]

    def fmt(v):
        if isinstance(v, bool) or v is None:
            return str(v)
        if isinstance(v, float):
            return f"{v:.3f}"
        return str(v)

    for r in result["rows"]:
        verdict = "REGRESSED" if r["regressed"] else "ok"
        if r["note"]:
            verdict += f" ({r['note']})"
        out.append(
            f"  {r['kind']:<8} {r['name']:<40} {fmt(r['median']):>12} "
            f"{fmt(r['band']):>10} {fmt(r['current']):>12}  {verdict}"
        )
    out.append("")
    n = len(result["regressions"])
    out.append(
        "trend OK: inside the band"
        if not n
        else f"trend FAILED: {n} regression(s) vs trend"
    )
    return "\n".join(out)

"""Learned per-phase cost model over the telemetry feature store (obs v3).

"A Learned Performance Model for TPUs" (arxiv 2008.01040) shows that a
small set of per-phase cost features predicts runtime well; the feature
store (``obs/store.py``) already persists exactly those features for every
run this repo has executed. This module closes the loop: a stdlib-only
least-squares fit per phase (features -> seconds-per-run), and a study
predictor that turns a proposed config (case studies x runs x phases x
backend x workers) into a wall-clock estimate with a stated error — the
admission-control number ``obs predict``, ``run_scheduler`` and
``full_study.py`` quote before launching anything.

Honesty rules:

- a phase with fewer than ``min_rows`` corpus rows is **insufficient**: it
  falls back to the phase median (or nothing at all) and is named loudly
  in the prediction's ``insufficient`` list — silent extrapolation from a
  2-row corpus is how wall-clock estimates become fiction;
- degraded rows never train the model (a CPU-fallback run teaches the
  wrong coefficients for every healthy launch);
- the stated error is the fit's mean absolute error scaled to the study
  size — optimistic for extrapolation, but it is *stated*, so the reader
  can judge.

The solver is normal equations + Gaussian elimination with a small ridge
term — 5 features never justify a linear-algebra dependency, and this must
run in the tier-0 dependency-free CI gate.
"""

import math

from simple_tip_tpu.obs import store

#: Minimum corpus rows per phase before the least-squares fit is trusted.
DEFAULT_MIN_ROWS = 3

#: Ridge regularizer added to the normal equations' diagonal: keeps the
#: solve stable when a feature column is constant (e.g. all-CPU corpus).
RIDGE = 1e-6


def _features(platform, count, batch, group=None) -> list:
    """The feature vector of one observation:
    ``[1, cpu?, ln(1+n), ln(1+batch), ln(group)]``.

    ``group`` is the cross-run dispatch-fusion group size (models per chain
    dispatch, ``TIP_CHAIN_GROUP``); ``ln(group)`` is 0 at the ungrouped
    baseline (group=1 or absent), so corpora without grouped rows fit the
    exact pre-group model (the ridge pins the dead column's coefficient to
    ~0) and grouped rows teach the G-vs-throughput slope the planner ranks.
    """
    cpu = 1.0 if str(platform or "").lower() == "cpu" else 0.0
    return [
        1.0,
        cpu,
        math.log1p(max(float(count or 1), 1.0)),
        math.log1p(max(float(batch or 0), 0.0)),
        math.log(max(float(group or 1), 1.0)),
    ]


def _solve(matrix, rhs) -> list:
    """Gaussian elimination with partial pivoting: ``matrix @ x = rhs``."""
    n = len(rhs)
    a = [row[:] + [rhs[i]] for i, row in enumerate(matrix)]
    for col in range(n):
        pivot = max(range(col, n), key=lambda r: abs(a[r][col]))
        a[col], a[pivot] = a[pivot], a[col]
        if abs(a[col][col]) < 1e-12:
            raise ValueError("singular system")
        inv = 1.0 / a[col][col]
        for r in range(n):
            if r == col:
                continue
            factor = a[r][col] * inv
            for c in range(col, n + 1):
                a[r][c] -= factor * a[col][c]
    return [a[i][n] / a[i][i] for i in range(n)]


def _least_squares(xs, ys) -> list:
    """Ridge-regularized least-squares coefficients of ``xs @ c ~ ys``."""
    k = len(xs[0])
    xtx = [[RIDGE if i == j else 0.0 for j in range(k)] for i in range(k)]
    xty = [0.0] * k
    for x, y in zip(xs, ys):
        for i in range(k):
            xty[i] += x[i] * y
            for j in range(k):
                xtx[i][j] += x[i] * x[j]
    return _solve(xtx, xty)


def _median(values) -> float:
    """The sample median (stdlib-free of statistics for a hot loop)."""
    vals = sorted(values)
    mid = len(vals) // 2
    if len(vals) % 2:
        return float(vals[mid])
    return (vals[mid - 1] + vals[mid]) / 2.0


def fit(rows, min_rows: int = DEFAULT_MIN_ROWS) -> dict:
    """Fit the per-phase cost model over feature-store ``rows``.

    Only non-degraded rows with a ``seconds`` target train; the target is
    seconds-per-unit (``seconds / count``) so scheduler aggregates and
    single runs land on the same scale. Returns ``{phases: {name: {coef,
    n, mae_s, median_s, sufficient}}, rows_used}`` — an insufficient phase
    has ``coef: None`` and only its median as a fallback estimate.
    """
    by_phase = {}
    used = 0
    for row in rows:
        secs = row.get("seconds")
        if not isinstance(secs, (int, float)) or secs < 0:
            continue
        if row.get("degraded") is True:
            continue
        count = max(float(row.get("count") or 1), 1.0)
        by_phase.setdefault(str(row.get("phase")), []).append(
            (
                _features(
                    row.get("platform"), count, row.get("batch"),
                    row.get("group"),
                ),
                float(secs) / count,
            )
        )
        used += 1
    phases = {}
    for name, obs in sorted(by_phase.items()):
        ys = [y for _x, y in obs]
        entry = {
            "coef": None,
            "n": len(obs),
            "mae_s": None,
            "median_s": round(_median(ys), 6),
            "sufficient": len(obs) >= min_rows,
        }
        if entry["sufficient"]:
            try:
                coef = _least_squares([x for x, _y in obs], ys)
                mae = _median(  # median abs error: robust to one outlier run
                    abs(sum(c * f for c, f in zip(coef, x)) - y)
                    for x, y in obs
                )
                entry["coef"] = [round(c, 8) for c in coef]
                entry["mae_s"] = round(mae, 6)
            except ValueError:
                entry["sufficient"] = False
        phases[name] = entry
    return {"phases": phases, "rows_used": used}


def phase_estimate(model: dict, phase: str, platform=None, batch=None,
                   group=None):
    """``(seconds_per_run, error_s, basis)`` for one phase, or Nones.

    ``basis`` is ``model`` (trusted fit), ``median`` (insufficient corpus
    fallback) or ``missing`` (phase absent from the corpus entirely).
    """
    entry = (model.get("phases") or {}).get(phase)
    if entry is None:
        return None, None, "missing"
    if entry["sufficient"] and entry["coef"]:
        x = _features(platform, 1, batch, group)
        est = sum(c * f for c, f in zip(entry["coef"], x))
        return max(est, 0.0), entry["mae_s"] or 0.0, "model"
    return entry["median_s"], entry["median_s"], "median"


def predict_study(
    model: dict,
    phases,
    runs: int,
    case_studies: int = 1,
    platform=None,
    workers: int = 1,
    batch=None,
    group=None,
) -> dict:
    """Wall-clock estimate of ``case_studies x runs`` over ``phases``.

    Per phase: seconds-per-run from ``phase_estimate`` x total runs,
    divided by ``workers`` (ideal packing — real schedules straggle, and
    the stated error does not cover that). Returns ``{total_s, error_s,
    by_phase, insufficient, ok}``; ``ok`` is False when NO requested phase
    had a trusted or fallback estimate — the loud "insufficient corpus"
    case callers must surface, not bury.
    """
    workers = max(int(workers), 1)
    total_runs = max(int(runs), 0) * max(int(case_studies), 1)
    by_phase = {}
    insufficient = []
    total = err = 0.0
    any_estimate = False
    for phase in phases:
        per_run, per_err, basis = phase_estimate(
            model, phase, platform, batch, group
        )
        if basis != "model":
            insufficient.append(phase)
        if per_run is None:
            by_phase[phase] = {
                "per_run_s": None,
                "total_s": None,
                "basis": basis,
            }
            continue
        any_estimate = True
        phase_total = per_run * total_runs / workers
        phase_err = (per_err or 0.0) * total_runs / workers
        by_phase[phase] = {
            "per_run_s": round(per_run, 4),
            "total_s": round(phase_total, 2),
            "error_s": round(phase_err, 2),
            "basis": basis,
            "corpus_rows": model["phases"][phase]["n"],
        }
        total += phase_total
        err += phase_err
    return {
        "total_s": round(total, 2),
        "error_s": round(err, 2),
        "runs": total_runs,
        "workers": workers,
        "by_phase": by_phase,
        "insufficient": insufficient,
        "ok": any_estimate,
    }


def quick_phase_estimate(
    phase: str,
    n_runs: int,
    platform=None,
    workers: int = 1,
    index_dir=None,
):
    """Failure-safe pre-launch estimate for one scheduler phase, or None.

    Loads the index, fits, predicts — and returns None on ANY problem
    (no index, empty corpus, unknown phase): admission control is
    advisory; a missing estimate must never block a launch.
    """
    try:
        rows = store.load_corpus(index_dir)
        if not rows:
            return None
        prediction = predict_study(
            fit(rows), [phase], n_runs, platform=platform, workers=workers
        )
        info = prediction["by_phase"].get(phase) or {}
        if info.get("total_s") is None:
            return None
        return {
            "predicted_s": info["total_s"],
            "error_s": info.get("error_s"),
            "basis": info.get("basis"),
            "corpus_rows": info.get("corpus_rows"),
        }
    except Exception:  # noqa: BLE001 — advisory, never load-bearing
        return None


def render_prediction(result: dict) -> str:
    """A study prediction as a deterministic text table."""
    out = [
        f"predicted wall-clock: {result['total_s']:.1f} s "
        f"(+/- {result['error_s']:.1f} s) for {result['runs']} run(s) "
        f"across {result['workers']} worker(s)",
        "",
        f"  {'phase':<32} {'per-run s':>10} {'total s':>10} "
        f"{'+/- s':>8} {'rows':>5}  basis",
    ]
    for phase, info in sorted(result["by_phase"].items()):
        per_run = info.get("per_run_s")
        total_s = info.get("total_s")
        error_s = info.get("error_s", 0)
        out.append(
            f"  {phase:<32} "
            f"{(f'{per_run:.3f}' if per_run is not None else '-'):>10} "
            f"{(f'{total_s:.1f}' if total_s is not None else '-'):>10} "
            f"{(f'{error_s:.1f}' if total_s is not None else '-'):>8} "
            f"{str(info.get('corpus_rows', '-')):>5}  {info['basis']}"
        )
    if result["insufficient"]:
        out.append("")
        out.append(
            "INSUFFICIENT CORPUS for: "
            + ", ".join(result["insufficient"])
            + " (median fallback or no estimate — grow the index by "
            "running studies with TIP_OBS_DIR=auto)"
        )
    return "\n".join(out)

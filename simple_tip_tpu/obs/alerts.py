"""Alert evaluation engine: state machine, persistence, sinks, incidents.

The procedural half of obs v5 (the declarative half — rule documents and
burn-rate math — is ``obs/slo.py``). An :class:`Evaluator` consumes the
live metrics registry in-process plus the feature-store index
cross-process, and drives one state machine per rule::

    inactive -> pending -> firing -> resolved

- **pending**   the slow-burn warn condition holds, or the fast-burn page
  condition holds but hasn't yet held for the rule's ``for_s``;
- **firing**    the fast-burn condition held for ``for_s`` — the page;
- **resolved**  a firing rule whose burn dropped below both thresholds;
  behaves like inactive for re-trips (a fresh breach starts a fresh
  pending), but keeps the resolve timestamp for the operator.

Crash-safety and the fleet: state is one JSON file (``TIP_ALERT_STATE``
dir, default ``$TIP_ASSETS/obs/alerts/``) written atomically (pid-unique
tmp + fsync + ``os.replace``, the bus pattern) and carrying a monotonic
**fence**: every save re-reads the on-disk fence and loses (adopting the
disk state instead of writing) when another evaluator advanced it — a
stale fleet member can never roll back a newer evaluator's transitions,
and transitions are emitted only AFTER the save wins, so a resolve is
emitted exactly once per state-file history. A restarted evaluator
resumes mid-firing with the original ``started_ts`` intact (sample
windows persist too, so recovery still needs real healthy ticks). The
save path carries the ``alerts.save`` fault seam, so chaos plans can
kill the evaluator mid-persist.

Transitions go to pluggable sinks (``TIP_ALERT_SINKS``, default
``stderr,jsonl``): a one-line stderr pager, the append-only
``alerts.jsonl`` next to the state file, and a webhook-shaped file sink
(``webhook:/path`` — each transition as a POST-shaped JSON doc, the
test/integration stand-in for a real receiver). Every transition is also
a schema-stamped obs event (``alert.firing`` etc.) in the span stream.

Incidents: a rule entering firing opens an incident record stamped with
the active ExecutionPlan fingerprint and a correlation of the alert
window against the run's obs streams — overlapping span names,
request_ids, breaker/chaos/fault events. Resolving closes it with
duration and budget-burn, appending the record to ``incidents.jsonl``.

Surfaces: the exporter's ``/alerts`` route serves :meth:`Evaluator.view`
(an in-memory cached dict — the blocking-endpoint contract); ``obs
alerts`` / ``obs incidents`` read the state file cross-process. Owner
loops (scheduler health cadence, fleet beat, ScoringEngine) mount the
evaluator via the module-level :func:`tick` — rate-limited
(``TIP_ALERT_EVAL_S``), failure-safe, and a cheap no-op when no rule
document is configured.

Stdlib-only, tier-0-importable, crash-safe like the rest of obs.
"""

import hashlib
import json
import logging
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

from simple_tip_tpu.obs import metrics, slo

logger = logging.getLogger(__name__)

#: Stamp on the state file, every transition record and every incident
#: row (the obs JSONL schema contract).
SCHEMA = 1

STATE_ENV = "TIP_ALERT_STATE"
SINKS_ENV = "TIP_ALERT_SINKS"
EVAL_S_ENV = "TIP_ALERT_EVAL_S"

STATES = ("inactive", "pending", "firing", "resolved")

#: Feature-store rows are re-read at most this often (they change on
#: `obs runs` cadence, not per tick).
_INDEX_REFRESH_S = 30.0
#: Quiet-state persistence cadence: transitions always persist
#: immediately; sample windows at most this often.
_PERSIST_S = 5.0
#: Obs-event name prefixes the incident correlator collects as "what else
#: happened in the alert window".
_CORRELATE_EVENTS = ("breaker.", "fault.", "chaos.", "scheduler.fail",
                     "serving.backend_error", "fleet.")


def default_state_dir() -> str:
    """The alert-state directory: ``TIP_ALERT_STATE`` or
    ``$TIP_ASSETS/obs/alerts``."""
    raw = os.environ.get(STATE_ENV, "").strip()
    if raw:
        return os.path.abspath(raw)
    assets = os.environ.get("TIP_ASSETS", os.path.join(os.getcwd(), "assets"))
    return os.path.join(os.path.abspath(assets), "obs", "alerts")


def _state_path(state_dir: str) -> str:
    return os.path.join(state_dir, "alert_state.json")


def alerts_log_path(state_dir: Optional[str] = None) -> str:
    """The append-only transition log next to the state file."""
    return os.path.join(state_dir or default_state_dir(), "alerts.jsonl")


def incidents_path(state_dir: Optional[str] = None) -> str:
    """The append-only closed-incident log next to the state file."""
    return os.path.join(state_dir or default_state_dir(), "incidents.jsonl")


class AlertStore:
    """Fenced, atomic persistence for the evaluator's state document.

    ``load`` returns the on-disk document (empty skeleton when absent/
    corrupt — a torn state file must not kill the evaluator). ``save``
    implements the fencing-token protocol described in the module
    docstring: it re-reads the on-disk fence and REFUSES to write when a
    higher fence landed since this evaluator's last load, returning the
    winner's document so the caller adopts it instead of clobbering.
    """

    def __init__(self, state_dir: Optional[str] = None):
        self.state_dir = state_dir or default_state_dir()
        self.path = _state_path(self.state_dir)

    def _read(self) -> dict:
        try:
            with open(self.path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return {}
        return doc if isinstance(doc, dict) and doc.get("schema") == SCHEMA else {}

    def load(self) -> dict:
        """The persisted state document (skeleton when absent/corrupt)."""
        doc = self._read()
        doc.setdefault("schema", SCHEMA)
        doc.setdefault("fence", 0)
        doc.setdefault("rules", {})
        doc.setdefault("incidents_open", {})
        return doc

    def save(self, doc: dict, expected_fence: int) -> Tuple[bool, dict]:
        """Persist ``doc`` if nobody outran ``expected_fence``.

        Returns ``(True, doc)`` on a winning write (``doc["fence"]`` is
        advanced), ``(False, winner)`` when a newer evaluator already
        wrote — the caller must adopt ``winner`` and drop its pending
        transitions. The ``alerts.save`` fault seam fires before the
        atomic rename, so a chaos plan can kill the evaluator between
        deciding a transition and persisting it.
        """
        on_disk = self._read()
        disk_fence = int(on_disk.get("fence", 0) or 0)
        if disk_fence > expected_fence:
            return False, self.load()
        doc = dict(doc)
        doc["schema"] = SCHEMA
        doc["fence"] = disk_fence + 1
        doc["pid"] = os.getpid()
        doc["updated_ts"] = time.time()
        from simple_tip_tpu.resilience import faults

        faults.maybe_inject("alerts.save", fence=doc["fence"])
        os.makedirs(self.state_dir, exist_ok=True)
        tmp = f"{self.path}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, sort_keys=True, default=repr)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        return True, doc


# -- sinks -----------------------------------------------------------------


def _parse_sinks(state_dir: str) -> List[Tuple[str, Optional[str]]]:
    """``TIP_ALERT_SINKS`` as (kind, path) pairs; default stderr+jsonl."""
    raw = os.environ.get(SINKS_ENV, "").strip() or "stderr,jsonl"
    if raw.lower() in ("0", "off", "none"):
        return []
    out: List[Tuple[str, Optional[str]]] = []
    for tok in raw.split(","):
        tok = tok.strip()
        if tok == "stderr":
            out.append(("stderr", None))
        elif tok == "jsonl":
            out.append(("jsonl", alerts_log_path(state_dir)))
        elif tok.startswith("webhook:"):
            out.append(("webhook", tok.split(":", 1)[1]))
        elif tok:
            logger.warning("%s: unknown sink %r ignored", SINKS_ENV, tok)
    return out


def _emit_transition(sinks, rec: dict) -> None:
    """Fan one transition out to every sink + the obs event stream.

    Failure-safe per sink: a full disk or unwritable webhook path must
    not take down the process being watched.
    """
    from simple_tip_tpu import obs

    try:
        obs.event(
            f"alert.{rec['to']}", schema=SCHEMA, rule=rec["rule"],
            severity=rec["severity"],
            **({"incident": rec["incident"]} if rec.get("incident") else {}),
        )
    except Exception:  # noqa: BLE001 — telemetry never takes the host down
        pass
    line = json.dumps(rec, sort_keys=True, default=repr)
    for kind, path in sinks:
        try:
            if kind == "stderr":
                burn = rec.get("burn_fast")
                sys.stderr.write(
                    f"ALERT {rec['to'].upper()} [{rec['severity']}] "
                    f"{rec['rule']}: value={rec.get('value')} "
                    f"burn={'-' if burn is None else round(burn, 2)}x"
                    f"{' incident=' + rec['incident'] if rec.get('incident') else ''}\n"
                )
                sys.stderr.flush()
            elif kind == "jsonl":
                os.makedirs(os.path.dirname(path), exist_ok=True)
                with open(path, "a", encoding="utf-8") as f:
                    f.write(line + "\n")
            elif kind == "webhook":
                # POST-shaped doc: what a real webhook receiver would get.
                body = json.dumps(
                    {"schema": SCHEMA, "method": "POST", "path": "/alert",
                     "headers": {"content-type": "application/json"},
                     "body": rec},
                    sort_keys=True, default=repr,
                )
                with open(path, "a", encoding="utf-8") as f:
                    f.write(body + "\n")
        except OSError as e:
            logger.warning("alert sink %s failed: %s", kind, e)


# -- incident correlation --------------------------------------------------


def _correlate(start: float, end: float) -> dict:
    """What else happened in ``[start, end]``: spans, request_ids, events.

    Reads the run's obs streams (``TIP_OBS_DIR``) — a filesystem walk,
    so this runs only on incident open/close from the evaluator's owner
    loop, never in an HTTP handler. Empty (never raises) when the stream
    is disabled or unreadable.
    """
    empty = {"spans": {}, "events": {}, "request_ids": []}
    try:
        from simple_tip_tpu import obs

        run_dir = obs.obs_dir()
        if not run_dir:
            return empty
        from simple_tip_tpu.obs.cli import load_events

        events, _files, _bad = load_events(run_dir)
    except Exception:  # noqa: BLE001 — correlation is best-effort color
        return empty
    spans: Dict[str, int] = {}
    names: Dict[str, int] = {}
    rids: List[str] = []
    for rec in events:
        ts = rec.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        kind = rec.get("type")
        attrs = rec.get("attrs") or {}
        if kind == "span":
            t1 = ts + float(rec.get("dur", 0) or 0)
            if t1 < start or ts > end:
                continue
            name = str(rec.get("name", "?"))
            spans[name] = spans.get(name, 0) + 1
        elif kind == "event":
            if ts < start or ts > end:
                continue
            name = str(rec.get("name", ""))
            if name.startswith(_CORRELATE_EVENTS):
                names[name] = names.get(name, 0) + 1
        else:
            continue
        raw = attrs.get("request_ids") or attrs.get("request_id")
        if isinstance(raw, str):
            rids.extend(r for r in raw.split(",") if r)
        elif isinstance(raw, (list, tuple)):
            rids.extend(str(r) for r in raw)
    top_spans = dict(
        sorted(spans.items(), key=lambda kv: (-kv[1], kv[0]))[:12]
    )
    seen = set()
    uniq = []
    for r in rids:
        if r not in seen:
            seen.add(r)
            uniq.append(r)
    return {"spans": top_spans, "events": names, "request_ids": uniq[:32]}


def _plan_fingerprint() -> str:
    """The active ExecutionPlan id ("unplanned" when none / on error)."""
    try:
        from simple_tip_tpu.plan.plan import active_plan_id

        return active_plan_id()
    except Exception:  # noqa: BLE001 — the stamp is color, never a blocker
        return "unplanned"


# -- the evaluator ---------------------------------------------------------


class Evaluator:
    """Per-rule alert state machines over live + cross-process signals.

    Deterministic under an explicit clock: every public entry takes
    ``now`` (wall seconds) so tests and the smoke replay trajectories
    without sleeping. Production mounts call :meth:`tick`, which
    rate-limits, snapshots the registry and delegates to
    :meth:`evaluate`.
    """

    def __init__(
        self,
        rules_doc: Optional[dict] = None,
        state_dir: Optional[str] = None,
        min_interval_s: Optional[float] = None,
    ):
        self.rules_doc = rules_doc if rules_doc is not None else slo.load_rules()
        self.rules = (self.rules_doc or {}).get("rules", [])
        self.store = AlertStore(state_dir)
        self.sinks = _parse_sinks(self.store.state_dir)
        if min_interval_s is None:
            try:
                min_interval_s = float(os.environ.get(EVAL_S_ENV, "") or 1.0)
            except ValueError:
                min_interval_s = 1.0
        self.min_interval_s = max(0.0, min_interval_s)
        self._doc = self.store.load()  # restart-resume: adopt persisted state
        self._last_eval = 0.0
        self._last_persist = 0.0
        self._index_rows: List[dict] = []
        self._index_read = 0.0
        self._view: dict = self._build_view(time.time())
        self._needs_index = any(
            r["objective"]["kind"] == "index" for r in self.rules
        )
        # /alerts serves this instance's cached view: a plain in-memory
        # read, per the blocking-endpoint contract.
        from simple_tip_tpu.obs import exporter

        exporter.set_provider("alerts", self.view)

    @property
    def enabled(self) -> bool:
        """Whether any rule survived document resolution."""
        return bool(self.rules)

    # -- public entry points ----------------------------------------------

    def tick(self, now: Optional[float] = None) -> List[dict]:
        """One rate-limited production tick over the live registry."""
        if not self.enabled:
            return []
        now = time.time() if now is None else float(now)
        if now - self._last_eval < self.min_interval_s:
            return []
        return self.evaluate(metrics.snapshot(), now=now)

    def evaluate(self, snap: dict, now: Optional[float] = None) -> List[dict]:
        """Evaluate every rule against ``snap``; the emitted transitions.

        Samples each rule, advances its burn windows and state machine,
        opens/closes incidents, persists (fenced), and only then emits
        transitions — losing the fence race drops this tick's transitions
        and adopts the winner's state, so the transition history in
        ``alerts.jsonl`` matches the state-file history exactly once.
        """
        now = time.time() if now is None else float(now)
        self._last_eval = now
        index_rows = self._load_index(now)
        prev_counters = self._doc.get("prev_counters")
        transitions: List[dict] = []
        for rule in self.rules:
            rs = self._doc["rules"].setdefault(
                rule["name"], {"state": "inactive", "samples": []}
            )
            rs["severity"] = rule["severity"]  # the CLI renders from disk
            sample = slo.sample_rule(rule, snap, prev_counters, index_rows)
            if sample is not None:
                rs["samples"] = list(rs.get("samples") or [])
                rs["samples"].append([round(now, 3), round(sample["bad"], 4)])
                rs["last_value"] = sample["value"]
            keep_s = rule["windows"]["slow"]["window_s"] + 60.0
            rs["samples"] = slo.prune_samples(
                rs.get("samples") or [], now, keep_s
            )
            transitions.extend(self._advance(rule, rs, now))
        self._doc["prev_counters"] = dict(snap.get("counters") or {})
        persisted = self._persist(now, force=bool(transitions))
        if not persisted:
            # Fence lost: a newer evaluator owns the state now. Its
            # transitions are already emitted by it; ours never happened.
            return []
        if transitions:
            for rec in transitions:
                _emit_transition(self.sinks, rec)
        self._view = self._build_view(now)
        return transitions

    def view(self) -> dict:
        """The cached in-memory /alerts document (handler-thread safe)."""
        return self._view

    # -- state machine -----------------------------------------------------

    def _advance(self, rule: dict, rs: dict, now: float) -> List[dict]:
        """Advance one rule's state machine; its transition records."""
        budget = rule["budget"]
        w = rule["windows"]
        burn_f = slo.burn_rate(rs["samples"], now, w["fast"]["window_s"], budget)
        burn_s = slo.burn_rate(rs["samples"], now, w["slow"]["window_s"], budget)
        rs["burn_fast"] = None if burn_f is None else round(burn_f, 4)
        rs["burn_slow"] = None if burn_s is None else round(burn_s, 4)
        fast_hot = burn_f is not None and burn_f >= w["fast"]["burn"]
        slow_hot = burn_s is not None and burn_s >= w["slow"]["burn"]
        state = rs.get("state", "inactive")
        out: List[dict] = []

        def to(new_state: str) -> None:
            rec = {
                "schema": SCHEMA,
                "ts": round(now, 3),
                "rule": rule["name"],
                "severity": rule["severity"],
                "from": state,
                "to": new_state,
                "value": rs.get("last_value"),
                "burn_fast": rs["burn_fast"],
                "burn_slow": rs["burn_slow"],
                "budget": budget,
            }
            rs["state"] = new_state
            rs["since_ts"] = round(now, 3)
            if new_state == "firing":
                rs["started_ts"] = round(now, 3)
                rec["incident"] = self._open_incident(rule, rs, now)
            elif new_state == "resolved":
                rec["started_ts"] = rs.get("started_ts")
                rec["incident"] = self._close_incident(rule, rs, now)
            out.append(rec)

        if fast_hot:
            if state != "firing":
                if rs.get("pending_since") is None:
                    rs["pending_since"] = round(now, 3)
                held = now - rs["pending_since"]
                if held >= rule["for_s"] and state != "firing":
                    if state not in ("pending",) and rule["for_s"] > 0:
                        # A cold rule crossing the page threshold always
                        # passes through pending first (the hold window),
                        # so operators see the escalation, not a jump.
                        to("pending")
                        state = "pending"
                    to("firing")
                elif state not in ("pending",):
                    to("pending")
        elif slow_hot:
            if state == "firing":
                pass  # still burning the budget: the page stays up
            elif state != "pending":
                rs["pending_since"] = round(now, 3)
                to("pending")
        else:
            rs["pending_since"] = None
            if state == "firing":
                to("resolved")
            elif state == "pending":
                to("inactive")
        return out

    # -- incidents ---------------------------------------------------------

    def _open_incident(self, rule: dict, rs: dict, now: float) -> str:
        """Open the incident record for a rule entering firing; its id."""
        ident = hashlib.sha256(
            f"{rule['name']}:{now:.3f}".encode()
        ).hexdigest()[:8]
        inc_id = f"inc-{ident}"
        lookback = rule["windows"]["fast"]["window_s"]
        start = (rs.get("pending_since") or now) - lookback
        inc = {
            "schema": SCHEMA,
            "id": inc_id,
            "rule": rule["name"],
            "severity": rule["severity"],
            "opened_ts": round(now, 3),
            "window_start_ts": round(start, 3),
            "plan": _plan_fingerprint(),
            "value": rs.get("last_value"),
            "burn_fast": rs.get("burn_fast"),
            "budget": rule["budget"],
            "correlated": _correlate(start, now),
        }
        self._doc["incidents_open"][rule["name"]] = inc
        rs["incident"] = inc_id
        return inc_id

    def _close_incident(
        self, rule: dict, rs: dict, now: float
    ) -> Optional[str]:
        """Close a firing rule's incident: duration, budget-burn, append."""
        inc = self._doc["incidents_open"].pop(rule["name"], None)
        if inc is None:
            return None
        opened = float(inc.get("opened_ts") or now)
        duration = max(0.0, now - opened)
        window = [s[1] for s in rs.get("samples") or []
                  if opened <= s[0] <= now]
        mean_bad = (sum(window) / len(window)) if window else 0.0
        inc = dict(inc)
        inc["closed_ts"] = round(now, 3)
        inc["duration_s"] = round(duration, 3)
        # Budget accounting the operator can act on: bad_s is raw error
        # time inside the incident; budget_burn_x is how many times
        # faster than the budget it burned while open.
        inc["bad_s"] = round(mean_bad * duration, 3)
        inc["budget_burn_x"] = round(mean_bad / rule["budget"], 3)
        inc["correlated"] = _correlate(
            float(inc.get("window_start_ts") or opened), now
        )
        try:
            path = incidents_path(self.store.state_dir)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "a", encoding="utf-8") as f:
                f.write(json.dumps(inc, sort_keys=True, default=repr) + "\n")
        except OSError as e:
            logger.warning("incident log write failed: %s", e)
        rs["incident"] = None
        rs["last_incident"] = inc["id"]
        return inc["id"]

    # -- plumbing ----------------------------------------------------------

    def _load_index(self, now: float) -> List[dict]:
        """Feature-store rows for index rules, refreshed on a slow cadence."""
        if not self._needs_index:
            return []
        if now - self._index_read >= _INDEX_REFRESH_S or not self._index_read:
            self._index_read = now
            try:
                from simple_tip_tpu.obs import store

                self._index_rows = store.load_corpus()
            except Exception:  # noqa: BLE001 — a torn index is not an outage
                self._index_rows = []
        return self._index_rows

    def _persist(self, now: float, force: bool) -> bool:
        """Fenced save (transitions force it; quiet ticks batch). True
        when this evaluator still owns the state afterwards."""
        if not force and now - self._last_persist < _PERSIST_S:
            return True
        self._last_persist = now
        ok, doc = self.store.save(
            self._doc, int(self._doc.get("fence", 0) or 0)
        )
        self._doc = doc
        if not ok:
            logger.warning(
                "alert state fence lost (pid %d): adopting the newer "
                "evaluator's state", os.getpid(),
            )
            self._view = self._build_view(now)
        return ok

    def _build_view(self, now: float) -> dict:
        """The /alerts document (rebuilt per evaluation, served cached)."""
        rules = []
        for rule in self.rules:
            rs = self._doc.get("rules", {}).get(rule["name"], {})
            rules.append(
                {
                    "rule": rule["name"],
                    "severity": rule["severity"],
                    "state": rs.get("state", "inactive"),
                    "since_ts": rs.get("since_ts"),
                    "started_ts": rs.get("started_ts"),
                    "value": rs.get("last_value"),
                    "burn_fast": rs.get("burn_fast"),
                    "burn_slow": rs.get("burn_slow"),
                    "budget": rule["budget"],
                    "incident": rs.get("incident"),
                }
            )
        return {
            "schema": SCHEMA,
            "generated_ts": round(now, 3),
            "source": (self.rules_doc or {}).get("source"),
            "state_dir": self.store.state_dir,
            "firing": sum(1 for r in rules if r["state"] == "firing"),
            "pending": sum(1 for r in rules if r["state"] == "pending"),
            "rules": rules,
            "incidents_open": sorted(
                self._doc.get("incidents_open", {}).values(),
                key=lambda i: i.get("opened_ts") or 0,
            ),
        }


# -- module-level singleton (the owner-loop mount point) -------------------

_singleton: Optional[Evaluator] = None


def enabled() -> bool:
    """Whether an alert rule document is configured for this process."""
    return slo.rules_configured()


def get(create: bool = True) -> Optional[Evaluator]:
    """The process's evaluator (lazily created when rules are configured)."""
    global _singleton
    if _singleton is not None:
        return _singleton
    if not create or not slo.rules_configured():
        return None
    _singleton = Evaluator()
    return _singleton


def tick(now: Optional[float] = None) -> None:
    """The production mount: evaluate if configured, swallow everything.

    Owner loops (scheduler health cadence, fleet beat, ScoringEngine)
    call this unconditionally; it is a single env read when alerting is
    off, rate-limited when on, and failure-safe always — the watcher
    must never take down the watched.
    """
    try:
        ev = get()
        if ev is not None:
            ev.tick(now=now)
    except Exception:  # noqa: BLE001 — telemetry never takes the host down
        logger.debug("alert tick failed", exc_info=True)


def reset() -> None:
    """Test hook: drop the singleton and its /alerts provider."""
    global _singleton
    _singleton = None
    try:
        from simple_tip_tpu.obs import exporter

        exporter.clear_provider("alerts")
    except Exception:  # noqa: BLE001 — teardown is best-effort
        pass


# -- cross-process readers + CLI entries (obs alerts / obs incidents) ------


def load_state(state_dir: Optional[str] = None) -> Optional[dict]:
    """The persisted state document, or None when nothing ever evaluated.

    Raises ``ValueError`` on a present-but-corrupt file so the CLI can
    distinguish "no evaluator ran" (exit 3) from "bad input" (exit 2).
    """
    path = _state_path(state_dir or default_state_dir())
    try:
        with open(path, encoding="utf-8") as f:
            raw = f.read()
    except OSError:
        return None
    try:
        doc = json.loads(raw)
    except ValueError as e:
        raise ValueError(f"{path}: corrupt alert state ({e})") from e
    if not isinstance(doc, dict) or doc.get("schema") != SCHEMA:
        raise ValueError(f"{path}: not a schema-{SCHEMA} alert state document")
    return doc


def load_incidents(
    state_dir: Optional[str] = None,
) -> Tuple[List[dict], List[dict]]:
    """``(open, closed)`` incidents from the state file + incidents.jsonl.

    Torn tail lines are skipped (the append-only crash contract); a
    corrupt state file propagates ``ValueError`` like :func:`load_state`.
    """
    state_dir = state_dir or default_state_dir()
    doc = load_state(state_dir)
    open_incs = sorted(
        (doc or {}).get("incidents_open", {}).values(),
        key=lambda i: i.get("opened_ts") or 0,
    )
    closed: List[dict] = []
    try:
        with open(incidents_path(state_dir), encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and rec.get("schema") == SCHEMA:
                    closed.append(rec)
    except OSError:
        pass
    return open_incs, closed


def _iso(ts) -> str:
    from simple_tip_tpu.obs.cli import _iso_utc

    return _iso_utc(ts)


def render_alerts(doc: dict) -> str:
    """The state document as the ``obs alerts`` fixed-width table."""
    lines = [
        f"{'rule':<26} {'sev':<5} {'state':<9} {'burn_f':>8} {'burn_s':>8} "
        f"{'value':>10} {'since (utc)':<26} incident"
    ]
    for name in sorted(doc.get("rules", {})):
        rs = doc["rules"][name]

        def _b(v):
            return "-" if not isinstance(v, (int, float)) else f"{v:.2f}x"

        value = rs.get("last_value")
        shown = "-" if not isinstance(value, (int, float)) else f"{value:.4g}"
        sev = rs.get("severity") if isinstance(rs.get("severity"), str) else "-"
        lines.append(
            f"{name:<26} {sev:<5} "
            f"{rs.get('state', 'inactive'):<9} {_b(rs.get('burn_fast')):>8} "
            f"{_b(rs.get('burn_slow')):>8} {shown:>10} "
            f"{_iso(rs.get('since_ts')):<26} {rs.get('incident') or '-'}"
        )
    firing = sum(
        1 for rs in doc.get("rules", {}).values() if rs.get("state") == "firing"
    )
    lines.append(
        f"\n{firing} firing, "
        f"{sum(1 for rs in doc.get('rules', {}).values() if rs.get('state') == 'pending')} "
        f"pending (fence {doc.get('fence')}, updated {_iso(doc.get('updated_ts'))})"
    )
    return "\n".join(lines)


def render_incidents(open_incs: List[dict], closed: List[dict]) -> str:
    """Open + closed incidents as the ``obs incidents`` table."""
    lines = [
        f"{'id':<13} {'rule':<26} {'sev':<5} {'opened (utc)':<26} "
        f"{'dur_s':>8} {'burn_x':>7} {'req_ids':>7} {'plan':<16} state"
    ]
    for inc in open_incs + closed:
        is_open = "closed_ts" not in inc
        rids = len((inc.get("correlated") or {}).get("request_ids") or [])
        burn = inc.get("budget_burn_x")
        dur = "-" if is_open else f"{float(inc.get('duration_s', 0) or 0):.1f}"
        burn_s = "-" if not isinstance(burn, (int, float)) else f"{burn:.2f}"
        lines.append(
            f"{inc.get('id', '?'):<13} {inc.get('rule', '?'):<26} "
            f"{inc.get('severity', '-'):<5} {_iso(inc.get('opened_ts')):<26} "
            f"{dur:>8} {burn_s:>7} "
            f"{rids:>7} {str(inc.get('plan', '-')):<16} "
            f"{'OPEN' if is_open else 'closed'}"
        )
    return "\n".join(lines)


def cli_alerts(state_dir: Optional[str] = None, as_json: bool = False) -> int:
    """``obs alerts`` entry: render the persisted rule states; exit code.

    Trend-style codes: 0 nothing firing, 1 at least one rule firing,
    2 corrupt state file, 3 no evaluator ever persisted state (a skip).
    """
    # CLI command body (dispatched only from obs/cli.py): stdout/stderr IS
    # the contract here, same as the cli.py entry surface itself.
    try:
        doc = load_state(state_dir)
    except ValueError as e:
        sys.stderr.write(f"obs alerts: {e}\n")
        return 2
    if doc is None:
        sys.stderr.write(
            "obs alerts: no alert state found — no evaluator has run "
            "(set TIP_ALERT_RULES or write $TIP_ASSETS/obs/slo_rules.json; "
            "exit 3: nothing to report, not a failure)\n"
        )
        return 3
    body = (
        json.dumps(doc, indent=2, sort_keys=True, default=repr)
        if as_json
        else render_alerts(doc)
    )
    print(body)  # tiplint: disable=bare-print (`obs alerts` command body; stdout is the CLI contract)
    firing = any(
        rs.get("state") == "firing" for rs in doc.get("rules", {}).values()
    )
    return 1 if firing else 0


def cli_incidents(
    state_dir: Optional[str] = None,
    as_json: bool = False,
    limit: Optional[int] = None,
) -> int:
    """``obs incidents`` entry: the incident timeline; exit code.

    0 all incidents closed, 1 at least one open, 2 corrupt state,
    3 no incidents ever recorded (a skip, not a failure).
    """
    # CLI command body (dispatched only from obs/cli.py): stdout/stderr IS
    # the contract here, same as the cli.py entry surface itself.
    try:
        open_incs, closed = load_incidents(state_dir)
    except ValueError as e:
        sys.stderr.write(f"obs incidents: {e}\n")
        return 2
    if limit is not None:
        closed = closed[-limit:]
    if not open_incs and not closed:
        sys.stderr.write(
            "obs incidents: no incidents recorded (exit 3: nothing to "
            "report, not a failure)\n"
        )
        return 3
    body = (
        json.dumps(
            {"schema": SCHEMA, "open": open_incs, "closed": closed},
            indent=2, sort_keys=True, default=repr,
        )
        if as_json
        else render_incidents(open_incs, closed)
    )
    print(body)  # tiplint: disable=bare-print (`obs incidents` command body; stdout is the CLI contract)
    return 1 if open_incs else 0

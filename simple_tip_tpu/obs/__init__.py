"""Unified telemetry: span tracing, metrics, and a run-inspection CLI.

The pipeline's wall-clock story is decided by components that used to emit
nothing an operator could correlate after the fact — the spawn-based run
scheduler, the SA fit cache, the device watchdog, XLA recompiles. This
subsystem gives every process one append-only JSONL event stream under
``TIP_OBS_DIR`` (see ``tracer`` for the resolution rules), merged across the
spawn boundary by worker stamping, and a CLI that renders a whole study as a
per-phase summary table or one Perfetto/Chrome flame chart:

- ``obs.span("fit", variant="dsa")`` / ``@obs.traced()``  nested spans
- ``obs.study_root("mini_study")``                        study root span
- ``obs.event("scheduler.requeue", model_id=3)``          lifecycle events
- ``obs.counter("sa_fit_cache.hit").inc()``               metrics registry
- ``python -m simple_tip_tpu.obs summary|export|check|regress``  inspection
- ``python -m simple_tip_tpu.obs runs|predict|trend``     feature store,
  cost model, N-run trend gate (obs v3)
- ``python -m simple_tip_tpu.obs tail|top|audit``         live tail, live
  progress table, plan-vs-actual cost-model audit (obs v4)

obs v4 adds the live telemetry plane: ``exporter`` mounts a stdlib HTTP
daemon thread (``TIP_OBS_HTTP=port|auto``, no-op when unset) serving
``/healthz`` (200/503 from pushed breaker/journal/lease component
health), ``/metrics`` (the registry incl. Quantile windows as Prometheus
text), ``/slo`` (the serving engine's snapshot) and ``/fleet`` (the
coordinator's membership/lease view); ``live`` is the torn-tail-tolerant
merged tail, the refreshing top table, and the predicted_s-vs-actual_s
audit that feeds cost-model drift back through ``obs trend``.

obs v5 adds the alerting plane: ``slo`` declares schema-stamped SLO/
alert-rule documents (``$TIP_ASSETS/obs/slo_rules.json``,
``TIP_ALERT_RULES`` override) with error budgets and Google-SRE-style
multi-window multi-burn-rate thresholds over the existing metric
families; ``alerts`` evaluates them on the owner loops (scheduler health
cadence, fleet beat, serving scheduler), drives per-rule
inactive→pending→firing→resolved state machines (file-backed, atomic,
fencing-token-safe under the fleet), emits transitions to pluggable
sinks (stderr, ``alerts.jsonl``, webhook-shaped file) plus the obs event
stream, and opens/closes incident records correlating the alert window
to spans, request_ids, breaker/chaos events and the active
ExecutionPlan fingerprint. Surfaces: ``/alerts`` on the exporter and
``python -m simple_tip_tpu.obs alerts|incidents``.

obs v2 adds the trace lifecycle (``TIP_OBS_MAX_BYTES`` rotating size cap
with oldest-segment eviction, ``TIP_OBS_SAMPLE`` keep-1-in-N span
sampling, the ``study_root`` span every process's top spans nest under),
``export --splice-xla`` (device timelines merged into the host flame
chart) and ``regress`` (cross-run per-phase/metric regression gating).

obs v3 closes the loop from telemetry to scheduling: ``store`` normalizes
every run's trace/bench/host record into schema-versioned (run, phase)
feature rows in an append-only index (``TIP_OBS_INDEX``, default
``$TIP_ASSETS/obs/index``); ``costmodel`` fits a stdlib least-squares
per-phase cost model over it and predicts study wall-clock pre-launch
(run_scheduler and scripts/full_study.py stamp ``predicted_s`` vs
``actual_s`` into their spans); ``regress.trend`` replaces 2-run diffs
with robust median/MAD bands over the last K non-degraded runs.

Zero third-party dependencies (stdlib json), crash-safe (append-only JSONL;
partial files still parse line-wise), and no-op when ``TIP_OBS_DIR`` is
unset (overhead pinned by tests/test_obs.py). See README "Observability".
"""

from simple_tip_tpu.obs.logbridge import install_worker_logging
from simple_tip_tpu.obs.metrics import (
    counter,
    gauge,
    histogram,
    install_jax_hooks,
    poll_device_memory,
    quantile,
    record_device_memory,
    snapshot as metrics_snapshot,
    flush as flush_metrics,
)
from simple_tip_tpu.obs.tracer import (
    enabled,
    event,
    obs_dir,
    record_span,
    reset,
    span,
    study_root,
    traced,
)

__all__ = [
    "counter",
    "enabled",
    "event",
    "flush_metrics",
    "gauge",
    "histogram",
    "install_jax_hooks",
    "install_worker_logging",
    "metrics_snapshot",
    "obs_dir",
    "poll_device_memory",
    "quantile",
    "record_device_memory",
    "record_span",
    "reset",
    "span",
    "study_root",
    "traced",
]


def reset_all() -> None:
    """Full test-hook reset: tracer, metrics, log bridge, exporter, alerts."""
    # alerts is imported lazily (never at module level) so the obs package
    # root stays import-cycle-free: alerts imports exporter/metrics/slo.
    from simple_tip_tpu.obs import alerts, exporter, logbridge, metrics, tracer

    tracer.reset()
    metrics.reset()
    logbridge.reset()
    alerts.reset()  # before exporter.reset(): drops its /alerts provider
    exporter.reset()

"""``python -m simple_tip_tpu.obs`` — the run-inspection CLI (see cli.py)."""

import sys

from simple_tip_tpu.obs.cli import main

if __name__ == "__main__":
    sys.exit(main())

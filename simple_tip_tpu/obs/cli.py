"""Run-inspection CLI: merge a run's JSONL streams, summarize, export.

``python -m simple_tip_tpu.obs <command> <run-dir-or-files>``:

- ``summary``  merge every ``events-*.jsonl`` in the run directory and print
  a per-process, per-span-name (phase) and per-scheduled-run table plus the
  summed metrics counters — the after-the-fact answer to "where did this
  study's wall-clock go";
- ``export``   write a Chrome/Perfetto ``trace_event`` JSON (``-o`` path;
  load in https://ui.perfetto.dev or chrome://tracing) so a whole 100-run
  study is one flame chart: one track group per process (worker-stamped),
  spans as complete events, lifecycle events as instants, metrics flushes
  as counter tracks;
- ``check``    validate a trace (CI self-check): every line parses or is
  counted as a torn tail, every event carries the schema's required keys,
  every file opens with its ``meta`` stamp. Exit 1 on schema violations.
- ``regress``  diff two runs (trace dirs, ``summary --json`` documents or
  bench records — see ``obs/regress.py``) per phase/metric; prints the
  comparison table and exits nonzero on any regression (phase-duration
  growth past the threshold, a ``degraded`` false->true flip, health
  counter growth). ``--against`` names the baseline explicitly; with no
  current operand the newest ``BENCH_r*.json`` in the working directory
  is compared.
- ``runs``     build/refresh the telemetry feature store (``obs/store.py``):
  normalize obs run dirs + ``BENCH_r*.json`` + ``HOST_PHASE.json`` +
  ``MULTICHIP_r*.json`` under the given roots into schema-versioned
  (run, phase) feature rows in the append-only index at ``TIP_OBS_INDEX``
  (default ``$TIP_ASSETS/obs/index``), then print the queryable table.
- ``predict``  fit the per-phase cost model (``obs/costmodel.py``) over the
  index and estimate wall-clock for a proposed study config (case studies
  x runs x phases x backend x workers), with a stated error and a loud
  insufficient-corpus fallback.
- ``trend``    gate the LAST of N chronological snapshots against robust
  median/MAD trend bands over its non-degraded predecessors
  (``obs/regress.py``'s N-run upgrade of the 2-run diff).
- ``roofline`` per-program MFU / achieved-bandwidth table with a
  compute-bound vs HBM-bound verdict per program (``obs/devicemeter.py``),
  from ``MFU_BREAKDOWN.json`` captures or a run's live dispatch gauges.
- ``alerts``   the SLO evaluator's per-rule alert states and burn rates
  from the persisted state file (``obs/alerts.py``); exit 1 while any
  rule is firing, so a watch loop can page on the exit code alone.
- ``incidents`` the incident timeline: open incidents from the state file
  plus the closed records in ``incidents.jsonl``, each correlating its
  alert window to spans, request_ids and the active plan fingerprint.

Exit codes (``regress`` and ``trend``, so CI can tell skip from failure):
**0** inside the band / no regression, **1** regression detected,
**2** bad input (unreadable/unrecognizable snapshot), **3** no comparable
baseline (empty corpus, all-degraded history — a skip, not a failure).
``predict`` reuses 3 for "insufficient corpus for every requested phase".

``export --splice-xla`` additionally reads each span's ``xla_trace_dir``
attribute (written by ``utils/profiling.maybe_trace`` when
``TIP_PROFILE_DIR`` is set), parses the XLA profiler's own trace-event
JSON, shifts it onto the span clock and emits the device timelines into
the SAME Perfetto file, grouped under ``xla:<span>`` track groups — the
host story and the device story in one flame chart (``obs/splice.py``).

Merging is tolerant by construction: files are read line-wise, unparsable
lines (a crash's torn tail) are skipped and counted, and ordering is by the
events' wall-clock ``ts`` — the streams share the host clock, which is
exactly why spans record ``time.time`` starts next to their monotonic
durations.

Stdlib-only: this CLI is part of the tier-0 gate (no jax/numpy installed).
"""

import argparse
import datetime
import json
import os
import sys


def _iso_utc(ts) -> str:
    """Epoch seconds as UTC ISO-8601 with millisecond precision."""
    if not isinstance(ts, (int, float)):
        return "-"
    dt = datetime.datetime.fromtimestamp(ts, datetime.timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%S.") + f"{dt.microsecond // 1000:03d}Z"


def filter_phase(events, phase: str):
    """The events belonging to ``phase``: spans named it (or attributed to
    it via ``attrs.phase``) and lifecycle events attributed to it. ``meta``
    events survive so process identity still renders; metrics/log records
    are dropped (they are not phase-scoped and would mislead)."""
    kept = []
    for rec in events:
        kind = rec.get("type")
        if kind == "meta":
            kept.append(rec)
            continue
        if kind not in ("span", "event"):
            continue
        attrs = rec.get("attrs") or {}
        if rec.get("name") == phase or attrs.get("phase") == phase:
            kept.append(rec)
    return kept


def iter_trace_files(target):
    """Yield the JSONL files of ``target`` (a run dir, a file, or several)."""
    targets = target if isinstance(target, (list, tuple)) else [target]
    for t in targets:
        if os.path.isdir(t):
            names = sorted(
                n
                for n in os.listdir(t)
                if n.startswith("events-") and n.endswith(".jsonl")
            )
            for n in names:
                yield os.path.join(t, n)
        else:
            yield t


def load_events(target):
    """Merge ``target``'s streams into one ts-ordered event list.

    Returns ``(events, files, bad_lines)``; every event is annotated with
    its source file under ``_file``. Lines that fail to parse (torn crash
    tails) are skipped and counted, never fatal.
    """
    events, files, bad = [], [], 0
    for path in iter_trace_files(target):
        files.append(path)
        try:
            with open(path, encoding="utf-8") as f:
                for lineno, line in enumerate(f, start=1):
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        bad += 1
                        continue
                    if not isinstance(rec, dict):
                        bad += 1
                        continue
                    rec["_file"] = os.path.basename(path)
                    # File-order position: spans are written on EXIT with
                    # their (earlier) start ts, so ts order is NOT file
                    # order — ``check`` needs the latter for the meta stamp.
                    rec["_line"] = lineno
                    events.append(rec)
        except OSError as e:
            print(f"obs: cannot read {path}: {e}", file=sys.stderr)
    events.sort(key=lambda r: (r.get("ts") or 0, r.get("pid") or 0))
    return events, files, bad


def _processes(events):
    """pid -> {worker, platform, first, last, spans, events, logs} rollup."""
    procs = {}
    for rec in events:
        pid = rec.get("pid")
        if pid is None:
            continue
        p = procs.setdefault(
            pid,
            {"worker": "", "platform": "", "first": None, "last": None,
             "spans": 0, "events": 0, "logs": 0},
        )
        ts = rec.get("ts")
        if isinstance(ts, (int, float)):
            p["first"] = ts if p["first"] is None else min(p["first"], ts)
            end = ts + rec.get("dur", 0) if rec.get("type") == "span" else ts
            p["last"] = end if p["last"] is None else max(p["last"], end)
        kind = rec.get("type")
        if kind == "meta":
            p["worker"] = str(rec.get("worker", p["worker"]) or p["worker"])
            p["platform"] = str(rec.get("platform", p["platform"]) or p["platform"])
        elif kind == "span":
            p["spans"] += 1
        elif kind == "event":
            p["events"] += 1
        elif kind == "log":
            p["logs"] += 1
    return procs


def _span_table(events):
    """span name -> (count, total_s, max_s) aggregate."""
    table = {}
    for rec in events:
        if rec.get("type") != "span":
            continue
        name = str(rec.get("name", "?"))
        dur = float(rec.get("dur", 0) or 0)
        cnt, tot, mx = table.get(name, (0, 0.0, 0.0))
        table[name] = (cnt + 1, tot + dur, max(mx, dur))
    return table


def _scheduler_runs(events):
    """model id -> lifecycle rollup from the scheduler's ``scheduler.*`` events."""
    runs = {}
    for rec in events:
        if rec.get("type") != "event":
            continue
        name = str(rec.get("name", ""))
        if not name.startswith("scheduler."):
            continue
        attrs = rec.get("attrs") or {}
        mid = attrs.get("model_id")
        if mid is None:
            continue
        r = runs.setdefault(
            mid, {"events": [], "first": None, "last": None, "pid": None}
        )
        stage = name.split(".", 1)[1]
        r["events"].append(stage)
        ts = rec.get("ts")
        if isinstance(ts, (int, float)):
            r["first"] = ts if r["first"] is None else min(r["first"], ts)
            r["last"] = ts if r["last"] is None else max(r["last"], ts)
        if stage == "start" and attrs.get("worker_pid") is not None:
            r["pid"] = attrs["worker_pid"]
    return runs


def _summed_counters(events):
    """Final metrics flush per pid, counters summed across processes."""
    last_by_pid = {}
    for rec in events:
        if rec.get("type") == "metrics" and rec.get("pid") is not None:
            last_by_pid[rec["pid"]] = rec
    summed = {}
    for rec in last_by_pid.values():
        for name, value in (rec.get("counters") or {}).items():
            if isinstance(value, (int, float)):
                summed[name] = summed.get(name, 0) + value
    return summed


def summarize(events, files, bad) -> str:
    """Render the merged run as the deterministic text summary."""
    out = []
    spans = [r for r in events if r.get("type") == "span"]
    out.append(
        f"files: {len(files)}  events: {len(events)}  spans: {len(spans)}  "
        f"bad lines: {bad}"
    )
    tss = [r["ts"] for r in events if isinstance(r.get("ts"), (int, float))]
    t0 = min(tss) if tss else 0.0
    if tss:
        out.append(f"start: {_iso_utc(t0)}")

    procs = _processes(events)
    if procs:
        out.append("")
        out.append("processes:")
        for pid in sorted(procs):
            p = procs[pid]
            first = 0.0 if p["first"] is None else p["first"] - t0
            last = 0.0 if p["last"] is None else p["last"] - t0
            tag = f"worker={p['worker'] or '-'} platform={p['platform'] or '-'}"
            out.append(
                f"  pid {pid:<8} {tag:<28} spans={p['spans']:<5} "
                f"events={p['events']:<5} logs={p['logs']:<5} "
                f"window={first:.3f}s..{last:.3f}s"
            )

    table = _span_table(events)
    if table:
        out.append("")
        out.append("spans by name (the per-phase table):")
        out.append(f"  {'name':<40} {'count':>6} {'total_s':>10} {'mean_s':>9} {'max_s':>9}")
        for name in sorted(table, key=lambda n: -table[n][1]):
            cnt, tot, mx = table[name]
            out.append(
                f"  {name:<40} {cnt:>6} {tot:>10.3f} {tot / cnt:>9.3f} {mx:>9.3f}"
            )

    runs = _scheduler_runs(events)
    if runs:
        out.append("")
        out.append("scheduled runs:")
        out.append(
            f"  {'model_id':<9} {'start_utc':<26} {'lifecycle':<34} "
            f"{'wall_s':>8} {'worker_pid':>11}"
        )
        for mid in sorted(runs, key=lambda m: (str(type(m)), m)):
            r = runs[mid]
            wall = (
                (r["last"] - r["first"])
                if r["first"] is not None and r["last"] is not None
                else 0.0
            )
            out.append(
                f"  {str(mid):<9} {_iso_utc(r['first']):<26} "
                f"{','.join(r['events']):<34} {wall:>8.3f} "
                f"{str(r['pid'] if r['pid'] is not None else '-'):>11}"
            )

    counters = _summed_counters(events)
    if counters:
        out.append("")
        out.append("counters (summed over processes):")
        for name in sorted(counters):
            out.append(f"  {name:<44} {counters[name]}")
    return "\n".join(out)


def to_chrome_trace(events) -> dict:
    """The merged events as a Chrome/Perfetto ``trace_event`` document.

    Timestamps become microseconds relative to the earliest event; spans are
    ``X`` complete events, lifecycle events ``i`` instants, log records
    ``i`` instants in a ``log`` category, and each metrics flush fans out
    into ``C`` counter samples. Process metadata (``M``) names each track
    group ``pid <pid> [worker i] [(platform)]``.
    """
    tss = [r["ts"] for r in events if isinstance(r.get("ts"), (int, float))]
    t0 = min(tss) if tss else 0.0

    def us(ts):
        return max(0, int(round((ts - t0) * 1e6)))

    trace = []
    for pid, p in sorted(_processes(events).items()):
        label = f"pid {pid}"
        if p["worker"]:
            label += f" worker {p['worker']}"
        if p["platform"]:
            label += f" ({p['platform']})"
        trace.append(
            {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
             "args": {"name": label}}
        )
    for rec in events:
        kind = rec.get("type")
        pid = rec.get("pid", 0)
        tid = rec.get("tid", 0)
        ts = rec.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        if kind == "span":
            args = dict(rec.get("attrs") or {})
            if "error" in rec:
                args["error"] = rec["error"]
            trace.append(
                {"ph": "X", "name": str(rec.get("name", "?")), "cat": "span",
                 "pid": pid, "tid": tid, "ts": us(ts),
                 "dur": max(1, int(round(float(rec.get("dur", 0) or 0) * 1e6))),
                 "args": args}
            )
        elif kind == "event":
            trace.append(
                {"ph": "i", "name": str(rec.get("name", "?")), "cat": "event",
                 "pid": pid, "tid": tid, "ts": us(ts), "s": "t",
                 "args": dict(rec.get("attrs") or {})}
            )
        elif kind == "log":
            trace.append(
                {"ph": "i", "name": f"{rec.get('level', '?')}: {rec.get('msg', '')}"[:120],
                 "cat": "log", "pid": pid, "tid": tid, "ts": us(ts), "s": "t",
                 "args": {"logger": rec.get("logger", "")}}
            )
        elif kind == "metrics":
            # Counters AND gauges become counter tracks: the per-device
            # memory high-water (device.*.peak_bytes_in_use gauges, polled
            # by the scheduler loop) graphs over the run this way.
            for source in ("counters", "gauges"):
                for name, value in (rec.get(source) or {}).items():
                    if isinstance(value, (int, float)):
                        trace.append(
                            {"ph": "C", "name": name, "pid": pid, "tid": 0,
                             "ts": us(ts), "args": {"value": value}}
                        )
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


#: type -> keys every event of that type must carry (the schema contract
#: ``check`` enforces; README "Observability" documents it).
REQUIRED_KEYS = {
    "meta": ("ts", "pid"),
    "span": ("ts", "dur", "name", "pid", "tid", "id", "depth"),
    "event": ("ts", "name", "pid"),
    "log": ("ts", "pid", "level", "msg"),
    "metrics": ("ts", "pid", "counters", "gauges", "histograms"),
}


def check(events, files, bad):
    """Validate the trace against the event schema; returns problem strings."""
    problems = []
    if not files:
        problems.append("no events-*.jsonl files found")
    first_by_file = {}
    for rec in events:
        f = rec["_file"]
        head = first_by_file.get(f)
        if head is None or rec.get("_line", 0) < head.get("_line", 0):
            first_by_file[f] = rec
        kind = rec.get("type")
        if kind not in REQUIRED_KEYS:
            problems.append(f"{f}: unknown event type {kind!r}")
            continue
        missing = [k for k in REQUIRED_KEYS[kind] if k not in rec]
        if missing:
            problems.append(f"{f}: {kind} event missing keys {missing}")
        if kind == "span" and not (
            isinstance(rec.get("dur"), (int, float)) and rec["dur"] >= 0
        ):
            problems.append(f"{f}: span {rec.get('name')!r} has bad dur")
    for path in files:
        name = os.path.basename(path)
        head = first_by_file.get(name)
        if head is not None and head.get("type") != "meta":
            problems.append(f"{name}: first event is not the meta stamp")
    return problems


def _newest_bench_record(cwd: str):
    """The newest ``BENCH_r*.json`` in ``cwd`` (by round number), or None."""
    names = sorted(
        n
        for n in os.listdir(cwd)
        if n.startswith("BENCH_r") and n.endswith(".json")
    )
    return os.path.join(cwd, names[-1]) if names else None


def _regress(args) -> int:
    """``obs regress`` entry: resolve operands, compare, print, exit code."""
    from simple_tip_tpu.obs import regress as regress_mod

    targets = list(args.targets)
    baseline_path = args.against
    if baseline_path is None:
        if len(targets) < 2:
            print(
                "obs regress: need BASELINE and CURRENT (or --against BASELINE)",
                file=sys.stderr,
            )
            return 2
        baseline_path = targets.pop(0)
    if targets:
        current_path = targets.pop(0)
    else:
        # `obs regress --against BENCH_r04.json`: current defaults to the
        # newest bench round record in the working directory.
        current_path = _newest_bench_record(os.getcwd())
        if current_path is None or os.path.abspath(current_path) == os.path.abspath(
            baseline_path
        ):
            print(
                "obs regress: no CURRENT operand and no newer BENCH_r*.json "
                "in the working directory (exit 3: nothing comparable, "
                "not a regression)",
                file=sys.stderr,
            )
            return 3
    if targets:
        print(f"obs regress: unexpected extra operands {targets}", file=sys.stderr)
        return 2
    try:
        baseline = regress_mod.load_snapshot(baseline_path)
        current = regress_mod.load_snapshot(current_path)
    except ValueError as e:
        print(f"obs regress: {e}", file=sys.stderr)
        return 2
    kwargs = {}
    if args.max_growth is not None:
        kwargs["max_growth"] = args.max_growth
    result = regress_mod.compare(baseline, current, **kwargs)
    if args.json:
        print(
            json.dumps(
                {
                    "baseline": baseline["source"],
                    "current": current["source"],
                    "ok": result["ok"],
                    "rows": result["rows"],
                },
                indent=2,
                sort_keys=True,
            )
        )
    else:
        print(regress_mod.render(result, baseline, current))
    return 0 if result["ok"] else 1


def _runs(args) -> int:
    """``obs runs`` entry: refresh the feature-store index, print it."""
    from simple_tip_tpu.obs import store

    index_dir = args.index or store.default_index_dir()
    if not args.no_refresh:
        report = store.refresh(args.roots or [os.getcwd()], index_dir)
        print(
            f"index: {report['index']}  sources: {report['sources']} "
            f"({len(report['indexed'])} indexed, {report['skipped']} "
            f"unchanged)  rows: +{report['rows_appended']} -> "
            f"{report['rows_total']}",
            file=sys.stderr,
        )
    rows = store.load_rows(index_dir)
    if args.json:
        print(json.dumps(rows, indent=2, sort_keys=True))
    else:
        print(store.render_rows(rows, limit=args.limit))
    return 0


def _predict(args) -> int:
    """``obs predict`` entry: fit over the index, estimate study wall-clock."""
    from simple_tip_tpu.obs import costmodel, store

    # Shared cached corpus load: the planner (simple_tip_tpu.plan) and
    # this CLI score against the identical parsed rows, one walk per
    # index stat instead of one per call.
    rows = store.load_corpus(args.index or store.default_index_dir())
    if not rows:
        if args.json:
            # The --json contract: stdout ALWAYS carries one valid JSON
            # document, even on the exit-3 path — diagnostics stay on
            # stderr so piped consumers never parse an empty/corrupt body.
            print(
                json.dumps(
                    {"ok": False, "error": "insufficient_corpus",
                     "phases": {}, "total_s": None},
                    indent=2, sort_keys=True,
                )
            )
        print(
            "obs predict: the feature-store index is empty — run "
            "`obs runs <roots>` first (exit 3: insufficient corpus)",
            file=sys.stderr,
        )
        return 3
    phases = [p.strip() for p in args.phases.split(",") if p.strip()]
    if not phases:
        print("obs predict: --phases must name at least one phase", file=sys.stderr)
        return 2
    model = costmodel.fit(rows)
    result = costmodel.predict_study(
        model,
        phases,
        runs=args.runs,
        case_studies=args.case_studies,
        platform=args.platform,
        workers=args.workers,
        batch=args.batch,
        group=args.group,
    )
    if args.json:
        print(json.dumps(result, indent=2, sort_keys=True))
    else:
        print(costmodel.render_prediction(result))
    if not result["ok"]:
        print(
            "obs predict: INSUFFICIENT CORPUS — no requested phase has any "
            "estimate (exit 3)",
            file=sys.stderr,
        )
        return 3
    return 0


def _merged_metrics_snapshot(events) -> dict:
    """Final metrics flush per pid, gauges/quantiles merged across
    processes (last flush wins per name) — the live-registry view
    ``obs roofline`` reads out of a run directory."""
    last_by_pid = {}
    for rec in events:
        if rec.get("type") == "metrics" and rec.get("pid") is not None:
            last_by_pid[rec["pid"]] = rec
    gauges, quantiles = {}, {}
    for pid in sorted(last_by_pid):
        rec = last_by_pid[pid]
        for name, v in (rec.get("gauges") or {}).items():
            if isinstance(v, (int, float)):
                gauges[name] = v
        for name, v in (rec.get("quantiles") or {}).items():
            if isinstance(v, dict):
                quantiles[name] = v
    return {"gauges": gauges, "quantiles": quantiles}


def _roofline(args) -> int:
    """``obs roofline`` entry: per-program MFU/bandwidth table with a
    compute-bound vs HBM-bound verdict per program. Targets are
    ``MFU_BREAKDOWN.json`` captures (devicemeter documents) and/or obs
    run dirs / ``events-*.jsonl`` streams (live gauges + dispatch
    quantiles). Exit 0 with rows, 3 with nothing to render, 2 bad input."""
    from simple_tip_tpu.obs import devicemeter

    sections = []
    for target in args.targets:
        if os.path.isdir(target) or str(target).endswith(".jsonl"):
            events, files, _bad = load_events(target)
            if not files:
                print(
                    f"obs roofline: {target}: no events-*.jsonl streams found",
                    file=sys.stderr,
                )
                return 2
            rows = devicemeter.rows_from_metrics(
                _merged_metrics_snapshot(events)
            )
            sections.append({"target": str(target), "rows": rows})
            continue
        try:
            with open(target, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(
                f"obs roofline: {target}: not a readable JSON document ({e})",
                file=sys.stderr,
            )
            return 2
        if not isinstance(doc, dict) or doc.get("kind") != devicemeter.KIND:
            print(
                f"obs roofline: {target}: not an MFU_BREAKDOWN document "
                f"(kind != {devicemeter.KIND!r})",
                file=sys.stderr,
            )
            return 2
        rows = devicemeter.rows_from_breakdown(doc)
        label = (
            f"{target}  [{doc.get('platform', '?')}/"
            f"{doc.get('device_kind', '?')}"
            f"{', DEGRADED' if doc.get('degraded') else ''}]"
        )
        sections.append({"target": label, "rows": rows})
    if not any(s["rows"] for s in sections):
        print(
            "obs roofline: no graded programs found (exit 3: nothing to "
            "render, not a failure)",
            file=sys.stderr,
        )
        return 3
    if args.json:
        print(json.dumps(sections, indent=2, sort_keys=True))
        return 0
    blocks = []
    for s in sections:
        if not s["rows"]:
            blocks.append(f"{s['target']}\n  (no graded programs)")
            continue
        blocks.append(devicemeter.render_roofline(s["rows"], header=s["target"]))
    print("\n\n".join(blocks))
    return 0


def _trend(args) -> int:
    """``obs trend`` entry: N-run trend gate; exit 0/1/2/3."""
    from simple_tip_tpu.obs import regress as regress_mod

    try:
        snapshots = [regress_mod.load_snapshot(t) for t in args.targets]
    except ValueError as e:
        print(f"obs trend: {e}", file=sys.stderr)
        return 2
    kwargs = {}
    if args.window is not None:
        kwargs["window"] = args.window
    if args.band is not None:
        kwargs["band"] = args.band
    if args.min_baseline is not None:
        kwargs["min_baseline"] = args.min_baseline
    result = regress_mod.trend(snapshots, **kwargs)
    if args.json:
        print(json.dumps(result, indent=2, sort_keys=True))
    else:
        print(regress_mod.render_trend(result))
    if result["verdict"] == "no_comparable_baseline":
        return 3
    return 0 if result["ok"] else 1


def main(argv=None) -> int:
    """CLI entry point; returns the process exit code."""
    ap = argparse.ArgumentParser(
        prog="python -m simple_tip_tpu.obs",
        description="Inspect a TIP_OBS_DIR run: summary table, Perfetto "
        "export (optionally with spliced XLA timelines), schema "
        "self-check, or cross-run regression detection.",
    )
    sub = ap.add_subparsers(dest="command", required=True)
    for name, doc in (
        ("summary", "per-process / per-phase / per-run summary table"),
        ("export", "write Chrome/Perfetto trace_event JSON"),
        ("check", "validate a trace against the event schema (CI)"),
    ):
        p = sub.add_parser(name, help=doc)
        p.add_argument("target", nargs="+", help="run directory or .jsonl files")
        if name == "summary":
            p.add_argument("--json", action="store_true", help="machine-readable output")
            p.add_argument(
                "--phase",
                default=None,
                metavar="NAME",
                help="only spans/events of this phase (span name or "
                "attrs.phase match)",
            )
        if name == "export":
            p.add_argument("-o", "--out", default="trace.json", help="output path")
            p.add_argument(
                "--splice-xla",
                action="store_true",
                help="splice XLA profiler traces (each span's xla_trace_dir) "
                "into the same file, time-shifted onto the span clock",
            )
    rp = sub.add_parser(
        "regress",
        help="diff two runs/bench records; exit nonzero on regressions",
    )
    rp.add_argument(
        "targets",
        nargs="*",
        help="BASELINE CURRENT (run dirs, summary --json files, or bench "
        "records); with --against, just CURRENT",
    )
    rp.add_argument(
        "--against",
        default=None,
        metavar="BASELINE",
        help="baseline snapshot (e.g. a previous BENCH_r0*.json)",
    )
    rp.add_argument(
        "--max-growth",
        type=float,
        default=None,
        metavar="FRAC",
        help="phase-duration growth (and bench value drop) threshold as a "
        "fraction (default 0.25)",
    )
    rp.add_argument("--json", action="store_true", help="machine-readable output")

    runp = sub.add_parser(
        "runs",
        help="build/refresh the feature-store index and print the row table",
    )
    runp.add_argument(
        "roots",
        nargs="*",
        help="directories/files to index (obs run dirs, BENCH_r*.json, "
        "HOST_PHASE.json, MULTICHIP_r*.json); default: the working dir",
    )
    runp.add_argument(
        "--index",
        default=None,
        metavar="DIR",
        help="index directory (default: $TIP_OBS_INDEX or $TIP_ASSETS/obs/index)",
    )
    runp.add_argument(
        "--no-refresh",
        action="store_true",
        help="query the existing index without re-walking the sources",
    )
    runp.add_argument(
        "--limit",
        type=int,
        default=None,
        metavar="N",
        help="only print the newest N rows",
    )
    runp.add_argument("--json", action="store_true", help="machine-readable output")

    pp = sub.add_parser(
        "predict",
        help="estimate study wall-clock from the cost model over the index",
    )
    pp.add_argument(
        "--phases",
        required=True,
        metavar="A,B,...",
        help="comma-separated phase names the study will run",
    )
    pp.add_argument(
        "--runs", type=int, default=100, metavar="N",
        help="runs per case study (default 100, the paper's study size)",
    )
    pp.add_argument(
        "--case-studies", type=int, default=1, metavar="N",
        help="number of case studies (default 1)",
    )
    pp.add_argument(
        "--workers", type=int, default=1, metavar="N",
        help="parallel workers (ideal packing; default 1)",
    )
    pp.add_argument(
        "--platform", default=None, metavar="NAME",
        help="target backend feature (e.g. cpu, tpu)",
    )
    pp.add_argument(
        "--batch", type=float, default=None, metavar="N",
        help="batch-size feature for the fit",
    )
    pp.add_argument(
        "--group", type=int, default=None, metavar="G",
        help="cross-run dispatch-fusion group size feature "
        "(TIP_CHAIN_GROUP; default 1 = ungrouped)",
    )
    pp.add_argument(
        "--index", default=None, metavar="DIR",
        help="index directory (default: $TIP_OBS_INDEX or $TIP_ASSETS/obs/index)",
    )
    pp.add_argument("--json", action="store_true", help="machine-readable output")

    tp = sub.add_parser(
        "trend",
        help="gate the last snapshot against median/MAD trend bands "
        "(exit 0 ok / 1 regression / 2 bad input / 3 no baseline)",
    )
    tp.add_argument(
        "targets",
        nargs="+",
        help="chronological snapshots, oldest first; the LAST is gated "
        "(run dirs, bench records, BENCH_r*.json, summary --json files)",
    )
    tp.add_argument(
        "--window", type=int, default=None, metavar="K",
        help="non-degraded predecessors forming the baseline (default 5)",
    )
    tp.add_argument(
        "--band", type=float, default=None, metavar="SIGMA",
        help="band half-width in robust sigmas (default 3.0)",
    )
    tp.add_argument(
        "--min-baseline", type=int, default=None, metavar="N",
        help="fewer comparable predecessors than this exits 3 (default 3)",
    )
    tp.add_argument("--json", action="store_true", help="machine-readable output")

    rfp = sub.add_parser(
        "roofline",
        help="per-program MFU / bandwidth table with compute-bound vs "
        "HBM-bound verdicts (devicemeter; exit 3 when nothing is graded)",
    )
    rfp.add_argument(
        "targets",
        nargs="+",
        help="MFU_BREAKDOWN.json captures and/or obs run dirs / "
        "events-*.jsonl streams",
    )
    rfp.add_argument("--json", action="store_true", help="machine-readable output")

    tailp = sub.add_parser(
        "tail",
        help="merged live tail of a run's event streams (obs v4)",
    )
    tailp.add_argument("target", nargs="+", help="run directory or .jsonl files")
    tailp.add_argument(
        "-f", "--follow", action="store_true",
        help="keep polling for appended events (live mode)",
    )
    tailp.add_argument(
        "--poll", type=float, default=0.5, metavar="S",
        help="follow-mode poll interval in seconds (default 0.5)",
    )
    tailp.add_argument(
        "--duration", type=float, default=None, metavar="S",
        help="stop following after S seconds (default: a day)",
    )
    tailp.add_argument(
        "--max-events", type=int, default=None, metavar="N",
        help="stop after printing N events",
    )

    topp = sub.add_parser(
        "top",
        help="refreshing phase-progress / queue-depth / badge-fill table",
    )
    topp.add_argument("target", nargs="+", help="run directory or .jsonl files")
    topp.add_argument(
        "--refresh", type=float, default=2.0, metavar="S",
        help="refresh interval in seconds (default 2)",
    )
    topp.add_argument(
        "--iterations", type=int, default=None, metavar="N",
        help="render N refreshes then exit (default: until Ctrl-C)",
    )
    topp.add_argument(
        "--once", action="store_true", help="one-shot render (CI/tests)"
    )

    alp = sub.add_parser(
        "alerts",
        help="per-rule alert states + burn rates from the evaluator's "
        "state file (exit 0 quiet / 1 firing / 2 corrupt / 3 no state)",
    )
    alp.add_argument(
        "--state", default=None, metavar="DIR",
        help="alert-state directory (default: $TIP_ALERT_STATE or "
        "$TIP_ASSETS/obs/alerts)",
    )
    alp.add_argument("--json", action="store_true", help="machine-readable output")

    inp = sub.add_parser(
        "incidents",
        help="the incident timeline: open + closed incident records "
        "(exit 0 closed-only / 1 open / 2 corrupt / 3 none)",
    )
    inp.add_argument(
        "--state", default=None, metavar="DIR",
        help="alert-state directory (default: $TIP_ALERT_STATE or "
        "$TIP_ASSETS/obs/alerts)",
    )
    inp.add_argument(
        "--limit", type=int, default=None, metavar="N",
        help="only the newest N closed incidents",
    )
    inp.add_argument("--json", action="store_true", help="machine-readable output")

    audp = sub.add_parser(
        "audit",
        help="grade predicted_s vs actual_s across a run's phase spans; "
        "emit per-phase error rows (exit 3 when nothing carries a pair)",
    )
    audp.add_argument(
        "targets", nargs="+", help="run directories or .jsonl files"
    )
    audp.add_argument(
        "--index", default=None, metavar="DIR",
        help="also refresh these targets into the feature-store index "
        "(emits the audit.* error rows)",
    )
    audp.add_argument(
        "--json", action="store_true",
        help="emit the trend-gateable audit snapshot document",
    )

    args = ap.parse_args(argv)

    if args.command in ("alerts", "incidents"):
        from simple_tip_tpu.obs import alerts as alerts_mod

        if args.command == "alerts":
            return alerts_mod.cli_alerts(args.state, as_json=args.json)
        return alerts_mod.cli_incidents(
            args.state, as_json=args.json, limit=args.limit
        )

    if args.command in ("tail", "top", "audit"):
        from simple_tip_tpu.obs import live as live_mod

        if args.command == "tail":
            return live_mod.tail(
                args.target, follow=args.follow, poll_s=args.poll,
                duration_s=args.duration, max_events=args.max_events,
            )
        if args.command == "top":
            iterations = 1 if args.once else args.iterations
            return live_mod.top(
                args.target, refresh_s=args.refresh, iterations=iterations
            )
        return live_mod.audit(
            args.targets, index=args.index, as_json=args.json
        )

    if args.command == "regress":
        return _regress(args)
    if args.command == "runs":
        return _runs(args)
    if args.command == "predict":
        return _predict(args)
    if args.command == "trend":
        return _trend(args)
    if args.command == "roofline":
        return _roofline(args)

    events, files, bad = load_events(args.target)
    if args.command == "summary":
        if args.phase:
            events = filter_phase(events, args.phase)
        if args.json:
            print(
                json.dumps(
                    {
                        "files": [os.path.basename(f) for f in files],
                        "bad_lines": bad,
                        "spans": {
                            n: {"count": c, "total_s": t, "max_s": m}
                            for n, (c, t, m) in sorted(_span_table(events).items())
                        },
                        "counters": _summed_counters(events),
                    },
                    indent=2,
                    sort_keys=True,
                )
            )
        else:
            print(summarize(events, files, bad))
        return 0
    if args.command == "export":
        doc = to_chrome_trace(events)
        if args.splice_xla:
            from simple_tip_tpu.obs import splice as splice_mod

            tss = [
                r["ts"] for r in events if isinstance(r.get("ts"), (int, float))
            ]
            spliced, report = splice_mod.splice(events, min(tss) if tss else 0.0)
            doc["traceEvents"].extend(spliced)
            for line in report:
                print(f"splice: {line}", file=sys.stderr)
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(doc, f)
        print(
            f"wrote {args.out}: {len(doc['traceEvents'])} trace events from "
            f"{len(files)} files ({bad} bad lines skipped); open in "
            "https://ui.perfetto.dev or chrome://tracing"
        )
        return 0
    problems = check(events, files, bad)
    if problems:
        for p in problems:
            print(f"obs check: {p}", file=sys.stderr)
        return 1
    print(
        f"obs check OK: {len(files)} files, {len(events)} events, "
        f"{bad} torn lines skipped"
    )
    return 0

"""Analytic FLOPs models + peak-FLOPs table → MFU accounting.

The round-3 verdict's top gap: every throughput number in this repo
(bench.py inputs/sec, SCALING.md samples/s) lacked a FLOPs model, so
model-FLOPs-utilization — the metric that actually answers "is it fast on
this chip" — was uncomputable. This module closes that:

- ``conv_net_forward_flops`` — analytic matmul/conv FLOPs (2·MACs
  convention) for the case-study convnets, layer by layer, matching the
  architectures in ``models/convnet.py`` (reference:
  src/dnn_test_prio/case_study_mnist.py:50-69, case_study_cifar10.py:33-57).
  Elementwise work (relu, pooling, softmax, uncertainty quantifiers) is
  excluded, the standard MFU convention — it is <1% of the conv FLOPs and
  rides the VPU, not the MXU.
- ``transformer_forward_flops`` — the IMDB transformer's matmul FLOPs
  (embed excluded: gather, not matmul; attention scored at 2·2·T²·D plus
  projections).
- ``training_step_flops`` — fwd + bwd ≈ 3× forward (standard accounting:
  backward is two matmuls per forward matmul).
- ``peak_flops`` — nominal per-chip peaks keyed by jax device_kind, bf16
  MXU numbers for TPUs (public spec sheets). For float32 compute the MXU
  peak is *lower* than bf16 on every TPU generation, so dividing an f32
  program's achieved FLOP/s by the bf16 peak UNDERSTATES utilization —
  the conservative direction; records label the peak's dtype explicitly.
- ``mfu`` — achieved/peak with the lookup applied.

Used by bench.py (mfu field in every record, degraded included) and by
scripts/measure_scaling.py (MFU column for the epoch table).
"""

from typing import Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# Analytic per-input forward FLOPs
# ---------------------------------------------------------------------------


def conv2d_flops(h_out: int, w_out: int, c_out: int, kh: int, kw: int, c_in: int) -> int:
    """2·MACs for one VALID conv layer at one input."""
    return 2 * h_out * w_out * c_out * kh * kw * c_in


def dense_flops(n_in: int, n_out: int) -> int:
    """FLOPs of one dense layer forward (multiply-add counted as 2)."""
    return 2 * n_in * n_out


def conv_net_forward_flops(model: str = "mnist") -> int:
    """Per-input forward matmul/conv FLOPs for the case-study convnets.

    Shapes follow Keras VALID-padding arithmetic exactly; see
    models/convnet.py for the layer list these mirror.
    """
    if model in ("mnist", "fmnist"):
        # 28x28x1 -> conv 32 3x3 -> 26x26x32 -> pool -> 13x13x32
        #         -> conv 64 3x3 -> 11x11x64 -> pool -> 5x5x64 = 1600
        #         -> dense 10
        return (
            conv2d_flops(26, 26, 32, 3, 3, 1)
            + conv2d_flops(11, 11, 64, 3, 3, 32)
            + dense_flops(5 * 5 * 64, 10)
        )
    if model == "cifar10":
        # 32x32x3 -> conv 32 -> 30x30x32 -> pool -> 15x15x32
        #         -> conv 64 -> 13x13x64 -> pool -> 6x6x64
        #         -> conv 64 -> 4x4x64 = 1024 -> dense 64 -> dense 10
        return (
            conv2d_flops(30, 30, 32, 3, 3, 3)
            + conv2d_flops(13, 13, 64, 3, 3, 32)
            + conv2d_flops(4, 4, 64, 3, 3, 64)
            + dense_flops(4 * 4 * 64, 64)
            + dense_flops(64, 10)
        )
    raise ValueError(f"no FLOPs model for {model!r}")


def transformer_forward_flops(
    seq_len: int = 100,
    d_model: int = 32,
    n_heads: int = 2,
    d_ff: int = 32,
    n_layers: int = 1,
    pooled_dense: Sequence[Tuple[int, int]] = ((32, 20), (20, 2)),
) -> int:
    """Per-input matmul FLOPs for the IMDB transformer (embedding gather
    excluded — it is a memory op). Defaults mirror models/transformer.py's
    keras-parity configuration (reference: case_study_imdb.py), including
    the Keras ``key_dim=embed_dim`` quirk: total qkv width is
    ``n_heads * d_model``, wider than the residual stream."""
    qkv = n_heads * d_model
    per_layer = (
        # q, k, v projections d_model->qkv, out projection qkv->d_model
        (3 * dense_flops(d_model, qkv) + dense_flops(qkv, d_model)) * seq_len
        + 2 * 2 * seq_len * seq_len * qkv  # scores + values matmuls
        + (dense_flops(d_model, d_ff) + dense_flops(d_ff, d_model)) * seq_len
    )
    head = sum(dense_flops(i, o) for i, o in pooled_dense)
    return n_layers * per_layer + head


def training_step_flops(forward_flops_per_input: int, batch: int) -> int:
    """fwd+bwd ≈ 3× forward (each forward matmul costs two in backward)."""
    return 3 * forward_flops_per_input * batch


# ---------------------------------------------------------------------------
# Analytic per-input HBM traffic (roofline denominator)
# ---------------------------------------------------------------------------


def conv_net_forward_hbm_bytes(
    model: str = "mnist", act_bytes: int = 2, in_bytes: int = 4
) -> int:
    """Lower-bound mandatory HBM bytes per input for the convnet forward.

    Counts: input read once + each layer's activation written once and read
    once by its consumer (the standard roofline accounting for a layer
    pipeline; XLA fusion can only REDUCE this by keeping an activation in
    VMEM, so at large batch — where per-core activations exceed VMEM — this
    is close to tight). Weights are excluded: they are KiB-sized and read
    once per *batch*, amortizing to ~0 bytes per input at batch 32k.

    Used to decide whether a low MFU is actually an HBM-bound ceiling
    (round-4 verdict, weak #1): achieved_bytes/s = rate × this, compared
    against ``hbm_peak_bytes``.
    """
    if model in ("mnist", "fmnist"):
        # activation element counts along models/convnet.py's forward
        acts = [26 * 26 * 32, 13 * 13 * 32, 11 * 11 * 64, 5 * 5 * 64, 10]
        inp = 28 * 28 * 1
    elif model == "cifar10":
        acts = [
            30 * 30 * 32,
            15 * 15 * 32,
            13 * 13 * 64,
            6 * 6 * 64,
            4 * 4 * 64,
            64,
            10,
        ]
        inp = 32 * 32 * 3
    else:
        raise ValueError(f"no HBM model for {model!r}")
    return inp * in_bytes + 2 * act_bytes * sum(acts)


# Nominal per-chip HBM bandwidth (bytes/s) from public spec sheets.
_TPU_HBM_BW = (
    ("v5 lite", 819e9),  # v5e
    ("v5e", 819e9),
    ("v5p", 2765e9),
    ("v6", 1640e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
)


def hbm_peak_bytes(device_kind: str = ""):
    """(peak_bytes_per_sec, label) for one chip; v5e assumed when unknown."""
    kind = (device_kind or "").lower()
    for needle, bw in _TPU_HBM_BW:
        if needle in kind:
            return bw, f"HBM bandwidth for {device_kind!r} (public spec)"
    return 819e9, (
        f"HBM bandwidth, v5e assumed (device_kind {device_kind!r} not in table)"
    )


# ---------------------------------------------------------------------------
# Peak FLOPs lookup
# ---------------------------------------------------------------------------

# Nominal per-chip peaks (FLOP/s) from public spec sheets, keyed by
# substrings of jax's device_kind. TPU entries are bf16 MXU peaks — the
# canonical MFU denominator; f32 programs measured against them yield a
# conservative (under-) estimate of utilization.
_TPU_PEAKS_BF16 = (
    ("v5 lite", 197e12),  # v5e ("TPU v5 lite")
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v6", 918e12),  # trillium
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)

# Nominal one-core x86 f32 peak: 2 FMA ports x 8 f32 lanes x 2 flops x ~3GHz.
_CPU_CORE_PEAK_F32 = 96e9


def peak_flops(platform: str, device_kind: str = "", cores: int = 1):
    """(peak_flop_per_sec, label) for one chip/core of this backend.

    Unknown TPU kinds fall back to the v5e number (the chip this repo's
    tunnel exposes); the label always says what was assumed.
    """
    kind = (device_kind or "").lower()
    if platform == "cpu":
        return _CPU_CORE_PEAK_F32 * max(1, cores), (
            f"nominal {max(1, cores)}-core x86 f32 peak (2 FMA x 8 lanes x 3GHz/core)"
        )
    for needle, peak in _TPU_PEAKS_BF16:
        if needle in kind:
            return peak, f"bf16 MXU peak for {device_kind!r} (public spec)"
    return 197e12, (
        f"bf16 MXU peak, v5e assumed (device_kind {device_kind!r} not in table)"
    )


def mfu(
    achieved_flops_per_sec: float,
    platform: str,
    device_kind: str = "",
    cores: int = 1,
) -> Tuple[float, float, str]:
    """(mfu, peak, peak_label); mfu = achieved / nominal peak."""
    peak, label = peak_flops(platform, device_kind, cores)
    return achieved_flops_per_sec / peak, peak, label

"""Cross-cutting utilities: profiling hooks and artifact-bus checking."""

from simple_tip_tpu.utils.profiling import maybe_trace

__all__ = ["maybe_trace"]

"""Artifact-bus completeness checking (failure detection).

The reference's resilience model is idempotent, file-granular artifacts +
restartable phases, with missing-run warnings at aggregation time (SURVEY.md
section 5). This utility makes that proactive: scan the bus and report which
(case study, run) pairs are missing which artifacts, so a partial/aborted
sweep can be resumed with exactly the runs that need re-running.
"""

import os
from typing import Dict, List, Set

from simple_tip_tpu.config import output_folder
from simple_tip_tpu.plotters.utils import APPROACHES


def expected_priority_types(has_dropout: bool) -> List[str]:
    """The artifact type-suffixes one complete prio run writes per dataset."""
    types = ["is_misclassified"]
    for unc in ["softmax", "pcs", "softmax_entropy", "deep_gini"] + (
        ["VR"] if has_dropout else []
    ):
        types.append(f"uncertainty_{unc}")
    for approach in APPROACHES:
        if approach.endswith("-cam") or approach in (
            "deep_gini",
            "softmax",
            "pcs",
            "softmax_entropy",
            "VR",
        ):
            continue
        types.append(f"{approach}_scores")
        types.append(f"{approach}_cam_order")
    return types


def _usable_files(folder: str) -> Set[str]:
    """Artifact names present AND non-empty: a crash can cut a write short,
    and a zero-byte .npy/.pickle would pass a pure name-membership audit only
    to fail at aggregation time."""
    if not os.path.isdir(folder):
        return set()
    usable = set()
    for e in os.scandir(folder):
        try:
            if e.stat().st_size > 0:
                usable.add(e.name)
        except FileNotFoundError:
            # vanished between listing and stat (a writer is replacing it
            # mid-audit): not usable right now
            continue
    return usable


def check_prio_artifacts(
    case_study: str, runs: range, has_dropout: bool = True
) -> Dict[int, Set[str]]:
    """Missing or truncated prio artifacts per run id (empty dict = complete)."""
    existing = _usable_files(os.path.join(output_folder(), "priorities"))
    missing: Dict[int, Set[str]] = {}
    for run in runs:
        for ds in ["nominal", "ood"]:
            for t in expected_priority_types(has_dropout):
                name = f"{case_study}_{ds}_{run}_{t}.npy"
                if name not in existing:
                    missing.setdefault(run, set()).add(name)
    return missing


def check_al_artifacts(
    case_study: str, runs: range, has_dropout: bool = True
) -> Dict[int, int]:
    """Missing active-learning pickles per run id (empty dict = complete).

    One complete AL run writes 40 selections x {nominal, ood} + 1 original
    evaluation (reference: src/dnn_test_prio/eval_active_learning.py:97-147);
    the VR selection exists only for models with dropout layers.
    """
    existing = _usable_files(os.path.join(output_folder(), "active_learning"))
    approaches = [a for a in APPROACHES if has_dropout or a != "VR"]
    expected_names = ["original_na"] + [
        f"{approach}_{oodnom}"
        for approach in approaches + ["random"]
        for oodnom in ("nominal", "ood")
    ]
    missing: Dict[int, int] = {}
    for run in runs:
        n = sum(
            1
            for name in expected_names
            if f"{case_study}_{run}_{name}.pickle" not in existing
        )
        if n:
            missing[run] = n
    return missing


def expected_times_metrics(has_dropout: bool) -> List[str]:
    """Metric keys that get a ``[setup, pred, quant, cam]`` times pickle per
    (case study, dataset, run): 12 NC configs + 5 SA variants + the
    uncertainty quantifiers (VR only for models with dropout). Matches the
    reference's file-per-metric layout (src/dnn_test_prio/
    eval_prioritization.py:46-52). Derived from the canonical APPROACHES
    list (its non-CAM entries are exactly the timed metric keys), so new
    metrics are picked up here automatically."""
    return [
        a
        for a in APPROACHES
        if not a.endswith("-cam") and (has_dropout or a != "VR")
    ]


def check_times_artifacts(
    case_study: str, runs: range, has_dropout: bool = True
) -> Dict[int, int]:
    """Missing times pickles per run id (empty dict = complete).

    The APFD table's runtime columns average over the first 10 runs
    (plotters/times_collector.py), so audit at least those.
    """
    existing = _usable_files(os.path.join(output_folder(), "times"))
    missing: Dict[int, int] = {}
    for run in runs:
        n = sum(
            1
            for ds in ("nominal", "ood")
            for metric in expected_times_metrics(has_dropout)
            if f"{case_study}_{ds}_{run}_{metric}" not in existing
        )
        if n:
            missing[run] = n
    return missing


def check_model_checkpoints(case_study: str, runs: range) -> List[int]:
    """Run ids without a usable (present, non-empty) model checkpoint."""
    existing = _usable_files(os.path.join(output_folder(), "models", case_study))
    return [r for r in runs if f"{r}.msgpack" not in existing]


def data_source(case_study: str) -> str:
    """Human-readable data-source verdict for the check phase.

    Paper-comparable runs require REAL (RUNBOOK.md section 2 gate); a
    SYNTHETIC verdict means results are structurally valid only. Presence
    semantics come from the loaders themselves (loaders.dataset_presence),
    so this report cannot drift from what load_* actually does."""
    from simple_tip_tpu.data.loaders import dataset_presence

    state = dataset_presence(case_study)
    if case_study == "imdb":
        return {
            "real": "REAL (tokenized caches)",
        }.get(state, "SYNTHETIC stand-in (mount imdb/*.npy or imdb/raw/*.jsonl + onramp)")
    return {
        "real": "REAL (nominal + corruption cache)",
        "nominal-only": (
            "REAL nominal; corruption cache will be GENERATED "
            "(not the *-C benchmark)"
        ),
        "incomplete-cache": (
            f"BROKEN corruption cache (exactly one of {case_study}_c_images/"
            f"_c_labels present) — the loader refuses to overwrite it and "
            f"uses a generated set in-memory; fix or remove the stray file"
        ),
    }.get(state, f"SYNTHETIC stand-in (mount {case_study}.npz)")


def report(case_study: str, num_runs: int = 100, has_dropout: bool = True) -> str:
    """Human-readable completeness report for one case study."""
    lines = [f"artifact check: {case_study} (runs 0..{num_runs - 1})"]
    lines.append(f"  data: {data_source(case_study)}")
    missing_models = check_model_checkpoints(case_study, range(num_runs))
    lines.append(
        f"  models: {num_runs - len(missing_models)}/{num_runs} trained"
        + (f" (missing: {missing_models[:10]}...)" if missing_models else "")
    )
    missing_prio = check_prio_artifacts(case_study, range(num_runs), has_dropout)
    complete = num_runs - len(missing_prio)
    lines.append(f"  prio artifacts: {complete}/{num_runs} runs complete")
    for run, names in sorted(missing_prio.items())[:5]:
        lines.append(f"    run {run}: {len(names)} artifacts missing")
    missing_al = check_al_artifacts(case_study, range(num_runs), has_dropout)
    lines.append(
        f"  active-learning artifacts: {num_runs - len(missing_al)}/{num_runs} runs complete"
    )
    for run, n in sorted(missing_al.items())[:5]:
        lines.append(f"    run {run}: {n} pickles missing")
    timed_runs = min(num_runs, 10)  # the APFD table times the first 10 runs
    missing_times = check_times_artifacts(
        case_study, range(timed_runs), has_dropout
    )
    lines.append(
        f"  times pickles (first {timed_runs} runs): "
        f"{timed_runs - len(missing_times)}/{timed_runs} runs complete"
    )
    for run, n in sorted(missing_times.items())[:5]:
        lines.append(f"    run {run}: {n} pickles missing")
    return "\n".join(lines)

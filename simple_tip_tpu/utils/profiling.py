"""Profiling hooks.

The reference's only tracing is wall-clock Timers (SURVEY.md section 5); this
build layers two optional capture planes over that schema, both driven by
``maybe_trace(label)``:

- set ``TIP_OBS_DIR`` (simple_tip_tpu/obs) and every ``maybe_trace`` phase is
  an obs span — the label lands on the run flame chart next to the scheduler
  and engine spans, with the XLA trace directory cross-referenced when both
  planes are on;
- set ``TIP_PROFILE_DIR`` to additionally capture a ``jax.profiler`` trace
  (viewable in TensorBoard / Perfetto) around the phase.

With neither set, ``maybe_trace`` is a no-op context manager.
"""

import contextlib
import os

from simple_tip_tpu import obs


@contextlib.contextmanager
def maybe_trace(label: str):
    """Context manager: obs span when TIP_OBS_DIR is set, plus a jax
    profiler trace when TIP_PROFILE_DIR is set."""
    profile_dir = os.environ.get("TIP_PROFILE_DIR")
    span_attrs = {"kind": "phase"}
    if profile_dir:
        span_attrs["xla_trace_dir"] = os.path.join(profile_dir, label)
    with obs.span(label, **span_attrs):
        if not profile_dir:
            yield
            return
        import jax

        out = os.path.join(profile_dir, label)
        os.makedirs(out, exist_ok=True)
        with jax.profiler.trace(out):
            yield

"""Profiling hooks.

The reference's only tracing is wall-clock Timers (SURVEY.md section 5); this
build layers two optional capture planes over that schema, both driven by
``maybe_trace(label)``:

- set ``TIP_OBS_DIR`` (simple_tip_tpu/obs) and every ``maybe_trace`` phase is
  an obs span — the label lands on the run flame chart next to the scheduler
  and engine spans, with the XLA trace directory cross-referenced when both
  planes are on;
- set ``TIP_PROFILE_DIR`` to additionally capture a ``jax.profiler`` trace
  (viewable in TensorBoard / Perfetto) around the phase.

With neither set, ``maybe_trace`` is a no-op context manager.

When BOTH are set the span carries ``xla_trace_dir`` (where the profiler
capture went) and ``xla_started_ts`` (the wall-clock instant the profiler
actually started, after its startup cost) — exactly the attributes
``obs export --splice-xla`` needs to time-shift the device timeline under
this host span in one merged Perfetto file (simple_tip_tpu/obs/splice.py).
"""

import contextlib
import os
import time

from simple_tip_tpu import obs


@contextlib.contextmanager
def maybe_trace(label: str):
    """Context manager: obs span when TIP_OBS_DIR is set, plus a jax
    profiler trace when TIP_PROFILE_DIR is set."""
    profile_dir = os.environ.get("TIP_PROFILE_DIR")
    span_attrs = {"kind": "phase"}
    if profile_dir:
        span_attrs["xla_trace_dir"] = os.path.join(profile_dir, label)
    with obs.span(label, **span_attrs) as sp:
        if not profile_dir:
            yield
            return
        import jax

        out = os.path.join(profile_dir, label)
        os.makedirs(out, exist_ok=True)
        with jax.profiler.trace(out):
            # Stamped INSIDE the profiler context: the splice anchors the
            # device timeline here, past the profiler's own startup cost.
            sp.set(xla_started_ts=time.time())
            yield

"""Profiling hooks.

The reference's only tracing is wall-clock Timers (SURVEY.md section 5); this
build keeps that timing schema and adds optional XLA-level traces: set
``TIP_PROFILE_DIR`` to capture a ``jax.profiler`` trace (viewable in
TensorBoard / Perfetto) around any phase wrapped in ``maybe_trace``.
"""

import contextlib
import os


@contextlib.contextmanager
def maybe_trace(label: str):
    """Context manager: jax profiler trace when TIP_PROFILE_DIR is set."""
    profile_dir = os.environ.get("TIP_PROFILE_DIR")
    if not profile_dir:
        yield
        return
    import jax

    out = os.path.join(profile_dir, label)
    os.makedirs(out, exist_ok=True)
    with jax.profiler.trace(out):
        yield

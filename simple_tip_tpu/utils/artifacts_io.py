"""Atomic JSON persistence for evidence artifacts.

Every measurement script in this repo follows persist-on-measure (a later
tunnel outage or kill must never erase evidence that already existed); the
write itself must therefore be atomic — a reader (the driver, the tunnel
watcher's gating helper) must never observe a half-written file. One shared
helper instead of per-script copies of the tmp+rename idiom (round-5
advisor reuse finding).
"""

import json
import os


def atomic_write_json(path: str, obj, indent: int = 1) -> None:
    """Write ``obj`` as JSON to ``path`` via tmp-file + atomic rename.

    fsync before the rename: this host loses power/connectivity mid-round
    often enough that a rename pointing at un-flushed blocks would defeat
    the persist-on-measure contract.
    """
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=indent)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)

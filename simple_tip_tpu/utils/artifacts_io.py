"""Atomic persistence for evidence artifacts and cache entries.

Every measurement script in this repo follows persist-on-measure (a later
tunnel outage or kill must never erase evidence that already existed); the
write itself must therefore be atomic — a reader (the driver, the tunnel
watcher's gating helper, a concurrent scheduler worker sharing the SA fit
cache) must never observe a half-written file. One shared helper instead
of per-script copies of the tmp+rename idiom (round-5 advisor reuse
finding); the SA fit cache and the circuit-breaker state ride the same
byte-level helper.

Chaos seam: both writers consult the ``artifact.write`` fault site
(resilience/faults.py). A ``torn`` fault writes half the payload to the
tmp file and raises before the rename; a ``kill`` fault writes half and
hard-exits the process — the mid-write kill the atomicity contract exists
for. Either way the destination path never sees partial bytes, which is
exactly what the kill-during-store test asserts.
"""

import json
import logging
import os
import re
import time

from simple_tip_tpu.resilience import faults

logger = logging.getLogger(__name__)

#: The tmp-file idiom every atomic writer in this repo uses: ``<base>.<pid>.tmp``.
#: The sweep matches ONLY this shape so it can never eat foreign files.
_ORPHAN_TMP_RE = re.compile(r"\.\d+\.tmp$")

#: Default age gate for the orphan sweep: anything younger may belong to a
#: live writer mid-rename; an hour-old tmp is a kill leftover.
DEFAULT_TMP_SWEEP_AGE_S = 3600.0


def sweep_orphan_tmp(directory: str, max_age_s: float = None) -> int:
    """Remove aged ``*.<pid>.tmp`` orphans in ``directory`` (same-dir only,
    never recursive). Returns the number removed.

    ``atomic_write_bytes`` cleans its tmp on every *exception* path, but a
    kill between the write and the rename (the ``artifact.write`` ``kill``
    fault, a real power loss) leaks it — harmless individually, unbounded
    across a long study's restarts. Journal/cache/bus open paths call this
    with the default age gate (``TIP_TMP_SWEEP_AGE_S``, 3600 s): old
    enough that no live writer — pid-unique and seconds-lived — can still
    own the file.
    """
    if max_age_s is None:
        raw = os.environ.get("TIP_TMP_SWEEP_AGE_S", "").strip()
        try:
            max_age_s = float(raw) if raw else DEFAULT_TMP_SWEEP_AGE_S
        except ValueError:
            max_age_s = DEFAULT_TMP_SWEEP_AGE_S
    removed = 0
    try:
        names = os.listdir(directory)
    except OSError:
        return 0
    now = time.time()
    for name in names:
        if not _ORPHAN_TMP_RE.search(name):
            continue
        path = os.path.join(directory, name)
        try:
            if now - os.stat(path).st_mtime < max_age_s:
                continue
            os.remove(path)
            removed += 1
        except OSError:
            continue  # raced a concurrent sweep/writer: benign
    if removed:
        obs_counter_inc("artifacts.tmp_swept", removed)
        logger.info(
            "swept %d orphan tmp file(s) from %s (kill leftovers)",
            removed, directory,
        )
    return removed


def obs_counter_inc(name: str, n: int) -> None:
    """Late-bound obs counter bump (keeps the module import-light)."""
    from simple_tip_tpu import obs

    obs.counter(name).inc(n)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` via tmp-file + fsync + atomic rename.

    The tmp name is pid-unique so concurrent writers (scheduler workers
    sharing one cache dir) cannot collide; fsync before the rename because
    this host loses power/connectivity mid-round often enough that a
    rename pointing at un-flushed blocks would defeat persist-on-measure.
    """
    tmp = f"{path}.{os.getpid()}.tmp"
    fault = faults.maybe_inject("artifact.write", path=path)
    torn = fault is not None and fault.kind in ("torn", "kill")
    try:
        with open(tmp, "wb") as f:
            if torn:
                f.write(data[: max(1, len(data) // 2)])
                f.flush()
                os.fsync(f.fileno())
                if fault.kind == "kill":
                    os._exit(1)  # simulated power loss mid-write
                raise faults.InjectedFault(f"torn write injected for {path}")
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        # Leave no tmp litter behind a failed write; the destination is
        # untouched either way (that is the whole point of the rename).
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def atomic_write_json(path: str, obj, indent: int = 1) -> None:
    """Write ``obj`` as JSON to ``path`` atomically (see
    ``atomic_write_bytes`` for the durability contract)."""
    atomic_write_bytes(
        path, json.dumps(obj, indent=indent).encode("utf-8")
    )


def load_json(path: str, default=None):
    """Read a JSON artifact, retrying transient IO; ``default`` on failure.

    The bus side of the unified retry policy (``TIP_RETRY_BUS_*``): a
    briefly unavailable shared mount must not make a reader conclude an
    artifact does not exist. A missing file and unparsable content are
    NOT transient (retrying cannot help) and return ``default``
    immediately — evidence readers (bench's last-good-TPU record, the
    measured-baseline proxy) must degrade, never raise.
    """
    from simple_tip_tpu.resilience import RetryGiveUp, RetryPolicy

    def _read():
        with open(path, encoding="utf-8") as f:
            return json.load(f)

    try:
        return RetryPolicy.from_env(
            scope="bus", attempts=2, base_s=0.05, deadline_s=10.0
        ).call(
            _read,
            transient=(OSError,),
            fatal=(FileNotFoundError,),
            describe=f"bus read ({path})",
        )
    except (RetryGiveUp, OSError, ValueError):
        return default

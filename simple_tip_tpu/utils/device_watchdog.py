"""Accelerator responsiveness watchdog.

The TPU in this deployment is reached through a tunnel that can wedge: device
programs then hang indefinitely rather than erroring (observed: a killed
client left the device stream stuck; every later jax op blocked forever).
``ensure_responsive_backend`` probes the default backend and, when the probe
hangs or fails, switches the process to the CPU backend so benchmarks and
smoke tests degrade loudly instead of hanging a pipeline forever.

The probe runs in a SUBPROCESS, not a thread: backend initialization inside
jax is serialized behind a process-wide lock, so an in-process probe that
wedges during init leaves the lock held and the CPU fallback then blocks on
the same lock (observed during a live tunnel outage — the previous
thread-based probe turned the watchdog itself into a hang). A stuck
subprocess is simply killed.

Call this BEFORE the first jax device use in the process (bench.py and the
driver entry do), otherwise the broken backend may already be wedging the
in-process init lock.
"""

import logging
import os
import subprocess
import sys

from simple_tip_tpu import obs

logger = logging.getLogger(__name__)

_PROBE = (
    "import jax, jax.numpy as jnp; "
    "jax.jit(lambda x: x + 1)(jnp.ones(8)).block_until_ready(); "
    "print(jax.devices()[0].platform)"
)

_CHIP_PROBE = (
    "import jax, jax.numpy as jnp; "
    "jax.jit(lambda x: x + 1)(jnp.ones(8)).block_until_ready(); "
    "print(jax.devices()[0].platform, jax.local_device_count())"
)

_chip_probe_cache: dict = {}


def probe_local_chips(timeout_s: float = 90.0) -> int:
    """Number of responsive local accelerator chips, WITHOUT initializing any
    backend in this process.

    The probe runs in a subprocess, so a caller about to spawn
    'default'-platform workers never grabs the accelerator itself first — on
    runtimes with exclusive per-process device access a parent-side init
    would wedge or fail the worker, and during a tunnel outage the parent
    init itself would hang (round-2 advisor, medium). Returns 0 when CPU is
    forced via ``JAX_PLATFORMS``, when the default platform is cpu, or when
    the probe fails or times out. The (timeout-keyed) result is cached: the
    probe costs a jax import + device init per call.
    """
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return 0
    if timeout_s in _chip_probe_cache:
        return _chip_probe_cache[timeout_s]
    chips = 0
    try:
        proc = subprocess.Popen(
            [sys.executable, "-c", _CHIP_PROBE],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=os.environ.copy(),
        )
        try:
            out, err = proc.communicate(timeout=timeout_s)
            if proc.returncode == 0 and out.strip():
                platform, n = out.strip().splitlines()[-1].split()
                chips = 0 if platform == "cpu" else int(n)
                obs.counter("watchdog.probe_ok").inc()
            else:
                logger.error(
                    "chip-count probe exited %s (stderr tail: %s) — assuming 0",
                    proc.returncode,
                    (err or "").strip()[-300:],
                )
                obs.counter("watchdog.probe_fail").inc()
        except subprocess.TimeoutExpired:
            logger.error(
                "chip-count probe unresponsive after %.0fs — assuming 0 chips",
                timeout_s,
            )
            obs.counter("watchdog.probe_timeout").inc()
            proc.kill()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:  # pragma: no cover
                logger.error("probe child survived SIGKILL; abandoning it")
    except (OSError, subprocess.SubprocessError, ValueError) as e:
        logger.error("chip-count probe could not run (%s) — assuming 0", e)
    _chip_probe_cache[timeout_s] = chips
    return chips


def ensure_responsive_backend(timeout_s: float = 90.0) -> str:
    """Return the platform that will be used ('tpu', 'cpu', ...).

    Probes the default jax backend with a tiny jitted op in a subprocess;
    if that does not complete within ``timeout_s``, reconfigures this
    process for the CPU backend. Every failure mode of the probe itself
    (spawn failure, crash, hang, kill-resistant D-state child) degrades to
    the CPU fallback — this function must never hang or raise.
    """
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # CPU is already forced (tests, explicit fallback): nothing to probe,
        # and skipping avoids paying a jax import in a discarded subprocess.
        # The env var alone is NOT enough on deployments whose sitecustomize
        # pre-registers an accelerator plugin (it silently wins over the env);
        # setting jax.config makes the CPU choice binding.
        import jax

        jax.config.update("jax_platforms", "cpu")
        return "cpu"
    try:
        proc = subprocess.Popen(
            [sys.executable, "-c", _PROBE],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            env=os.environ.copy(),
        )
        try:
            out, err = proc.communicate(timeout=timeout_s)
            if proc.returncode == 0 and out.strip():
                platform = out.strip().splitlines()[-1]
                obs.counter("watchdog.probe_ok").inc()
                obs.event("watchdog.probe", outcome="ok", platform=platform)
                return platform
            logger.error(
                "device probe exited %s (stderr tail: %s) — falling back to CPU",
                proc.returncode,
                err.strip()[-300:],
            )
            obs.counter("watchdog.probe_fail").inc()
            obs.event("watchdog.probe", outcome="fail", rc=proc.returncode)
        except subprocess.TimeoutExpired:
            logger.error(
                "default accelerator unresponsive after %.0fs — falling back "
                "to CPU",
                timeout_s,
            )
            obs.counter("watchdog.probe_timeout").inc()
            obs.event("watchdog.probe", outcome="timeout", timeout_s=timeout_s)
            proc.kill()
            try:
                # bounded: a child wedged in an uninterruptible device ioctl
                # can survive SIGKILL; abandon it rather than hang ourselves
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:  # pragma: no cover
                logger.error("probe child survived SIGKILL; abandoning it")
    except (OSError, subprocess.SubprocessError) as e:
        logger.error("device probe could not run (%s) — falling back to CPU", e)

    import jax

    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    try:
        import jax.extend.backend

        jax.extend.backend.clear_backends()
    except Exception:  # pragma: no cover
        pass
    return "cpu"

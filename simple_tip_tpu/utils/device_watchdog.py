"""Accelerator responsiveness watchdog.

The TPU in this deployment is reached through a tunnel that can wedge: device
programs then hang indefinitely rather than erroring (observed: a killed
client left the device stream stuck; every later jax op blocked forever).
``ensure_responsive_backend`` probes the default backend with a trivial op
under a timeout and, when the probe hangs or fails, switches the process to
the CPU backend so benchmarks and smoke tests degrade loudly instead of
hanging a pipeline forever.
"""

import logging
import threading

logger = logging.getLogger(__name__)


def ensure_responsive_backend(timeout_s: float = 90.0) -> str:
    """Return the platform that will be used ('tpu', 'cpu', ...).

    Probes the default jax backend with a tiny jitted op in a daemon thread;
    if it does not complete within ``timeout_s``, reconfigures jax for the CPU
    backend (the stuck probe thread is abandoned — it holds no locks the CPU
    backend needs).
    """
    import jax

    result = []

    def probe():
        try:
            import jax.numpy as jnp

            jax.jit(lambda x: x + 1)(jnp.ones(8)).block_until_ready()
            result.append(jax.devices()[0].platform)
        except Exception as e:  # pragma: no cover - depends on broken backend
            logger.warning("device probe failed: %s", e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if result:
        return result[0]

    logger.error(
        "default accelerator unresponsive after %.0fs — falling back to CPU",
        timeout_s,
    )
    jax.config.update("jax_platforms", "cpu")
    try:
        import jax.extend.backend

        jax.extend.backend.clear_backends()
    except Exception:  # pragma: no cover
        pass
    return "cpu"

"""Accelerator responsiveness watchdog, fronted by a circuit breaker.

The TPU in this deployment is reached through a tunnel that can wedge:
device programs then hang indefinitely rather than erroring (observed: a
killed client left the device stream stuck; every later jax op blocked
forever). ``ensure_responsive_backend`` probes the default backend and,
when the probe hangs or fails, switches the process to the CPU backend so
benchmarks and smoke tests degrade LOUDLY instead of hanging a pipeline
forever.

The probe runs in a SUBPROCESS, not a thread: backend initialization inside
jax is serialized behind a process-wide lock, so an in-process probe that
wedges during init leaves the lock held and the CPU fallback then blocks on
the same lock (observed during a live tunnel outage — the previous
thread-based probe turned the watchdog itself into a hang). A stuck
subprocess is simply killed.

Resilience integration (this is the promoted form the ROADMAP's
fleet-scheduler item depends on):

- **circuit breaker** (resilience/breaker.py): consecutive probe failures
  open a shared breaker; while open, callers skip the ~90 s probe and
  either fail fast (``TIP_BREAKER_MODE=fail``) or degrade to CPU with the
  degradation stamped into health counters and ``degradation_reason()`` —
  which bench.py writes into its record, so ``obs regress`` fails against
  a healthy baseline instead of silently swallowing a CPU number (the
  BENCH_r05 failure mode);
- **unified retry** (resilience/retry.py): a probe that cannot even spawn
  (transient OSError — fork pressure, a briefly full /tmp) is retried
  with backoff under the ``watchdog`` scope instead of instantly
  condemning the backend; a probe that RAN and timed out is evidence,
  not noise, and is never retried here — that is the breaker's domain;
- **fault seam** (``watchdog.probe``): a fault plan can force ``timeout``
  or ``fail`` outcomes without touching a real backend — the tunnel-flap
  / device-init-failure simulation the chaos suite drives.

Call this BEFORE the first jax device use in the process (bench.py and the
driver entry do), otherwise the broken backend may already be wedging the
in-process init lock.
"""

import logging
import os
import subprocess
import sys
from typing import Optional, Tuple

from simple_tip_tpu import obs
from simple_tip_tpu.resilience import (
    BackendUnavailable,
    CircuitBreaker,
    RetryGiveUp,
    RetryPolicy,
    faults,
)

logger = logging.getLogger(__name__)

_PROBE = (
    "import jax, jax.numpy as jnp; "
    "jax.jit(lambda x: x + 1)(jnp.ones(8)).block_until_ready(); "
    "print(jax.devices()[0].platform)"
)

_CHIP_PROBE = (
    "import jax, jax.numpy as jnp; "
    "jax.jit(lambda x: x + 1)(jnp.ones(8)).block_until_ready(); "
    "print(jax.devices()[0].platform, jax.local_device_count())"
)

_chip_probe_cache: dict = {}

# Why the last ensure_responsive_backend call in this process degraded to
# CPU (None = it did not): "probe-timeout", "probe-fail", "probe-error",
# or "breaker-open". bench.py stamps this into its record as
# ``degraded_reason`` — the degraded-record contract (RUNBOOK §7).
_last_reason: Optional[str] = None


def degradation_reason() -> Optional[str]:
    """Why this process fell back to CPU, or None if it did not."""
    return _last_reason


def _spawn_probe(code: str) -> subprocess.Popen:
    """Launch one probe subprocess (retried for transient spawn errors)."""
    return subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=os.environ.copy(),
    )


def _run_probe(code: str, timeout_s: float) -> Tuple[str, str]:
    """One probe round: ('ok', stdout) | ('fail', detail) | ('timeout', '').

    The ``watchdog.probe`` fault seam can dictate the outcome without
    spawning anything (the chaos suite's tunnel-flap stand-in). Spawn
    failures are retried with backoff (``TIP_RETRY_WATCHDOG_*``); a probe
    that actually timed out is killed (bounded wait — a child wedged in an
    uninterruptible device ioctl can survive SIGKILL; abandon it rather
    than hang ourselves) and never retried here.
    """
    fault = faults.maybe_inject("watchdog.probe", timeout_s=timeout_s)
    if fault is not None and fault.kind == "timeout":
        return "timeout", ""
    if fault is not None and fault.kind == "fail":
        return "fail", "injected probe failure"
    try:
        proc = RetryPolicy.from_env(
            scope="watchdog", attempts=2, base_s=0.5, deadline_s=30.0
        ).call(_spawn_probe, code, describe="device probe spawn")
    except (RetryGiveUp, ValueError) as e:
        return "fail", f"probe could not run ({e})"
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:  # pragma: no cover
            logger.error("probe child survived SIGKILL; abandoning it")
        return "timeout", ""
    if proc.returncode == 0 and out.strip():
        return "ok", out
    return "fail", (
        f"probe exited {proc.returncode} (stderr tail: {(err or '').strip()[-300:]})"
    )


def probe_local_chips(timeout_s: float = 90.0) -> int:
    """Number of responsive local accelerator chips, WITHOUT initializing any
    backend in this process.

    The probe runs in a subprocess, so a caller about to spawn
    'default'-platform workers never grabs the accelerator itself first — on
    runtimes with exclusive per-process device access a parent-side init
    would wedge or fail the worker, and during a tunnel outage the parent
    init itself would hang (round-2 advisor, medium). Returns 0 when CPU is
    forced via ``JAX_PLATFORMS``, when the default platform is cpu, when
    the probe fails or times out — or, immediately, when the backend
    circuit breaker is open (no point burning a 90 s probe per dispatch
    during a known outage). The (timeout-keyed) result is cached: the
    probe costs a jax import + device init per call.
    """
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return 0
    if timeout_s in _chip_probe_cache:
        return _chip_probe_cache[timeout_s]
    breaker = CircuitBreaker.from_env()
    if breaker is not None and not breaker.allow():
        return 0  # NOT cached: the breaker may close before the next call
    outcome, out = _run_probe(_CHIP_PROBE, timeout_s)
    chips = 0
    if outcome == "ok":
        try:
            platform, n = out.strip().splitlines()[-1].split()
            chips = 0 if platform == "cpu" else int(n)
            obs.counter("watchdog.probe_ok").inc()
            if breaker is not None:
                breaker.record_success()
        except ValueError:
            logger.error("chip-count probe output unparsable: %r", out[-200:])
            obs.counter("watchdog.probe_fail").inc()
    elif outcome == "timeout":
        logger.error(
            "chip-count probe unresponsive after %.0fs — assuming 0 chips",
            timeout_s,
        )
        obs.counter("watchdog.probe_timeout").inc()
        if breaker is not None:
            breaker.record_failure()
    else:
        logger.error("chip-count probe failed (%s) — assuming 0", out)
        obs.counter("watchdog.probe_fail").inc()
        if breaker is not None:
            breaker.record_failure()
    _chip_probe_cache[timeout_s] = chips
    return chips


def _force_cpu() -> None:
    """Bind this process to the CPU backend (env var + jax.config: the env
    alone silently loses to sitecustomize plugin pre-registration)."""
    import jax

    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    try:
        import jax.extend.backend

        jax.extend.backend.clear_backends()
    except Exception:  # pragma: no cover
        pass


def ensure_responsive_backend(timeout_s: float = 90.0) -> str:
    """Return the platform that will be used ('tpu', 'cpu', ...).

    Probes the default jax backend with a tiny jitted op in a subprocess;
    if that does not complete within ``timeout_s``, reconfigures this
    process for the CPU backend. Every failure mode of the probe itself
    (spawn failure, crash, hang, kill-resistant D-state child) degrades to
    the CPU fallback — this function must never hang and raises ONLY when
    the circuit breaker is open with ``TIP_BREAKER_MODE=fail`` (the
    fail-fast contract callers opted into). Degradations are loud:
    ``degradation_reason()`` reports why, and the breaker counts every
    short-circuit into the health counters ``obs regress`` gates on.
    """
    global _last_reason
    _last_reason = None
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # CPU is already forced (tests, explicit fallback): nothing to probe,
        # and skipping avoids paying a jax import in a discarded subprocess.
        import jax

        jax.config.update("jax_platforms", "cpu")
        return "cpu"

    breaker = CircuitBreaker.from_env()
    if breaker is not None and not breaker.allow():
        if breaker.mode == "fail":
            raise BackendUnavailable(
                "backend circuit breaker is open (recent probe failures) and "
                "TIP_BREAKER_MODE=fail: refusing to degrade to CPU; wait out "
                "the cooldown, fix the tunnel, or delete the breaker state "
                "file to force a probe"
            )
        logger.error(
            "backend circuit breaker OPEN — degrading to CPU WITHOUT a probe; "
            "this run's records will be stamped degraded (reason: breaker-open)"
        )
        obs.counter("breaker.degraded").inc()
        _last_reason = "breaker-open"
        _force_cpu()
        return "cpu"

    outcome, detail = _run_probe(_PROBE, timeout_s)
    if outcome == "ok":
        platform = detail.strip().splitlines()[-1]
        obs.counter("watchdog.probe_ok").inc()
        obs.event("watchdog.probe", outcome="ok", platform=platform)
        if breaker is not None:
            breaker.record_success()
        return platform
    if outcome == "timeout":
        logger.error(
            "default accelerator unresponsive after %.0fs — falling back to CPU",
            timeout_s,
        )
        obs.counter("watchdog.probe_timeout").inc()
        obs.event("watchdog.probe", outcome="timeout", timeout_s=timeout_s)
        _last_reason = "probe-timeout"
    else:
        logger.error("device probe failed (%s) — falling back to CPU", detail)
        obs.counter("watchdog.probe_fail").inc()
        obs.event("watchdog.probe", outcome="fail", detail=str(detail)[:200])
        _last_reason = "probe-fail"
    if breaker is not None:
        breaker.record_failure()
    _force_cpu()
    return "cpu"

"""Active-learning results table (paper Table 2).

Loads the AL pickles by regex, averages accuracies per approach over runs,
reports deltas vs. the ``random`` selection baseline, and emits
``results/active.csv`` + a latex table
(reference: src/plotters/eval_active_learning_table.py).
"""

import os
import re
import warnings
from typing import Dict, List, Tuple

import pandas as pd

from simple_tip_tpu.config import subdir
from simple_tip_tpu.plotters.utils import (
    APPROACHES,
    PAPER_APPROACHES,
    _row,
    human_appraoch_name,
    load_all_for_regex,
    vertical_categories,
)

BASELINE = "random"
RANDOM = "random"


def load_arrays_active_learning(
    case_study: str, ds_name: str, by_id: bool = False
) -> Dict[str, List[Dict[Tuple[str, str], float]]]:
    """Per-run raw AL results for one case study and active split."""
    res = dict()
    incl_random = APPROACHES.copy()
    incl_random.append(RANDOM)
    for approach in incl_random:
        regex = re.compile(f"{re.escape(case_study)}_\\d*_{re.escape(approach)}_{ds_name}\\.")
        vals, files = load_all_for_regex("active_learning", regex)
        if not by_id:
            res[approach] = vals
        else:
            res[approach] = {int(files[i].split("_")[1]): vals[i] for i in range(len(vals))}

    original_regex = re.compile(f"{re.escape(case_study)}_\\d*_original_na\\.")
    original_vals, original_files = load_all_for_regex("active_learning", original_regex)
    if not by_id:
        res["original"] = original_vals
    else:
        res["original"] = {
            int(original_files[i].split("_")[1]): original_vals[i]
            for i in range(len(original_vals))
        }
    return res


def _reduce_active_learning(cs, active_learning_files):
    """Average each approach's per-split accuracies over runs."""
    res = dict()
    for approach, run_results in active_learning_files.items():
        if len(run_results) == 0:
            if not (approach == "VR" and cs == "cifar10"):
                warnings.warn(f"missing AL results for {approach} on {cs}")
            continue
        assert all(
            run_results[0].keys() == run_results[i].keys()
            for i in range(1, len(run_results))
        )
        res[approach] = {
            key: sum(r[key] for r in run_results) / len(run_results)
            for key in run_results[0].keys()
        }
    return res


def _relative_active_learning_gains(reduced, baseline: str):
    """Per-approach accuracy minus the baseline selection's accuracy."""
    assert baseline in ["random", "original"]
    assert baseline in reduced.keys()
    res = dict()
    for approach, performance in reduced.items():
        if approach == baseline:
            continue
        res[approach] = {
            key: performance[key] - reduced[baseline][key] for key in performance.keys()
        }
    return res


def _forma(x):
    return "{:.2%}".format(x)


def build_data_frame(case_studies: List[str]) -> pd.DataFrame:
    """Assemble the full AL results dataframe."""
    col_idx = pd.MultiIndex.from_product(
        [
            case_studies,
            ["nominal", "ood"],
            ["nominal:observed", "nominal:future", "ood:observed", "ood:future"],
        ]
    )
    rows = ["original", "random"]
    rows.extend(APPROACHES)
    category_and_rows = [_row(row) for row in rows]
    row_index = pd.MultiIndex.from_tuples(category_and_rows, names=["category", "approach"])
    df = pd.DataFrame(columns=col_idx, index=row_index)

    for cs in case_studies:
        for obs in ["nominal", "ood"]:
            file_values = load_arrays_active_learning(cs, obs)
            reduced = _reduce_active_learning(cs, file_values)
            if BASELINE not in reduced:
                continue
            relative = _relative_active_learning_gains(reduced, BASELINE)
            for approach in ["original", "random"]:
                if approach not in reduced:
                    continue
                for key in reduced[approach].keys():
                    df.at[_row(approach), (cs, obs, f"{key[0]}:{key[1]}")] = _forma(
                        reduced[approach][key]
                    )
            for approach in APPROACHES:
                try:
                    for key in relative[approach].keys():
                        df.at[_row(approach), (cs, obs, f"{key[0]}:{key[1]}")] = _forma(
                            relative[approach][key]
                        )
                except KeyError:
                    for split in ["nominal:observed", "nominal:future", "ood:observed", "ood:future"]:
                        df.at[_row(approach), (cs, obs, split)] = "n.a."
    return df


def latex_table(pd_df: pd.DataFrame):
    """Emit the paper-subset latex table."""
    paper_approaches = PAPER_APPROACHES.copy()
    paper_approaches.extend(["original", "random"])
    pd_df = pd_df.iloc[pd_df.index.get_level_values("approach").isin(paper_approaches)]
    pd_df = pd_df.rename(mapper=human_appraoch_name, axis="index")
    paper_columns = [
        c for c in pd_df.columns if c[2].startswith(c[1]) and c[2].endswith("future")
    ]
    try:
        latex = pd_df.to_latex(
            columns=paper_columns,
            multicolumn_format="c",
            multirow=True,
            column_format="llcccccccc",
        )
    except Exception as e:
        warnings.warn(f"latex table rendering failed: {e}")
        return
    latex = vertical_categories(latex)
    latex = latex.replace("category", "", 1)
    with open(os.path.join(subdir("results"), "active_paper_table.tex"), "w") as f:
        f.write(latex)


def run(case_studies: List[str] = ("mnist", "fmnist", "cifar10", "imdb")):
    """Generate results/active.csv and the latex table."""
    df = build_data_frame(list(case_studies))
    df.to_csv(os.path.join(subdir("results"), "active.csv"))
    latex_table(df)
    return df


if __name__ == "__main__":
    run()

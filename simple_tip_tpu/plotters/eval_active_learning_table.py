"""Active-learning results table (paper Table 2).

Consumes the ``active_learning/`` pickle bus
(``{cs}_{run}_{approach}_{observed-split}.pickle`` holding the four-split
accuracy dict), averages each approach over its runs, reports gains
relative to the ``random``-selection baseline (absolute accuracies for the
``original`` model and the baseline itself), and emits
``results/active.csv`` + the paper-subset latex table. The artifact regex
and table layout are the reference contract
(src/plotters/eval_active_learning_table.py); a missing VR on cifar10 is
expected (no dropout) and not warned about.
"""

import os
import re
import warnings
from typing import Dict, List, Tuple

import pandas as pd

from simple_tip_tpu.config import subdir
from simple_tip_tpu.plotters.utils import (
    APPROACHES,
    PAPER_APPROACHES,
    _row,
    human_approach_name,
    load_all_for_regex,
    vertical_categories,
)

BASELINE = "random"
RANDOM = "random"

_SPLITS = ("nominal:observed", "nominal:future", "ood:observed", "ood:future")


def _load_approach(case_study: str, approach: str, ds_name: str):
    """(accuracy dicts, run ids) of one approach's AL pickles."""
    pattern = re.compile(
        f"{re.escape(case_study)}_\\d*_{re.escape(approach)}_{ds_name}\\."
    )
    values, names = load_all_for_regex("active_learning", pattern)
    return values, [int(name.split("_")[1]) for name in names]


def load_arrays_active_learning(
    case_study: str, ds_name: str, by_id: bool = False
) -> Dict[str, List[Dict[Tuple[str, str], float]]]:
    """Raw per-run AL results for one (case study, observed split), per
    approach — including the ``random`` baseline and the untouched
    ``original`` model (whose artifact carries split 'na')."""
    wanted = [*APPROACHES, RANDOM, ("original", "na")]
    res = {}
    for entry in wanted:
        approach, split = entry if isinstance(entry, tuple) else (entry, ds_name)
        values, run_ids = _load_approach(case_study, approach, split)
        res[approach] = dict(zip(run_ids, values)) if by_id else values
    return res


def _reduce_active_learning(cs, active_learning_files):
    """Run-average each approach's per-split accuracies."""
    reduced = {}
    for approach, runs in active_learning_files.items():
        if not runs:
            if approach != "VR" or cs != "cifar10":
                warnings.warn(f"missing AL results for {approach} on {cs}")
            continue
        splits = runs[0].keys()
        assert all(r.keys() == splits for r in runs[1:]), approach
        reduced[approach] = {
            split: sum(r[split] for r in runs) / len(runs) for split in splits
        }
    return reduced


def _relative_active_learning_gains(reduced, baseline: str):
    """Accuracy delta vs the baseline selection, per approach and split."""
    assert baseline in ("random", "original") and baseline in reduced
    base = reduced[baseline]
    return {
        approach: {split: acc - base[split] for split, acc in performance.items()}
        for approach, performance in reduced.items()
        if approach != baseline
    }


def _forma(x):
    return "{:.2%}".format(x)


def build_data_frame(case_studies: List[str]) -> pd.DataFrame:
    """Assemble the full AL results dataframe ('n.a.' for missing cells)."""
    col_idx = pd.MultiIndex.from_product(
        [case_studies, ["nominal", "ood"], list(_SPLITS)]
    )
    rows = ["original", "random", *APPROACHES]
    row_index = pd.MultiIndex.from_tuples(
        [_row(r) for r in rows], names=["category", "approach"]
    )
    df = pd.DataFrame(columns=col_idx, index=row_index)

    for cs in case_studies:
        for obs in ("nominal", "ood"):
            raw = load_arrays_active_learning(cs, obs)
            reduced = _reduce_active_learning(cs, raw)
            if BASELINE not in reduced:
                continue
            gains = _relative_active_learning_gains(reduced, BASELINE)
            # Absolute accuracies for the two baselines, deltas for the rest.
            for approach in ("original", "random"):
                for split, acc in reduced.get(approach, {}).items():
                    col = (cs, obs, f"{split[0]}:{split[1]}")
                    df.at[_row(approach), col] = _forma(acc)
            for approach in APPROACHES:
                per_split = gains.get(approach)
                if per_split is None:
                    for split in _SPLITS:
                        df.at[_row(approach), (cs, obs, split)] = "n.a."
                else:
                    for split, delta in per_split.items():
                        col = (cs, obs, f"{split[0]}:{split[1]}")
                        df.at[_row(approach), col] = _forma(delta)
    return df


def latex_table(pd_df: pd.DataFrame) -> None:
    """Emit the paper-subset latex table (the future-split columns whose
    active split matches the evaluated dataset)."""
    keep = [*PAPER_APPROACHES, "original", "random"]
    pd_df = pd_df.iloc[pd_df.index.get_level_values("approach").isin(keep)]
    pd_df = pd_df.rename(mapper=human_approach_name, axis="index")
    paper_columns = [
        c for c in pd_df.columns if c[2].startswith(c[1]) and c[2].endswith("future")
    ]
    try:
        latex = pd_df.to_latex(
            columns=paper_columns,
            multicolumn_format="c",
            multirow=True,
            column_format="llcccccccc",
        )
    except Exception as e:
        warnings.warn(f"latex table rendering failed: {e}")
        return
    latex = vertical_categories(latex).replace("category", "", 1)
    with open(os.path.join(subdir("results"), "active_paper_table.tex"), "w") as f:
        f.write(latex)


def run(case_studies: List[str] = ("mnist", "fmnist", "cifar10", "imdb")):
    """Generate results/active.csv and the latex table."""
    df = build_data_frame(list(case_studies))
    df.to_csv(os.path.join(subdir("results"), "active.csv"))
    latex_table(df)
    return df


if __name__ == "__main__":
    run()

"""APFD performance table (paper Table 1).

Consumes the ``priorities/`` artifact bus — masks
(``{cs}_{ds}_{run}_is_misclassified``), score arrays
(``..._{approach}_scores``) and CAM orders (``..._{approach}_cam_order``) —
derives a prioritization order per (approach, run) (descending score
argsort; CAM orders verbatim), scores APFD, averages over the first 100
runs, attaches the first-10-runs timing columns, and emits
``results/apfds.csv`` + the paper-subset latex table. Artifact naming and
table layout follow the reference contract
(src/plotters/eval_apfd_table.py); the parsing and aggregation below are
suffix-driven rather than the reference's token-count dispatch.
"""

import os
import warnings
from collections import defaultdict
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

from simple_tip_tpu.config import output_folder, subdir
from simple_tip_tpu.ops.apfd import apfd_from_order
from simple_tip_tpu.plotters import times_collector
from simple_tip_tpu.plotters.utils import (
    APPROACHES,
    PAPER_APPROACHES,
    _row,
    human_approach_name,
    vertical_categories,
)

TIME_COL = "time"

FIRST_K_MODELS_CONSIDERED = 100

_MASK_SUFFIX = "is_misclassified"
_SCORE_SUFFIX = "_scores"
_CAM_SUFFIX = "_cam_order"


def _parse_artifact(stem: str) -> Optional[Tuple[str, Optional[str]]]:
    """``{run}_{rest}`` -> (run_id, approach) — approach None for the mask.

    The approach embedded in ``rest`` is already in canonical
    ``{metric}[_{param}]`` form, so suffix stripping recovers it for every
    family at once (NC with params, SA stems, uncertainty quantifiers).
    """
    run_id, _, rest = stem.partition("_")
    if not run_id.isdigit():
        return None
    if rest == _MASK_SUFFIX:
        return run_id, None
    if rest.endswith(_CAM_SUFFIX):
        return run_id, rest[: -len(_CAM_SUFFIX)] + "-cam"
    if rest.endswith(_SCORE_SUFFIX):
        return run_id, rest[: -len(_SCORE_SUFFIX)]
    if rest.startswith("uncertainty_"):
        return run_id, rest[len("uncertainty_"):]
    return None


def load_apfd_values(case_study: str, ds_name: str) -> Dict[str, Dict[int, float]]:
    """``{approach: {run: apfd}}`` for one (case study, dataset)."""
    folder = Path(output_folder()) / "priorities"
    prefix = f"{case_study}_{ds_name}_"
    masks: Dict[int, np.ndarray] = {}
    orders: Dict[Tuple[str, int], np.ndarray] = {}
    if folder.is_dir():
        for path in sorted(folder.rglob("*.npy")):
            if not path.name.startswith(prefix):
                continue
            parsed = _parse_artifact(path.name[len(prefix):-len(".npy")])
            if parsed is None:
                continue
            run_id, approach = parsed
            run = int(run_id)
            if run >= FIRST_K_MODELS_CONSIDERED:
                continue
            arr = np.load(path)
            if approach is None:
                masks[run] = arr
            elif approach.endswith("-cam"):
                orders[approach, run] = arr
            else:
                orders[approach, run] = np.argsort(-arr)

    apfds: Dict[str, Dict[int, float]] = {}
    for (approach, run), order in orders.items():
        if approach not in APPROACHES or run not in masks:
            continue
        apfds.setdefault(approach, {})[run] = apfd_from_order(masks[run], order)
    return apfds


def _get_as_df(case_studies: List[str]) -> pd.DataFrame:
    """Run-averaged APFD per (approach, case study, dataset); 'n.a.' gaps."""
    col_idx = pd.MultiIndex.from_product([case_studies, ["nominal", "ood", TIME_COL]])
    rows = [_row(a) for a in APPROACHES]
    df = pd.DataFrame(
        columns=col_idx,
        index=pd.MultiIndex.from_tuples(rows, names=["category", "approach"]),
    )
    for cs in case_studies:
        for ds in ("nominal", "ood"):
            per_approach = load_apfd_values(cs, ds)
            for row in rows:
                runs = per_approach.get(row[1])
                df.loc[row, (cs, ds)] = (
                    float(np.mean(list(runs.values()))) if runs else "n.a."
                )
    return df


def _plot_latex_table(pd_df: pd.DataFrame) -> None:
    """Emit the paper-subset latex table (rendering is non-essential)."""
    pd_df = pd_df.iloc[pd_df.index.get_level_values("approach").isin(PAPER_APPROACHES)]
    pd_df = pd_df.rename(mapper=human_approach_name, axis="index")
    try:
        latex = pd_df.to_latex(
            multicolumn_format="c",
            multirow=True,
            column_format="llcccccccccccc",
            # pandas>=2 to_latex no longer escapes cell text, so the percent
            # sign must be emitted pre-escaped or it comments out the rest
            # of every data row
            float_format=lambda v: f"{v:.2%}".replace("%", r"\%"),
        )
    except Exception as e:
        warnings.warn(f"latex table rendering failed: {e}")
        return
    latex = vertical_categories(latex).replace("category", "", 1)
    Path(subdir("results"), "apfd_paper_table.tex").write_text(latex)


# Reverse of times_collector's filename aliases.
_METRIC_OF_ALIAS = {"SM": "softmax", "SE": "softmax_entropy", "PCS": "pcs", "DeepGini": "deep_gini"}


def _add_reported_times(df: pd.DataFrame, times: Dict) -> None:
    """Fill the time columns from the first-10-runs records.

    Reported total = setup + 2*(pred + quant) — both datasets share one
    setup — plus 2*cam for the -cam variant of scored approaches.
    """
    if not times:
        return
    assert all(
        int(run) < times_collector.N_FIRST_MODELS_CONSIDERED
        for _, _, run, _, _ in times
    ), "Should only consider first 10 runs"

    # Pool the per-(run, dataset) stage records of each (cs, metric, param).
    pooled = defaultdict(list)
    for (cs, _ds, _run, metric, param), record in times.items():
        # Uncertainty quantifiers have no cam stage; pad to 4.
        stages = (list(record) + [0.0] * 4)[:4]
        pooled[cs, metric, param].append(stages)

    for (cs, metric, param), records in pooled.items():
        if (cs, TIME_COL) not in df.columns:
            continue
        setup_s, pred_s, quant_s, cam_s = np.mean(records, axis=0)
        base = _METRIC_OF_ALIAS.get(metric, metric)
        row = _row(base + (f"_{param}" if param else ""))
        if row[0] is None:
            continue
        plain_s = setup_s + 2 * (pred_s + quant_s)
        if row in df.index:
            df.loc[row, (cs, TIME_COL)] = f"{round(plain_s)}s"
        cam_row = (row[0], f"{row[1]}-cam")
        if row[0] in ("surprise", "neuron coverage") and cam_row in df.index:
            df.loc[cam_row, (cs, TIME_COL)] = f"{round(plain_s + 2 * cam_s)}s"


def run(case_studies: List[str] = ("mnist", "fmnist", "cifar10", "imdb")):
    """Generate results/apfds.csv and the latex table."""
    df = _get_as_df(list(case_studies))
    _add_reported_times(df, times_collector.load_times())
    df.to_csv(os.path.join(subdir("results"), "apfds.csv"))
    _plot_latex_table(df)
    return df


if __name__ == "__main__":
    run()

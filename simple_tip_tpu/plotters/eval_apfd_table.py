"""APFD performance table (paper Table 1).

Walks ``priorities/``, parses the underscore-delimited artifact names, derives
orders (scores -> descending argsort; cam orders used directly), computes APFD
per (approach, run), averages over runs, adds the timing columns and emits
``results/apfds.csv`` plus a latex table
(reference: src/plotters/eval_apfd_table.py).
"""

import os
import warnings
from statistics import mean
from typing import Dict, List

import numpy as np
import pandas as pd

from simple_tip_tpu.config import output_folder, subdir
from simple_tip_tpu.ops.apfd import apfd_from_order
from simple_tip_tpu.plotters import times_collector
from simple_tip_tpu.plotters.utils import (
    APPROACHES,
    PAPER_APPROACHES,
    _row,
    approach_name,
    human_appraoch_name,
    vertical_categories,
)

TIME_COL = "time"

FIRST_K_MODELS_CONSIDERED = 100


def load_apfd_values(case_study: str, ds_name: str) -> Dict[str, Dict[int, float]]:
    """APFD per (approach, run) for one case study and dataset."""
    misclassifications = dict()
    orders = dict()

    for root, dirs, files in os.walk(os.path.join(output_folder(), "priorities")):
        for file in files:
            if not file.endswith(".npy"):
                continue
            if not file.startswith(f"{case_study}_{ds_name}"):
                continue
            arr = np.load(os.path.join(root, file))
            if file.endswith("is_misclassified.npy"):
                _, _, model_id, _, _ = file.split("_")
                if int(model_id) < FIRST_K_MODELS_CONSIDERED:
                    misclassifications[model_id] = arr
            elif file.endswith("cam_order.npy"):
                if "dsa" in file or "lsa" in file:
                    _, _, model_id, metric, _, _ = file.split("_")
                    metric = approach_name(metric, cam=True)
                else:
                    _, _, model_id, metric, param, _, _ = file.split("_")
                    metric = approach_name(metric, param=param, cam=True)
                orders[(metric, model_id)] = arr
            else:
                # scores
                if "uncertainty" in file:
                    stem = file.replace(".npy", "").replace(f"{case_study}_{ds_name}_", "")
                    model_id, metric = stem.split("_uncertainty_")
                elif "dsa" in file or "lsa" in file:
                    _, _, model_id, metric, _ = file.split("_")
                else:
                    _, _, model_id, metric, param, _ = file.split("_")
                    metric = approach_name(metric, param=param, cam=False)
                orders[(metric, model_id)] = np.argsort(-arr)

    apfds: Dict[str, Dict[int, float]] = dict()
    for i in range(FIRST_K_MODELS_CONSIDERED):
        for approach in APPROACHES:
            try:
                order = orders[(approach, str(i))]
                m = misclassifications[str(i)]
            except KeyError:
                continue
            apfd = apfd_from_order(m, order)
            apfds.setdefault(approach, dict())[i] = apfd
    return apfds


def _get_as_df(case_studies: List[str]) -> pd.DataFrame:
    col_idx = pd.MultiIndex.from_product([case_studies, ["nominal", "ood", TIME_COL]])
    category_and_rows = [_row(row) for row in APPROACHES]
    row_index = pd.MultiIndex.from_tuples(category_and_rows, names=["category", "approach"])
    df = pd.DataFrame(columns=col_idx, index=row_index)

    for case_study in case_studies:
        for ds in ["nominal", "ood"]:
            apfds = load_apfd_values(case_study, ds)
            for category, approach in category_and_rows:
                if approach in apfds and len(apfds[approach]) > 0:
                    df.loc[(category, approach), (case_study, ds)] = np.mean(
                        list(apfds[approach].values())
                    )
                else:
                    df.loc[(category, approach), (case_study, ds)] = "n.a."
    return df


def _plot_latex_table(pd_df: pd.DataFrame):
    """Emit the paper-subset latex table."""
    pd_df = pd_df.iloc[pd_df.index.get_level_values("approach").isin(PAPER_APPROACHES)]
    pd_df = pd_df.rename(mapper=human_appraoch_name, axis="index")
    try:
        latex = pd_df.to_latex(
            multicolumn_format="c",
            multirow=True,
            column_format="llcccccccccccc",
            float_format="{:.2%}".format,
        )
    except Exception as e:  # latex rendering is non-essential
        warnings.warn(f"latex table rendering failed: {e}")
        return
    latex = vertical_categories(latex)
    latex = latex.replace("category", "", 1)
    with open(os.path.join(subdir("results"), "apfd_paper_table.tex"), "w") as f:
        f.write(latex)


def _add_reported_times(df: pd.DataFrame, partial_times: Dict):
    """Fill the per-case-study time columns: total = setup + 2*(pred + quant)
    (+ 2*cam for -cam rows), averaged over the first 10 runs."""
    if not partial_times:
        return
    assert int(max(k[2] for k in partial_times.keys())) <= 9, "Should only consider first 10 runs"

    tips = set((k[3], k[4]) for k in partial_times.keys())
    case_studies = set(k[0] for k in partial_times.keys())
    for cs in case_studies:
        for tc, tn in tips:

            def _match_k(k):
                return k[0] == cs and k[3] == tc and k[4] == tn

            matching = {k: v for k, v in partial_times.items() if _match_k(k)}
            if not matching:
                continue
            # Pad time records to 4 entries (uncertainty metrics have no cam).
            vals = [list(v) + [0.0] * (4 - len(v)) for v in matching.values()]
            avg_setup = mean(v[0] for v in vals)
            avg_pred = mean(v[1] for v in vals)
            avg_quant = mean(v[2] for v in vals)
            avg_cam = mean(v[3] for v in vals)

            row = _times_naming_to_table_row(tc, tn)
            if row[0] is None:
                continue

            def _format_time(t):
                return f"{round(t)}s"

            non_cam_time = avg_setup + 2 * (avg_pred + avg_quant)
            if (cs, TIME_COL) in df.columns and row in df.index:
                df.loc[row, (cs, TIME_COL)] = _format_time(non_cam_time)
            if row[0] in ("surprise", "neuron coverage"):
                cam_row = row[0], f"{row[1]}-cam"
                if (cs, TIME_COL) in df.columns and cam_row in df.index:
                    df.loc[cam_row, (cs, TIME_COL)] = _format_time(
                        non_cam_time + 2 * avg_cam
                    )


def _times_naming_to_table_row(tip_type: str, param: str):
    tip_type = "softmax" if tip_type == "SM" else tip_type
    tip_type = "softmax_entropy" if tip_type == "SE" else tip_type
    tip_type = "pcs" if tip_type == "PCS" else tip_type
    tip_type = "deep_gini" if tip_type == "DeepGini" else tip_type
    if param != "":
        tip_type = f"{tip_type}_{param}"
    return _row(tip_type)


def run(case_studies: List[str] = ("mnist", "fmnist", "cifar10", "imdb")):
    """Generate results/apfds.csv and the latex table."""
    df = _get_as_df(list(case_studies))
    _add_reported_times(df, times_collector.load_times())
    df.to_csv(os.path.join(subdir("results"), "apfds.csv"))
    _plot_latex_table(df)
    return df


if __name__ == "__main__":
    run()

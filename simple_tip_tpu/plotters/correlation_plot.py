"""Wilcoxon p-value + Vargha-Delaney A12 effect-size statistics and dual
heatmap plots (paper Figs 3/4).

Reference: src/plotters/correlation_plot.py. The reference uses pingouin for
the Wilcoxon test; here it is scipy.stats.wilcoxon (identical two-sided
p-values). Bonferroni correction multiplies by C(num_approaches, 2).
"""

from math import comb
from typing import Dict, List, Union

import numpy as np
from scipy import stats

from simple_tip_tpu.config import subdir
from simple_tip_tpu.plotters.utils import human_approach_names

SAMPLE_KEY = Union[int, str]
APPROACH_KEY = Union[int, str]


def paired_vargha_delaney_a12(x: List[float], y: List[float], paired: bool = True) -> float:
    """Scaled paired A12 effect size: 2*|A12 - 0.5|
    (reference: correlation_plot.py:22-32)."""
    assert len(x) == len(y)
    x, y = np.array(x), np.array(y)
    if not paired:
        y = np.expand_dims(y, axis=1)
    same = np.sum(x == y)
    bigger = np.sum(x > y)
    a12 = (bigger + 0.5 * same) / (x == y).size
    return 2 * abs(a12 - 0.5)


def wilcoxon_p(x: List[float], y: List[float]) -> float:
    """Two-sided Wilcoxon signed-rank p-value."""
    x, y = np.asarray(x), np.asarray(y)
    try:
        return float(stats.wilcoxon(x, y, alternative="two-sided").pvalue)
    except ValueError:
        # all-zero differences
        return np.nan


class WilcoxonCorrelationPlot:
    """Pairwise Wilcoxon/A12 grid over pooled per-run measurements."""

    def __init__(self, approaches: List[str], num_tested_approaches: int):
        self.p_value_calculator = wilcoxon_p
        self.effect_size_calculator = paired_vargha_delaney_a12
        self.error_correction = lambda p_values: p_values * comb(num_tested_approaches, 2)
        assert len(set(approaches)) == len(approaches), "Approach names must be unique"
        self.approaches = approaches
        self.measurements: Dict[APPROACH_KEY, Dict[SAMPLE_KEY, float]] = {
            i: dict() for i in approaches
        }

    def add_measurement(self, approach, sample, value, unique: bool = True):
        """Register an observation for statistical comparison."""
        if approach not in self.approaches:
            return
        if unique:
            assert sample not in self.measurements[approach], (
                f"Sample key name must be unique for a given array. "
                f"Duplicate: {sample}. Pass `unique=False` to overwrite value."
            )
        self.measurements[approach][sample] = value

    def calc_values(self):
        """Compute the upper-triangle p-value / effect-size / n grids."""
        grid_size = (len(self.approaches), len(self.approaches))
        res = {
            "p": np.full(grid_size, 10000, dtype=np.float64),
            "e": np.full(grid_size, -10000, dtype=np.float64),
            "num_samples": np.full(grid_size, -1000, dtype=np.int64),
        }
        for i in range(len(self.approaches) - 1):
            for j in range(i + 1, len(self.approaches)):
                _, vals_i, vals_j = self._common(i, j)
                res["num_samples"][i, j] = len(vals_i)
                if len(vals_i) == 0 or vals_j == vals_i:
                    res["p"][i, j] = np.nan
                    res["e"][i, j] = np.nan
                else:
                    res["p"][i, j] = self.p_value_calculator(vals_i, vals_j)
                    res["e"][i, j] = self.effect_size_calculator(vals_i, vals_j)
        return res

    def _common(self, i: int, j: int):
        keys_1 = self.measurements[self.approaches[i]].keys()
        keys_2 = set(self.measurements[self.approaches[j]].keys())
        keys = sorted(set(keys_1).intersection(keys_2))
        values_1 = [self.measurements[self.approaches[i]][k] for k in keys]
        values_2 = [self.measurements[self.approaches[j]][k] for k in keys]
        return keys, values_1, values_2

    def plot_heatmap(self, exp: str, cs: str, ds: str):
        """Render the dual-triangle heatmap (effect sizes above, p-values below)."""
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        import seaborn as sns
        from matplotlib.colors import LogNorm

        values = self.calc_values()
        finite_p = values["p"][np.isfinite(values["p"]) & (values["p"] < 10000)]
        if finite_p.size == 0 or (finite_p <= 0).all():
            # Too little data for any valid p-value (e.g. a single-run smoke
            # pipeline): LogNorm would reject its vmin/vmax. CSVs are already
            # written by the callers; skip only the figure.
            import warnings

            warnings.warn(
                f"no finite positive p-values for {exp} ({cs}, {ds}) — "
                "skipping heatmap figure"
            )
            return
        matrix_0 = np.triu(values["e"].transpose())
        error_corrected_p = self.error_correction(values["p"])
        matrix_1 = np.tril(error_corrected_p)

        ax_1 = sns.heatmap(
            values["e"].transpose(),
            annot=False,
            mask=matrix_0,
            cmap="inferno",
            square=True,
            cbar_kws=dict(
                shrink=0.6,
                pad=0.05,
                use_gridspec=True,
                location="bottom",
                label="Effect size",
            ),
        )
        ax_2 = sns.heatmap(
            values["p"],
            annot=False,
            mask=matrix_1,
            cmap="viridis",
            vmax=0.1,
            square=True,
            norm=LogNorm(),
            cbar_kws=dict(use_gridspec=True, location="right", label="P-Value"),
        )
        plt.tick_params(
            axis="both",
            which="major",
            labelsize=10,
            labelbottom=False,
            bottom=False,
            top=True,
            labeltop=True,
        )
        human_labels = human_approach_names(self.approaches)
        ax_2.set_xticks(
            np.arange(len(self.approaches)) + 0.5, labels=human_labels, rotation=45, ha="left"
        )
        ax_2.set_yticks(np.arange(len(self.approaches)) + 0.5, labels=human_labels, rotation=0)
        ax_1.hlines([3, 6], *ax_1.get_xlim(), color="white")
        ax_1.vlines([3, 6], *ax_1.get_ylim(), color="white")
        plt.axline((9, 9), (0, 0), linewidth=2, color="black")

        import os

        if cs != "all" or ds != "both":
            out = os.path.join(subdir("results"), f"corr-{exp}-{cs}-{ds}.png")
        else:
            out = os.path.join(subdir("results"), f"corr-{exp}.png")
        plt.savefig(out, bbox_inches="tight")
        plt.close()

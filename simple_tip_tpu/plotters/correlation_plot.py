"""Pairwise Wilcoxon / Vargha-Delaney statistics and the dual-triangle
heatmap figures (paper Figs 3/4).

Artifact + figure contract (what the published outputs pin down): grids are
[approach x approach] with only the upper triangle tested; untested cells
hold the sentinels the CSV writers blank out (10000 for p, -10000 for
effect, -1000 for n); the figure shows effect sizes (inferno) above the
diagonal and Bonferroni-corrected p-values (viridis, log scale, capped at
0.1) below it, with white separators between the three approach families.
The reference computes its p-values with pingouin
(src/plotters/correlation_plot.py uses ``pg.wilcoxon``); here
``scipy.stats.wilcoxon`` produces the identical two-sided p
(tests/test_plotters.py checks them equal), and the all-tied pair that
makes the test undefined is NaN-guarded before scipy ever sees it.
"""

from itertools import combinations
from math import comb
from typing import Dict, Hashable, List, Sequence, Tuple

import numpy as np
from scipy import stats

from simple_tip_tpu.config import subdir

# Sentinels for never-tested grid cells — blanked by the CSV writers, so
# they are part of the results-artifact contract.
P_UNTESTED = 10_000.0
E_UNTESTED = -10_000.0
N_UNTESTED = -1_000


def paired_vargha_delaney_a12(x: List[float], y: List[float], paired: bool = True) -> float:
    """Scaled paired A12 effect size: ``2 * |A12 - 0.5|`` ∈ [0, 1]."""
    assert len(x) == len(y)
    x, y = np.array(x), np.array(y)
    if not paired:
        y = np.expand_dims(y, axis=1)
    wins = np.sum(x > y) + 0.5 * np.sum(x == y)
    return 2 * abs(wins / (x == y).size - 0.5)


def wilcoxon_p(x: List[float], y: List[float]) -> float:
    """Two-sided Wilcoxon signed-rank p-value (NaN when all diffs are 0)."""
    try:
        return float(stats.wilcoxon(np.asarray(x), np.asarray(y), alternative="two-sided").pvalue)
    except ValueError:
        return np.nan


class WilcoxonCorrelationPlot:
    """Pairwise significance grid over pooled per-run measurements.

    Feed it ``(approach, sample_id, value)`` observations; it compares every
    approach pair on their COMMON sample ids (a pair with disjoint runs is
    NaN, not an error), Bonferroni-corrects against the full experiment's
    C(num_tested_approaches, 2) comparisons, and renders/exports the grids.
    """

    def __init__(self, approaches: Sequence[str], num_tested_approaches: int):
        assert len(set(approaches)) == len(approaches), "Approach names must be unique"
        self.approaches = list(approaches)
        self.bonferroni_factor = comb(num_tested_approaches, 2)
        self._samples: Dict[str, Dict[Hashable, float]] = {
            a: {} for a in self.approaches
        }

    def add_measurement(self, approach, sample, value, unique: bool = True) -> None:
        """Register one observation; approaches outside the grid are ignored
        (callers iterate the full 39-approach pool even for subset grids)."""
        pool = self._samples.get(approach)
        if pool is None:
            return
        if unique and sample in pool:
            raise AssertionError(
                f"Sample key name must be unique for a given array. Duplicate: "
                f"{sample}. Pass `unique=False` to overwrite value."
            )
        pool[sample] = value

    @property
    def measurements(self) -> Dict[str, Dict[Hashable, float]]:
        """approach -> {sample id -> value}, as collected so far."""
        return self._samples

    def _paired(self, a: str, b: str) -> Tuple[List[float], List[float]]:
        """Values of both approaches on their shared sample ids (sorted for
        determinism — the reference iterates an unordered set)."""
        pool_a, pool_b = self._samples[a], self._samples[b]
        shared = sorted(pool_a.keys() & pool_b.keys())
        return [pool_a[k] for k in shared], [pool_b[k] for k in shared]

    def calc_values(self) -> Dict[str, np.ndarray]:
        """Upper-triangle p / effect-size / sample-count grids."""
        n = len(self.approaches)
        grids = {
            "p": np.full((n, n), P_UNTESTED, dtype=np.float64),
            "e": np.full((n, n), E_UNTESTED, dtype=np.float64),
            "num_samples": np.full((n, n), N_UNTESTED, dtype=np.int64),
        }
        for i, j in combinations(range(n), 2):
            vals_i, vals_j = self._paired(self.approaches[i], self.approaches[j])
            grids["num_samples"][i, j] = len(vals_i)
            if not vals_i or vals_i == vals_j:
                # no shared runs, or identical value lists (zero diffs make
                # the signed-rank test undefined)
                grids["p"][i, j] = grids["e"][i, j] = np.nan
            else:
                grids["p"][i, j] = wilcoxon_p(vals_i, vals_j)
                grids["e"][i, j] = paired_vargha_delaney_a12(vals_i, vals_j)
        return grids

    # -- figure --------------------------------------------------------------

    def plot_heatmap(self, exp: str, cs: str, ds: str) -> None:
        """Render the dual-triangle heatmap to ``results/corr-...png``."""
        import os

        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
        import seaborn as sns
        from matplotlib.colors import LogNorm

        grids = self.calc_values()
        tested_p = grids["p"][np.isfinite(grids["p"]) & (grids["p"] < P_UNTESTED)]
        if tested_p.size == 0 or (tested_p <= 0).all():
            # Too little data for any positive p-value (e.g. a single-run
            # smoke pipeline): LogNorm would reject its vmin/vmax. The CSV
            # grids are written by the callers regardless; only the figure
            # is skipped, loudly.
            import warnings

            warnings.warn(
                f"no finite positive p-values for {exp} ({cs}, {ds}) — "
                "skipping heatmap figure"
            )
            return

        # Upper triangle: effect sizes (transposed so [i, j] renders above
        # the diagonal). Lower: Bonferroni-corrected p-values, log-scaled.
        effect_ax = sns.heatmap(
            grids["e"].transpose(),
            annot=False,
            mask=np.triu(grids["e"].transpose()),
            cmap="inferno",
            square=True,
            cbar_kws=dict(
                shrink=0.6,
                pad=0.05,
                use_gridspec=True,
                location="bottom",
                label="Effect size",
            ),
        )
        p_ax = sns.heatmap(
            grids["p"],
            annot=False,
            mask=np.tril(grids["p"] * self.bonferroni_factor),
            cmap="viridis",
            vmax=0.1,
            square=True,
            norm=LogNorm(),
            cbar_kws=dict(use_gridspec=True, location="right", label="P-Value"),
        )
        plt.tick_params(
            axis="both",
            which="major",
            labelsize=10,
            labelbottom=False,
            bottom=False,
            top=True,
            labeltop=True,
        )
        from simple_tip_tpu.plotters.utils import human_approach_names

        labels = human_approach_names(self.approaches)
        ticks = np.arange(len(self.approaches)) + 0.5
        p_ax.set_xticks(ticks, labels=labels, rotation=45, ha="left")
        p_ax.set_yticks(ticks, labels=labels, rotation=0)
        # White separators between the three approach families; black
        # diagonal dividing the two triangles.
        effect_ax.hlines([3, 6], *effect_ax.get_xlim(), color="white")
        effect_ax.vlines([3, 6], *effect_ax.get_ylim(), color="white")
        plt.axline((9, 9), (0, 0), linewidth=2, color="black")

        stem = f"corr-{exp}" if (cs, ds) == ("all", "both") else f"corr-{exp}-{cs}-{ds}"
        plt.savefig(os.path.join(subdir("results"), f"{stem}.png"), bbox_inches="tight")
        plt.close()


def pooled_statistics(
    exp: str,
    pooled: Dict[str, Dict[Hashable, float]],
    subset_approaches: Sequence[str],
    full_approaches: Sequence[str],
    csv_prefix: str,
    plot: bool = True,
):
    """Shared tail of both correlation evaluations: render the paper-subset
    heatmap, compute the full-grid statistics, export them as
    ``results/{csv_prefix}_{p,eff}.csv`` (sentinels blanked), and return the
    two dataframes. (The reference duplicates this block across its two
    eval_*_correlation modules.)"""
    import os

    import pandas as pd

    from simple_tip_tpu.plotters.utils import human_approach_names

    def _filled(approaches: Sequence[str]) -> "WilcoxonCorrelationPlot":
        grid = WilcoxonCorrelationPlot(
            approaches=list(approaches), num_tested_approaches=39
        )
        for approach, samples in pooled.items():
            for sample, value in samples.items():
                grid.add_measurement(approach, sample, value)
        return grid

    if plot:
        _filled(subset_approaches).plot_heatmap(exp, "all", "both")

    grids = _filled(full_approaches).calc_values()
    labels = human_approach_names(list(full_approaches))
    frames = []
    for key, sentinel, suffix in (("p", P_UNTESTED, "p"), ("e", E_UNTESTED, "eff")):
        frame = pd.DataFrame(data=grids[key], index=labels, columns=labels)
        frame = frame.replace(sentinel, "")
        frame.to_csv(os.path.join(subdir("results"), f"{csv_prefix}_{suffix}.csv"))
        frames.append(frame)
    return tuple(frames)

"""Result aggregation: APFD tables (Table 1), active-learning tables (Table 2),
and Wilcoxon/Vargha-Delaney statistics (Figs 3/4), reading the filesystem
artifact bus. CPU-only, pandas-based — mirrors the reference's src/plotters/.
"""

"""Active-learning statistics (paper Fig 4): pooled Wilcoxon p-values and
A12 effect sizes over the (dataset, future)-split AL accuracies, emitting
the heatmap and ``results/active_correlation_{p,eff}.csv`` (artifact
contract: src/plotters/eval_active_correlation.py)."""

import logging
from typing import Dict

from simple_tip_tpu.plotters import utils
from simple_tip_tpu.plotters.correlation_plot import pooled_statistics
from simple_tip_tpu.plotters.eval_active_learning_table import (
    load_arrays_active_learning,
)
from simple_tip_tpu.plotters.utils import identify_incomplete_values, named_tuples

logger = logging.getLogger(__name__)

_EXTENDED = [*utils.APPROACHES, "original", "random"]


def _future_split_accuracies(case_study: str, dataset: str) -> Dict[str, Dict[int, float]]:
    """Per-(approach, run) accuracy on the (dataset, future) split — the
    only split the significance analysis considers."""
    raw = load_arrays_active_learning(case_study, dataset, by_id=True)
    return {
        approach: {
            run: accs[(dataset, "future")]
            for run, accs in raw.get(approach, {}).items()
            if run < utils.NUM_RUNS
        }
        for approach in _EXTENDED
    }


def _warn_missing(cs: str, ds: str, values) -> None:
    missing = identify_incomplete_values(values, has_dropout=cs != "cifar10")
    if missing:
        logger.warning("Missing values %s - %s: %s", cs, ds, missing)


def run(case_studies=("mnist", "fmnist", "cifar10", "imdb"), plot: bool = True):
    """Pool future-split AL accuracies over every (case study, dataset),
    then delegate to the shared heatmap/CSV tail."""
    pooled: Dict[str, Dict[str, float]] = {a: {} for a in _EXTENDED}
    for cs in case_studies:
        for ds in ("nominal", "ood"):
            values = _future_split_accuracies(cs, ds)
            _warn_missing(cs, ds, values)
            named = named_tuples(cs, values, None, _EXTENDED)
            for approach, samples in named.items():
                # Reference pooling semantics: ood replaces nominal for the
                # shared {cs}_{run} sample ids (see eval_apfd_correlation).
                pooled[approach].update(samples)

    return pooled_statistics(
        "active",
        pooled,
        subset_approaches=utils.CORRELATION_PLOT_APPROACHES,
        full_approaches=utils.APPROACHES,
        csv_prefix="active_correlation",
        plot=plot,
    )


if __name__ == "__main__":
    run()

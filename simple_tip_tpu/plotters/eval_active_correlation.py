"""Active-learning statistics (paper Fig 4): pooled Wilcoxon p-values and A12
effect sizes over the (dataset, future)-split accuracies, emitting the heatmap
and ``results/active_correlation_{p,eff}.csv``
(reference: src/plotters/eval_active_correlation.py).
"""

import os
from typing import Dict, List

import pandas as pd

from simple_tip_tpu.config import subdir
from simple_tip_tpu.plotters import utils
from simple_tip_tpu.plotters.correlation_plot import WilcoxonCorrelationPlot
from simple_tip_tpu.plotters.eval_active_learning_table import load_arrays_active_learning
from simple_tip_tpu.plotters.utils import identify_incomplete_values, named_tuples


def _load(case_study: str, dataset: str) -> Dict[str, Dict[int, float]]:
    res: Dict[str, Dict[int, float]] = {approach: dict() for approach in utils.APPROACHES}
    res["original"] = dict()
    res["random"] = dict()
    loaded = load_arrays_active_learning(case_study, dataset, by_id=True)
    for i in range(100):
        for approach in loaded:
            if i in loaded[approach]:
                # Significance is checked on the (dataset, future) split only.
                split_key = (dataset, "future")
                res[approach][i] = loaded[approach][i][split_key]
    return res


def _print_missing_values(cs, ds, values):
    missing = identify_incomplete_values(values, has_dropout=cs != "cifar10")
    if len(missing) > 0:
        print(f"Missing values {cs} - {ds}: {missing}")


def run(case_studies=("mnist", "fmnist", "cifar10", "imdb"), plot: bool = True):
    """Pool AL accuracies, plot the 9-approach heatmap, emit the full CSVs."""
    vals: List[Dict[str, Dict[str, float]]] = []
    for cs in case_studies:
        for ds in ["nominal", "ood"]:
            values = _load(cs, ds)
            _print_missing_values(cs, ds, values)
            approaches = utils.APPROACHES.copy()
            approaches.extend(["original", "random"])
            vals.append(named_tuples(cs, values, None, approaches=approaches))

    all_by_approach: Dict[str, Dict[str, float]] = dict()
    for named in vals:
        for approach, data in named.items():
            all_by_approach.setdefault(approach, dict()).update(data)

    if plot:
        heat = WilcoxonCorrelationPlot(
            approaches=utils.CORRELATION_PLOT_APPROACHES, num_tested_approaches=39
        )
        for approach, data in all_by_approach.items():
            for measurement, value in data.items():
                heat.add_measurement(approach, measurement, value)
        heat.plot_heatmap("active", "all", "both")

    full = WilcoxonCorrelationPlot(approaches=utils.APPROACHES, num_tested_approaches=39)
    for approach, data in all_by_approach.items():
        for measurement, value in data.items():
            full.add_measurement(approach, measurement, value)
    p_and_eff = full.calc_values()
    human = utils.human_approach_names(utils.APPROACHES)
    p_pd = pd.DataFrame(data=p_and_eff["p"], index=human, columns=human)
    p_pd = p_pd.replace(10000, "")
    p_pd.to_csv(os.path.join(subdir("results"), "active_correlation_p.csv"))
    e_pd = pd.DataFrame(data=p_and_eff["e"], index=human, columns=human)
    e_pd = e_pd.replace(-10000, "")
    e_pd.to_csv(os.path.join(subdir("results"), "active_correlation_eff.csv"))
    return p_pd, e_pd


if __name__ == "__main__":
    run()

"""Timing-artifact reader for the evaluation phase.

The prioritization engine drops one pickle per (case study, dataset,
model, approach) under ``<output>/times/``, holding the four-stage
wall-clock record ``[setup, pred, quant, cam]`` (same bus layout as the
reference, src/plotters/times_collector.py, which the times tables
consume). Filenames are underscore-delimited —
``{cs}_{ds}_{model}_{metric}[_{param}]`` — so approach names that
themselves contain underscores are collapsed to their display aliases
before splitting. Only the first ten model runs count toward the
published timing averages (reference behavior).
"""

import pickle
from pathlib import Path
from typing import Dict, Optional, Tuple

from simple_tip_tpu.config import output_folder

N_FIRST_MODELS_CONSIDERED = 10

# Underscore-bearing approach names -> display aliases, longest first so
# "softmax_entropy" never half-matches as "softmax".
_ALIASES = (
    ("softmax_entropy", "SE"),
    ("deep_gini", "DeepGini"),
    ("softmax", "SM"),
    ("pcs", "PCS"),
)

TimesKey = Tuple[str, str, str, str, str]


def _parse_name(name: str) -> Optional[TimesKey]:
    """``{cs}_{ds}_{model}_{metric}[_{param}]`` -> 5-tuple key, or None."""
    for needle, alias in _ALIASES:
        name = name.replace(needle, alias)
    fields = name.split("_")
    if len(fields) == 4:
        fields.append("")  # param-less approaches (uncertainty family)
    if len(fields) != 5:
        return None
    return tuple(fields)


def load_times() -> Dict[TimesKey, list]:
    """All timing records on the bus, keyed (cs, ds, model, metric, param)."""
    times: Dict[TimesKey, list] = {}
    folder = Path(output_folder()) / "times"
    if not folder.is_dir():
        return times
    for path in sorted(p for p in folder.rglob("*") if p.is_file()):
        key = _parse_name(path.name)
        if key is None or int(key[2]) >= N_FIRST_MODELS_CONSIDERED:
            continue
        times[key] = pickle.loads(path.read_bytes())
    return times

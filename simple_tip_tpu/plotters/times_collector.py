"""Loads the per-(cs, ds, model, metric) timing pickles from the bus
(reference: src/plotters/times_collector.py): record = [setup, pred, quant,
cam], first 10 models only."""

import os
import pickle

from simple_tip_tpu.config import output_folder

N_FIRST_MODELS_CONSIDERED = 10


def load_times():
    """Load all timing records keyed by (cs, dataset, model, metric, param)."""
    times = dict()
    folder = os.path.join(output_folder(), "times")
    for root, dirs, files in os.walk(folder):
        for file in files:
            file_san = (
                file.replace("softmax_entropy", "SE")
                .replace("pcs", "PCS")
                .replace("deep_gini", "DeepGini")
                .replace("softmax", "SM")
            )
            split = file_san.split("_")
            if len(split) == 5:
                case_study, dataset, model_id, metric, param = split
            else:
                case_study, dataset, model_id, metric = split
                param = ""
            if int(model_id) >= N_FIRST_MODELS_CONSIDERED:
                continue
            with open(os.path.join(root, file), "rb") as f:
                times[(case_study, dataset, model_id, metric, param)] = pickle.load(f)
    return times

"""Shared plotter utilities: the canonical approach lists, category mapping and
artifact-bus loaders (reference: src/plotters/utils.py)."""

import logging
import os
import pickle
import re
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from simple_tip_tpu.config import output_folder

NUM_RUNS = 100

VERTI_DEF = (
    "\\newcommand{\\verti}[1]{\\begin{tabular}{@{}c@{}}"
    "\\rotatebox[origin=c]{90}{\\centering #1}\\end{tabular}}"
)

# All 39 approaches tested in the experiments (load-bearing canonical order).
APPROACHES = [
    "NAC_0.75-cam",
    "NAC_0.75",
    "NAC_0-cam",
    "NAC_0",
    "NBC_0.5-cam",
    "NBC_0.5",
    "NBC_0-cam",
    "NBC_0",
    "NBC_1-cam",
    "NBC_1",
    "SNAC_0.5-cam",
    "SNAC_0.5",
    "SNAC_0-cam",
    "SNAC_0",
    "SNAC_1-cam",
    "SNAC_1",
    "TKNC_1-cam",
    "TKNC_1",
    "TKNC_2-cam",
    "TKNC_2",
    "TKNC_3-cam",
    "TKNC_3",
    "KMNC_2-cam",
    "KMNC_2",
    "dsa-cam",
    "dsa",
    "pc-lsa-cam",
    "pc-lsa",
    "pc-mdsa-cam",
    "pc-mdsa",
    "pc-mlsa-cam",
    "pc-mlsa",
    "pc-mmdsa-cam",
    "pc-mmdsa",
    "deep_gini",
    "softmax",
    "pcs",
    "softmax_entropy",
    "VR",
]

# The subset shown in the paper tables.
PAPER_APPROACHES = [
    "NAC_0.75-cam",
    "NAC_0.75",
    "NBC_0-cam",
    "NBC_0",
    "SNAC_0-cam",
    "SNAC_0",
    "TKNC_1-cam",
    "KMNC_2",
    "dsa",
    "pc-lsa",
    "pc-mdsa",
    "pc-mlsa",
    "pc-mmdsa",
    "deep_gini",
    "softmax",
    "pcs",
    "softmax_entropy",
    "VR",
]

# The 9-approach subset used in the correlation plots.
CORRELATION_PLOT_APPROACHES = [
    "SNAC_0",
    "SNAC_0-cam",
    "NBC_0-cam",
    "dsa",
    "pc-mdsa",
    "pc-mlsa",
    "deep_gini",
    "softmax",
    "softmax_entropy",
]


def human_appraoch_name(approach: str) -> str:
    """Internal approach name -> paper name. (Typo kept for reference parity.)"""
    if approach == "softmax_entropy":
        return "Entropy"
    elif approach == "VR":
        return "MC-Dropout"
    elif approach == "softmax":
        return "Vanilla SM"
    elif approach == "deep_gini":
        return "DeepGini"
    elif approach in ["uncertainty", "surprise", "neuron coverage", "baseline"]:
        return approach
    else:
        return approach.replace("_", "-").upper()


def human_approach_names(approaches: List[str]) -> List[str]:
    """Internal approach names -> paper names."""
    return [human_appraoch_name(a) for a in approaches]


def approach_name(approach: str, param: str = "", cam: bool = False) -> str:
    """Compose an approach name with parameter and optional -cam suffix."""
    res = approach
    if param:
        res += f"_{param}"
    if cam:
        res += "-cam"
    return res


def _row(approach: str) -> Tuple[str, str]:
    return category(approach), approach


def category(approach: str) -> Optional[str]:
    """TIP category of an approach name."""
    if approach in ["deep_gini", "softmax", "pcs", "softmax_entropy", "VR"]:
        return "uncertainty"
    if approach in [
        "dsa-cam",
        "dsa",
        "pc-lsa-cam",
        "pc-lsa",
        "pc-mdsa-cam",
        "pc-mdsa",
        "pc-mlsa-cam",
        "pc-mlsa",
        "pc-mmdsa-cam",
        "pc-mmdsa",
    ]:
        return "surprise"
    if approach in ["original", "random"]:
        return "baseline"
    if any(approach.startswith(nc) for nc in ["NAC", "NBC", "SNAC", "TKNC", "KMNC"]):
        return "neuron coverage"
    return None


def vertical_categories(latex: str) -> str:
    """Rotate the category cells in a latex table."""
    latex = VERTI_DEF + latex
    for cat in ["uncertainty", "surprise", "baseline", "neuron coverage"]:
        latex = latex.replace(cat, "\\verti{" + cat + "}", 1)
    return latex


def load_all_for_regex(research_question: str, regex: re.Pattern) -> Tuple[List, List]:
    """Load all artifacts in a bus subfolder whose filename matches the regex."""
    file_contents = []
    matches = []
    folder = os.path.join(output_folder(), research_question)
    for root, dirs, files in os.walk(folder):
        for file in files:
            if regex.match(file, pos=0):
                matches.append(file)
                if file.endswith(".npy"):
                    file_contents.append(np.load(os.path.join(root, file)))
                else:
                    with open(os.path.join(root, file), "rb") as f:
                        file_contents.append(pickle.load(f))
    return file_contents, matches


def identify_incomplete_values(
    data: Dict[str, Dict[int, float]], has_dropout: bool
) -> Set[int]:
    """Indices of runs with incomplete artifacts (sanity check)."""
    missing_or_incomplete_runs = set()
    for approach, runs in data.items():
        for i in range(NUM_RUNS):
            if i not in runs and (approach != "VR" or has_dropout):
                missing_or_incomplete_runs.add(i)
    return missing_or_incomplete_runs


def named_tuples(
    cs_data_id: str,
    data: Dict[str, Dict[int, float]],
    collection: Optional[Dict[str, Dict[str, float]]],
    approaches: List[str],
) -> Dict[str, Dict[str, float]]:
    """Merge per-(cs,ds) run values into a pooled collection keyed by
    '{cs_ds}_{run}' sample ids (for the pooled statistics)."""
    if collection is None:
        collection = {approach: dict() for approach in approaches}
    else:
        for approach in approaches:
            assert approach in collection.keys()
    for approach, runs in data.items():
        if approach not in collection:
            continue
        for run_id, value in runs.items():
            unique_id = f"{cs_data_id}_{run_id}"
            if unique_id in collection[approach]:
                logging.warning("%s: Run %s already in collection", cs_data_id, unique_id)
            else:
                collection[approach][unique_id] = value
    return collection

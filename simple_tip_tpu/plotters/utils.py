"""Shared plotter vocabulary and artifact-bus loaders.

Holds the three canonical approach lists (all 39 tested approaches, the
paper-table subset, the correlation-plot subset) and the name/category
mapping the published tables use. These lists and the filename contract
are the SPEC this framework reproduces (reference: src/plotters/utils.py
defines the same canon); the machinery around them — loaders, run
bookkeeping, latex helpers — is this repo's own.
"""

import copy
import logging
import pickle
import re
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from simple_tip_tpu.config import output_folder

logger = logging.getLogger(__name__)

NUM_RUNS = 100

VERTI_DEF = (
    "\\newcommand{\\verti}[1]{\\begin{tabular}{@{}c@{}}"
    "\\rotatebox[origin=c]{90}{\\centering #1}\\end{tabular}}"
)

# The experiment grid. Canonical approach-name order is load-bearing (it is
# the published tables' row order): every scored variant appears as its
# CAM-prioritized form first, then its plain top-k form; uncertainty
# quantifiers have no CAM form.
_NC_GRID = (
    ("NAC", "0.75"),
    ("NAC", "0"),
    ("NBC", "0.5"),
    ("NBC", "0"),
    ("NBC", "1"),
    ("SNAC", "0.5"),
    ("SNAC", "0"),
    ("SNAC", "1"),
    ("TKNC", "1"),
    ("TKNC", "2"),
    ("TKNC", "3"),
    ("KMNC", "2"),
)
_SA_NAMES = ("dsa", "pc-lsa", "pc-mdsa", "pc-mlsa", "pc-mmdsa")
_UNCERTAINTY = ("deep_gini", "softmax", "pcs", "softmax_entropy", "VR")

# All 39 tested approaches, canonical order (verified verbatim against the
# reference canon by tests/test_plotters.py).
APPROACHES = [
    name
    for stem in [f"{m}_{p}" for m, p in _NC_GRID] + list(_SA_NAMES)
    for name in (f"{stem}-cam", stem)
] + list(_UNCERTAINTY)

# The subset shown in the paper tables.
PAPER_APPROACHES = [
    "NAC_0.75-cam",
    "NAC_0.75",
    "NBC_0-cam",
    "NBC_0",
    "SNAC_0-cam",
    "SNAC_0",
    "TKNC_1-cam",
    "KMNC_2",
    "dsa",
    "pc-lsa",
    "pc-mdsa",
    "pc-mlsa",
    "pc-mmdsa",
    "deep_gini",
    "softmax",
    "pcs",
    "softmax_entropy",
    "VR",
]

# The 9-approach subset used in the correlation plots.
CORRELATION_PLOT_APPROACHES = [
    "SNAC_0",
    "SNAC_0-cam",
    "NBC_0-cam",
    "dsa",
    "pc-mdsa",
    "pc-mlsa",
    "deep_gini",
    "softmax",
    "softmax_entropy",
]

# -- naming ------------------------------------------------------------------

_NC_PREFIXES = tuple(dict.fromkeys(m for m, _ in _NC_GRID))
_CATEGORIES = ("uncertainty", "surprise", "baseline", "neuron coverage")

# Paper display names that are not derivable by the uppercase rule.
_PAPER_NAME_OF = {
    "softmax_entropy": "Entropy",
    "VR": "MC-Dropout",
    "softmax": "Vanilla SM",
    "deep_gini": "DeepGini",
}


def human_approach_name(approach: str) -> str:
    """Internal approach name -> the name the paper tables print."""
    special = _PAPER_NAME_OF.get(approach)
    if special is not None:
        return special
    if approach in _CATEGORIES:
        return approach  # category header cells pass through untouched
    return approach.replace("_", "-").upper()


def human_approach_names(approaches: List[str]) -> List[str]:
    """Vectorized :func:`human_approach_name`."""
    return [human_approach_name(a) for a in approaches]


def approach_name(approach: str, param: str = "", cam: bool = False) -> str:
    """Compose the canonical ``{metric}[_{param}][-cam]`` approach name."""
    return approach + (f"_{param}" if param else "") + ("-cam" if cam else "")


def category(approach: str) -> Optional[str]:
    """TIP category of an approach name (None for unknown names)."""
    if approach in _UNCERTAINTY:
        return "uncertainty"
    base = approach[:-4] if approach.endswith("-cam") else approach
    if base in _SA_NAMES:
        return "surprise"
    if approach in ("original", "random"):
        return "baseline"
    if approach.startswith(_NC_PREFIXES):
        return "neuron coverage"
    return None


def _row(approach: str) -> Tuple[Optional[str], str]:
    """(category, approach) — the two-level row index of the paper tables."""
    return category(approach), approach


def vertical_categories(latex: str) -> str:
    """Rotate each category's (first) header cell in a latex table."""
    out = VERTI_DEF + latex
    for cat in _CATEGORIES:
        out = out.replace(cat, "\\verti{" + cat + "}", 1)
    return out


# -- artifact bus ------------------------------------------------------------


def _load_artifact(path: Path):
    if path.suffix == ".npy":
        return np.load(path)
    return pickle.loads(path.read_bytes())


# The two AL evaluations (table + correlation) each sweep the SAME
# (folder, per-approach regex) keys — at 100-run scale every sweep
# re-unpickles thousands of small accuracy dicts. A bounded FIFO memo lets
# the second and later sweeps skip the unpickling; an entry is invalidated
# by any (name, size, mtime_ns) change in its hit set, so a phase writing
# new artifacts mid-process is picked up on the next call. Every call
# returns a DEEP COPY of the memoized objects (round-4 advisor finding: a
# caller mutating a loaded dict must not corrupt later sweeps — pinned by
# tests/test_plotters.py); a deep copy of array-heavy artifacts is memcpys,
# still far cheaper than disk + unpickle. The bound comfortably covers
# one full sweep's distinct keys (approaches x splits) while capping RSS.
_ARTIFACT_MEMO: "dict" = {}
_ARTIFACT_MEMO_MAX = 256


def load_all_for_regex(research_question: str, regex: re.Pattern) -> Tuple[List, List]:
    """(contents, filenames) of every artifact in a bus subfolder whose name
    matches ``regex`` at position 0. Filenames sort deterministically (the
    reference inherits os.walk order)."""
    folder = Path(output_folder()) / research_question
    if not folder.is_dir():
        return [], []
    hits = sorted(
        p for p in folder.rglob("*") if p.is_file() and regex.match(p.name, pos=0)
    )
    stamp = tuple((p.name, s.st_size, s.st_mtime_ns) for p in hits for s in (p.stat(),))
    memo_key = (str(folder), regex.pattern, regex.flags)
    cached = _ARTIFACT_MEMO.get(memo_key)
    if cached is not None and cached[0] == stamp:
        contents, names = cached[1]
        return copy.deepcopy(contents), list(names)
    contents = [_load_artifact(p) for p in hits]
    names = [p.name for p in hits]
    while len(_ARTIFACT_MEMO) >= _ARTIFACT_MEMO_MAX:
        _ARTIFACT_MEMO.pop(next(iter(_ARTIFACT_MEMO)))
    _ARTIFACT_MEMO[memo_key] = (stamp, (contents, names))
    # the first caller gets a copy too: it must not be able to mutate the
    # objects the memo just captured
    return copy.deepcopy(contents), list(names)


def identify_incomplete_values(
    data: Dict[str, Dict[int, float]], has_dropout: bool
) -> Set[int]:
    """Run ids that lack at least one approach's artifact. A missing VR is
    expected (not incomplete) for dropout-free case studies."""
    return {
        run
        for approach, runs in data.items()
        if approach != "VR" or has_dropout
        for run in range(NUM_RUNS)
        if run not in runs
    }


def named_tuples(
    cs_data_id: str,
    data: Dict[str, Dict[int, float]],
    collection: Optional[Dict[str, Dict[str, float]]],
    approaches: List[str],
) -> Dict[str, Dict[str, float]]:
    """Pool per-(cs, ds) run values across case studies under globally unique
    ``{cs_ds}_{run}`` sample ids (input to the pooled statistics)."""
    if collection is None:
        collection = {approach: {} for approach in approaches}
    else:
        missing = [a for a in approaches if a not in collection]
        assert not missing, f"collection lacks approaches {missing}"
    for approach, runs in data.items():
        pooled = collection.get(approach)
        if pooled is None:
            continue
        for run_id, value in runs.items():
            sample_id = f"{cs_data_id}_{run_id}"
            if sample_id in pooled:
                logger.warning("%s: run %s already pooled", cs_data_id, sample_id)
            else:
                pooled[sample_id] = value
    return collection

"""APFD statistics (paper Fig 3): pooled Wilcoxon p-values and A12 effect
sizes across all (case study x dataset) APFD values, emitting the heatmap
and ``results/apfd_correlation_{p,eff}.csv`` (artifact contract:
src/plotters/eval_apfd_correlation.py)."""

import logging
from typing import Dict

from simple_tip_tpu.plotters import utils
from simple_tip_tpu.plotters.correlation_plot import pooled_statistics
from simple_tip_tpu.plotters.eval_apfd_table import load_apfd_values
from simple_tip_tpu.plotters.utils import identify_incomplete_values, named_tuples

logger = logging.getLogger(__name__)


def _warn_missing(cs: str, ds: str, values) -> None:
    missing = identify_incomplete_values(values, has_dropout=cs != "cifar10")
    if missing:
        logger.warning("Missing values %s - %s: %s", cs, ds, missing)


def run(case_studies=("mnist", "fmnist", "cifar10", "imdb"), plot: bool = True):
    """Pool APFD values over every (case study, dataset), then delegate to
    the shared heatmap/CSV tail."""
    pooled: Dict[str, Dict[str, float]] = {a: {} for a in utils.APPROACHES}
    for cs in case_studies:
        for ds in ("nominal", "ood"):
            values = load_apfd_values(cs, ds)
            _warn_missing(cs, ds, values)
            named = named_tuples(cs, values, None, utils.APPROACHES)
            for approach, samples in named.items():
                # dict.update, NOT uniqueness-checked insertion: sample ids
                # are {cs}_{run}, so the ood pass intentionally replaces the
                # nominal pass's value — the reference's pooling semantics
                # (its run() merges per-(cs,ds) collections with .update()).
                pooled[approach].update(samples)

    return pooled_statistics(
        "apfd",
        pooled,
        subset_approaches=utils.CORRELATION_PLOT_APPROACHES,
        full_approaches=utils.APPROACHES,
        csv_prefix="apfd_correlation",
        plot=plot,
    )


if __name__ == "__main__":
    run()

"""APFD statistics (paper Fig 3): pooled Wilcoxon p-values and A12 effect
sizes across all (case study x dataset) APFD values, emitting the heatmap and
``results/apfd_correlation_{p,eff}.csv``
(reference: src/plotters/eval_apfd_correlation.py).
"""

import os
from typing import Dict, List

import pandas as pd

from simple_tip_tpu.config import subdir
from simple_tip_tpu.plotters import utils
from simple_tip_tpu.plotters.correlation_plot import WilcoxonCorrelationPlot
from simple_tip_tpu.plotters.eval_apfd_table import load_apfd_values
from simple_tip_tpu.plotters.utils import identify_incomplete_values, named_tuples


def _print_missing_values(cs, ds, values):
    missing = identify_incomplete_values(values, has_dropout=cs != "cifar10")
    if len(missing) > 0:
        print(f"Missing values {cs} - {ds}: {missing}")


def run(case_studies=("mnist", "fmnist", "cifar10", "imdb"), plot: bool = True):
    """Pool APFD values, plot the 9-approach heatmap, emit the full CSVs."""
    vals: List[Dict[str, Dict[str, float]]] = []
    for cs in case_studies:
        for ds in ["nominal", "ood"]:
            values = load_apfd_values(cs, ds)
            _print_missing_values(cs, ds, values)
            vals.append(named_tuples(cs, values, None, utils.APPROACHES))

    all_by_approach: Dict[str, Dict[str, float]] = dict()
    for named in vals:
        for approach, data in named.items():
            all_by_approach.setdefault(approach, dict()).update(data)

    if plot:
        heat = WilcoxonCorrelationPlot(
            approaches=utils.CORRELATION_PLOT_APPROACHES, num_tested_approaches=39
        )
        for approach, data in all_by_approach.items():
            for measurement, value in data.items():
                heat.add_measurement(approach, measurement, value)
        heat.plot_heatmap("apfd", "all", "both")

    full = WilcoxonCorrelationPlot(approaches=utils.APPROACHES, num_tested_approaches=39)
    for approach, data in all_by_approach.items():
        for measurement, value in data.items():
            full.add_measurement(approach, measurement, value)
    p_and_eff = full.calc_values()
    human = utils.human_approach_names(utils.APPROACHES)
    p_pd = pd.DataFrame(data=p_and_eff["p"], index=human, columns=human)
    p_pd = p_pd.replace(10000, "")
    p_pd.to_csv(os.path.join(subdir("results"), "apfd_correlation_p.csv"))
    e_pd = pd.DataFrame(data=p_and_eff["e"], index=human, columns=human)
    e_pd = e_pd.replace(-10000, "")
    e_pd.to_csv(os.path.join(subdir("results"), "apfd_correlation_eff.csv"))
    return p_pd, e_pd


if __name__ == "__main__":
    run()

"""Ring attention: sequence/context-parallel attention over a device mesh.

The reference has no long-context machinery (max sequence length is 100,
SURVEY.md section 5), but this framework treats sequence parallelism as a
first-class capability: ``ring_attention`` computes exact (non-approximate)
attention with the sequence axis sharded across devices. Each device holds its
local Q/K/V block; K/V blocks rotate around the ring via ``jax.lax.ppermute``
while a numerically-stable streaming softmax (flash-attention style
max/normalizer/output accumulators) folds in one block per step. Communication
is neighbor-to-neighbor only, so it rides ICI on a TPU pod slice.

``ring_attention_sharded`` wraps the collective in ``shard_map`` over a mesh
axis; ``ring_self_attention_reference`` is the dense single-device oracle used
by the tests.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _block_update(carry, k_blk, v_blk, q, scale):
    """Fold one K/V block into the streaming-softmax accumulators.

    Accumulators and softmax state are f32 no matter the operand dtype:
    bf16 q/k/v keep both matmuls MXU-native (and halve the ring's ICI
    traffic), but a bf16 running normalizer would decay accuracy with every
    folded block."""
    o, m, l = carry  # [B,H,Tq,Dh], [B,H,Tq], [B,H,Tq] — all f32
    # scores: [B, H, Tq, Tkv]
    scores = (
        jnp.einsum(
            "bqhd,bkhd->bhqk", q, k_blk, preferred_element_type=jnp.float32
        )
        * scale
    )
    m_new = jnp.maximum(m, scores.max(axis=-1))
    correction = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    l_new = l * correction + p.sum(axis=-1)
    pv = jnp.einsum(
        "bhqk,bkhd->bhqd",
        p.astype(v_blk.dtype),
        v_blk,
        preferred_element_type=jnp.float32,
    )
    o_new = o * correction[..., None] + pv
    return o_new, m_new, l_new


def ring_attention(q, k, v, axis_name: str, n_dev: int):
    """Exact attention with K/V ring-rotated across ``axis_name``.

    Shapes (per device): q/k/v = [batch, seq_local, heads, head_dim].
    ``n_dev`` is the static size of the mesh axis.
    Returns [batch, seq_local, heads, head_dim].
    """
    scale = np.float32(1.0 / np.sqrt(q.shape[-1]))
    b, t_q, h, dh = q.shape

    # mark the fresh accumulators as device-varying over the ring axis so the
    # scan carry types line up (shard_map vma semantics).
    def _varying(x):
        if hasattr(jax.lax, "pcast"):
            return jax.lax.pcast(x, axis_name, to="varying")
        return jax.lax.pvary(x, axis_name)

    o = _varying(jnp.zeros((b, h, t_q, dh), jnp.float32))
    m = _varying(jnp.full((b, h, t_q), -jnp.inf, jnp.float32))
    l = _varying(jnp.zeros((b, h, t_q), jnp.float32))
    perm = [(j, (j + 1) % n_dev) for j in range(n_dev)]

    def step(i, carry):
        o, m, l, k_blk, v_blk = carry
        o, m, l = _block_update((o, m, l), k_blk, v_blk, q, scale)
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return o, m, l, k_blk, v_blk

    o, m, l, _, _ = jax.lax.fori_loop(0, n_dev, step, (o, m, l, k, v))
    out = o / l[..., None]
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def dense_attention_f32_softmax(q, k, v):
    """Dense attention core, [batch, seq, heads, head_dim] in and out, with
    the shared precision contract of all attention cores here: softmax and
    accumulation in f32 no matter the operand dtype (bf16 operands change
    matmul precision only), output in ``q.dtype``. Used as the single-device
    oracle and as ulysses' dense local core."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    weights = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd",
        weights.astype(v.dtype),
        v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def ring_self_attention_reference(q, k, v):
    """Dense single-device attention oracle (same layout as ring_attention)."""
    return dense_attention_f32_softmax(q, k, v)


def check_ring_divisibility(seq_len: int, n_dev: int) -> None:
    """Reject sequence lengths that don't shard evenly: JAX would silently
    pad the shards, and padded K/V rows (all-zero keys, score 0) leak weight
    into the streaming softmax — a subtle numerical corruption, observed as
    ~1e-3 output error instead of an exception."""
    if seq_len % n_dev != 0:
        raise ValueError(
            f"ring attention requires the sequence length ({seq_len}) to be "
            f"divisible by the sequence-parallel mesh size ({n_dev}); pad the "
            f"sequence or choose a different mesh"
        )


@functools.lru_cache(maxsize=32)
def _sharded_attention_fn(kernel, mesh: Mesh, axis: str, kernel_kw: tuple):
    """Jitted shard_map program per (kernel, mesh, axis, kernel kwargs).

    The cache key is the RAW kernel function plus hashable kwargs — a
    ``functools.partial`` built by the caller would hash by object identity
    and never hit, so the partial is applied in here instead. Without this
    cache every ``sharded_attention`` call constructed (and retraced) a
    fresh jitted callable — the retrace-risk pattern tiplint now flags.
    """
    spec = P(None, axis, None, None)
    kernel_fn = functools.partial(kernel, **dict(kernel_kw)) if kernel_kw else kernel
    fn = jax.shard_map(
        kernel_fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )
    return jax.jit(fn), NamedSharding(mesh, spec)


def sharded_attention(q, k, v, mesh: Mesh, axis: str, kernel, **kernel_kw):
    """Shared scaffolding for the sequence-parallel attention wrappers:
    shard q/k/v over ``axis`` of ``mesh`` and run ``kernel`` (a per-shard
    collective taking (q, k, v), partially applied with ``kernel_kw``)
    under shard_map + jit."""
    fn, sharding = _sharded_attention_fn(
        kernel, mesh, axis, tuple(sorted(kernel_kw.items()))
    )
    q = jax.device_put(jnp.asarray(q), sharding)
    k = jax.device_put(jnp.asarray(k), sharding)
    v = jax.device_put(jnp.asarray(v), sharding)
    return fn(q, k, v)


def ring_attention_sharded(
    q: np.ndarray, k: np.ndarray, v: np.ndarray, mesh: Mesh, axis: str = "sp"
):
    """Run ring attention with the sequence axis of q/k/v sharded over
    ``axis`` of ``mesh``. Host-convenience wrapper around shard_map."""
    check_ring_divisibility(q.shape[1], mesh.shape[axis])
    return sharded_attention(
        q, k, v, mesh, axis, ring_attention,
        axis_name=axis, n_dev=mesh.shape[axis],
    )


def sequence_parallel_mesh(n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the sequence-parallel axis."""
    devices = jax.devices() if n_devices is None else jax.devices()[:n_devices]
    return Mesh(np.asarray(devices), ("sp",))

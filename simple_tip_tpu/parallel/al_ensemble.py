"""Batched active-learning retraining: all ~80 per-TIP retrainings of one AL
run train simultaneously as a vmapped parameter ensemble.

The reference retrains sequentially, one full ``model.fit`` per selection
(reference: src/dnn_test_prio/eval_active_learning.py:100-115) — its
wall-clock monster. Here every retraining shares the same base training set
and differs only in its ``num_selected`` extra samples, so device memory holds
ONE copy of the base set plus a stacked ``[S, k, ...]`` extras tensor; the
vmapped epoch gathers each member's batch from base-or-extras by index.

Keras parity detail: the reference shuffles base+selection and then lets
``fit`` hold out the LAST 10% as validation — so selected samples can land in
the held-out part. We reproduce that exactly with a per-member host
permutation (``member_perm``) mapping logical slots to physical rows; the
training loop only touches the first 90% of logical slots.

Memory scales with the member-group size (activations are materialized per
member under vmap), so retrainings run in groups of ``group_size``.
"""

import logging
import math
from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)

from simple_tip_tpu.models.train import (
    TrainConfig,
    adam_like_keras,
    categorical_crossentropy,
    init_params,
)


def make_al_epoch_core(model, tx, batch_size: int):
    """Un-jitted epoch over (shared base set + per-member extras).

    Args per call: params, opt_state, shared_x [n,...], shared_y [n,C],
    extra_x [k,...], extra_y [k,C], member_perm [n_train] (logical->physical
    over n+k rows), rng. vmapped over (params, opt_state, extra_x, extra_y,
    member_perm, rng).
    """

    def loss_fn(params, xb, yb, mask, dropout_rng):
        probs, _ = model.apply(
            {"params": params}, xb, train=True, rngs={"dropout": dropout_rng}
        )
        losses = categorical_crossentropy(probs, yb)
        return jnp.sum(losses * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    def epoch(params, opt_state, shared_x, shared_y, extra_x, extra_y, member_perm, rng):
        n_shared = shared_x.shape[0]
        n_train = member_perm.shape[0]
        steps = math.ceil(n_train / batch_size)
        padded = steps * batch_size
        perm_rng, dropout_rng = jax.random.split(rng)
        perm = jax.random.permutation(perm_rng, n_train)
        physical = jnp.take(member_perm, perm)
        physical = jnp.concatenate(
            [physical, jnp.zeros(padded - n_train, physical.dtype)]
        )
        mask = (jnp.arange(padded) < n_train).astype(jnp.float32)
        physical = physical.reshape(steps, batch_size)
        mask = mask.reshape(steps, batch_size)
        step_rngs = jax.random.split(dropout_rng, steps)

        def gather(idx):
            in_shared = idx < n_shared
            xb_s = jnp.take(shared_x, jnp.clip(idx, 0, n_shared - 1), axis=0)
            yb_s = jnp.take(shared_y, jnp.clip(idx, 0, n_shared - 1), axis=0)
            e_idx = jnp.clip(idx - n_shared, 0, extra_x.shape[0] - 1)
            xb_e = jnp.take(extra_x, e_idx, axis=0)
            yb_e = jnp.take(extra_y, e_idx, axis=0)
            sel = in_shared.reshape((-1,) + (1,) * (xb_s.ndim - 1))
            return (
                jnp.where(sel, xb_s, xb_e),
                jnp.where(in_shared[:, None], yb_s, yb_e),
            )

        def step(carry, sl):
            params, opt_state = carry
            idx, batch_mask, step_rng = sl
            xb, yb = gather(idx)
            loss, grads = jax.value_and_grad(loss_fn)(
                params, xb, yb, batch_mask, step_rng
            )
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax_apply(params, updates)
            return (params, opt_state), loss

        (params, opt_state), losses = jax.lax.scan(
            step, (params, opt_state), (physical, mask, step_rngs)
        )
        return params, opt_state, jnp.mean(losses)

    return epoch


def optax_apply(params, updates):
    """Apply optax updates (lazy import keeps module import light)."""
    import optax

    return optax.apply_updates(params, updates)


def al_retrain_ensemble(
    model,
    cfg: TrainConfig,
    train_x: np.ndarray,
    train_y_onehot: np.ndarray,
    selections: List[Tuple[np.ndarray, np.ndarray, int]],
    group_size: int = 16,
    verbose: bool = False,
) -> List:
    """Train one fresh model per (x_sel, y_sel_onehot, seed) selection; all
    selections must have equal k. Returns host-side params per selection."""
    n = train_x.shape[0]
    k = selections[0][0].shape[0]
    assert all(s[0].shape[0] == k for s in selections), "equal selection sizes required"
    total = n + k
    n_train = total - int(total * cfg.validation_split)

    tx = adam_like_keras(cfg.learning_rate)
    epoch_core = make_al_epoch_core(model, tx, cfg.batch_size)
    epoch_vmapped = partial(jax.jit, donate_argnums=(0, 1))(
        jax.vmap(epoch_core, in_axes=(0, 0, None, None, 0, 0, 0, 0))
    )

    shared_x = jnp.asarray(train_x)
    shared_y = jnp.asarray(train_y_onehot)

    results: List = []
    for g_start in range(0, len(selections), group_size):
        group = list(selections[g_start : g_start + group_size])
        n_real = len(group)
        # Pad the ragged last group so every group compiles to the same shape.
        while len(group) < group_size and len(selections) > group_size:
            group.append(group[0])
        extra_x = jnp.asarray(np.stack([s[0] for s in group]))
        extra_y = jnp.asarray(np.stack([s[1] for s in group]))
        seeds = [s[2] for s in group]
        # Per-member shuffle-then-split permutation (keras fit parity).
        perms = np.stack(
            [np.random.RandomState(seed).permutation(total)[:n_train] for seed in seeds]
        ).astype(np.int32)
        member_perm = jnp.asarray(perms)

        # RNG derivation IDENTICAL to the sequential Trainer.train path
        # (models/train.py): PRNGKey(seed) -> (init_rng, epoch_rng), then a
        # per-epoch split chain. With member_perm already matching the
        # sequential shuffle-then-head-split, every member of this ensemble
        # computes the SAME training trajectory the sequential path would —
        # batch==sequential equivalence is a tested invariant
        # (tests/test_al_ensemble.py), not a hope.
        def one_init(seed):
            init_rng = jax.random.split(jax.random.PRNGKey(seed))[0]
            return init_params(model, init_rng, shared_x[:1])

        params = jax.vmap(one_init)(jnp.asarray(seeds, dtype=jnp.uint32))
        opt_state = jax.vmap(tx.init)(params)
        epoch_rngs = jnp.stack(
            [jax.random.split(jax.random.PRNGKey(int(s)))[1] for s in seeds]
        )

        for epoch in range(cfg.epochs):
            both = jax.vmap(jax.random.split)(epoch_rngs)
            epoch_rngs, this_rngs = both[:, 0], both[:, 1]
            params, opt_state, losses = epoch_vmapped(
                params,
                opt_state,
                shared_x,
                shared_y,
                extra_x,
                extra_y,
                member_perm,
                this_rngs,
            )
            if verbose:
                logger.info(
                    "AL group %d: epoch %d/%d loss=%.4f",
                    g_start // group_size,
                    epoch + 1,
                    cfg.epochs,
                    np.asarray(losses).mean(),
                )
        for i in range(n_real):
            results.append(jax.tree.map(lambda leaf: np.asarray(leaf[i]), params))
    return results

"""Device-mesh parallel execution.

The reference's only parallelism axis is "runs": 100 independently-trained
models scheduled over forked worker processes by uncertainty-wizard's
LazyEnsemble (SURVEY.md section 2.5). Here that axis becomes a *vmapped
parameter ensemble* sharded over a ``jax.sharding.Mesh``:

- all N models' parameters live in one pytree with a leading ensemble axis;
- one jitted program trains all of them simultaneously (vmap of the epoch
  scan), with the ensemble axis sharded across devices ("ensemble" mesh axis)
  and, optionally, each model's batch sharded across a "data" axis;
- XLA inserts the collectives; on a pod slice the ensemble axis rides ICI.

On a single chip this still wins big: the case-study models are tiny
(~100k params), so one chip trains dozens of them at once at high MXU
utilization instead of 100 sequential fits.
"""

from simple_tip_tpu.parallel.ensemble import (
    ensemble_mesh,
    stack_init,
    train_ensemble,
    unstack,
)

__all__ = ["train_ensemble", "stack_init", "unstack", "ensemble_mesh"]

"""Device-mesh parallel execution.

The reference's only parallelism axis is "runs": 100 independently-trained
models scheduled over forked worker processes by uncertainty-wizard's
LazyEnsemble (SURVEY.md section 2.5). Here that axis becomes a *vmapped
parameter ensemble* sharded over a ``jax.sharding.Mesh``:

- all N models' parameters live in one pytree with a leading ensemble axis;
- one jitted program trains all of them simultaneously (vmap of the epoch
  scan), with the ensemble axis sharded across devices ("ensemble" mesh axis)
  and, optionally, each model's batch sharded across a "data" axis;
- XLA inserts the collectives; on a pod slice the ensemble axis rides ICI.

On a single chip this still wins big: the case-study models are tiny
(~100k params), so one chip trains dozens of them at once at high MXU
utilization instead of 100 sequential fits.

The re-exports are lazy (same pattern as the top-level package): the
``run_scheduler`` submodule is deliberately jax-free so the spawn workers
(and the dependency-free CI chaos smoke job) can import it without paying
— or wedging on — a backend init; an eager ``ensemble`` import here would
defeat that.
"""

_LAZY_EXPORTS = {
    "ensemble_mesh": "ensemble",
    "stack_init": "ensemble",
    "train_ensemble": "ensemble",
    "unstack": "ensemble",
    "LoopThread": "aio",
    "shared_loop": "aio",
}

__all__ = [
    "LoopThread",
    "ensemble_mesh",
    "shared_loop",
    "stack_init",
    "train_ensemble",
    "unstack",
]


def __getattr__(name):
    """Lazy re-exports of the (jax-heavy) ensemble helpers."""
    from importlib import import_module

    if name in _LAZY_EXPORTS:
        return getattr(
            import_module(f"simple_tip_tpu.parallel.{_LAZY_EXPORTS[name]}"), name
        )
    raise AttributeError(f"module 'simple_tip_tpu.parallel' has no attribute {name!r}")


def __dir__():
    """Make the lazy exports visible to dir()/tab-completion."""
    return sorted(list(globals()) + list(_LAZY_EXPORTS))

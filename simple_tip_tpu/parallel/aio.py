"""Shared worker event loop: one asyncio loop thread for sync callers.

The serving engine is asyncio-native, but most of this codebase's entry
points are synchronous (bench.py's child process, smoke scripts, the
scheduler's worker loop). Rather than each caller spinning a private
``asyncio.run`` — which would tear the engine down between calls and
serialize everything — one process-wide daemon loop thread hosts
long-lived async components, and sync code submits coroutines to it.

Stdlib-only (asyncio + threading) and jax-free, like the rest of the
``parallel`` package's scheduler surface, so spawn workers and the
dependency-free CI lane can import it without a backend init.
"""

import asyncio
import threading
from typing import Optional

_lock = threading.Lock()
_shared: Optional["LoopThread"] = None


class LoopThread:
    """An asyncio event loop running on a dedicated daemon thread."""

    def __init__(self, name: str = "tip-aio"):
        self._loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._loop.run_forever, name=name, daemon=True
        )
        self._thread.start()

    @property
    def loop(self) -> asyncio.AbstractEventLoop:
        """The underlying event loop (for advanced callers)."""
        return self._loop

    def submit(self, coro):
        """Schedule ``coro`` on the loop; returns a concurrent Future."""
        return asyncio.run_coroutine_threadsafe(coro, self._loop)

    def run(self, coro, timeout: Optional[float] = None):
        """Run ``coro`` to completion from sync code (blocks the caller,
        never the loop). ``timeout`` bounds the wait in seconds."""
        return self.submit(coro).result(timeout)

    def stop(self) -> None:
        """Stop the loop and join the thread (idempotent)."""
        if self._loop.is_closed():
            return
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5.0)
        if not self._loop.is_running():
            self._loop.close()


def shared_loop() -> LoopThread:
    """The process-wide shared loop thread (created on first use)."""
    global _shared
    with _lock:
        if _shared is None or _shared.loop.is_closed():
            _shared = LoopThread()
        return _shared

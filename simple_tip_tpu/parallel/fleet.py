"""Host-level fleet execution over the lease substrate.

``run_scheduler`` made the *process* the failure domain: a worker dies and
the parent requeues its id. This module moves the boundary one level up to
the *host* (ROADMAP "Fleet-scale study scheduler"): several host-level
schedulers — separate VMs sharing a filesystem bus, or separate processes
standing in for them — execute ONE phase together, and any of them can be
preempted mid-unit without losing work or double-completing it. The design
follows Podracer's split (PAPERS.md, arxiv 2104.06272): group workers into
independently-failing units and keep the controller stateless enough that
any member can take over.

Three cooperating pieces:

- :class:`FleetContext` — one member's view of the fleet. The scheduler
  calls ``tick()`` every loop (heartbeat + coordinator duties + the
  ``host.die`` chaos seam), ``try_claim``/``renew``/``release`` around the
  lease protocol (resilience/lease.py), ``elsewhere()`` for units other
  members resolved, and ``report_failure`` to spend the fleet-wide attempt
  budget (``TIP_RETRY_FLEET_*``).
- **Coordinator** — not a distinct process: the member currently holding
  the ``__coordinator__`` lease. Its only extra duty is straggler
  speculation (below). Kill it and a standby steals the lease within about
  one heartbeat interval; the steal bumps the fencing epoch, which is what
  ``fleet.handoffs`` counts.
- :func:`run_phase_fleet` — spawns N member processes, each running the
  ordinary ``run_phase_parallel`` with a ``FleetContext``, and watches the
  journal for completion. Elastic membership: if every member dies with
  work outstanding, it launches standby members (up to
  ``TIP_FLEET_MAX_STANDBYS``) that join late and steal the dead members'
  expired leases.

Straggler speculation: the coordinator compares each live lease's age
against the cost model's per-run estimate (obs/costmodel.py) scaled by
``TIP_FLEET_STRAGGLER_SLACK`` (a p95-ish bound: predicted + 2·error,
times the slack), or against an explicit ``TIP_FLEET_STRAGGLER_S``.
A straggler's lease is merely *expired early* (``expire_now``), never
revoked: the original holder may still finish first, and the journal's
fencing epoch — not the speculation — decides which commit stands.

Exactly-once: the journal is the single commit point. A member commits a
unit only through ``mark_done(fence=token)``; a stolen lease means a
bumped epoch, so the stale holder's commit raises ``LeaseLost`` and is
discarded. Completion state is therefore exactly "the journal plus the
fleet's failed-units directory" — which is also what a late joiner reads
to know what is left.

Stdlib-only (the CI chaos job imports this with jax poisoned), like the
rest of the scheduler path.
"""

import json
import logging
import multiprocessing as mp
import os
import time
from typing import Dict, List, Optional, Set, Tuple

from simple_tip_tpu import obs
from simple_tip_tpu.resilience import (
    COORDINATOR_UNIT,
    LeaseLost,
    LeaseManager,
    Membership,
    RetryPolicy,
    faults,
    fleet_now,
    journal_from_env,
)
from simple_tip_tpu.resilience.lease import _safe

logger = logging.getLogger(__name__)

#: How often (fraction of the membership TTL) a member heartbeats and the
#: coordinator lease is renewed/contested. 3 beats per TTL tolerates two
#: dropped beats before the fleet declares the member gone.
_BEATS_PER_TTL = 3.0


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        logger.warning("%s=%r is not a number; using %s", name, raw, default)
        return default


class FleetContext:
    """One member's handle on a shared fleet root.

    The root directory is the fleet: ``leases/`` (work-unit and
    coordinator leases), ``members/`` (heartbeat files), ``failed/``
    (fleet-wide permanent failures) and ``attempts/`` (the cross-host
    attempt ledger). Everything rides atomic file ops on the shared bus —
    no network protocol, same as the rest of the repo's filesystem bus.
    """

    def __init__(
        self,
        root: str,
        host_id: str,
        case_study: str,
        phase: str,
        lease_ttl_s: float = 30.0,
        member_ttl_s: float = 10.0,
        journal=None,
    ):
        self.root = root
        self.host_id = str(host_id)
        self.case_study = case_study
        self.phase = phase
        self.leases = LeaseManager(
            os.path.join(root, "leases"), owner=self.host_id, ttl_s=lease_ttl_s
        )
        # The coordinator lease rides the (shorter) membership TTL so a
        # dead coordinator is replaced within about one heartbeat interval,
        # not a full work-lease TTL.
        self._coord_mgr = LeaseManager(
            os.path.join(root, "leases"), owner=self.host_id, ttl_s=member_ttl_s
        )
        self.members = Membership(
            os.path.join(root, "members"), self.host_id, ttl_s=member_ttl_s
        )
        self.failed_dir = os.path.join(root, "failed")
        self.attempts_dir = os.path.join(root, "attempts")
        self.beat_interval_s = member_ttl_s / _BEATS_PER_TTL
        self._journal = journal
        self._coord_tok = None
        self._last_fleet_view: Dict = {}
        self._ticks = 0
        self._last_beat = 0.0  # monotonic; 0 forces a beat on the first tick
        self._last_elsewhere = 0.0
        self._elsewhere_cache: Tuple[Set, Dict] = (set(), {})
        self._straggler_cache = ("unset",)
        # Total attempts per unit ACROSS hosts (local requeues are separate
        # and cheaper; this bounds how many hosts re-run a poisoned unit).
        self.attempt_budget = RetryPolicy.from_env(
            scope="fleet", inherit=False, attempts=2
        ).attempts

    # -- journal -----------------------------------------------------------

    def _get_journal(self):
        if self._journal is None:
            self._journal = journal_from_env(self.case_study, self.phase)
        return self._journal

    # -- per-tick housekeeping --------------------------------------------

    def tick(self, workers: Optional[List] = None) -> None:
        """One housekeeping pass; the scheduler calls this every loop.

        Fires the ``host.die`` chaos seam (kind ``kill`` terminates this
        member's worker pool and hard-exits — the whole-host preemption),
        then, on the beat cadence, heartbeats and runs coordinator duties.
        """
        self._ticks += 1
        role = "coordinator" if self._coord_tok is not None else "member"
        fault = faults.maybe_inject(
            "host.die", host=self.host_id, role=role, tick=self._ticks,
            phase=self.phase,
        )
        if fault is not None and fault.kind == "kill":
            # Terminate the worker pool BEFORE exiting: os._exit skips the
            # daemon-cleanup atexit hooks, and orphaned workers would keep
            # draining queues for a host the fleet considers dead.
            for w in workers or []:
                try:
                    if w.is_alive():
                        w.terminate()
                except Exception:  # noqa: BLE001 — dying anyway
                    pass
            obs.event("fleet.host_die", host=self.host_id, role=role)
            obs.flush_metrics()
            logger.error("fleet member %s killed by host.die fault", self.host_id)
            os._exit(1)
        now = time.monotonic()
        if now - self._last_beat < self.beat_interval_s:
            return
        self._last_beat = now
        self.members.beat(role=role, phase=self.phase)
        self._coordinate()
        # Refresh the cached /fleet view on the same cadence: the bus reads
        # happen here (one beat per interval), never on an HTTP thread.
        self.fleet_view()

    def _coordinate(self) -> None:
        """Renew-or-contest the coordinator lease; speculate if we hold it."""
        if self._coord_tok is not None:
            try:
                self._coord_mgr.renew(self._coord_tok)
            except LeaseLost:
                # Fenced out (e.g. our own heartbeat stalled past the TTL
                # and a standby took over). Step down; the new coordinator
                # is authoritative.
                self._coord_tok = None
                obs.event("fleet.demoted", host=self.host_id)
                logger.warning(
                    "fleet member %s lost the coordinator lease", self.host_id
                )
        if self._coord_tok is None:
            tok = self._coord_mgr.claim(COORDINATOR_UNIT)
            if tok is not None:
                self._coord_tok = tok
                if tok.epoch > 1:
                    # epoch 1 is the founding claim; every later epoch means
                    # the previous coordinator died/stalled and we took over.
                    obs.counter("fleet.handoffs").inc()
                    obs.event(
                        "fleet.handoff", host=self.host_id, epoch=tok.epoch
                    )
                    logger.warning(
                        "fleet member %s PROMOTED to coordinator (epoch %d)",
                        self.host_id, tok.epoch,
                    )
                else:
                    obs.event("fleet.coordinator", host=self.host_id)
                    logger.info(
                        "fleet member %s is the coordinator", self.host_id
                    )
        if self._coord_tok is not None:
            self._speculate_stragglers()

    # -- straggler speculation --------------------------------------------

    def _straggler_timeout(self) -> Optional[float]:
        """Age past which a live lease is speculatively re-leased, or None
        (no explicit knob and no cost-model estimate = no speculation)."""
        if self._straggler_cache != ("unset",):
            return self._straggler_cache[0]
        timeout: Optional[float] = None
        raw = os.environ.get("TIP_FLEET_STRAGGLER_S", "").strip()
        if raw:
            try:
                timeout = float(raw) or None  # 0 disables
            except ValueError:
                logger.warning("TIP_FLEET_STRAGGLER_S=%r is not a number", raw)
        else:
            try:
                # Plan first: speculation sized from the same per-phase
                # prediction the planner committed to (and `obs audit`
                # grades), falling back to the live cost model.
                from simple_tip_tpu import plan as _plan
                from simple_tip_tpu.obs import costmodel

                est = _plan.phase_estimate(self.phase, 1, workers=1)
                if est is None:
                    est = costmodel.quick_phase_estimate(
                        self.phase, 1, workers=1
                    )
            except Exception:  # noqa: BLE001 — advisory, never fatal
                est = None
            if est is not None:
                slack = _env_float("TIP_FLEET_STRAGGLER_SLACK", 4.0)
                p95 = est["predicted_s"] + 2.0 * (est.get("error_s") or 0.0)
                timeout = max(p95 * slack, 1.0)
        self._straggler_cache = (timeout,)
        return timeout

    def _speculate_stragglers(self) -> None:
        timeout = self._straggler_timeout()
        if timeout is None:
            return
        now = fleet_now()
        for rec in self.leases.active():
            unit = rec.get("unit")
            if unit == COORDINATOR_UNIT:
                continue
            age = now - float(rec.get("claimed_ts", now))
            if age <= timeout:
                continue
            # Expire early, never revoke: if the straggler is merely slow
            # it may still commit first — the fencing epoch at the journal
            # decides the race, this is only a hint that lets someone else
            # start a second attempt.
            if self.leases.expire_now(unit):
                obs.counter("fleet.speculations").inc()
                obs.event(
                    "fleet.speculate", unit=unit, holder=rec.get("owner"),
                    age_s=round(age, 3), timeout_s=round(timeout, 3),
                )
                logger.warning(
                    "fleet: unit %s on %s is a straggler (%.1fs > %.1fs); "
                    "lease expired for speculative re-run",
                    unit, rec.get("owner"), age, timeout,
                )

    # -- live fleet view (the exporter's /fleet route) ---------------------

    def fleet_view(self) -> Dict:
        """Aggregate the fleet's live state into one JSON-safe dict.

        Per-host heartbeat age with a ``stale`` verdict (age past the
        membership TTL — the host stopped beating but has not rejoined),
        the coordinator lease (owner + fencing epoch), every in-flight
        work lease with its age and straggler verdict (the same timeout
        the coordinator speculates on), and this member's own identity.

        Reads the filesystem bus, so callers refresh it on the beat
        cadence (``tick()`` does) and the HTTP exporter serves the CACHED
        copy via :meth:`last_fleet_view` — handlers never walk the bus.
        """
        now = fleet_now()
        members = {}
        for host, rec in self.members.table().items():
            age = max(0.0, now - float(rec.get("ts", now)))
            members[host] = {
                "age_s": round(age, 3),
                "stale": age > self.members.ttl_s,
                "role": rec.get("role"),
                "phase": rec.get("phase"),
                "pid": rec.get("pid"),
            }
        timeout = self._straggler_timeout()
        coordinator = None
        leases = []
        for rec in self.leases.active():
            unit = rec.get("unit")
            age = max(0.0, now - float(rec.get("claimed_ts", now)))
            entry = {
                "unit": unit,
                "owner": rec.get("owner"),
                "epoch": rec.get("epoch"),
                "age_s": round(age, 3),
            }
            if unit == COORDINATOR_UNIT:
                coordinator = entry
                continue
            entry["verdict"] = (
                "straggler" if timeout is not None and age > timeout else "ok"
            )
            leases.append(entry)
        view = {
            "host": self.host_id,
            "case_study": self.case_study,
            "phase": self.phase,
            "is_coordinator": self._coord_tok is not None,
            "coordinator": coordinator,
            "members": members,
            "member_ttl_s": self.members.ttl_s,
            "leases": sorted(leases, key=lambda r: str(r["unit"])),
            "in_flight": len(leases),
            "straggler_timeout_s": timeout,
            "ts": now,
        }
        self._last_fleet_view = view
        return view

    def last_fleet_view(self) -> Dict:
        """The newest :meth:`fleet_view` result — a pure in-memory read,
        safe as an exporter route provider."""
        return self._last_fleet_view

    # -- claims ------------------------------------------------------------

    def try_claim(self, model_id):
        """A fence token for ``model_id`` if this host may run it, else None
        (someone else holds it, or it already failed fleet-wide)."""
        _, failed = self.elsewhere()
        if model_id in failed:
            return None
        return self.leases.claim(str(model_id))

    def renew(self, token) -> None:
        self.leases.renew(token)

    def release(self, token) -> None:
        self.leases.release(token)

    # -- cross-host completion view ---------------------------------------

    def elsewhere(self) -> Tuple[Set, Dict]:
        """(done ids, failed id -> error) as resolved by ANY member.

        Done is simply the journal (the commit point); failed is the
        fleet's permanent-failure directory. Cached for half a beat so the
        scheduler can call this every loop without hammering the bus.
        """
        now = time.monotonic()
        if now - self._last_elsewhere < min(0.5, self.beat_interval_s):
            return self._elsewhere_cache
        self._last_elsewhere = now
        journal = self._get_journal()
        done = journal.completed() if journal is not None else set()
        failed: Dict = {}
        try:
            names = os.listdir(self.failed_dir)
        except OSError:
            names = []
        for name in names:
            if not (name.startswith("failed_") and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.failed_dir, name), encoding="utf-8") as f:
                    rec = json.load(f)
            except (OSError, ValueError):
                continue
            if isinstance(rec, dict) and "unit" in rec:
                failed[rec["unit"]] = str(rec.get("error", "failed fleet-wide"))
        self._elsewhere_cache = (done, failed)
        return self._elsewhere_cache

    # -- failures ----------------------------------------------------------

    def report_failure(self, model_id, token, error: str) -> Optional[str]:
        """Spend one fleet-wide attempt for ``model_id``.

        Returns the final error string once the shared budget
        (``TIP_RETRY_FLEET_ATTEMPTS``) is exhausted — the unit is recorded
        in ``failed/`` so no member re-claims it — or None after releasing
        the lease for another member to retry.
        """
        unit = _safe(str(model_id))
        os.makedirs(self.attempts_dir, exist_ok=True)
        path = os.path.join(self.attempts_dir, f"attempts_{unit}.json")
        # The per-unit lease lock also serializes the attempt ledger: two
        # members reporting the same unit must not both read n and write n+1.
        with self.leases._locked(str(model_id)):
            rec = {"attempts": 0, "errors": []}
            try:
                with open(path, encoding="utf-8") as f:
                    loaded = json.load(f)
                if isinstance(loaded, dict):
                    rec = loaded
            except (OSError, ValueError):
                pass
            rec["attempts"] = int(rec.get("attempts", 0)) + 1
            rec["errors"] = (list(rec.get("errors", [])) + [
                {"host": self.host_id, "error": str(error)[:300], "ts": fleet_now()}
            ])[-5:]
            tmp = f"{path}.{os.getpid()}.tmp"
            try:
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(rec, f)
                os.replace(tmp, path)
            except OSError as e:
                logger.warning("fleet attempt ledger write failed: %s", e)
            attempts = rec["attempts"]
        if token is not None:
            self.release(token)
        if attempts < self.attempt_budget:
            obs.counter("fleet.retries_released").inc()
            return None
        final = (
            f"{error} (fleet attempts {attempts}/{self.attempt_budget} "
            f"exhausted across hosts)"
        )
        os.makedirs(self.failed_dir, exist_ok=True)
        fpath = os.path.join(self.failed_dir, f"failed_{unit}.json")
        try:
            tmp = f"{fpath}.{os.getpid()}.tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(
                    {"unit": model_id, "error": final, "attempts": attempts}, f
                )
            os.replace(tmp, fpath)
        except OSError as e:
            logger.warning("fleet failure record write failed: %s", e)
        obs.counter("fleet.failures").inc()
        obs.event(
            "fleet.fail", unit=model_id, host=self.host_id,
            attempts=attempts, error=str(error)[:200],
        )
        return final

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        """Clean departure: give up the coordinator role and stop beating."""
        if self._coord_tok is not None:
            try:
                self._coord_mgr.release(self._coord_tok)
            except Exception:  # noqa: BLE001 — expiry is the backstop
                pass
            self._coord_tok = None
        self.members.leave()


def _fleet_member_main(
    host_id,
    root,
    case_study,
    phase,
    model_ids,
    num_workers,
    phase_kwargs,
    run_timeout_s,
    lease_ttl_s,
    member_ttl_s,
    env_overrides,
):
    """Entry point of one spawned fleet member process.

    A member is just ``run_phase_parallel`` with a :class:`FleetContext`:
    the same scheduler, worker pool and requeue machinery, plus the lease
    claim path. Exit code 0 when every unit this member saw is resolved
    (here or elsewhere), 1 on failure — the parent decides whether a
    standby is warranted.
    """
    os.environ.update(env_overrides)
    obs.install_worker_logging()
    from simple_tip_tpu.parallel.run_scheduler import run_phase_parallel

    ctx = FleetContext(
        root, host_id, case_study, phase,
        lease_ttl_s=lease_ttl_s, member_ttl_s=member_ttl_s,
    )
    rc = 0
    with obs.span(
        "fleet.member", host=host_id, phase=phase, case_study=case_study
    ):
        try:
            run_phase_parallel(
                case_study, phase, list(model_ids), num_workers,
                phase_kwargs=phase_kwargs, run_timeout_s=run_timeout_s,
                fleet=ctx,
            )
        except Exception as e:  # noqa: BLE001 — reported via exit code
            logger.error("fleet member %s failed: %s", host_id, e)
            rc = 1
        finally:
            ctx.close()
    obs.flush_metrics()
    if rc:
        raise SystemExit(rc)


def run_phase_fleet(
    case_study: str,
    phase: str,
    model_ids: List[int],
    root: str,
    n_hosts: int = 2,
    workers_per_host: int = 1,
    phase_kwargs: Optional[Dict] = None,
    run_timeout_s: Optional[float] = None,
    lease_ttl_s: float = 5.0,
    member_ttl_s: float = 5.0,
    member_env: Optional[List[Dict[str, str]]] = None,
    max_standbys: Optional[int] = None,
    deadline_s: float = 600.0,
) -> None:
    """Run ``phase`` across ``n_hosts`` member processes sharing ``root``.

    Each member is a full host-level scheduler (``run_phase_parallel`` with
    ``workers_per_host`` workers); the lease directory under ``root``
    partitions the ids between them. Membership is elastic: members that
    die (preemption, the ``host.die`` chaos seam) simply stop renewing and
    the survivors steal their expired leases; if EVERY member dies with
    work outstanding, standby members are launched late (up to
    ``max_standbys``, default ``TIP_FLEET_MAX_STANDBYS`` = 1) and catch up
    from the journal. ``member_env`` optionally gives per-member env
    overrides (e.g. ``TIP_FLEET_CLOCK_SKEW_S`` for one member in the chaos
    suite). Raises ``RuntimeError`` if any unit is unresolved or failed
    fleet-wide once the fleet drains (or ``deadline_s`` passes).
    """
    journal = journal_from_env(case_study, phase)
    if journal is None:
        raise ValueError(
            "fleet execution requires a journal as the commit point: pin "
            "TIP_ASSETS or set TIP_JOURNAL to a shared path"
        )
    if max_standbys is None:
        max_standbys = int(_env_float("TIP_FLEET_MAX_STANDBYS", 1.0))
    os.makedirs(root, exist_ok=True)
    obs.enabled()  # pin an auto obs dir before any member spawns
    probe = FleetContext(
        root, "fleet-parent", case_study, phase,
        lease_ttl_s=lease_ttl_s, member_ttl_s=member_ttl_s, journal=journal,
    )
    # Live telemetry plane (obs v4): the fleet parent serves the
    # coordinator-aggregated /fleet view (and /healthz//metrics) while the
    # fleet runs. No-op unless TIP_OBS_HTTP is set; members do not mount —
    # one port, one aggregated view.
    from simple_tip_tpu.obs import alerts as alerts_mod
    from simple_tip_tpu.obs import exporter

    http_port = exporter.start()
    if http_port is not None:
        exporter.set_provider("fleet", probe.last_fleet_view)
        probe.fleet_view()  # seed the cache so the route is never empty

    ctx = mp.get_context("spawn")
    members: List = []

    def _spawn_member(host_id: str, env: Dict[str, str]):
        # NOT daemonic: members spawn their own (daemonic) worker pools,
        # and a daemonic process may not have children.
        p = ctx.Process(
            target=_fleet_member_main,
            args=(
                host_id, root, case_study, phase, list(model_ids),
                workers_per_host, dict(phase_kwargs or {}), run_timeout_s,
                lease_ttl_s, member_ttl_s,
                {"TIP_FLEET_HOST": host_id, **env},
            ),
            name=f"fleet-{host_id}",
        )
        p.start()
        members.append(p)
        logger.info("fleet: launched member %s (pid %s)", host_id, p.pid)
        return p

    member_env = list(member_env or [])
    for i in range(n_hosts):
        env = member_env[i] if i < len(member_env) else {}
        _spawn_member(f"host{i}", env)

    def _unresolved() -> List[int]:
        done, failed = probe.elsewhere()
        return [m for m in model_ids if m not in done and m not in failed]

    standbys = 0
    deadline = time.monotonic() + deadline_s
    next_view = 0.0
    try:
        while _unresolved():
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"fleet did not drain within {deadline_s:.0f}s; "
                    f"unresolved: {_unresolved()}"
                )
            if (
                http_port is not None or alerts_mod.enabled()
            ) and time.monotonic() >= next_view:
                # Refresh the cached /fleet view on the beat cadence from
                # THIS loop — handler threads only ever read the cache.
                # The SLO evaluator rides the same beat (its
                # fleet-members-alive rule samples the gauge set here),
                # with or without a live exporter.
                next_view = time.monotonic() + probe.beat_interval_s
                view = probe.fleet_view()
                fleet_members = view.get("members", {})
                alive = [
                    h for h, m in fleet_members.items() if not m.get("stale")
                ]
                obs.gauge("fleet.members_alive").set(len(alive))
                alerts_mod.tick()
                if http_port is not None:
                    exporter.set_health(
                        "fleet", ok=bool(alive), members_alive=len(alive),
                        members_total=len(fleet_members),
                        unresolved=len(_unresolved()),
                    )
            if not any(p.is_alive() for p in members):
                if standbys >= max_standbys:
                    break  # nobody left and no standby budget: report below
                standbys += 1
                obs.counter("fleet.elastic_joins").inc()
                obs.event("fleet.standby", host=f"standby{standbys}")
                logger.warning(
                    "fleet: all members dead with work outstanding; "
                    "launching standby%d", standbys,
                )
                _spawn_member(f"standby{standbys}", {})
            time.sleep(0.2)
    finally:
        if http_port is not None:
            # Unhook: the probe goes out of scope with this call frame, and
            # a dangling provider would serve a view of a finished fleet.
            exporter.clear_provider("fleet")
            exporter.clear_health("fleet")
        for p in members:
            p.join(timeout=30)
            if p.is_alive():
                logger.error("fleet member pid %s wedged; terminating", p.pid)
                p.terminate()
                p.join(timeout=10)

    done, failed = probe.elsewhere()
    missing = [m for m in model_ids if m not in done and m not in failed]
    if failed or missing:
        parts = [f"run {m}: {failed[m]}" for m in sorted(failed) if m in failed]
        parts += [f"run {m}: unresolved (no member completed it)" for m in missing]
        raise RuntimeError(
            f"{phase} fleet failed for {len(parts)}/{len(model_ids)} runs: "
            + "; ".join(parts)
        )
    logger.info(
        "fleet: %s complete — %d units journaled across %d member(s) "
        "(+%d standby)", phase, len(done & set(model_ids)),
        n_hosts, standbys,
    )

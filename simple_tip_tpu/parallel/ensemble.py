"""Vmapped model-ensemble training over a device mesh.

TPU-native replacement for the reference's process-pool run scheduler
(uncertainty-wizard ``LazyEnsemble.create/consume``, reference:
src/dnn_test_prio/case_study.py:18-25,87-92): instead of forking one process
per model id, all requested models train inside ONE jitted program — a vmap of
the keras-equivalent epoch function over a stacked parameter pytree — with the
ensemble axis laid out across mesh devices by ``NamedSharding``. Each model
keeps its own rng stream (init, per-epoch shuffle, dropout), so the ensemble
is statistically identical to N independent trainings.
"""

import logging
import math
from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)

from simple_tip_tpu.models.train import (
    TrainConfig,
    adam_like_keras,
    make_epoch_core,
)

ENSEMBLE_AXIS = "ensemble"
DATA_AXIS = "data"


def ensemble_mesh(
    n_ensemble: Optional[int] = None, n_data: int = 1, devices=None
) -> Mesh:
    """Build an (ensemble, data) mesh over the available devices."""
    devices = devices if devices is not None else jax.devices()
    n_dev = len(devices)
    if n_ensemble is None:
        n_ensemble = n_dev // n_data
    assert n_ensemble * n_data == n_dev, (
        f"mesh {n_ensemble}x{n_data} does not match {n_dev} devices"
    )
    dev_array = np.asarray(devices).reshape(n_ensemble, n_data)
    return Mesh(dev_array, (ENSEMBLE_AXIS, DATA_AXIS))


def stack_init(model, seeds: List[int], example_x) -> dict:
    """Initialize a stacked parameter pytree: leading axis = ensemble member."""

    def one(seed):
        rng = jax.random.PRNGKey(seed)
        variables = model.init({"params": rng, "dropout": rng}, example_x, train=False)
        return variables["params"]

    return jax.vmap(one)(jnp.asarray(seeds, dtype=jnp.uint32))


def stack_params(params_list):
    """Stack per-member parameter pytrees into ONE pytree with a leading
    member axis — the canonical host-side stacker.

    This is the inverse of ``unstack`` and the layout both ``train_ensemble``
    and the grouped study executor (``engine/run_program.GroupChainRunner``)
    speak: leaf ``[G, ...]`` with member g at index g. ``np.stack`` on the
    host preserves leaf dtypes exactly (a bf16 checkpoint stays bf16 — no
    silent upcast doubling the stacked-weights HBM residency).
    """
    if not params_list:
        raise ValueError("stack_params needs at least one member")
    return jax.tree.map(
        lambda *leaves: np.stack([np.asarray(l) for l in leaves]),
        *params_list,
    )


def unstack(stacked, i: int):
    """Extract member ``i``'s parameters from a stacked pytree (host copy)."""
    return jax.tree.map(lambda leaf: np.asarray(leaf[i]), stacked)


def _shard_ensemble(tree, mesh: Optional[Mesh]):
    """Lay the leading (ensemble) axis of every leaf across the mesh."""
    if mesh is None:
        return tree
    sharding = NamedSharding(mesh, P(ENSEMBLE_AXIS))
    return jax.tree.map(lambda leaf: jax.device_put(leaf, sharding), tree)


def train_ensemble(
    model,
    x: np.ndarray,
    y_onehot: np.ndarray,
    cfg: TrainConfig,
    seeds: List[int],
    mesh: Optional[Mesh] = None,
    verbose: bool = False,
):
    """Train ``len(seeds)`` independent models simultaneously.

    Returns the stacked parameter pytree (leading axis = ensemble member,
    ordered like ``seeds``). With a mesh, members are sharded across the
    ``ensemble`` axis and the training data is replicated (the per-model batch
    is small; sharding the batch across a ``data`` axis is available for the
    larger-batch regimes via ``mesh`` shape).
    """
    n_models = len(seeds)
    n = x.shape[0]
    n_train = n - int(n * cfg.validation_split)
    x_train = jnp.asarray(x[:n_train])
    y_train = jnp.asarray(y_onehot[:n_train])

    if mesh is not None:
        # Pad the ensemble to a multiple of the mesh's ensemble axis.
        ens_size = mesh.shape[ENSEMBLE_AXIS]
        padded = math.ceil(n_models / ens_size) * ens_size
        all_seeds = list(seeds) + [0] * (padded - n_models)
    else:
        all_seeds = list(seeds)

    params = stack_init(model, all_seeds, x_train[:1])
    tx = adam_like_keras(cfg.learning_rate)
    opt_state = jax.vmap(tx.init)(params)

    params = _shard_ensemble(params, mesh)
    opt_state = _shard_ensemble(opt_state, mesh)
    if mesh is not None:
        data_sharding = NamedSharding(mesh, P())  # replicated
        x_train = jax.device_put(x_train, data_sharding)
        y_train = jax.device_put(y_train, data_sharding)

    epoch_core = make_epoch_core(model, tx, cfg.batch_size)
    epoch_vmapped = partial(jax.jit, donate_argnums=(0, 1))(
        jax.vmap(epoch_core, in_axes=(0, 0, None, None, 0))
    )

    epoch_rngs = jnp.stack(
        [jax.random.PRNGKey(int(s) + 10_000) for s in all_seeds]
    )
    for epoch in range(cfg.epochs):
        this_rngs = jax.vmap(lambda r: jax.random.fold_in(r, epoch))(epoch_rngs)
        params, opt_state, losses = epoch_vmapped(
            params, opt_state, x_train, y_train, this_rngs
        )
        if verbose:
            losses = np.asarray(losses)
            logger.info(
                "ensemble epoch %d/%d mean_loss=%.4f",
                epoch + 1,
                cfg.epochs,
                losses[:n_models].mean(),
            )

    # Drop padding members.
    params = jax.tree.map(lambda leaf: leaf[:n_models], params)
    return params

"""Multi-host distribution helpers.

The reference's inter-process transport is fork+pickle plus the ``/assets``
filesystem (SURVEY.md section 2.5); scale-out here is JAX-native:
``initialize()`` wires up ``jax.distributed`` (ICI within a slice, DCN across
hosts), ``global_ensemble_mesh`` builds a mesh over all global devices, and
``host_local_model_ids`` splits the 100-run id range so each host trains and
persists its own shard of the ensemble (keeping artifact writes
host-local — the filesystem bus stays the coordination-free checkpoint
mechanism it is in the reference).
"""

import logging
from typing import List, Optional, Sequence

import jax

logger = logging.getLogger(__name__)


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize jax.distributed (no-op when single-process or already up).

    Must run before anything initializes the XLA backend — so the
    already-up check uses ``jax.distributed.is_initialized()``, NOT
    ``jax.process_count()`` (which would itself initialize the backend and
    make distributed startup impossible)."""
    if jax.distributed.is_initialized():
        return
    if coordinator_address is None:
        logger.info("single-process run; jax.distributed not initialized")
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info(
        "jax.distributed up: process %d/%d, %d global devices",
        jax.process_index(),
        jax.process_count(),
        len(jax.devices()),
    )


def barrier(name: str, timeout_s: float = 1800.0) -> None:
    """Cross-process rendezvous via the coordination service.

    Deliberately NOT ``multihost_utils.sync_global_devices``: that runs a
    device collective, which on CPU backends lazily initializes a Gloo
    context whose key exchange has a fixed ~30 s timeout — when one host
    reaches the sync minutes before another (phase skew is the NORM here:
    hosts carry different run-id shards and the evaluation phase runs on
    process 0 only), Gloo init dies with DEADLINE_EXCEEDED and poisons the
    whole cluster (observed as the round-4 flaky-under-contention
    failure). A barrier is pure control flow; the coordination service's
    ``wait_at_barrier`` does exactly that with an explicit, generous
    timeout and no data plane.

    No-op in single-process runs. Falls back to ``sync_global_devices`` if
    the internal client API is unavailable in some jax version.
    """
    if not jax.distributed.is_initialized() or jax.process_count() <= 1:
        return
    try:
        from jax._src import distributed as _dist

        client = _dist.global_state.client
        if client is None:
            raise AttributeError("no distributed client")
        client.wait_at_barrier(name, timeout_in_ms=int(timeout_s * 1000))
        return
    except (ImportError, AttributeError, TypeError) as e:
        # jax internals moved/renamed: degrade to the collective — LOUDLY,
        # because the collective reintroduces the Gloo lazy-init skew
        # sensitivity this function exists to avoid, and drops timeout_s.
        logger.warning(
            "coordination-service barrier unavailable (%r); falling back "
            "to sync_global_devices(%s) — phase skew beyond Gloo's ~30 s "
            "init window will fail here",
            e,
            name,
        )
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def global_ensemble_mesh(n_data: int = 1):
    """(ensemble, data) mesh over all global devices (multi-host aware)."""
    from simple_tip_tpu.parallel.ensemble import ensemble_mesh

    return ensemble_mesh(n_data=n_data, devices=jax.devices())


def host_local_model_ids(model_ids: Sequence[int]) -> List[int]:
    """The subset of run ids this host is responsible for (contiguous split,
    remainder to the leading hosts)."""
    ids = list(model_ids)
    n_proc = jax.process_count()
    if n_proc == 1:
        return ids
    rank = jax.process_index()
    base, rem = divmod(len(ids), n_proc)
    start = rank * base + min(rank, rem)
    size = base + (1 if rank < rem else 0)
    return ids[start : start + size]

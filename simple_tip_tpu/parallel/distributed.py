"""Multi-host distribution helpers.

The reference's inter-process transport is fork+pickle plus the ``/assets``
filesystem (SURVEY.md section 2.5); scale-out here is JAX-native:
``initialize()`` wires up ``jax.distributed`` (ICI within a slice, DCN across
hosts), ``global_ensemble_mesh`` builds a mesh over all global devices, and
``host_local_model_ids`` splits the 100-run id range so each host trains and
persists its own shard of the ensemble (keeping artifact writes
host-local — the filesystem bus stays the coordination-free checkpoint
mechanism it is in the reference).
"""

import logging
from typing import List, Optional, Sequence

import jax

logger = logging.getLogger(__name__)


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Initialize jax.distributed (no-op when single-process or already up).

    Must run before anything initializes the XLA backend — so the
    already-up check uses ``jax.distributed.is_initialized()``, NOT
    ``jax.process_count()`` (which would itself initialize the backend and
    make distributed startup impossible)."""
    if jax.distributed.is_initialized():
        return
    if coordinator_address is None:
        logger.info("single-process run; jax.distributed not initialized")
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info(
        "jax.distributed up: process %d/%d, %d global devices",
        jax.process_index(),
        jax.process_count(),
        len(jax.devices()),
    )


def global_ensemble_mesh(n_data: int = 1):
    """(ensemble, data) mesh over all global devices (multi-host aware)."""
    from simple_tip_tpu.parallel.ensemble import ensemble_mesh

    return ensemble_mesh(n_data=n_data, devices=jax.devices())


def host_local_model_ids(model_ids: Sequence[int]) -> List[int]:
    """The subset of run ids this host is responsible for (contiguous split,
    remainder to the leading hosts)."""
    ids = list(model_ids)
    n_proc = jax.process_count()
    if n_proc == 1:
        return ids
    rank = jax.process_index()
    base, rem = divmod(len(ids), n_proc)
    start = rank * base + min(rank, rem)
    size = base + (1 if rank < rem else 0)
    return ids[start : start + size]

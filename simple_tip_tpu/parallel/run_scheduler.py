"""Run-level host parallelism: worker processes over model ids.

TPU-native counterpart of the reference's LazyEnsemble process scheduler
(reference: src/dnn_test_prio/case_study.py:87-109, which forks
``num_processes`` workers, each loading model ``i`` from disk and running a
picklable per-model function). The host-bound half of the prio/AL phases —
float64 KDE fit/eval for LSA, KMeans+silhouette for pc-mmdsa, artifact IO —
does not ride the accelerator, so without this axis it serializes across the
100 runs no matter how fast the chip is.

Design:

- ``spawn`` (never ``fork``): a forked child would inherit an initialized
  JAX backend and the tunnel transport state, which is unsafe and, during an
  outage, wedged. Each worker is a fresh interpreter that re-imports the
  package (the persistent XLA compilation cache makes re-compiles cheap).
- Work is a queue of model ids, not a pre-chunked split, so a slow run does
  not strand its worker's remaining ids behind it.
- Platform policy: the first ``local_chips`` workers inherit the parent's
  default backend (they get the accelerator); the rest are pinned to CPU
  with the jax.config binding (the env var alone loses to sitecustomize's
  plugin registration). On this deployment that means one accelerator
  worker + N-1 CPU workers; on a real multi-chip host, per-chip pinning can
  be expressed with ``TIP_WORKER_PLATFORMS`` (comma list cycled over
  workers, entries ``default`` or ``cpu``).
- Failures are per-model-id: a worker exception (or a worker death) marks
  that id failed and the scheduler raises ONE error at the end listing the
  failed ids. Artifacts are file-granular and idempotent, so re-running
  exactly the failed ids is safe — same restart contract as the reference's
  filesystem bus.
- Wedge recovery: workers announce each id before running it, so the
  scheduler knows what is in flight. An id that exceeds ``run_timeout_s``
  (default ``TIP_RUN_TIMEOUT_S``, 3600s) — the documented mid-run tunnel
  drop, where a device call blocks forever instead of erroring — gets its
  worker terminated and is requeued ONCE onto a freshly spawned CPU-pinned
  replacement worker; a second timeout marks the id failed. A worker that
  dies without reporting (segfault/OOM-kill) is handled the same way. This
  is the component's reason to exist on a box with multi-hour tunnel
  outages: the scheduler must never spin forever on a wedged-alive worker.
- Reproducibility note: with the chips-first platform policy, WHICH run id
  lands on the accelerator worker is queue-timing-dependent, so chip (bf16/
  f32) vs host (f64) numerics can differ run-to-run between invocations.
  Set ``TIP_WORKER_PLATFORMS=cpu`` for reproducibility-sensitive studies
  (see SCALING.md).
"""

import logging
import multiprocessing as mp
import os
import queue as queue_mod
import time
from typing import Dict, List, Optional, Set

from simple_tip_tpu import obs
from simple_tip_tpu.resilience import (
    LeaseLost,
    RetryPolicy,
    faults,
    journal_from_env,
)

logger = logging.getLogger(__name__)

# Grace added to run_timeout_s before presuming a silent worker pool wedged
# at startup: a fresh spawn pays interpreter + jax import (tens of seconds).
_STARTUP_GRACE_S = 120.0

# Device-memory poll period for the scheduler loop (TIP_OBS_MEMPOLL_S, 0
# disables): with telemetry on, the per-device peak_bytes_in_use gauges are
# sampled and flushed on this cadence, so the exported flame chart carries
# the memory high-water as a moving counter track instead of one
# end-of-phase value.
_DEFAULT_MEMPOLL_S = 30.0

# Consecutive wedged-journal probes before /healthz flips the journal
# component unhealthy. One failed non-blocking flock probe is ordinary
# contention with a fenced commit or a compaction; several in a row (at the
# ~1s health cadence) means a holder died or stalled with the lock held —
# the wedge /healthz exists to surface.
_JOURNAL_WEDGE_POLLS = 3

# Cadence of the health push + scheduler gauges while the phase loop runs.
_HEALTH_PUSH_S = 1.0

# Registered phase runners, by name so the spawn pickling stays trivial.
# Each maps (case_study_obj, [model_id], kwargs) -> None and must itself be
# single-process (num_workers forced to 1 inside the worker).


def _phase_test_prio(cs, ids, **kw):
    cs.run_prio_eval(ids, num_workers=1, **kw)


def _phase_active_learning(cs, ids, **kw):
    cs.run_active_learning_eval(ids, num_workers=1, **kw)


def _phase_at_collection(cs, ids, **kw):
    cs.collect_activations(ids, num_workers=1, **kw)


def _phase_test_sleep(
    cs,
    ids,
    seconds=0.5,
    marker_dir=None,
    fail_ids=(),
    barrier_n=0,
    barrier_timeout=120.0,
    **kw,
):
    """Scheduler-test phase: sleeps, records a [start, end] interval marker.

    Sleeping (not spinning) lets the concurrency-overlap test pass on a
    1-core host; ``fail_ids`` exercises the per-id failure path. With
    ``barrier_n`` > 0, the phase first rendezvouses until that many DISTINCT
    worker pids have arrived (filesystem barrier) — without real
    concurrency, one worker could drain the whole queue while the other is
    still paying interpreter startup, making interval overlap flaky.
    """
    for i in ids:
        if i in set(fail_ids):
            raise RuntimeError(f"synthetic failure for run {i}")
        if marker_dir and barrier_n:
            with open(os.path.join(marker_dir, f"arrived_{os.getpid()}"), "w"):
                pass
            deadline = time.time() + barrier_timeout
            while time.time() < deadline:
                arrived = [
                    f for f in os.listdir(marker_dir) if f.startswith("arrived_")
                ]
                if len(arrived) >= barrier_n:
                    break
                time.sleep(0.05)
        start = time.time()
        time.sleep(seconds)
        if marker_dir:
            with open(os.path.join(marker_dir, f"run_{i}.txt"), "w") as f:
                f.write(f"{start} {time.time()} {os.getpid()}")


def _phase_test_fault(cs, ids, marker_dir=None, plan=None, seconds=0.0, **kw):
    """Scheduler-chaos phase: run ids under an INLINE fault plan.

    The generalization the old ``_test_die``/``_test_wedge`` phases grew
    into (resilience/faults.py): ``plan`` is a fault-plan dict whose
    ``worker.run`` faults fire per id — ``die`` hard-exits the worker,
    ``wedge`` blocks until SIGTERM, ``error`` raises — with the
    cross-process ``times`` ledger (claim markers under ``marker_dir``)
    replacing the old hand-rolled first-attempt markers. Env-driven plans
    (``TIP_FAULT_PLAN``) fire at the ``_worker_main`` seam instead and
    need no special phase at all; this phase exists so tests and the
    chaos smoke can also write attempt/completion markers.
    """
    fault_plan = (
        faults.FaultPlan.from_obj(plan, state_dir=marker_dir) if plan else None
    )
    for i in ids:
        if marker_dir:
            with open(os.path.join(marker_dir, f"attempt_{i}"), "a") as f:
                f.write(f"{os.getpid()}\n")
        if fault_plan is not None:
            fault_plan.fire("worker.run", model_id=i)
        if seconds:
            time.sleep(seconds)
        if marker_dir:
            with open(os.path.join(marker_dir, f"run_{i}.txt"), "w") as f:
                f.write(f"{time.time()} {time.time()} {os.getpid()}")


def _phase_test_wedge(cs, ids, marker_dir=None, wedge_ids=(), always_wedge=False, **kw):
    """Compat shim over ``_test_fault``: the FIRST attempt at a
    ``wedge_ids`` id blocks far beyond any test timeout (a tunnel-outage
    stand-in — the call never returns, it must be terminated); the retry
    (requeued onto a fresh worker, which sees the spent fault claim)
    completes. ``always_wedge`` (``times: 0`` = unlimited) wedges every
    attempt — the both-attempts-dead path.
    """
    plan = {
        "faults": [
            {
                "site": "worker.run",
                "kind": "wedge",
                "match": {"model_id": list(wedge_ids)},
                "times": 0 if always_wedge else 1,
            }
        ]
    }
    _phase_test_fault(cs, ids, marker_dir=marker_dir, plan=plan, **kw)


def _phase_test_die(cs, ids, marker_dir=None, die_ids=(), **kw):
    """Compat shim over ``_test_fault``: the first attempt at a ``die_ids``
    id hard-exits the worker without reporting (segfault/OOM-kill
    stand-in); the requeued retry completes. The fault's ``delay_s``
    (default 0.5) lets the mp.Queue feeder flush the preceding done_q
    "start" put and RELEASE the shared write-lock semaphore before dying —
    ``os._exit`` mid-feeder-write would deadlock every sibling process on
    the orphaned lock (an mp.Queue property, not a scheduler bug).
    """
    plan = {
        "faults": [
            {
                "site": "worker.run",
                "kind": "die",
                "match": {"model_id": list(die_ids)},
                "times": 1,
                "delay_s": 0.5,
            }
        ]
    }
    _phase_test_fault(cs, ids, marker_dir=marker_dir, plan=plan, **kw)


PHASES = {
    "test_prio": _phase_test_prio,
    "active_learning": _phase_active_learning,
    "at_collection": _phase_at_collection,
    "_test_sleep": _phase_test_sleep,
    "_test_fault": _phase_test_fault,
    "_test_wedge": _phase_test_wedge,
    "_test_die": _phase_test_die,
}

#: Phases that never touch the case study or jax — workers skip the
#: backend pin, compilation cache and case-study construction for them, so
#: the scheduler (and the CI chaos smoke job) runs dependency-free.
_SYNTHETIC_PHASES = frozenset(p for p in PHASES if p.startswith("_test_"))


def _unit_members(unit) -> List[int]:
    """Model ids inside one work unit (scalar id, or a G-id group tuple)."""
    return list(unit) if isinstance(unit, (tuple, list)) else [unit]


def _worker_main(case_study, phase, work_q, done_q, stop_event, phase_kwargs, env_overrides):
    """Entry point of one spawned worker process."""
    os.environ.update(env_overrides)
    # Fresh interpreter, no logging config: without this, every logger.* in
    # the phase code (cache hits, watchdog fallbacks) is silently dropped.
    # Routes records to stderr with a [pid/worker-idx] prefix and — when
    # TIP_OBS_DIR is set — into this worker's obs event stream.
    obs.install_worker_logging()
    if phase in _SYNTHETIC_PHASES:
        # Synthetic/chaos phases never touch the case study or a backend:
        # skipping the jax imports keeps worker spawn cheap AND lets the
        # dependency-free CI chaos job exercise the real scheduler.
        cs = None
    else:
        if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
            # Make the CPU pin binding before any backend init: on
            # deployments whose sitecustomize pre-registers an accelerator
            # plugin the env var alone silently loses, and a wedged tunnel
            # then hangs the worker at its first device op.
            import jax

            jax.config.update("jax_platforms", "cpu")

        from simple_tip_tpu.casestudies.base import get_case_study
        from simple_tip_tpu.config import enable_compilation_cache

        enable_compilation_cache()
        # jax is imported (and the backend chosen) by the case-study
        # machinery above; count this worker's XLA compiles from here on.
        obs.install_jax_hooks()
        cs = get_case_study(case_study)
    fn = PHASES[phase]
    while True:
        try:
            # Blocking with timeout (NOT get_nowait): queue items travel
            # through a feeder thread, so an early get_nowait can see Empty
            # before already-put ids reach the pipe and silently strand them.
            # The stop event — set by the scheduler only once every id has
            # resolved — is the exit signal. A unit is either one model id
            # or a G-id group tuple (cross-run dispatch fusion): the phase
            # fn receives all its ids in ONE call so the grouped chain can
            # score them per-dispatch.
            unit = work_q.get(timeout=0.5)
        except queue_mod.Empty:
            if stop_event.is_set():
                # Explicit flush (not only atexit): the scheduler may
                # terminate() a worker that dallies at shutdown.
                obs.flush_metrics()
                return
            continue
        ids = _unit_members(unit)
        # Announce the claim so the scheduler can detect a wedged/killed
        # worker holding this unit and requeue it.
        done_q.put(("start", unit, os.getpid()))
        try:
            # Env-plan chaos seam: a TIP_FAULT_PLAN "worker.run" fault
            # kills, wedges or errors this attempt AFTER the claim is
            # announced — the shape of a real mid-run worker loss, for
            # any phase (error kinds report as per-id failures). Fired per
            # member so a plan matching any grouped id still triggers.
            for model_id in ids:
                faults.maybe_inject("worker.run", phase=phase, model_id=model_id)
            span_kw = (
                {"model_id": ids[0]} if len(ids) == 1 else {"model_ids": ids}
            )
            with obs.span(
                "run", phase=phase, case_study=case_study, **span_kw
            ):
                fn(cs, ids, **phase_kwargs)
            done_q.put(("done", unit, None))
        except (KeyboardInterrupt, SystemExit) as e:
            # Report the interrupted unit, then actually stop — an
            # interrupted worker must not keep draining the queue.
            done_q.put(("done", unit, repr(e)))
            obs.flush_metrics()
            raise
        except BaseException as e:  # noqa: BLE001 — reported; scheduler decides
            done_q.put(("done", unit, repr(e)))
        obs.record_device_memory()


def default_worker_platforms(num_workers: int, local_chips: int) -> List[str]:
    """Platform per worker: chips-first, CPU for the overflow workers.

    ``TIP_WORKER_PLATFORMS`` (comma list of ``default``/``cpu``, cycled)
    overrides the policy, e.g. for per-chip pinning setups.
    """
    override = os.environ.get("TIP_WORKER_PLATFORMS", "").strip()
    if override:
        entries = [e.strip() for e in override.split(",") if e.strip()]
        return [entries[i % len(entries)] for i in range(num_workers)]
    n_accel = min(max(local_chips, 0), num_workers)
    return ["default"] * n_accel + ["cpu"] * (num_workers - n_accel)


def run_phase_parallel(
    case_study: str,
    phase: str,
    model_ids: List[int],
    num_workers: int,
    phase_kwargs: Optional[Dict] = None,
    worker_platforms: Optional[List[str]] = None,
    run_timeout_s: Optional[float] = None,
    fleet=None,
    group_size: int = 1,
) -> None:
    """Run ``phase`` for ``model_ids`` across ``num_workers`` processes.

    ``group_size > 1`` makes the work unit a TUPLE of up to G model ids
    instead of a single id (cross-run dispatch fusion: the phase fn gets
    all of a unit's ids in one call, so the grouped chain runner scores
    them per-dispatch). Journaling, fencing and the failure report stay at
    MODEL granularity: journaled members are filtered out BEFORE units are
    formed — a resumed phase replays only a group's unjournaled members —
    and in fleet mode each member carries its own lease/fence token, so a
    lost lease discards exactly that member's commit, never the group's.

    ``run_timeout_s`` bounds one id's attempt on one worker (default env
    ``TIP_RUN_TIMEOUT_S``, 3600): past it the worker is presumed wedged in a
    dead device call, gets terminated, and the id is requeued once onto a
    fresh CPU-pinned worker. Raises ``RuntimeError`` at the end if any id
    failed, naming every failed id and its error; completed ids keep their
    artifacts either way.

    ``fleet`` (a :class:`~simple_tip_tpu.parallel.fleet.FleetContext`)
    switches the claim path onto file-backed leases so MULTIPLE host-level
    schedulers can share one phase: ids are enqueued only after this host
    wins their lease (late joiners steal expired leases), in-flight leases
    are renewed every tick, completions commit through the journal with a
    fencing token (a host whose lease was stolen cannot double-commit),
    failures are released for cross-host retry up to the fleet attempt
    budget (``TIP_RETRY_FLEET_*``), and ids finished or failed on OTHER
    hosts count toward completion. Fleet mode requires a journal (pin
    ``TIP_ASSETS`` or ``TIP_JOURNAL``) — it is the single commit point.
    """
    if phase not in PHASES:
        raise ValueError(f"unknown phase {phase!r}; one of {sorted(PHASES)}")
    if run_timeout_s is None:
        run_timeout_s = float(os.environ.get("TIP_RUN_TIMEOUT_S", "3600"))
    phase_kwargs = dict(phase_kwargs or {})

    # Journaled resume (resilience/journal.py): ids already journaled as
    # completed for this (case study, phase) are skipped outright — a
    # restarted study pays nothing for finished runs and rides the
    # restart-safe SAFitCache/artifact bus back to warm state. Off unless
    # the bus is pinned (TIP_ASSETS) or TIP_JOURNAL names a path.
    journal = journal_from_env(case_study, phase)
    if fleet is not None and journal is None:
        raise ValueError(
            "fleet execution requires a journal as the commit point: pin "
            "TIP_ASSETS or set TIP_JOURNAL to a shared path"
        )
    already_done = journal.completed() if journal is not None else set()
    skipped = [m for m in model_ids if m in already_done]
    pending = [m for m in model_ids if m not in already_done]
    if skipped:
        logger.info(
            "[%s] %s: skipping %d/%d runs already journaled as complete "
            "(journal: %s; delete it to force re-runs)",
            case_study, phase, len(skipped), len(model_ids), journal.path,
        )
        obs.counter("scheduler.journal_skips").inc(len(skipped))
        for m in skipped:
            obs.event("scheduler.skip_journaled", model_id=m, phase=phase)

    # Group units form AFTER the journal filter: a resumed mid-group run
    # re-chunks only the unjournaled members (exactly-once at model
    # granularity — acceptance-pinned in tests/test_run_scheduler.py).
    group_size = max(1, int(group_size))
    if group_size > 1:
        units = [
            tuple(pending[i : i + group_size])
            for i in range(0, len(pending), group_size)
        ]
    else:
        units = list(pending)

    num_workers = max(1, min(num_workers, max(1, len(units))))
    if worker_platforms is None:
        worker_platforms = ["default"] * num_workers

    # Requeue budget from the unified retry policy (resilience/retry.py):
    # attempts=2 keeps the historical contract (one requeue onto a fresh
    # CPU-pinned worker, then fail). Only the scoped TIP_RETRY_SCHED_*
    # knobs tune it (inherit=False): requeues cost a whole run_timeout_s
    # each, so a blanket TIP_RETRY_ATTEMPTS bump for cache/probe IO must
    # not silently multiply hour-long wedge retries. Under a fleet the
    # budget is promoted to host scope (TIP_RETRY_FLEET_*): local requeues
    # AND cross-host lease epochs draw from the same attempt contract.
    max_requeues = (
        RetryPolicy.from_env(
            scope="fleet" if fleet is not None else "sched",
            inherit=False,
            attempts=2,
        ).attempts
        - 1
    )

    # Resolve the obs run directory BEFORE any spawn: an ``auto``
    # TIP_OBS_DIR pins itself into os.environ here, so every worker (which
    # inherits the parent environment) appends into the SAME run directory
    # and the streams merge across the spawn boundary.
    obs.enabled()
    # Live telemetry plane (obs v4): serve /healthz + /metrics from THIS
    # process while the phase runs. No-op unless TIP_OBS_HTTP is set. The
    # endpoint handlers read only in-memory state, so every filesystem-
    # backed health input (breaker state file, journal flock) is polled
    # HERE, on the scheduler loop's cadence, and pushed in.
    from simple_tip_tpu.obs import alerts as alerts_mod
    from simple_tip_tpu.obs import exporter
    from simple_tip_tpu.resilience.breaker import CircuitBreaker

    http_port = exporter.start()
    health_breaker = CircuitBreaker.from_env() if http_port is not None else None
    # Admission control (obs v3): quote the cost model's wall-clock estimate
    # for this phase before launching, and stamp predicted_s next to the
    # span's eventual actual_s so every executed study grades (and feeds)
    # the corpus. Advisory by contract — no index, no estimate, no change.
    from simple_tip_tpu.obs import costmodel as _costmodel

    # An active ExecutionPlan outranks the live fit: its stored per-phase
    # prediction is what the planner chose the knobs AGAINST, so stamping
    # it as predicted_s makes `obs audit` grade the PLAN, not a fresher
    # model the plan never saw. The plan id rides the span for the same
    # reason — the feature store turns it into a per-plan column.
    from simple_tip_tpu import plan as _plan

    estimate = _plan.phase_estimate(phase, len(pending), workers=num_workers)
    if estimate is None:
        estimate = _costmodel.quick_phase_estimate(
            phase, len(pending), workers=num_workers
        )
    predicted = {}
    if _plan.active_plan() is not None:
        predicted["plan"] = _plan.active_plan_id()
    if estimate is not None:
        predicted["predicted_s"] = estimate["predicted_s"]
        logger.info(
            "[%s] %s: %s predicts %.1fs (+/- %.1fs, basis=%s, "
            "corpus=%s rows) for %d runs on %d workers",
            case_study, phase,
            "plan" if estimate.get("basis") == "plan" else "cost model",
            estimate["predicted_s"],
            estimate.get("error_s") or 0.0, estimate.get("basis"),
            estimate.get("corpus_rows"), len(pending), num_workers,
        )
    from simple_tip_tpu.engine.run_program import fused_chain_enabled

    phase_span = obs.span(
        "scheduler.phase", phase=phase, case_study=case_study,
        runs=len(model_ids), workers=num_workers,
        journal_skipped=len(skipped),
        fused_chain=fused_chain_enabled(), **predicted,
    )
    phase_span.__enter__()
    phase_started = time.perf_counter()

    ctx = mp.get_context("spawn")
    work_q = ctx.Queue()
    # Retries ride a SEPARATE queue read only by the CPU-pinned replacement
    # workers: putting a retry back on the shared queue would let an idle
    # default-platform worker — possibly on the same dead tunnel — steal it
    # and wedge again, burning the id's retry budget.
    retry_q = ctx.Queue()
    done_q = ctx.Queue()
    stop_event = ctx.Event()
    if fleet is None:
        for u in units:
            work_q.put(u)
            for m in _unit_members(u):
                obs.event("scheduler.announce", model_id=m, phase=phase)
    # Fleet mode enqueues nothing up front: an id reaches work_q only once
    # THIS host wins its lease (see _fleet_tick below), so two members
    # sharing a phase partition the ids instead of both running all of them.

    workers: List = []
    worker_queue: Dict[int, object] = {}  # pid -> the queue that worker reads

    def _spawn(platform: str, queue=work_q):
        env = {"JAX_PLATFORMS": "cpu"} if platform == "cpu" else {}
        # Stamp the worker's stream identity: index + platform land in the
        # child's meta event and its stderr log prefix.
        env["TIP_OBS_WORKER"] = str(len(workers))
        env["TIP_OBS_PLATFORM"] = platform
        w = ctx.Process(
            target=_worker_main,
            args=(case_study, phase, queue, done_q, stop_event, phase_kwargs, env),
            daemon=True,
        )
        w.start()
        workers.append(w)
        worker_queue[w.pid] = queue
        return w

    if pending:
        for i in range(num_workers):
            _spawn(worker_platforms[i % len(worker_platforms)])
    logger.info(
        "[%s] %s: %d runs (%d journal-skipped) across %d workers "
        "(platforms: %s, run timeout %.0fs)",
        case_study,
        phase,
        len(pending),
        len(skipped),
        num_workers,
        worker_platforms[:num_workers],
        run_timeout_s,
    )

    # Journal-skipped ids are pre-resolved successes; everything below
    # (the progress loop, the final failure report) sees them as done.
    results: Dict[int, Optional[str]] = {m: None for m in skipped}
    in_flight: Dict = {}  # unit (id or id-tuple) -> {"pid", "deadline"}
    requeues: Dict = {}  # unit -> requeue count so far

    # Fleet-mode state. ``claimed`` holds the fence token for every id whose
    # lease THIS host currently owns (renewed each tick, presented at the
    # journal commit). ``done_elsewhere``/``failed_elsewhere`` are ids some
    # OTHER member resolved — they count toward completion here without
    # ever entering ``results``.
    claimed: Dict[int, object] = {}
    done_elsewhere: Set[int] = set()
    failed_elsewhere: Dict[int, str] = {}

    def _outstanding() -> List[int]:
        """Ids nobody (here or elsewhere) has resolved yet."""
        return [
            m
            for m in model_ids
            if m not in results
            and m not in done_elsewhere
            and m not in failed_elsewhere
        ]

    _wedge_polls = [0]  # consecutive wedged-journal probes (debounced)

    def _push_health() -> None:
        """Poll the filesystem-backed health inputs, refresh the live
        scheduler gauges, run one alert-evaluator tick, and push the
        health components into the exporter. Runs on the scheduler loop
        (``_HEALTH_PUSH_S`` cadence) so HTTP handler threads never touch
        the breaker state file or the journal flock themselves — and the
        SLO evaluator (obs/alerts.py) rides the same cadence, with or
        without a live exporter to publish on."""
        breaker_ok = True
        if health_breaker is not None:
            breaker_ok = health_breaker.healthy()
            obs.gauge("breaker.open").set(0 if breaker_ok else 1)
        outstanding = len(_outstanding())
        obs.gauge("scheduler.in_flight").set(len(in_flight))
        obs.gauge("scheduler.outstanding").set(outstanding)
        alerts_mod.tick()
        if http_port is None:
            return
        if health_breaker is not None:
            exporter.set_health(
                "breaker", ok=breaker_ok, **health_breaker.snapshot()
            )
        if journal is not None:
            _wedge_polls[0] = _wedge_polls[0] + 1 if journal.wedged() else 0
            exporter.set_health(
                "journal",
                ok=_wedge_polls[0] < _JOURNAL_WEDGE_POLLS,
                wedged_polls=_wedge_polls[0],
                path=journal.path,
            )
        exporter.set_health(
            "scheduler", ok=True, phase=phase, case_study=case_study,
            outstanding=outstanding, in_flight=len(in_flight),
            workers_alive=sum(1 for w in workers if w.is_alive()),
        )

    def _fleet_tick() -> None:
        """One fleet housekeeping pass: heartbeat + coordinator duties,
        refresh the elsewhere view, claim unowned ids, renew held leases."""
        if fleet is None:
            return
        fleet.tick(workers)
        done_else, failed_else = fleet.elsewhere()
        for m in done_else:
            if m not in results and m not in claimed:
                done_elsewhere.add(m)
        for m, err in failed_else.items():
            if m not in results and m not in claimed and m not in done_elsewhere:
                failed_elsewhere[m] = err
        new_claims: List[int] = []
        for m in pending:
            if (
                m in results
                or m in claimed
                or m in done_elsewhere
                or m in failed_elsewhere
            ):
                continue
            tok = fleet.try_claim(m)
            if tok is None:
                continue  # leased to (or failed on) another member
            claimed[m] = tok
            new_claims.append(m)
        # Chunk this tick's winnings into group units (ragged tail flushes
        # same tick — every sweep covers all pending ids, so holding a
        # partial group back could strand it). Each member keeps its OWN
        # fence token; only the dispatch unit is grouped.
        for i in range(0, len(new_claims), group_size):
            chunk = new_claims[i : i + group_size]
            work_q.put(tuple(chunk) if group_size > 1 else chunk[0])
            for m in chunk:
                obs.event("scheduler.announce", model_id=m, phase=phase)
        for m, tok in list(claimed.items()):
            if m in results:
                continue
            try:
                fleet.renew(tok)
            except LeaseLost:
                # Stolen mid-run (our lease expired, or a straggler
                # speculation re-leased it). Keep the claim entry: the
                # fenced journal commit — not this loop — decides whether
                # our in-progress attempt still counts.
                obs.counter("lease.lost_renewals").inc()

    def _handle(msg) -> None:
        kind, unit, payload = msg
        if kind == "start":
            # Deadlines ride the monotonic clock: an NTP step mid-run must
            # not fire (or indefinitely defer) a wedge timeout.
            in_flight[unit] = {
                "pid": payload,
                "deadline": time.monotonic() + run_timeout_s,
            }
            for model_id in _unit_members(unit):
                obs.event(
                    "scheduler.start", model_id=model_id, phase=phase,
                    worker_pid=payload,
                )
            return
        in_flight.pop(unit, None)
        # A unit reports once, but members RESOLVE individually: journal
        # marks, fence commits and the failure report all stay at model
        # granularity so grouped dispatch never widens the exactly-once
        # unit.
        for model_id in _unit_members(unit):
            if model_id in results:
                continue  # late duplicate after a requeue race; first wins
            if fleet is not None:
                if payload is None:
                    # Fenced commit: the journal is the single commit
                    # point. A host whose lease was stolen mid-run (expired
                    # while wedged, speculative re-lease of a straggler) is
                    # rejected HERE — its finished work is discarded, the
                    # stealer's commit stands, and every member lands in
                    # the journal exactly once. Only THIS member's commit
                    # is discarded; its group-mates' leases stand on their
                    # own tokens.
                    tok = claimed.pop(model_id, None)
                    try:
                        if tok is None:
                            raise LeaseLost(
                                f"no live lease held for run {model_id}"
                            )
                        journal.mark_done(model_id, fence=tok)
                    except LeaseLost as e:
                        obs.counter("lease.fence_rejects").inc()
                        obs.event(
                            "scheduler.fence_reject", model_id=model_id,
                            phase=phase, error=str(e)[:200],
                        )
                        logger.warning(
                            "[%s] %s: run %d finished but its lease was "
                            "lost (%s); discarding — the stealing host owns "
                            "this unit",
                            case_study, phase, model_id, e,
                        )
                        continue
                    fleet.release(tok)
                    results[model_id] = None
                    logger.info(
                        "[%s] %s: run %d done", case_study, phase, model_id
                    )
                    obs.event("scheduler.done", model_id=model_id, phase=phase)
                else:
                    tok = claimed.pop(model_id, None)
                    final = fleet.report_failure(model_id, tok, str(payload))
                    if final is not None:
                        results[model_id] = final
                        logger.error(
                            "[%s] %s: run %d FAILED fleet-wide: %s",
                            case_study, phase, model_id, final,
                        )
                        obs.event(
                            "scheduler.fail", model_id=model_id, phase=phase,
                            error=str(final)[:300],
                        )
                    else:
                        logger.warning(
                            "[%s] %s: run %d failed here (%s); lease "
                            "released for retry on another member",
                            case_study, phase, model_id, payload,
                        )
                        obs.event(
                            "scheduler.release_retry", model_id=model_id,
                            phase=phase, error=str(payload)[:200],
                        )
                continue
            results[model_id] = payload
            if payload is None:
                logger.info("[%s] %s: run %d done", case_study, phase, model_id)
                obs.event("scheduler.done", model_id=model_id, phase=phase)
                if journal is not None:
                    journal.mark_done(model_id)
            else:
                logger.error(
                    "[%s] %s: run %d FAILED: %s",
                    case_study, phase, model_id, payload,
                )
                obs.event(
                    "scheduler.fail", model_id=model_id, phase=phase,
                    error=str(payload)[:300],
                )

    def _reap_stuck() -> None:
        """Terminate wedged/dead workers holding a unit; requeue once to CPU.

        A unit is reaped and requeued WHOLE (its members resolve together on
        a worker), but the give-up path and fleet failure reporting stay
        per member."""
        now = time.monotonic()
        by_pid = {w.pid: w for w in workers}
        for unit, info in list(in_flight.items()):
            members = _unit_members(unit)
            w = by_pid.get(info["pid"])
            worker_dead = w is not None and not w.is_alive()
            if now <= info["deadline"] and not worker_dead:
                continue
            reason = (
                "worker died mid-run"
                if worker_dead
                else f"no result after {run_timeout_s:.0f}s (wedged device call?)"
            )
            obs.counter(
                "scheduler.worker_deaths" if worker_dead else "scheduler.timeouts"
            ).inc()
            if w is not None and w.is_alive():
                logger.error(
                    "[%s] %s: run(s) %s %s — terminating worker pid %s",
                    case_study, phase, members, reason, w.pid,
                )
                w.terminate()
            in_flight.pop(unit, None)
            # A reaped work_q worker leaves the main pool one short; without a
            # replacement, still-unclaimed ids on work_q would strand behind
            # the stall timeout (or be abandoned outright on a 1-worker pool).
            outstanding = len(_outstanding()) - sum(
                len(_unit_members(u)) for u in in_flight
            )
            if w is not None and worker_queue.get(w.pid) is work_q and outstanding > 1:
                _spawn("cpu")  # reads work_q
            if all(m in results for m in members):
                continue  # a first attempt already reported; nothing to redo
            n = requeues.get(unit, 0)
            if n >= max_requeues:
                for model_id in members:
                    if model_id in results:
                        continue
                    if fleet is not None:
                        # Local budget spent: hand the member back to the
                        # fleet. Another host retries it (or it fails
                        # fleet-wide once the shared attempt budget is gone).
                        tok = claimed.pop(model_id, None)
                        final = fleet.report_failure(model_id, tok, reason)
                        if final is not None:
                            results[model_id] = final
                            logger.error(
                                "[%s] %s: run %d FAILED fleet-wide: %s",
                                case_study, phase, model_id, final,
                            )
                        else:
                            logger.warning(
                                "[%s] %s: run %d local requeues spent (%s); "
                                "lease released for retry on another member",
                                case_study, phase, model_id, reason,
                            )
                            obs.event(
                                "scheduler.release_retry", model_id=model_id,
                                phase=phase, error=reason[:200],
                            )
                        continue
                    spent = "once" if n == 1 else f"{n} times"
                    results[model_id] = (
                        f"{reason}; already requeued {spent} — giving up"
                    )
                    logger.error(
                        "[%s] %s: run %d failed after %d requeue(s)",
                        case_study, phase, model_id, n,
                    )
            else:
                requeues[unit] = n + 1
                logger.warning(
                    "[%s] %s: requeueing run(s) %s onto a fresh CPU-pinned "
                    "worker (%s; attempt %d/%d)",
                    case_study, phase, members, reason, n + 2, max_requeues + 1,
                )
                obs.counter("scheduler.requeues").inc()
                for model_id in members:
                    obs.event(
                        "scheduler.requeue", model_id=model_id, phase=phase,
                        reason=reason,
                    )
                retry_q.put(unit)
                _spawn("cpu", queue=retry_q)

    # A worker can also wedge BEFORE claiming anything (tunnel drops during
    # its jax/plugin init): then in_flight stays empty and no per-id deadline
    # exists. Track overall progress; past the stall threshold with nothing
    # in flight, replace the whole stuck pool with CPU-pinned workers once.
    # The threshold includes a startup grace on top of run_timeout_s so a
    # small test timeout does not misread normal interpreter+jax startup
    # (seconds to tens of seconds) as a wedged pool.
    stall_timeout_s = run_timeout_s + _STARTUP_GRACE_S
    last_progress = time.monotonic()
    startup_rescued = False
    mempoll_s = float(os.environ.get("TIP_OBS_MEMPOLL_S", str(_DEFAULT_MEMPOLL_S)))
    last_mempoll = time.monotonic()
    _push_health()  # seed /healthz before the first loop iteration
    last_health = time.monotonic()

    while _outstanding():
        _fleet_tick()
        # Runs whether or not the exporter is live: _push_health gates the
        # HTTP pushes itself, and the alert evaluator rides this cadence.
        if time.monotonic() - last_health >= _HEALTH_PUSH_S:
            last_health = time.monotonic()
            _push_health()
        if (
            mempoll_s > 0
            and obs.enabled()
            and time.monotonic() - last_mempoll >= mempoll_s
        ):
            last_mempoll = time.monotonic()
            obs.poll_device_memory()
        try:
            _handle(done_q.get(timeout=1.0))
            last_progress = time.monotonic()
            continue
        except queue_mod.Empty:
            pass
        _reap_stuck()
        if in_flight:
            last_progress = time.monotonic()  # per-id deadlines own this case
        elif fleet is not None and not claimed:
            # Every unresolved id is leased to another member: waiting on
            # the fleet to finish (or on an expiry we can steal) is
            # progress, not a local stall.
            last_progress = time.monotonic()
        elif time.monotonic() - last_progress > stall_timeout_s:
            alive = [w for w in workers if w.is_alive()]
            if alive and not startup_rescued:
                logger.error(
                    "[%s] %s: no worker claimed any run for %.0fs — presuming "
                    "the pool wedged at startup; replacing with CPU-pinned "
                    "workers",
                    case_study, phase, stall_timeout_s,
                )
                for w in alive:
                    w.terminate()
                startup_rescued = True
                for _ in range(min(num_workers, len(model_ids) - len(results))):
                    _spawn("cpu")
                last_progress = time.monotonic()
            elif alive:
                logger.error(
                    "[%s] %s: CPU replacement pool also made no progress for "
                    "%.0fs — giving up",
                    case_study, phase, stall_timeout_s,
                )
                break
        if not any(w.is_alive() for w in workers) and not in_flight:
            # Final drain, then give up: nobody is left to produce results.
            while True:
                try:
                    _handle(done_q.get_nowait())
                except queue_mod.Empty:
                    break
            if _outstanding():
                break

    stop_event.set()
    for w in workers:
        w.join(timeout=30)
        if w.is_alive():  # pragma: no cover — wedged worker (dead tunnel)
            logger.error("worker pid %s wedged at shutdown; terminating", w.pid)
            w.terminate()

    if fleet is not None:
        # Clean leaver: requeue any claim we still hold so surviving members
        # pick those ids up immediately instead of waiting out the lease TTL.
        for m, tok in list(claimed.items()):
            if m in results:
                continue
            try:
                fleet.release(tok)
            except Exception:  # noqa: BLE001 — best-effort; expiry is the backstop
                pass
        claimed.clear()

    span_extra = (
        dict(
            done_elsewhere=len(done_elsewhere),
            failed_elsewhere=len(failed_elsewhere),
        )
        if fleet is not None
        else {}
    )
    phase_span.set(
        completed=sum(1 for e in results.values() if e is None),
        failed=sum(1 for e in results.values() if e is not None),
        actual_s=round(time.perf_counter() - phase_started, 3),
        **span_extra,
    ).__exit__(None, None, None)
    # Final high-water sample even for phases shorter than the poll period.
    if obs.enabled():
        obs.record_device_memory()
    obs.flush_metrics()
    _push_health()  # terminal state: outstanding=0 (or the failure counts)

    failed = {m: e for m, e in results.items() if e is not None}
    failed.update(failed_elsewhere)
    missing = [
        m
        for m in model_ids
        if m not in results and m not in done_elsewhere and m not in failed_elsewhere
    ]
    if failed or missing:
        parts = [f"run {m}: {e}" for m, e in sorted(failed.items())]
        parts += [f"run {m}: worker died without reporting" for m in missing]
        raise RuntimeError(
            f"{phase} failed for {len(parts)}/{len(model_ids)} runs "
            f"(completed runs kept their artifacts; re-run the failed ids): "
            + "; ".join(parts)
        )

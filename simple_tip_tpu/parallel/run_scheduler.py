"""Run-level host parallelism: worker processes over model ids.

TPU-native counterpart of the reference's LazyEnsemble process scheduler
(reference: src/dnn_test_prio/case_study.py:87-109, which forks
``num_processes`` workers, each loading model ``i`` from disk and running a
picklable per-model function). The host-bound half of the prio/AL phases —
float64 KDE fit/eval for LSA, KMeans+silhouette for pc-mmdsa, artifact IO —
does not ride the accelerator, so without this axis it serializes across the
100 runs no matter how fast the chip is.

Design:

- ``spawn`` (never ``fork``): a forked child would inherit an initialized
  JAX backend and the tunnel transport state, which is unsafe and, during an
  outage, wedged. Each worker is a fresh interpreter that re-imports the
  package (the persistent XLA compilation cache makes re-compiles cheap).
- Work is a queue of model ids, not a pre-chunked split, so a slow run does
  not strand its worker's remaining ids behind it.
- Platform policy: the first ``local_chips`` workers inherit the parent's
  default backend (they get the accelerator); the rest are pinned to CPU
  with the jax.config binding (the env var alone loses to sitecustomize's
  plugin registration). On this deployment that means one accelerator
  worker + N-1 CPU workers; on a real multi-chip host, per-chip pinning can
  be expressed with ``TIP_WORKER_PLATFORMS`` (comma list cycled over
  workers, entries ``default`` or ``cpu``).
- Failures are per-model-id: a worker exception (or a worker death) marks
  that id failed and the scheduler raises ONE error at the end listing the
  failed ids. Artifacts are file-granular and idempotent, so re-running
  exactly the failed ids is safe — same restart contract as the reference's
  filesystem bus.
"""

import logging
import multiprocessing as mp
import os
import queue as queue_mod
import time
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

# Registered phase runners, by name so the spawn pickling stays trivial.
# Each maps (case_study_obj, [model_id], kwargs) -> None and must itself be
# single-process (num_workers forced to 1 inside the worker).


def _phase_test_prio(cs, ids, **kw):
    cs.run_prio_eval(ids, num_workers=1, **kw)


def _phase_active_learning(cs, ids, **kw):
    cs.run_active_learning_eval(ids, num_workers=1, **kw)


def _phase_at_collection(cs, ids, **kw):
    cs.collect_activations(ids, num_workers=1, **kw)


def _phase_test_sleep(
    cs,
    ids,
    seconds=0.5,
    marker_dir=None,
    fail_ids=(),
    barrier_n=0,
    barrier_timeout=120.0,
    **kw,
):
    """Scheduler-test phase: sleeps, records a [start, end] interval marker.

    Sleeping (not spinning) lets the concurrency-overlap test pass on a
    1-core host; ``fail_ids`` exercises the per-id failure path. With
    ``barrier_n`` > 0, the phase first rendezvouses until that many DISTINCT
    worker pids have arrived (filesystem barrier) — without real
    concurrency, one worker could drain the whole queue while the other is
    still paying interpreter startup, making interval overlap flaky.
    """
    for i in ids:
        if i in set(fail_ids):
            raise RuntimeError(f"synthetic failure for run {i}")
        if marker_dir and barrier_n:
            with open(os.path.join(marker_dir, f"arrived_{os.getpid()}"), "w"):
                pass
            deadline = time.time() + barrier_timeout
            while time.time() < deadline:
                arrived = [
                    f for f in os.listdir(marker_dir) if f.startswith("arrived_")
                ]
                if len(arrived) >= barrier_n:
                    break
                time.sleep(0.05)
        start = time.time()
        time.sleep(seconds)
        if marker_dir:
            with open(os.path.join(marker_dir, f"run_{i}.txt"), "w") as f:
                f.write(f"{start} {time.time()} {os.getpid()}")


PHASES = {
    "test_prio": _phase_test_prio,
    "active_learning": _phase_active_learning,
    "at_collection": _phase_at_collection,
    "_test_sleep": _phase_test_sleep,
}


def _worker_main(case_study, phase, work_q, done_q, phase_kwargs, env_overrides):
    """Entry point of one spawned worker process."""
    os.environ.update(env_overrides)
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        # Make the CPU pin binding before any backend init: on deployments
        # whose sitecustomize pre-registers an accelerator plugin the env
        # var alone silently loses, and a wedged tunnel then hangs the
        # worker at its first device op.
        import jax

        jax.config.update("jax_platforms", "cpu")

    from simple_tip_tpu.casestudies.base import get_case_study
    from simple_tip_tpu.config import enable_compilation_cache

    enable_compilation_cache()
    cs = get_case_study(case_study)
    fn = PHASES[phase]
    while True:
        try:
            model_id = work_q.get_nowait()
        except queue_mod.Empty:
            return
        try:
            fn(cs, [model_id], **phase_kwargs)
            done_q.put((model_id, None))
        except BaseException as e:  # noqa: BLE001 — reported, then re-queued by caller
            done_q.put((model_id, repr(e)))


def default_worker_platforms(num_workers: int, local_chips: int) -> List[str]:
    """Platform per worker: chips-first, CPU for the overflow workers.

    ``TIP_WORKER_PLATFORMS`` (comma list of ``default``/``cpu``, cycled)
    overrides the policy, e.g. for per-chip pinning setups.
    """
    override = os.environ.get("TIP_WORKER_PLATFORMS", "").strip()
    if override:
        entries = [e.strip() for e in override.split(",") if e.strip()]
        return [entries[i % len(entries)] for i in range(num_workers)]
    n_accel = min(max(local_chips, 0), num_workers)
    return ["default"] * n_accel + ["cpu"] * (num_workers - n_accel)


def run_phase_parallel(
    case_study: str,
    phase: str,
    model_ids: List[int],
    num_workers: int,
    phase_kwargs: Optional[Dict] = None,
    worker_platforms: Optional[List[str]] = None,
) -> None:
    """Run ``phase`` for ``model_ids`` across ``num_workers`` processes.

    Raises ``RuntimeError`` at the end if any id failed, naming every failed
    id and its error; completed ids keep their artifacts either way.
    """
    if phase not in PHASES:
        raise ValueError(f"unknown phase {phase!r}; one of {sorted(PHASES)}")
    num_workers = max(1, min(num_workers, len(model_ids)))
    if worker_platforms is None:
        worker_platforms = ["default"] * num_workers
    phase_kwargs = dict(phase_kwargs or {})

    ctx = mp.get_context("spawn")
    work_q = ctx.Queue()
    done_q = ctx.Queue()
    for m in model_ids:
        work_q.put(m)

    workers = []
    for i in range(num_workers):
        env = {}
        if worker_platforms[i % len(worker_platforms)] == "cpu":
            env["JAX_PLATFORMS"] = "cpu"
        w = ctx.Process(
            target=_worker_main,
            args=(case_study, phase, work_q, done_q, phase_kwargs, env),
            daemon=True,
        )
        w.start()
        workers.append(w)
    logger.info(
        "[%s] %s: %d runs across %d workers (platforms: %s)",
        case_study,
        phase,
        len(model_ids),
        num_workers,
        worker_platforms[:num_workers],
    )

    results: Dict[int, Optional[str]] = {}
    while len(results) < len(model_ids):
        try:
            model_id, err = done_q.get(timeout=5.0)
            results[model_id] = err
            if err is None:
                logger.info("[%s] %s: run %d done", case_study, phase, model_id)
            else:
                logger.error("[%s] %s: run %d FAILED: %s", case_study, phase, model_id, err)
        except queue_mod.Empty:
            if not any(w.is_alive() for w in workers):
                break  # a worker died without reporting (e.g. segfault/OOM-kill)
    for w in workers:
        w.join(timeout=30)
        if w.is_alive():  # pragma: no cover — wedged worker (dead tunnel)
            logger.error("worker pid %s wedged; terminating", w.pid)
            w.terminate()

    failed = {m: e for m, e in results.items() if e is not None}
    missing = [m for m in model_ids if m not in results]
    if failed or missing:
        parts = [f"run {m}: {e}" for m, e in sorted(failed.items())]
        parts += [f"run {m}: worker died without reporting" for m in missing]
        raise RuntimeError(
            f"{phase} failed for {len(parts)}/{len(model_ids)} runs "
            f"(completed runs kept their artifacts; re-run the failed ids): "
            + "; ".join(parts)
        )

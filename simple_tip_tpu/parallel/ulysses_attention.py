"""Ulysses-style all-to-all sequence/context-parallel attention.

The complementary long-context strategy to ring attention
(parallel/ring_attention.py): instead of rotating K/V blocks around a ring,
two ``all_to_all`` collectives re-shard the tensors between a
*sequence-sharded* layout and a *head-sharded* layout (DeepSpeed-Ulysses
pattern):

1. inputs arrive sharded over the sequence axis — each device holds
   ``[batch, seq/n, heads, head_dim]``;
2. an all-to-all scatters the head axis and gathers the sequence axis, so
   each device holds the FULL sequence for ``heads/n`` heads;
3. plain dense attention runs locally per head group (heads are independent
   in multi-head attention, so this is exact, not an approximation);
4. the inverse all-to-all restores the sequence-sharded layout.

Trade-off vs the ring: Ulysses does 2 all-to-alls of the whole Q/K/V/O
tensors (cheap on a TPU torus where all-to-all rides ICI) and then needs NO
communication inside the softmax, while the ring does ``n`` neighbor
ppermutes of K/V interleaved with compute. Ulysses requires
``num_heads % n == 0``; the ring has no head constraint but serializes the
softmax over ``n`` steps. Both are exact; which is faster depends on
seq_len/heads/mesh — this framework ships both behind one model switch
(models/transformer.py ``attention_impl``).

Because each device sees the FULL gathered sequence after the all-to-all,
the local core defaults to the Pallas flash kernel on TPU — a dense local
softmax would materialize the [T, T] score matrix in HBM and OOM at exactly
the lengths ulysses exists for (SCALING.md: dense dies at seq 8k on v5e).

The reference has no long-context machinery at all (max seq len 100,
SURVEY.md section 5); this subsystem is TPU-native new capability.
"""

import functools

import jax
import numpy as np
from jax.sharding import Mesh


def check_ulysses_divisibility(seq_len: int, num_heads: int, n_dev: int) -> None:
    """Reject shapes the head-scatter / seq-gather cannot split evenly.

    Like the ring's divisibility guard, failing loudly here avoids silent
    shard padding that would corrupt the softmax normalizer."""
    if seq_len % n_dev != 0:
        raise ValueError(
            f"ulysses attention requires the sequence length ({seq_len}) to be "
            f"divisible by the sequence-parallel mesh size ({n_dev})"
        )
    if num_heads % n_dev != 0:
        raise ValueError(
            f"ulysses attention requires the head count ({num_heads}) to be "
            f"divisible by the sequence-parallel mesh size ({n_dev}); use ring "
            f"attention (no head constraint) for this mesh"
        )


def ulysses_attention(
    q, k, v, axis_name: str, local_core: str = "auto", interpret: bool = False
):
    """Exact attention with sequence-sharded inputs via two all-to-alls.

    Shapes (per device): q/k/v = [batch, seq_local, heads, head_dim].
    Returns [batch, seq_local, heads, head_dim] (same sharded layout).
    Must run inside shard_map/pmap with ``axis_name`` bound.

    ``local_core`` selects the per-device attention over the gathered (full)
    sequence: "flash" tiles it through VMEM with the Pallas kernel
    (ops/flash_attention.py) so the [T, T] score matrix never hits HBM —
    essential at the long-context lengths ulysses exists for; "dense"
    materializes it (fine for short sequences and the CPU test mesh);
    "auto" picks flash on the TPU backend, dense elsewhere.
    """
    # seq-sharded -> head-sharded: split heads (axis 2), gather seq (axis 1).
    a2a = functools.partial(
        jax.lax.all_to_all, axis_name=axis_name, split_axis=2, concat_axis=1, tiled=True
    )
    q_h, k_h, v_h = a2a(q), a2a(k), a2a(v)  # [b, seq_full, heads/n, dh]

    if local_core == "auto":
        from simple_tip_tpu.ops.flash_attention import flash_available

        local_core = "flash" if flash_available() else "dense"
    if local_core == "flash":
        from simple_tip_tpu.ops.flash_attention import flash_attention

        # [b, seq_full, heads/n, dh]; interpret=True is the CPU test path
        out = flash_attention(q_h, k_h, v_h, interpret=interpret)
    elif local_core == "dense":
        from simple_tip_tpu.parallel.ring_attention import (
            dense_attention_f32_softmax,
        )

        out = dense_attention_f32_softmax(q_h, k_h, v_h)
    else:
        raise ValueError(
            f"unknown local_core {local_core!r}; use 'auto', 'flash' or 'dense'"
        )

    # head-sharded -> seq-sharded: split seq (axis 1), gather heads (axis 2).
    return jax.lax.all_to_all(
        out, axis_name=axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def ulysses_attention_sharded(
    q: np.ndarray, k: np.ndarray, v: np.ndarray, mesh: Mesh, axis: str = "sp"
):
    """Run ulysses attention with the sequence axis of q/k/v sharded over
    ``axis`` of ``mesh``. Host-convenience wrapper around shard_map."""
    from simple_tip_tpu.parallel.ring_attention import sharded_attention

    check_ulysses_divisibility(q.shape[1], q.shape[2], mesh.shape[axis])
    return sharded_attention(
        q, k, v, mesh, axis, ulysses_attention, axis_name=axis
    )

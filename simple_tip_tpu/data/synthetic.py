"""Deterministic synthetic stand-in datasets (zero-egress fallback).

Shapes, dtypes, value ranges and class structure match the real datasets; the
signal is class-dependent so the case-study models actually learn (accuracy
well above chance), which keeps misclassification masks, uncertainty orderings
and the active-learning deltas meaningful for framework validation and
benchmarking. NOT a substitute for the real data when reproducing paper
numbers — loaders warn loudly when falling back here.
"""

from typing import Tuple

import numpy as np


def image_classification(
    seed: int,
    n_train: int,
    n_test: int,
    shape: Tuple[int, int, int],
    num_classes: int = 10,
    noise: float = 0.25,
):
    """Class-stamped noisy images in [0,1], uint8-quantized like real data."""
    rng = np.random.default_rng(seed)
    h, w, c = shape

    # Per-class fixed random template with localized high-intensity stamp.
    # float32 throughout: at TIP_SYNTH_SCALE=paper (e.g. 50k x 32x32x3) f64
    # intermediates would peak at multiple GB and the result is lru_cached
    # for the process lifetime.
    templates = rng.uniform(0.0, 0.4, size=(num_classes, h, w, c)).astype(np.float32)
    for cls in range(num_classes):
        r = (cls * 7919) % (h - 8)
        col = (cls * 104729) % (w - 8)
        templates[cls, r : r + 8, col : col + 8, :] += np.float32(0.55)

    def make(n, rng):
        labels = rng.integers(0, num_classes, size=n)
        x = templates[labels]
        x += rng.normal(0, noise, size=(n, h, w, c)).astype(np.float32)
        x = np.clip(x, 0, 1)
        # quantize like uint8-sourced data
        x = np.round(x * 255).astype(np.uint8).astype(np.float32) / 255.0
        return x, labels.astype(np.int64)

    x_train, y_train = make(n_train, rng)
    x_test, y_test = make(n_test, rng)
    return (x_train, y_train), (x_test, y_test)


def corrupt_images(x: np.ndarray, seed: int, severity: float = 0.5) -> np.ndarray:
    """Synthetic corruption: mixture of additive noise, contrast loss and
    translation — a stand-in for the *-C corruption benchmarks."""
    rng = np.random.default_rng(seed)
    out = x.copy()
    n = x.shape[0]
    kinds = rng.integers(0, 3, size=n)
    # additive noise
    idx = np.where(kinds == 0)[0]
    out[idx] = np.clip(out[idx] + rng.normal(0, severity * 0.5, out[idx].shape), 0, 1)
    # contrast loss towards mean
    idx = np.where(kinds == 1)[0]
    out[idx] = out[idx] * (1 - severity) + out[idx].mean() * severity
    # translation (roll)
    idx = np.where(kinds == 2)[0]
    shift = max(1, int(severity * 6))
    out[idx] = np.roll(out[idx], shift, axis=1)
    return out.astype(np.float32)


def token_classification(
    seed: int,
    n_train: int,
    n_test: int,
    maxlen: int = 100,
    vocab_size: int = 2000,
    num_classes: int = 2,
):
    """Synthetic token sequences with class-dependent token distributions
    (IMDB stand-in): each class over-samples a disjoint vocabulary band."""
    rng = np.random.default_rng(seed)

    def make(n, rng):
        labels = rng.integers(0, num_classes, size=n)
        x = rng.integers(1, vocab_size, size=(n, maxlen))
        for cls in range(num_classes):
            idx = np.where(labels == cls)[0]
            band_lo = 100 + cls * 300
            # ~30% of positions drawn from the class band
            mask = rng.random((idx.shape[0], maxlen)) < 0.3
            band_tokens = rng.integers(band_lo, band_lo + 300, size=(idx.shape[0], maxlen))
            x[idx] = np.where(mask, band_tokens, x[idx])
        return x.astype(np.int32), labels.astype(np.int64)

    x_train, y_train = make(n_train, rng)
    x_test, y_test = make(n_test, rng)
    return (x_train, y_train), (x_test, y_test)


def corrupt_tokens(x: np.ndarray, seed: int, severity: float = 0.5, vocab_size: int = 2000) -> np.ndarray:
    """Token-level corruption: random token replacement at the given rate
    (stand-in for the thesaurus-corrupted IMDB set)."""
    rng = np.random.default_rng(seed)
    mask = rng.random(x.shape) < severity * 0.4
    noise = rng.integers(1, vocab_size, size=x.shape)
    return np.where(mask, noise, x).astype(x.dtype)

"""Deterministic synthetic stand-in datasets (zero-egress fallback).

Shapes, dtypes, value ranges and class structure match the real datasets; the
signal is class-dependent so the case-study models actually learn (accuracy
well above chance), which keeps misclassification masks, uncertainty orderings
and the active-learning deltas meaningful for framework validation and
benchmarking. NOT a substitute for the real data when reproducing paper
numbers — loaders warn loudly when falling back here.

Calibrated hardness (round-4 verdict, missing #3): a fully-separable
stand-in trains models that misclassify ZERO nominal test inputs, which
leaves the nominal half of the APFD contract
(/root/reference/src/core/apfd.py:8-19 — faults = misclassified inputs)
unexercised: every nominal table column comes out empty. Real datasets have
irreducible (Bayes) error, so a fraction ``TIP_SYNTH_HARDNESS`` (default
0.08) of generated samples is made genuinely AMBIGUOUS — its features are
an even blend of the labeled class and a random partner class. A
well-trained model then errs on roughly half the ambiguous samples
(~hardness/2 test error, a realistic few percent) and is maximally
UNCERTAIN exactly there, so uncertainty-based prioritization ranks those
faults early and nominal APFD is both defined and discriminative. Plain
label flips would NOT do this: the model stays confident on a mislabeled
separable input, every quantifier ranks it late, and all approaches
collapse to APFD ~0.5. Set TIP_SYNTH_HARDNESS=0 for the round-4
fully-separable behavior (used when resuming studies whose checkpoints
were trained pre-hardness).
"""

import os
from typing import Optional, Tuple

import numpy as np


DEFAULT_HARDNESS = 0.08


def _hardness(explicit: Optional[float]) -> float:
    """Ambiguous-sample fraction: explicit argument, else env, else the
    default.

    Read at GENERATION time; loaders lru_cache their datasets, so set the
    env var before the first load in a process (subprocess-driven studies
    always do).
    """
    if explicit is not None:
        return min(1.0, max(0.0, float(explicit)))
    try:
        val = float(os.environ.get("TIP_SYNTH_HARDNESS", DEFAULT_HARDNESS))
    except ValueError:
        val = DEFAULT_HARDNESS
    return min(1.0, max(0.0, val))


def image_classification(
    seed: int,
    n_train: int,
    n_test: int,
    shape: Tuple[int, int, int],
    num_classes: int = 10,
    noise: float = 0.25,
    hard_frac: Optional[float] = None,
):
    """Class-stamped noisy images in [0,1], uint8-quantized like real data.

    ``hard_frac`` of samples (default: TIP_SYNTH_HARDNESS, 0.08) are
    ambiguous 50/50 blends with a random partner class — the calibrated
    irreducible error that keeps nominal misclassifications (and therefore
    nominal APFD) non-degenerate; see module docstring.
    """
    hard_frac = _hardness(hard_frac)
    rng = np.random.default_rng(seed)
    h, w, c = shape

    # Per-class fixed random template with localized high-intensity stamp.
    # float32 throughout: at TIP_SYNTH_SCALE=paper (e.g. 50k x 32x32x3) f64
    # intermediates would peak at multiple GB and the result is lru_cached
    # for the process lifetime.
    templates = rng.uniform(0.0, 0.4, size=(num_classes, h, w, c)).astype(np.float32)
    for cls in range(num_classes):
        r = (cls * 7919) % (h - 8)
        col = (cls * 104729) % (w - 8)
        templates[cls, r : r + 8, col : col + 8, :] += np.float32(0.55)

    def make(n, rng):
        labels = rng.integers(0, num_classes, size=n)
        x = templates[labels]
        if hard_frac > 0 and num_classes > 1:
            hard = rng.random(n) < hard_frac
            partners = (labels + rng.integers(1, num_classes, size=n)) % num_classes
            x[hard] = 0.5 * x[hard] + 0.5 * templates[partners[hard]]
        x += rng.normal(0, noise, size=(n, h, w, c)).astype(np.float32)
        x = np.clip(x, 0, 1)
        # quantize like uint8-sourced data
        x = np.round(x * 255).astype(np.uint8).astype(np.float32) / 255.0
        return x, labels.astype(np.int64)

    x_train, y_train = make(n_train, rng)
    x_test, y_test = make(n_test, rng)
    return (x_train, y_train), (x_test, y_test)


def corrupt_images(x: np.ndarray, seed: int, severity: float = 0.5) -> np.ndarray:
    """Synthetic corruption: mixture of additive noise, contrast loss and
    translation — a stand-in for the *-C corruption benchmarks."""
    rng = np.random.default_rng(seed)
    out = x.copy()
    n = x.shape[0]
    kinds = rng.integers(0, 3, size=n)
    # additive noise
    idx = np.where(kinds == 0)[0]
    out[idx] = np.clip(out[idx] + rng.normal(0, severity * 0.5, out[idx].shape), 0, 1)
    # contrast loss towards mean
    idx = np.where(kinds == 1)[0]
    out[idx] = out[idx] * (1 - severity) + out[idx].mean() * severity
    # translation (roll)
    idx = np.where(kinds == 2)[0]
    shift = max(1, int(severity * 6))
    out[idx] = np.roll(out[idx], shift, axis=1)
    return out.astype(np.float32)


def token_classification(
    seed: int,
    n_train: int,
    n_test: int,
    maxlen: int = 100,
    vocab_size: int = 2000,
    num_classes: int = 2,
    hard_frac: Optional[float] = None,
):
    """Synthetic token sequences with class-dependent token distributions
    (IMDB stand-in): each class over-samples a disjoint vocabulary band.

    ``hard_frac`` of samples (default: TIP_SYNTH_HARDNESS) draw their
    class-band tokens evenly from BOTH their own and a partner class's band
    — the "mixed-sentiment review" analog of the image blends (module
    docstring): a calibrated irreducible error for nominal APFD.
    """
    hard_frac = _hardness(hard_frac)
    rng = np.random.default_rng(seed)

    def make(n, rng):
        labels = rng.integers(0, num_classes, size=n)
        if hard_frac == 0.0 or num_classes < 2:
            # byte-identical to the pre-hardness generator (same rng
            # stream): studies resumed with TIP_SYNTH_HARDNESS=0 against
            # pre-hardness checkpoints regenerate EXACTLY their data
            x = rng.integers(1, vocab_size, size=(n, maxlen))
            for cls in range(num_classes):
                idx = np.where(labels == cls)[0]
                band_lo = 100 + cls * 300
                # ~30% of positions drawn from the class band
                mask = rng.random((idx.shape[0], maxlen)) < 0.3
                band_tokens = rng.integers(
                    band_lo, band_lo + 300, size=(idx.shape[0], maxlen)
                )
                x[idx] = np.where(mask, band_tokens, x[idx])
            return x.astype(np.int32), labels.astype(np.int64)
        hard = rng.random(n) < hard_frac
        partners = (labels + rng.integers(1, num_classes, size=n)) % num_classes
        x = rng.integers(1, vocab_size, size=(n, maxlen))
        for cls in range(num_classes):
            band_lo = 100 + cls * 300
            band_all = rng.integers(band_lo, band_lo + 300, size=(n, maxlen))
            # ~30% of positions drawn from the class band; ambiguous samples
            # split that band budget evenly with the partner class (the two
            # bands' 15% masks are independent draws, so overlaps where the
            # later band wins are rare (~2%) and unbiased)
            own = (labels == cls) & ~hard
            half = ((labels == cls) | (partners == cls)) & hard
            mask = rng.random((n, maxlen))
            sel = (own[:, None] & (mask < 0.3)) | (half[:, None] & (mask < 0.15))
            x = np.where(sel, band_all, x)
        return x.astype(np.int32), labels.astype(np.int64)

    x_train, y_train = make(n_train, rng)
    x_test, y_test = make(n_test, rng)
    return (x_train, y_train), (x_test, y_test)


def corrupt_tokens(x: np.ndarray, seed: int, severity: float = 0.5, vocab_size: int = 2000) -> np.ndarray:
    """Token-level corruption: random token replacement at the given rate
    (stand-in for the thesaurus-corrupted IMDB set)."""
    rng = np.random.default_rng(seed)
    mask = rng.random(x.shape) < severity * 0.4
    noise = rng.integers(1, vocab_size, size=x.shape)
    return np.where(mask, noise, x).astype(x.dtype)

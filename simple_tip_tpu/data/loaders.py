"""Case-study dataset loaders: real npy/npz caches when present, synthetic
stand-ins otherwise.

Each loader returns ``((x_train, y_train), (x_test, y_test),
(ood_x_test, ood_y_test))`` with the reference's OOD construction: the OOD
eval set is nominal-test + corrupted-test concatenated then shuffled with
``np.random.default_rng(0)`` (reference: src/dnn_test_prio/
case_study_mnist.py:161-165, case_study_cifar10.py:149-153). The reference's
IMDB shuffle is *unseeded* (case_study_imdb.py:281) — a nondeterminism quirk
we fix by seeding with 0 (flagged in SURVEY.md section 7).

Real-data file layout under ``TIP_DATA_DIR`` (``./datasets`` by default):

- ``mnist.npz`` / ``fmnist.npz`` / ``cifar10.npz``: keras-style archives with
  x_train, y_train, x_test, y_test (uint8 images / int labels).
- ``{mnist,fmnist,cifar10}_c_images.npy`` + ``..._c_labels.npy``: 10k
  corrupted samples (the reference's cache naming).
- ``imdb/x_train.npy, y_train.npy, x_test.npy, y_test.npy, x_corrupted.npy``:
  tokenized+padded sequences (the reference's cache naming,
  case_study_imdb.py:272-276). These can be produced from raw text with
  ``simple_tip_tpu.data.imdb_prep``.
"""

import logging
import os
from functools import lru_cache
from typing import Optional, Tuple

import numpy as np

from simple_tip_tpu.config import data_folder
from simple_tip_tpu.data import synthetic

logger = logging.getLogger(__name__)

Triple = Tuple[
    Tuple[np.ndarray, np.ndarray],
    Tuple[np.ndarray, np.ndarray],
    Tuple[np.ndarray, np.ndarray],
]


IMDB_CACHE_FILES = [
    "x_train.npy",
    "y_train.npy",
    "x_test.npy",
    "y_test.npy",
    "x_corrupted.npy",
]


def dataset_presence(name: str) -> str:
    """What this loader would consume for ``name`` right now — the single
    source of truth for presence semantics (artifact_check's data-source
    verdict calls this; keep it in lockstep with the load paths below):

    - ``"real"``: nominal data + corruption cache both present.
    - ``"nominal-only"``: nominal archive present, corruption cache absent
      (the image loaders GENERATE a corrupted set and cache it).
    - ``"incomplete-cache"``: exactly one corruption-cache file present —
      the loader refuses to overwrite it and uses a generated set in-memory.
    - ``"synthetic"``: no real data; deterministic stand-ins.
    """
    root = data_folder()
    if name == "imdb":
        have = all(
            os.path.exists(os.path.join(root, "imdb", f)) for f in IMDB_CACHE_FILES
        )
        return "real" if have else "synthetic"
    if not os.path.exists(os.path.join(root, f"{name}.npz")):
        return "synthetic"
    img = os.path.exists(os.path.join(root, f"{name}_c_images.npy"))
    lab = os.path.exists(os.path.join(root, f"{name}_c_labels.npy"))
    if img and lab:
        return "real"
    if img or lab:
        return "incomplete-cache"
    return "nominal-only"


def _npz_path(name: str) -> Optional[str]:
    path = os.path.join(data_folder(), name)
    return path if os.path.exists(path) else None


def _atomic_save(path: str, array: np.ndarray) -> None:
    """Write an npy atomically (temp file + rename) so an interrupted run
    never leaves a truncated file that later loads would trip over."""
    tmp = path + ".tmp.npy"  # ends in .npy so np.save keeps the name as-is
    np.save(tmp, array)
    os.replace(tmp, path)


def _warn_synthetic(name: str):
    logger.warning(
        "Dataset %s not found under %s — falling back to a DETERMINISTIC "
        "SYNTHETIC stand-in. Pipeline results are structurally valid but are "
        "NOT paper-comparable numbers.",
        name,
        data_folder(),
    )


def _synth_sizes(default: Tuple[int, int], paper: Tuple[int, int]) -> Tuple[int, int]:
    """Synthetic stand-in sizes: the fast test-suite ``default``, or the
    dataset's real ``paper`` scale under ``TIP_SYNTH_SCALE=paper`` — so
    wall-clock measurements on synthetic data
    (scripts/capture_tpu_evidence.py) reflect full-study shapes."""
    if os.environ.get("TIP_SYNTH_SCALE", "").strip().lower() == "paper":
        return paper
    return default


def _ood_mix(x_test, y_test, x_corr, y_corr, seed: int = 0):
    ood_x = np.concatenate((x_test, x_corr), axis=0)
    ood_y = np.concatenate((y_test, y_corr), axis=0)
    perm = np.random.default_rng(seed).permutation(len(ood_y))
    return ood_x[perm], ood_y[perm]


def _load_image_case(
    name: str,
    shape,
    synth_seed: int,
    scale_uint8: bool,
    paper_sizes: Tuple[int, int] = (60000, 10000),
) -> Triple:
    npz = _npz_path(f"{name}.npz")
    c_img = _npz_path(f"{name}_c_images.npy")
    c_lab = _npz_path(f"{name}_c_labels.npy")
    if npz is not None:
        with np.load(npz) as d:
            x_train = d["x_train"].astype("float32") / 255.0
            y_train = d["y_train"].astype(np.int64).flatten()
            x_test = d["x_test"].astype("float32") / 255.0
            y_test = d["y_test"].astype(np.int64).flatten()
        if x_train.ndim == 3:
            x_train = x_train[..., None]
            x_test = x_test[..., None]
        if c_img is not None and c_lab is not None:
            x_corr = np.load(c_img).astype("float32")
            if scale_uint8:
                x_corr = x_corr / 255.0
            if x_corr.ndim == 3:
                x_corr = x_corr[..., None]
            y_corr = np.load(c_lab).astype(np.int64).flatten()
        else:
            # Generate the MNIST-C / CIFAR-10-C style corrupted set offline
            # (the reference downloads these; we synthesize them with the
            # jitted corruption kernels) and cache it in the loader's format.
            from simple_tip_tpu.data import image_corruptor

            logger.warning(
                "%s corruption cache missing — generating a %s-style corrupted "
                "set with simple_tip_tpu.data.image_corruptor (cached for reuse)",
                name,
                "CIFAR-10-C" if name == "cifar10" else "MNIST-C",
            )
            make = (
                image_corruptor.cifar10_c_like
                if name == "cifar10"
                else image_corruptor.mnist_c_like
            )
            x_corr, y_corr = make(x_test, y_test, seed=synth_seed)
            if scale_uint8:
                quantized = np.round(x_corr * 255.0).astype(np.uint8)
                to_cache = quantized
                x_corr = quantized.astype("float32") / 255.0
            else:
                to_cache = x_corr
            if c_img is not None or c_lab is not None:
                # Exactly one of the two cache files exists — likely a real
                # downloaded set with a missing/misnamed companion. Never
                # overwrite it with generated data; use the in-memory set.
                logger.error(
                    "%s corruption cache is INCOMPLETE (images: %s, labels: %s)"
                    " — refusing to overwrite; using generated set in-memory."
                    " Fix or remove the existing file to enable caching.",
                    name,
                    c_img or "missing",
                    c_lab or "missing",
                )
            else:
                try:
                    _atomic_save(
                        os.path.join(data_folder(), f"{name}_c_images.npy"), to_cache
                    )
                    _atomic_save(
                        os.path.join(data_folder(), f"{name}_c_labels.npy"), y_corr
                    )
                except OSError as e:  # read-only dataset volume: keep in-memory set
                    logger.warning("could not cache %s corrupted set (%s)", name, e)
    else:
        _warn_synthetic(name)
        n_train, n_test = _synth_sizes((12000, 2000), paper_sizes)
        (x_train, y_train), (x_test, y_test) = synthetic.image_classification(
            seed=synth_seed, n_train=n_train, n_test=n_test, shape=shape
        )
        x_corr = synthetic.corrupt_images(x_test, seed=synth_seed + 1)
        y_corr = y_test.copy()
    ood_x, ood_y = _ood_mix(x_test, y_test, x_corr, y_corr, seed=0)
    return (x_train, y_train), (x_test, y_test), (ood_x, ood_y)


@lru_cache(maxsize=None)
def load_mnist() -> Triple:
    """MNIST + MNIST-C (or synthetic stand-ins)."""
    return _load_image_case("mnist", (28, 28, 1), synth_seed=11, scale_uint8=True)


@lru_cache(maxsize=None)
def load_fmnist() -> Triple:
    """Fashion-MNIST + fmnist-C (or synthetic stand-ins). The reference ships
    fmnist-C labels and expects image blobs alongside
    (case_study_fashion_mnist.py:134-147)."""
    return _load_image_case("fmnist", (28, 28, 1), synth_seed=22, scale_uint8=False)


@lru_cache(maxsize=None)
def load_cifar10() -> Triple:
    """CIFAR-10 + CIFAR-10-C sample (or synthetic stand-ins)."""
    return _load_image_case(
        "cifar10",
        (32, 32, 3),
        synth_seed=33,
        scale_uint8=True,
        paper_sizes=(50000, 10000),  # CIFAR-10's real split is 50k/10k
    )


@lru_cache(maxsize=None)
def load_imdb(maxlen: int = 100, vocab_size: int = 2000) -> Triple:
    """Tokenized IMDB + thesaurus-corrupted OOD set (or synthetic stand-ins).

    OOD labels: the corrupted set reuses y_test (corruption is
    label-preserving), so ood = (x_test ++ x_corrupted, y_test ++ y_test),
    shuffled — with a seed, unlike the reference (see module docstring).
    """
    folder = os.path.join(data_folder(), "imdb")
    if dataset_presence("imdb") == "real":
        x_train = np.load(os.path.join(folder, "x_train.npy")).astype(np.int32)
        y_train = np.load(os.path.join(folder, "y_train.npy")).astype(np.int64)
        x_test = np.load(os.path.join(folder, "x_test.npy")).astype(np.int32)
        y_test = np.load(os.path.join(folder, "y_test.npy")).astype(np.int64)
        x_corr = np.load(os.path.join(folder, "x_corrupted.npy")).astype(np.int32)
    else:
        _warn_synthetic("imdb")
        n_train, n_test = _synth_sizes((10000, 2500), (25000, 25000))
        (x_train, y_train), (x_test, y_test) = synthetic.token_classification(
            seed=44, n_train=n_train, n_test=n_test, maxlen=maxlen, vocab_size=vocab_size
        )
        x_corr = synthetic.corrupt_tokens(x_test, seed=45, vocab_size=vocab_size)
    ood_x, ood_y = _ood_mix(x_test, y_test, x_corr, y_test.copy(), seed=0)
    return (x_train, y_train), (x_test, y_test), (ood_x, ood_y)

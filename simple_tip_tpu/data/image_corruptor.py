"""TPU-native image corruption generator (MNIST-C / CIFAR-10-C style OOD sets).

The reference does not *generate* its corrupted image sets — it downloads
MNIST-C via tfds (reference: src/dnn_test_prio/case_study_mnist.py:176-209),
ships fmnist-C blobs (case_study_fashion_mnist.py:134-147) and requires a
user-downloaded CIFAR-10-C Zenodo tar (case_study_cifar10.py:165-207). This
module is the framework's offline equivalent of those external generators: the
full corruption families of the MNIST-C and CIFAR-10-C papers, implemented as
pure-jnp per-image kernels that jit/vmap onto the TPU, so the corrupted OOD
caches can be produced from the nominal test sets with zero egress.

Design notes (TPU-first):

- Every corruption is a function ``(img[H,W,C] float in [0,1], key) -> img``
  built by a severity-indexed factory; batches run as ONE jitted
  ``vmap``-program per (corruption, severity) pair, chunked to bound memory.
- Determinism and subset-independence: per-image keys are
  ``fold_in(PRNGKey(seed), global_index)`` — corrupting a subset at the same
  global indices yields bit-identical images to slicing a full-set run
  (the same property the text corruptor gets from md5 per-sentence seeds,
  reference text_corruptor.py:365-394).
- Geometric warps use inverse-affine bilinear sampling
  (``jax.scipy.ndimage.map_coordinates``); blurs are small depthwise convs;
  JPEG is an 8x8 block-DCT quantization (matmul-friendly on the MXU).
- Corruptions that the originals build from *external assets or codecs*
  (frost textures, libjpeg, true fractal fog, Canny hysteresis) are
  procedural approximations with the same qualitative effect and
  severity-monotonic strength; each is marked "(approx)" below.

Severity is an int in 1..5 as in the corruption benchmarks.
"""

import logging
from functools import lru_cache, partial
from typing import Callable, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy.ndimage import map_coordinates

logger = logging.getLogger(__name__)

# ---------------------------------------------------------------------------
# Shared helpers
# ---------------------------------------------------------------------------


def _depthwise_conv(img: jnp.ndarray, kernel2d: jnp.ndarray) -> jnp.ndarray:
    """Convolve each channel of [H,W,C] with the same 2-D kernel (SAME pad)."""
    c = img.shape[-1]
    k = jnp.tile(kernel2d[:, :, None, None], (1, 1, 1, c))
    out = jax.lax.conv_general_dilated(
        img[None],
        k.astype(img.dtype),
        window_strides=(1, 1),
        padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=c,
    )
    return out[0]


def _gauss_kernel2d(sigma: float, radius: int) -> jnp.ndarray:
    ax = jnp.arange(-radius, radius + 1, dtype=jnp.float32)
    g = jnp.exp(-0.5 * (ax / max(sigma, 1e-6)) ** 2)
    g = g / g.sum()
    return jnp.outer(g, g)


def _gaussian_blur(img: jnp.ndarray, sigma: float) -> jnp.ndarray:
    radius = min(max(1, int(3.0 * sigma)), img.shape[0] // 2)
    return _depthwise_conv(img, _gauss_kernel2d(sigma, radius))


def _affine_warp(img: jnp.ndarray, mat: jnp.ndarray, offset: jnp.ndarray) -> jnp.ndarray:
    """Inverse-map bilinear warp: out(p) = img(center + M (p - center) + offset).

    ``mat``/``offset`` may be traced values (per-image random angles work
    under vmap).
    """
    h, w, c = img.shape
    yy, xx = jnp.meshgrid(
        jnp.arange(h, dtype=jnp.float32), jnp.arange(w, dtype=jnp.float32), indexing="ij"
    )
    pts = jnp.stack([yy.ravel(), xx.ravel()])  # [2, H*W]
    ctr = jnp.array([[(h - 1) / 2.0], [(w - 1) / 2.0]], dtype=jnp.float32)
    src = mat @ (pts - ctr) + ctr + offset.reshape(2, 1)
    rows = src[0].reshape(h, w)
    cols = src[1].reshape(h, w)
    chans = [
        map_coordinates(img[..., i], [rows, cols], order=1, mode="constant", cval=0.0)
        for i in range(c)
    ]
    return jnp.stack(chans, axis=-1)


def _smooth_noise(key, h: int, w: int, sigma: float) -> jnp.ndarray:
    """Low-pass-filtered uniform noise field normalized to [0,1] ("(approx)"
    stand-in for the fractal/plasma fields of the original fog/frost)."""
    u = jax.random.uniform(key, (h, w, 1))
    f = _gaussian_blur(u, sigma)[..., 0]
    lo, hi = f.min(), f.max()
    return (f - lo) / jnp.maximum(hi - lo, 1e-6)


def _to_gray(img: jnp.ndarray) -> jnp.ndarray:
    return img.mean(axis=-1, keepdims=True)


def _sev(table, severity: int):
    return table[severity - 1]


# ---------------------------------------------------------------------------
# Corruption factories: factory(severity) -> fn(img, key)
# ---------------------------------------------------------------------------


def _gaussian_noise(severity):
    c = _sev((0.08, 0.12, 0.18, 0.26, 0.38), severity)

    def f(img, key):
        return jnp.clip(img + c * jax.random.normal(key, img.shape), 0.0, 1.0)

    return f


def _shot_noise(severity):
    lam = _sev((60.0, 25.0, 12.0, 5.0, 3.0), severity)

    def f(img, key):
        return jnp.clip(jax.random.poisson(key, img * lam).astype(img.dtype) / lam, 0.0, 1.0)

    return f


def _impulse_noise(severity):
    amount = _sev((0.03, 0.06, 0.09, 0.17, 0.27), severity)

    def f(img, key):
        r = jax.random.uniform(key, img.shape)
        img = jnp.where(r < amount / 2, 1.0, img)
        return jnp.where(r > 1.0 - amount / 2, 0.0, img)

    return f


def _speckle_noise(severity):
    c = _sev((0.15, 0.20, 0.35, 0.45, 0.60), severity)

    def f(img, key):
        return jnp.clip(img + img * c * jax.random.normal(key, img.shape), 0.0, 1.0)

    return f


def _gaussian_blur_c(severity):
    sigma = _sev((0.4, 0.6, 0.8, 1.1, 1.5), severity)

    def f(img, key):
        del key
        return _gaussian_blur(img, sigma)

    return f


def _defocus_blur(severity):
    radius = _sev((1, 2, 2, 3, 4), severity)

    def f(img, key):
        del key
        r = min(radius, img.shape[0] // 2 - 1)
        ax = jnp.arange(-r, r + 1, dtype=jnp.float32)
        yy, xx = jnp.meshgrid(ax, ax, indexing="ij")
        disk = (yy**2 + xx**2 <= r**2 + 0.5).astype(jnp.float32)
        return _depthwise_conv(img, disk / disk.sum())

    return f


def _glass_blur(severity):
    sigma = _sev((0.3, 0.5, 0.7, 0.8, 1.0), severity)
    delta = _sev((1, 1, 1, 2, 2), severity)

    def f(img, key):
        h, w, _ = img.shape
        img = _gaussian_blur(img, sigma)
        dy_key, dx_key = jax.random.split(key)
        yy, xx = jnp.meshgrid(jnp.arange(h), jnp.arange(w), indexing="ij")
        dy = jax.random.randint(dy_key, (h, w), -delta, delta + 1)
        dx = jax.random.randint(dx_key, (h, w), -delta, delta + 1)
        sy = jnp.clip(yy + dy, 0, h - 1)
        sx = jnp.clip(xx + dx, 0, w - 1)
        return _gaussian_blur(img[sy, sx], sigma * 0.7)

    return f


def _motion_blur(severity):
    length = _sev((3, 5, 7, 9, 11), severity)

    def f(img, key):
        k = min(length, img.shape[0] - 1) | 1  # odd
        theta = jax.random.uniform(key, (), minval=0.0, maxval=np.pi)
        ax = jnp.arange(-(k // 2), k // 2 + 1, dtype=jnp.float32)
        yy, xx = jnp.meshgrid(ax, ax, indexing="ij")
        # soft rasterized line through the origin at angle theta
        perp = jnp.abs(xx * jnp.sin(theta) - yy * jnp.cos(theta))
        along = jnp.abs(xx * jnp.cos(theta) + yy * jnp.sin(theta))
        line = (perp <= 0.6) & (along <= k / 2)
        kern = line.astype(jnp.float32)
        kern = kern / jnp.maximum(kern.sum(), 1.0)
        return _depthwise_conv(img, kern)

    return f


def _zoom_blur(severity):
    zmax = _sev((1.06, 1.11, 1.16, 1.21, 1.26), severity)
    factors = [1.0 + i * 0.02 for i in range(int(round((zmax - 1.0) / 0.02)) + 1)]

    def f(img, key):
        del key
        eye = jnp.eye(2, dtype=jnp.float32)
        acc = img
        for z in factors[1:]:
            acc = acc + _affine_warp(img, eye / z, jnp.zeros(2))
        return jnp.clip(acc / len(factors), 0.0, 1.0)

    return f


def _fog(severity):
    """(approx) haze from a low-frequency noise field instead of plasma fractal."""
    a = _sev((0.15, 0.25, 0.35, 0.45, 0.55), severity)

    def f(img, key):
        h, w, _ = img.shape
        field = _smooth_noise(key, h, w, sigma=max(h, w) / 6.0)[..., None]
        return jnp.clip(img * (1.0 - a) + a * (0.75 * field + 0.25), 0.0, 1.0)

    return f


def _frost(severity):
    """(approx) icy overlay from mid-frequency noise instead of frost photos."""
    a = _sev((0.20, 0.30, 0.40, 0.50, 0.60), severity)

    def f(img, key):
        h, w, _ = img.shape
        field = _smooth_noise(key, h, w, sigma=2.0)[..., None]
        return jnp.clip(img * (1.0 - 0.6 * a) + a * field * 0.9, 0.0, 1.0)

    return f


def _snow(severity):
    """(approx) motion-blurred sparse flakes + slight whitening."""
    p = _sev((0.01, 0.02, 0.03, 0.05, 0.08), severity)

    def f(img, key):
        k1, k2 = jax.random.split(key)
        flakes = (jax.random.uniform(k1, img.shape[:2] + (1,)) < p).astype(img.dtype)
        flakes = _motion_blur(min(severity + 1, 5))(flakes, k2)
        flakes = flakes / jnp.maximum(flakes.max(), 1e-6)
        whitened = jnp.clip(img * 0.9 + 0.05, 0.0, 1.0)
        return jnp.clip(jnp.maximum(whitened, flakes * 0.8), 0.0, 1.0)

    return f


def _brightness(severity):
    b = _sev((0.1, 0.2, 0.3, 0.4, 0.5), severity)

    def f(img, key):
        del key
        return jnp.clip(img + b, 0.0, 1.0)

    return f


def _contrast(severity):
    c = _sev((0.75, 0.6, 0.45, 0.3, 0.2), severity)

    def f(img, key):
        del key
        m = img.mean()
        return jnp.clip((img - m) * c + m, 0.0, 1.0)

    return f


def _saturate(severity):
    """No-op on single-channel images (saturation is a chroma property)."""
    s = _sev((1.3, 1.6, 2.0, 2.5, 3.0), severity)

    def f(img, key):
        del key
        gray = _to_gray(img)
        return jnp.clip(gray + (img - gray) * s, 0.0, 1.0)

    return f


def _pixelate(severity):
    frac = _sev((0.75, 0.6, 0.5, 0.4, 0.3), severity)

    def f(img, key):
        del key
        h, w, c = img.shape
        sh, sw = max(1, int(h * frac)), max(1, int(w * frac))
        small = jax.image.resize(img, (sh, sw, c), method="linear")
        return jax.image.resize(small, (h, w, c), method="nearest")

    return f


def _jpeg_compression(severity):
    """(approx) 8x8 block-DCT quantization (libjpeg without the entropy coder);
    the quantization table grows with spatial frequency as in JPEG."""
    strength = _sev((0.5, 0.8, 1.2, 1.8, 2.6), severity)

    def f(img, key):
        del key
        h, w, c = img.shape
        ph, pw = (-h) % 8, (-w) % 8
        x = jnp.pad(img, ((0, ph), (0, pw), (0, 0)), mode="edge") - 0.5
        hh, ww = h + ph, w + pw
        n = jnp.arange(8, dtype=jnp.float32)
        kf = jnp.arange(8, dtype=jnp.float32)[:, None]
        dct = jnp.cos(jnp.pi * (2 * n + 1) * kf / 16.0) * jnp.where(
            kf == 0, jnp.sqrt(1.0 / 8.0), jnp.sqrt(2.0 / 8.0)
        )
        blocks = x.reshape(hh // 8, 8, ww // 8, 8, c).transpose(0, 2, 4, 1, 3)
        coefs = jnp.einsum("ab,nmcbd,ed->nmcae", dct, blocks, dct)
        u = jnp.arange(8, dtype=jnp.float32)
        q = (1.0 + u[:, None] + u[None, :]) * strength / 60.0
        coefs = jnp.round(coefs / q) * q
        # inverse: B = D^T C D for the orthonormal DCT-II matrix D
        out = jnp.einsum("ab,nmcae,ed->nmcbd", dct, coefs, dct)
        out = out.transpose(0, 3, 1, 4, 2).reshape(hh, ww, c) + 0.5
        return jnp.clip(out[:h, :w], 0.0, 1.0)

    return f


def _elastic_transform(severity):
    alpha = _sev((2.0, 3.0, 4.0, 5.0, 7.0), severity)

    def f(img, key):
        h, w, c = img.shape
        ky, kx = jax.random.split(key)
        sigma = max(h, w) / 7.0
        dy = (_smooth_noise(ky, h, w, sigma) - 0.5) * 2.0 * alpha
        dx = (_smooth_noise(kx, h, w, sigma) - 0.5) * 2.0 * alpha
        yy, xx = jnp.meshgrid(
            jnp.arange(h, dtype=jnp.float32), jnp.arange(w, dtype=jnp.float32), indexing="ij"
        )
        chans = [
            map_coordinates(img[..., i], [yy + dy, xx + dx], order=1, mode="constant", cval=0.0)
            for i in range(c)
        ]
        return jnp.stack(chans, axis=-1)

    return f


def _rotate(severity):
    deg = _sev((5.0, 10.0, 15.0, 25.0, 35.0), severity)

    def f(img, key):
        sign = jnp.where(jax.random.bernoulli(key), 1.0, -1.0)
        t = sign * deg * np.pi / 180.0
        mat = jnp.array([[jnp.cos(t), jnp.sin(t)], [-jnp.sin(t), jnp.cos(t)]])
        return _affine_warp(img, mat, jnp.zeros(2))

    return f


def _shear(severity):
    s = _sev((0.1, 0.2, 0.3, 0.4, 0.5), severity)

    def f(img, key):
        sign = jnp.where(jax.random.bernoulli(key), 1.0, -1.0)
        mat = jnp.array([[1.0, 0.0], [sign * s, 1.0]])  # x-shear proportional to y
        return _affine_warp(img, mat, jnp.zeros(2))

    return f


def _translate(severity):
    frac = _sev((0.05, 0.10, 0.15, 0.20, 0.25), severity)

    def f(img, key):
        h = img.shape[0]
        theta = jax.random.uniform(key, (), maxval=2 * np.pi)
        off = frac * h * jnp.array([jnp.sin(theta), jnp.cos(theta)])
        return _affine_warp(img, jnp.eye(2), off)

    return f


def _scale(severity):
    factor = _sev((0.9, 0.85, 0.8, 0.75, 0.7), severity)

    def f(img, key):
        del key
        return _affine_warp(img, jnp.eye(2) / factor, jnp.zeros(2))

    return f


def _stripe(severity):
    band = _sev((2, 3, 4, 5, 6), severity)

    def f(img, key):
        h = img.shape[0]
        band_ = min(band, max(1, h // 4))
        top = jax.random.randint(key, (), h // 4, max(h // 4 + 1, 3 * h // 4 - band_))
        rows = jnp.arange(h)
        in_band = ((rows >= top) & (rows < top + band_))[:, None, None]
        return jnp.where(in_band, 1.0 - img, img)

    return f


def _dotted_line(severity):
    n_lines = _sev((1, 1, 2, 2, 3), severity)

    def f(img, key):
        h, w, _ = img.shape
        yy, xx = jnp.meshgrid(
            jnp.arange(h, dtype=jnp.float32), jnp.arange(w, dtype=jnp.float32), indexing="ij"
        )
        out = img
        for i in range(n_lines):
            ka, kb = jax.random.split(jax.random.fold_in(key, i))
            theta = jax.random.uniform(ka, (), maxval=np.pi)
            offset = jax.random.uniform(kb, (), minval=-h / 4.0, maxval=h / 4.0)
            cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
            perp = (yy - cy) * jnp.cos(theta) - (xx - cx) * jnp.sin(theta) + offset
            along = (yy - cy) * jnp.sin(theta) + (xx - cx) * jnp.cos(theta)
            on = (jnp.abs(perp) <= 0.6) & (jnp.mod(along, 4.0) < 2.0)
            out = jnp.maximum(out, on[..., None].astype(img.dtype))
        return out

    return f


def _zigzag(severity):
    freq = _sev((1.0, 1.5, 2.0, 2.5, 3.0), severity)

    def f(img, key):
        h, w, _ = img.shape
        phase = jax.random.uniform(key, (), maxval=2.0)
        yy, xx = jnp.meshgrid(
            jnp.arange(h, dtype=jnp.float32), jnp.arange(w, dtype=jnp.float32), indexing="ij"
        )
        # triangle wave across x
        t = xx / w * freq * 2.0 + phase
        tri = 2.0 * jnp.abs(t - jnp.floor(t + 0.5))  # in [0,1]
        y_path = (h - 1) * (0.25 + 0.5 * tri)
        on = jnp.abs(yy - y_path) <= 0.7
        return jnp.maximum(img, on[..., None].astype(img.dtype))

    return f


def _spatter(severity):
    thresh = _sev((0.86, 0.82, 0.78, 0.74, 0.70), severity)

    def f(img, key):
        h, w, _ = img.shape
        field = _smooth_noise(key, h, w, sigma=1.2)
        blobs = (field > thresh).astype(img.dtype)[..., None]
        return jnp.maximum(img, blobs * 0.9)

    return f


def _canny_edges(severity):
    """(approx) Sobel magnitude threshold (no non-max suppression/hysteresis)."""
    thresh = _sev((0.5, 0.4, 0.3, 0.25, 0.2), severity)

    def f(img, key):
        del key
        gray = _to_gray(img)
        sx = jnp.array([[-1, 0, 1], [-2, 0, 2], [-1, 0, 1]], dtype=jnp.float32)
        gx = _depthwise_conv(gray, sx)[..., 0]
        gy = _depthwise_conv(gray, sx.T)[..., 0]
        mag = jnp.sqrt(gx**2 + gy**2)
        mag = mag / jnp.maximum(mag.max(), 1e-6)
        edges = (mag > thresh).astype(img.dtype)
        return jnp.broadcast_to(edges[..., None], img.shape)

    return f


CORRUPTIONS: Dict[str, Callable[[int], Callable]] = {
    "gaussian_noise": _gaussian_noise,
    "shot_noise": _shot_noise,
    "impulse_noise": _impulse_noise,
    "speckle_noise": _speckle_noise,
    "gaussian_blur": _gaussian_blur_c,
    "defocus_blur": _defocus_blur,
    "glass_blur": _glass_blur,
    "motion_blur": _motion_blur,
    "zoom_blur": _zoom_blur,
    "fog": _fog,
    "frost": _frost,
    "snow": _snow,
    "brightness": _brightness,
    "contrast": _contrast,
    "saturate": _saturate,
    "pixelate": _pixelate,
    "jpeg_compression": _jpeg_compression,
    "elastic_transform": _elastic_transform,
    "rotate": _rotate,
    "shear": _shear,
    "translate": _translate,
    "scale": _scale,
    "stripe": _stripe,
    "dotted_line": _dotted_line,
    "zigzag": _zigzag,
    "spatter": _spatter,
    "canny_edges": _canny_edges,
}

# The 15 MNIST-C corruption types (Mu & Gilmer 2019), as sampled by the
# reference's tfds loader (case_study_mnist.py:176-209).
MNIST_C_KINDS: Tuple[str, ...] = (
    "shot_noise",
    "impulse_noise",
    "glass_blur",
    "motion_blur",
    "shear",
    "scale",
    "rotate",
    "brightness",
    "translate",
    "stripe",
    "fog",
    "spatter",
    "dotted_line",
    "zigzag",
    "canny_edges",
)

# The 15 primary CIFAR-10-C corruption types (Hendrycks & Dietterich 2019),
# as sampled from the Zenodo tar by the reference (case_study_cifar10.py:165-207).
CIFAR10_C_KINDS: Tuple[str, ...] = (
    "gaussian_noise",
    "shot_noise",
    "impulse_noise",
    "defocus_blur",
    "glass_blur",
    "motion_blur",
    "zoom_blur",
    "snow",
    "frost",
    "fog",
    "brightness",
    "contrast",
    "elastic_transform",
    "pixelate",
    "jpeg_compression",
)


@lru_cache(maxsize=None)
def _batched_fn(corruption: str, severity: int):
    fn = CORRUPTIONS[corruption](severity)
    return jax.jit(jax.vmap(fn))


def corrupt_images(
    x: np.ndarray,
    corruption: str,
    severity: int = 3,
    seed: int = 0,
    global_indices: Sequence[int] = None,
    chunk: int = 4096,
) -> np.ndarray:
    """Corrupt a batch of [N,H,W,C] float images in [0,1].

    ``global_indices`` (default ``arange(N)``) drive the per-image keys, so a
    subset corrupted at the same indices matches the full-set result exactly.
    """
    if corruption not in CORRUPTIONS:
        raise ValueError(
            f"unknown corruption {corruption!r}; available: {sorted(CORRUPTIONS)}"
        )
    if not 1 <= int(severity) <= 5:
        raise ValueError(f"severity must be in 1..5, got {severity}")
    x = np.asarray(x, dtype=np.float32)
    n = len(x)
    idx = np.arange(n) if global_indices is None else np.asarray(global_indices)
    base = jax.random.PRNGKey(seed)
    fn = _batched_fn(corruption, int(severity))
    out = np.empty_like(x)
    for s in range(0, n, chunk):
        e = min(s + chunk, n)
        # Pad partial batches to the next power of two: jit specializes on the
        # batch dimension, so ragged group sizes (e.g. the per-severity groups
        # of corrupted_test_set) would each trigger a fresh compile. Padded
        # sizes collapse to a handful of shapes per (corruption, severity).
        size = e - s
        padded = 1 << (size - 1).bit_length()
        pad_idx = np.concatenate([idx[s:e], np.zeros(padded - size, idx.dtype)])
        pad_x = np.concatenate([x[s:e], np.zeros((padded - size,) + x.shape[1:], x.dtype)])
        keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(jnp.asarray(pad_idx))
        out[s:e] = np.asarray(fn(jnp.asarray(pad_x), keys))[:size]
    return out


def _allocate(rng: np.random.Generator, n_source: int, total: int, n_kinds: int):
    """~equal per-kind sample allocation (reference samples ~total/15 of each
    MNIST-C type, case_study_mnist.py:176-209)."""
    per = [total // n_kinds] * n_kinds
    for i in range(total - sum(per)):
        per[i] += 1
    return [rng.choice(n_source, size=p, replace=p > n_source) for p in per]


def corrupted_test_set(
    x_test: np.ndarray,
    y_test: np.ndarray,
    kinds: Sequence[str],
    total: int = None,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Build an MNIST-C / CIFAR-10-C style corrupted set: ``total`` samples
    drawn ~equally across ``kinds`` with per-sample random severity 1..5
    (CIFAR-10-C's random-corruption/severity sampling, reference
    case_study_cifar10.py:165-207), deterministic under ``seed``."""
    x_test = np.asarray(x_test)
    y_test = np.asarray(y_test)
    total = total or len(x_test)
    rng = np.random.default_rng(seed)
    parts_x, parts_y = [], []
    for kind, idx in zip(kinds, _allocate(rng, len(x_test), total, len(kinds))):
        # per-SAMPLE random severity: group the kind's samples by severity so
        # each (kind, severity) pair runs as one jitted batch
        sevs = rng.integers(1, 6, size=len(idx))
        corrupted = np.empty(
            (len(idx),) + tuple(np.asarray(x_test).shape[1:]), dtype=np.float32
        )
        for sev in np.unique(sevs):
            sel = sevs == sev
            corrupted[sel] = corrupt_images(
                x_test[idx[sel]],
                kind,
                severity=int(sev),
                seed=seed,
                global_indices=idx[sel],
            )
        parts_x.append(corrupted)
        parts_y.append(y_test[idx])
    perm = rng.permutation(total)
    return np.concatenate(parts_x)[perm], np.concatenate(parts_y)[perm]


def mnist_c_like(x_test, y_test, total: int = None, seed: int = 0):
    """MNIST-C-equivalent corrupted set from nominal test images."""
    return corrupted_test_set(x_test, y_test, MNIST_C_KINDS, total=total, seed=seed)


def cifar10_c_like(x_test, y_test, total: int = None, seed: int = 0):
    """CIFAR-10-C-equivalent corrupted set from nominal test images."""
    return corrupted_test_set(x_test, y_test, CIFAR10_C_KINDS, total=total, seed=seed)

"""Real-data onramp: turn MOUNTED raw reference-layout datasets into the
npy/npz caches this framework's loaders consume — so paper-Table-1 parity is
a mount away, not a rewrite away (round-2 verdict, missing #1 / next #8).

This environment has zero egress: the raw archives (keras dataset mirrors,
MNIST-C, Zenodo CIFAR-10-C, aclImdb) cannot be downloaded here. What CAN be
guaranteed is the exact transformation from each raw layout to the eval sets
the reference uses, with the reference's own seeds:

- **mnist.npz / fmnist.npz / cifar10.npz** — keras-style archives
  (x_train/y_train/x_test/y_test) are consumed directly by
  ``simple_tip_tpu.data.loaders`` at full 60k/10k scale; nothing to prepare.
- **MNIST-C** (google-research/mnist-c release: one folder per corruption
  with ``test_images.npy``/``test_labels.npy``): the reference takes, for
  corruption i of its fixed 15-type list, the ABSOLUTE test-split slice
  ``[i*667, min(10000, (i+1)*667))`` and concatenates to 10k (reference:
  src/dnn_test_prio/case_study_mnist.py:176-209 — tfds ReadInstruction
  "abs" over the same underlying arrays). The reference then shuffles with
  an UNSEEDED tf shuffle; we keep slice order: the OOD mix downstream
  re-permutes with rng(0) either way, and APFD/AL results are invariant to
  test-set ordering (scores are per-sample).
- **CIFAR-10-C** (Zenodo tar: ``{corruption}.npy`` x 19 + ``labels.npy``):
  concatenate all corruption arrays, tile labels, take the first 10k of
  ``np.random.default_rng(0).permutation`` — the reference's exact seed and
  math (case_study_cifar10.py:184-207). The reference iterates
  ``os.listdir`` (filesystem order, unreproducible); we sort filenames —
  flagged-and-fixed nondeterminism, same corruption distribution.
- **fmnist-C** (``fmnist-c-test.npy`` + ``fmnist-c-test-labels.npy``, the
  files the reference ships): scaled to [0,1] float32 + channel dim, saved
  under our cache names (case_study_fashion_mnist.py:134-147).
- **IMDB raw text** (``imdb/raw/{train,test}.jsonl``, lines of
  ``{"text": ..., "label": 0|1}`` — trivially produced from aclImdb or the
  HF dataset): tokenized (keras-equivalent tokenizer, vocab 2000, maxlen
  100) and thesaurus-corrupted at severity 0.5, seed 0, the reference's
  constants (case_study_imdb.py:23-25,319).

CLI: ``python -m simple_tip_tpu.data.real_onramp`` scans ``TIP_DATA_DIR``
for raw layouts and builds every cache it finds inputs for. See
RUNBOOK.md for the end-to-end Table-1 recipe.
"""

import json
import logging
import math
import os
from typing import List, Optional, Tuple

import numpy as np

from simple_tip_tpu.config import data_folder

logger = logging.getLogger(__name__)

# The reference's fixed corruption list (case_study_mnist.py:31-47).
MNIST_CORRUPTION_TYPES = [
    "shot_noise",
    "impulse_noise",
    "glass_blur",
    "motion_blur",
    "shear",
    "scale",
    "rotate",
    "brightness",
    "translate",
    "stripe",
    "fog",
    "spatter",
    "dotted_line",
    "zigzag",
    "canny_edges",
]

OOD_SIZE = 10_000


def _atomic_save(path: str, array: np.ndarray) -> None:
    tmp = path + ".tmp.npy"
    np.save(tmp, array)
    os.replace(tmp, path)


def prepare_mnist_c(raw_dir: str, out_dir: Optional[str] = None) -> Tuple[str, str]:
    """mnist-c release folders -> ``mnist_c_images.npy``/``mnist_c_labels.npy``.

    Per corruption i: absolute slice [i*ceil(10k/15), min(10k, (i+1)*...))
    of that corruption's test arrays, concatenated and truncated to 10k —
    the reference's tfds ReadInstruction math (case_study_mnist.py:176-209).
    """
    out_dir = out_dir or data_folder()
    img_per_corr = math.ceil(OOD_SIZE / len(MNIST_CORRUPTION_TYPES))
    xs: List[np.ndarray] = []
    ys: List[np.ndarray] = []
    for i, corr in enumerate(MNIST_CORRUPTION_TYPES):
        folder = os.path.join(raw_dir, corr)
        images = np.load(os.path.join(folder, "test_images.npy"))
        labels = np.load(os.path.join(folder, "test_labels.npy"))
        lo, hi = i * img_per_corr, min(OOD_SIZE, (i + 1) * img_per_corr)
        xs.append(images[lo:hi])
        ys.append(labels[lo:hi])
    x = np.concatenate(xs, axis=0)[:OOD_SIZE]
    y = np.concatenate(ys, axis=0)[:OOD_SIZE]
    if len(x) != OOD_SIZE:
        raise ValueError(
            f"mnist-c slices yielded {len(x)} samples, expected {OOD_SIZE} "
            f"(is {raw_dir} the full google-research/mnist-c test release?)"
        )
    if x.ndim == 3:
        x = x[..., None]
    img_path = os.path.join(out_dir, "mnist_c_images.npy")
    lab_path = os.path.join(out_dir, "mnist_c_labels.npy")
    _atomic_save(img_path, x.astype(np.uint8))
    _atomic_save(lab_path, y.astype(np.int64))
    logger.info("mnist-c cache written: %s %s", img_path, x.shape)
    return img_path, lab_path


def prepare_cifar10_c(raw_dir: str, out_dir: Optional[str] = None) -> Tuple[str, str]:
    """Zenodo CIFAR-10-C tar contents -> 10k-sample cache, reference seed.

    Exact reference math (case_study_cifar10.py:184-207): concatenate every
    corruption array, tile labels, take the first 10k indices of
    ``np.random.default_rng(0).permutation``. Deviation, flagged: the
    reference walks ``os.listdir`` (filesystem order); we SORT corruption
    filenames so the draw is reproducible across machines.
    """
    out_dir = out_dir or data_folder()
    files = sorted(f for f in os.listdir(raw_dir) if f.endswith(".npy"))
    if "labels.npy" not in files:
        raise FileNotFoundError(f"labels.npy not found in {raw_dir}")
    labels = np.load(os.path.join(raw_dir, "labels.npy"))
    corruption_files = [f for f in files if f != "labels.npy"]
    if not corruption_files:
        raise FileNotFoundError(f"no corruption npys found in {raw_dir}")
    all_corruptions = np.concatenate(
        [np.load(os.path.join(raw_dir, f)) for f in corruption_files], axis=0
    )
    indexes = np.random.default_rng(0).permutation(len(all_corruptions))[:OOD_SIZE]
    images = all_corruptions[indexes]
    labels = np.tile(labels, len(corruption_files))[indexes]
    img_path = os.path.join(out_dir, "cifar10_c_images.npy")
    lab_path = os.path.join(out_dir, "cifar10_c_labels.npy")
    _atomic_save(img_path, images.astype(np.uint8))
    _atomic_save(lab_path, labels.astype(np.int64))
    logger.info("cifar10-c cache written: %s %s", img_path, images.shape)
    return img_path, lab_path


def prepare_fmnist_c(
    test_images: str, test_labels: str, out_dir: Optional[str] = None
) -> Tuple[str, str]:
    """The reference's shipped fmnist-c files -> our cache names.

    ``fmnist-c-test.npy`` is uint8 (N,28,28); the loader's fmnist path
    expects float32 [0,1] with a channel dim and no further scaling
    (reference divides by 255 and expands dims at
    case_study_fashion_mnist.py:139-143)."""
    out_dir = out_dir or data_folder()
    x = np.load(test_images).astype("float32") / 255.0
    if x.ndim == 3:
        x = x[..., None]
    y = np.load(test_labels).astype(np.int64)
    img_path = os.path.join(out_dir, "fmnist_c_images.npy")
    lab_path = os.path.join(out_dir, "fmnist_c_labels.npy")
    _atomic_save(img_path, x)
    _atomic_save(lab_path, y)
    logger.info("fmnist-c cache written: %s %s", img_path, x.shape)
    return img_path, lab_path


def prepare_imdb_from_jsonl(raw_dir: str, out_dir: Optional[str] = None) -> str:
    """``{train,test}.jsonl`` ({"text","label"} lines) -> tokenized caches.

    Reference constants: vocab 2000, maxlen 100, corruption severity 0.5,
    seed 0 (case_study_imdb.py:23-25,319); the thesaurus-corrupted OOD set
    is built through ops.text_corruptor (bundled offline thesaurus, or a
    user wordnet export in TIP_DATA_DIR)."""
    from simple_tip_tpu.data.imdb_prep import build_imdb_caches

    def _read(split: str):
        texts, labels = [], []
        with open(os.path.join(raw_dir, f"{split}.jsonl")) as f:
            for line in f:
                if line.strip():
                    rec = json.loads(line)
                    texts.append(rec["text"])
                    labels.append(int(rec["label"]))
        if not texts:
            raise ValueError(f"no records in {raw_dir}/{split}.jsonl")
        return texts, labels

    x_train, y_train = _read("train")
    x_test, y_test = _read("test")
    out_folder = os.path.join(out_dir or data_folder(), "imdb")
    build_imdb_caches(
        x_train, y_train, x_test, y_test,
        out_folder=out_folder,
        vocab_size=2000,
        maxlen=100,
        severity=0.5,
        seed=0,
    )
    logger.info("imdb caches written under %s", out_folder)
    return out_folder


def prepare_all(root: Optional[str] = None) -> dict:
    """Scan ``root`` (default TIP_DATA_DIR) for raw layouts; build every
    cache whose inputs are present and whose outputs are missing. Returns a
    {name: status} report."""
    root = root or data_folder()
    report = {}

    mnist_c_raw = os.path.join(root, "mnist_c")
    if os.path.isdir(mnist_c_raw):
        if os.path.exists(os.path.join(root, "mnist_c_images.npy")):
            report["mnist_c"] = "cache already present"
        else:
            prepare_mnist_c(mnist_c_raw, root)
            report["mnist_c"] = "built"
    else:
        report["mnist_c"] = f"raw not mounted ({mnist_c_raw})"

    cifar_raw = os.path.join(root, "CIFAR-10-C")
    if os.path.isdir(cifar_raw):
        if os.path.exists(os.path.join(root, "cifar10_c_images.npy")):
            report["cifar10_c"] = "cache already present"
        else:
            prepare_cifar10_c(cifar_raw, root)
            report["cifar10_c"] = "built"
    else:
        report["cifar10_c"] = f"raw not mounted ({cifar_raw})"

    fm_img = os.path.join(root, "fmnist-c-test.npy")
    fm_lab = os.path.join(root, "fmnist-c-test-labels.npy")
    if os.path.exists(fm_img) and os.path.exists(fm_lab):
        if os.path.exists(os.path.join(root, "fmnist_c_images.npy")):
            report["fmnist_c"] = "cache already present"
        else:
            prepare_fmnist_c(fm_img, fm_lab, root)
            report["fmnist_c"] = "built"
    else:
        report["fmnist_c"] = f"raw not mounted ({fm_img})"

    imdb_raw = os.path.join(root, "imdb", "raw")
    if os.path.isdir(imdb_raw):
        if os.path.exists(os.path.join(root, "imdb", "x_corrupted.npy")):
            report["imdb"] = "cache already present"
        else:
            prepare_imdb_from_jsonl(imdb_raw, root)
            report["imdb"] = "built"
    else:
        report["imdb"] = f"raw not mounted ({imdb_raw})"

    for name in ("mnist.npz", "fmnist.npz", "cifar10.npz"):
        report[name] = (
            "present" if os.path.exists(os.path.join(root, name)) else "NOT mounted"
        )
    return report


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    rep = prepare_all()
    for k, v in sorted(rep.items()):
        # tiplint: disable=bare-print (__main__ report table; stdout is the interface)
        print(f"{k:12s} {v}")

"""Dataset loading for the four case studies.

The reference pulls MNIST/CIFAR-10 via keras, MNIST-C via tfds, CIFAR-10-C
from a user-downloaded Zenodo tar, fmnist-C from shipped npy files and IMDB
via HuggingFace datasets (SURVEY.md section 2.2 D10-D13). This build runs in
environments with no network egress, so every loader:

1. looks for cached arrays under ``TIP_DATA_DIR`` (same npy naming as the
   reference where one exists: ``mnist_c_images.npy`` etc.);
2. when nominal data IS present but the corrupted companion set is not,
   generates an MNIST-C / CIFAR-10-C style corrupted set on the spot with the
   jitted corruption kernels in ``image_corruptor`` and caches it;
3. otherwise falls back to a *deterministic synthetic stand-in* with identical
   shapes/dtypes/class structure (loudly warned) so every pipeline phase runs
   end-to-end anywhere. Synthetic sets are learnable-but-not-trivial:
   class-dependent spatial/token patterns plus noise, with a corrupted OOD
   variant at a fixed severity.
"""

from simple_tip_tpu.data.loaders import (
    load_cifar10,
    load_fmnist,
    load_imdb,
    load_mnist,
)

__all__ = ["load_mnist", "load_fmnist", "load_cifar10", "load_imdb"]

"""IMDB preprocessing: tokenizer + padding with Keras-equivalent semantics,
and the cache-building pipeline (reference: src/dnn_test_prio/
case_study_imdb.py:295-344 uses keras' Tokenizer + pad_sequences; this module
reimplements their exact behavior so token ids and shapes match).

Builds the ``TIP_DATA_DIR/imdb/*.npy`` caches from raw texts; raw IMDB texts
must be supplied locally (zero egress) — either via HuggingFace datasets'
on-disk cache or as two text files.
"""

import os
import re
from collections import Counter
from typing import Dict, List, Sequence

import numpy as np

KERAS_FILTERS = '!"#$%&()*+,-./:;<=>?@[\\]^_`{|}~\t\n'


class KerasLikeTokenizer:
    """Reimplementation of tf.keras.preprocessing.text.Tokenizer defaults:
    lowercase, strip filter chars, split on spaces; ranks words by frequency
    (ties broken by insertion order); ``texts_to_sequences`` keeps only words
    with rank < num_words."""

    def __init__(self, num_words: int = None):
        self.num_words = num_words
        self.word_counts: Counter = Counter()
        self.word_index: Dict[str, int] = {}

    @staticmethod
    def _text_to_word_sequence(text: str) -> List[str]:
        text = text.lower()
        translate_map = {ord(c): " " for c in KERAS_FILTERS}
        text = text.translate(translate_map)
        return [w for w in text.split(" ") if w]

    def fit_on_texts(self, texts: Sequence[str]) -> None:
        """Count words and assign frequency-ranked indices (1-based)."""
        word_order: List[str] = []
        for text in texts:
            seq = self._text_to_word_sequence(text)
            for w in seq:
                if w not in self.word_counts:
                    word_order.append(w)
                self.word_counts[w] += 1
        # Keras sorts by count desc; python's sort is stable, and keras uses
        # the counts dict's insertion order for ties.
        wcounts = sorted(
            ((w, self.word_counts[w]) for w in word_order),
            key=lambda x: x[1],
            reverse=True,
        )
        self.word_index = {w: i + 1 for i, (w, _) in enumerate(wcounts)}

    def texts_to_sequences(self, texts: Sequence[str]) -> List[List[int]]:
        """Map texts to lists of in-vocabulary word ranks."""
        res = []
        for text in texts:
            seq = self._text_to_word_sequence(text)
            vect = []
            for w in seq:
                i = self.word_index.get(w)
                if i is not None and (self.num_words is None or i < self.num_words):
                    vect.append(i)
            res.append(vect)
        return res


def pad_sequences(sequences: List[List[int]], maxlen: int) -> np.ndarray:
    """Keras pad_sequences defaults: pre-padding with 0, pre-truncating."""
    out = np.zeros((len(sequences), maxlen), dtype=np.int32)
    for i, seq in enumerate(sequences):
        if not seq:
            continue
        trunc = seq[-maxlen:]
        out[i, -len(trunc) :] = trunc
    return out


def build_imdb_caches(
    x_train_texts: List[str],
    y_train: List[int],
    x_test_texts: List[str],
    y_test: List[int],
    out_folder: str,
    vocab_size: int = 2000,
    maxlen: int = 100,
    severity: float = 0.5,
    seed: int = 0,
) -> None:
    """Produce the reference-named npy caches (x_train, y_train, x_test,
    y_test, x_corrupted) from raw texts, including the thesaurus-corrupted OOD
    set at the reference's severity (case_study_imdb.py:319)."""
    from simple_tip_tpu.ops.text_corruptor import TextCorruptor

    corruptor = TextCorruptor(
        base_dataset=list(x_train_texts) + list(x_test_texts),
        cache_dir=os.path.join(out_folder, "corruptor"),
    )
    x_test_ood = corruptor.corrupt(list(x_test_texts), severity=severity, seed=seed)

    tokenizer = KerasLikeTokenizer(num_words=vocab_size)
    tokenizer.fit_on_texts(x_train_texts)

    x_train = pad_sequences(tokenizer.texts_to_sequences(x_train_texts), maxlen)
    x_test = pad_sequences(tokenizer.texts_to_sequences(x_test_texts), maxlen)
    x_corrupted = pad_sequences(tokenizer.texts_to_sequences(x_test_ood), maxlen)

    os.makedirs(out_folder, exist_ok=True)
    np.save(os.path.join(out_folder, "x_train.npy"), x_train)
    np.save(os.path.join(out_folder, "y_train.npy"), np.asarray(y_train))
    np.save(os.path.join(out_folder, "x_test.npy"), x_test)
    np.save(os.path.join(out_folder, "y_test.npy"), np.asarray(y_test))
    np.save(os.path.join(out_folder, "x_corrupted.npy"), x_corrupted)

"""Flax models for the four case studies, with activation taps.

Each model's ``__call__`` returns ``(softmax_probs, taps)`` where ``taps`` maps
the *reference Keras layer index* to that layer's output (SURVEY.md section
2.2 D10-D13). Returning all taps unconditionally is free under jit: XLA's dead
code elimination prunes any tap the caller does not consume, so the same
traced program serves plain prediction, NC profile extraction and SA AT
collection.
"""

from simple_tip_tpu.models.convnet import Cifar10ConvNet, MnistConvNet
from simple_tip_tpu.models.transformer import ImdbTransformer

__all__ = ["MnistConvNet", "Cifar10ConvNet", "ImdbTransformer"]

"""Training and inference loops (single-model path).

Keras-`fit`-equivalent semantics (reference trains with
``model.fit(x, y, batch_size, epochs, validation_split=0.1)``, e.g.
src/dnn_test_prio/case_study_mnist.py:68):

- validation_split takes the LAST fraction of the data *before* shuffling;
  the remaining head is the training set, reshuffled every epoch.
- categorical cross-entropy on softmax outputs with keras' 1e-7 clipping.
- Adam with keras defaults (lr 1e-3, eps 1e-7).
- the final partial batch contributes a smaller-denominator mean.

TPU-native structure: one jitted epoch = ``lax.scan`` over per-batch gather +
train step (static shapes; the ragged final batch is padded and masked, which
reproduces keras' semantics exactly while keeping XLA happy). The epoch
function is pure in (params, opt_state, rng), so the ensemble layer can vmap
it over a stacked parameter axis without modification.
"""

import logging
import math
from dataclasses import dataclass
from functools import lru_cache, partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class TrainConfig:
    """Hyperparameters of one keras-`fit`-equivalent training run."""

    batch_size: int = 128
    epochs: int = 15
    learning_rate: float = 1e-3
    validation_split: float = 0.1


def adam_like_keras(learning_rate: float = 1e-3) -> optax.GradientTransformation:
    """Adam with tf.keras defaults (eps=1e-7 instead of optax's 1e-8)."""
    return optax.adam(learning_rate, b1=0.9, b2=0.999, eps=1e-7)


def categorical_crossentropy(probs: jnp.ndarray, y_onehot: jnp.ndarray) -> jnp.ndarray:
    """Per-sample keras categorical cross-entropy on softmax outputs."""
    p = jnp.clip(probs, 1e-7, 1.0)
    return -jnp.sum(y_onehot * jnp.log(p), axis=-1)


def _epoch_plan(n_train: int, batch_size: int) -> Tuple[int, int]:
    steps = math.ceil(n_train / batch_size)
    return steps, steps * batch_size


def make_epoch_core(
    model, tx: optax.GradientTransformation, batch_size: int
) -> Callable:
    """Build the *un-jitted* one-epoch function ``(params, opt_state, x, y,
    rng) -> (params, opt_state, mean_loss)``.

    ``x``/``y_onehot`` are full (device-resident) training arrays; each scan
    step gathers its shuffled batch by index. Pure in its arguments — the
    single-model path jits it directly; the ensemble layer vmaps it over a
    stacked parameter axis first (parallel/ensemble.py).
    """

    def loss_fn(params, xb, yb, mask, dropout_rng):
        probs, _ = model.apply(
            {"params": params}, xb, train=True, rngs={"dropout": dropout_rng}
        )
        losses = categorical_crossentropy(probs, yb)
        return jnp.sum(losses * mask) / jnp.maximum(jnp.sum(mask), 1.0)

    def epoch_fn(params, opt_state, x, y_onehot, rng):
        n_train = x.shape[0]
        steps, padded = _epoch_plan(n_train, batch_size)
        perm_rng, dropout_rng = jax.random.split(rng)
        perm = jax.random.permutation(perm_rng, n_train)
        idx = jnp.concatenate([perm, jnp.zeros(padded - n_train, perm.dtype)])
        mask = (jnp.arange(padded) < n_train).astype(jnp.float32)
        idx = idx.reshape(steps, batch_size)
        mask = mask.reshape(steps, batch_size)
        step_rngs = jax.random.split(dropout_rng, steps)

        def step(carry, sl):
            params, opt_state = carry
            batch_idx, batch_mask, step_rng = sl
            xb = jnp.take(x, batch_idx, axis=0)
            yb = jnp.take(y_onehot, batch_idx, axis=0)
            loss, grads = jax.value_and_grad(loss_fn)(
                params, xb, yb, batch_mask, step_rng
            )
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), loss

        (params, opt_state), losses = jax.lax.scan(
            step, (params, opt_state), (idx, mask, step_rngs)
        )
        return params, opt_state, jnp.mean(losses)

    return epoch_fn


def make_epoch_fn(model, tx: optax.GradientTransformation, batch_size: int) -> Callable:
    """Jitted (donating) single-model epoch function."""
    return partial(jax.jit, donate_argnums=(0, 1))(make_epoch_core(model, tx, batch_size))


@lru_cache(maxsize=64)
def _make_init_fn(model) -> Callable:
    """One jitted init program per model config (jit re-specializes per
    example shape on its own).

    Flax's ``module.init`` runs eagerly — every primitive dispatches (and
    round-trips the persistent compilation cache) separately, which on this
    deployment measured SECONDS per init and dominated active-learning
    retrains (~80 inits/run). Jitted, init is one cached program and the
    warm call is ~1 ms."""

    @jax.jit
    def init(rng, example_x):
        variables = model.init({"params": rng, "dropout": rng}, example_x, train=False)
        return variables["params"]

    return init


def init_params(model, rng, example_x) -> Any:
    """Initialize model parameters for an example input batch."""
    return _make_init_fn(model)(rng, example_x)


def train_model(
    model,
    x: np.ndarray,
    y_onehot: np.ndarray,
    cfg: TrainConfig,
    rng: jax.Array,
    verbose: bool = False,
) -> Any:
    """Train a fresh model, returning its parameters.

    Replicates ``model.fit(x, y, batch_size, epochs, validation_split)``: the
    last ``validation_split`` fraction is held out (not used for anything but
    parity of the effective training set), the head is shuffled per epoch.
    Delegates to the cached ``Trainer`` so repeated trainings share one
    compiled epoch program.
    """
    return get_trainer(model, cfg).train(x, y_onehot, rng, verbose=verbose)


@lru_cache(maxsize=64)
def make_predict_fn(model, batch_size: int = 1024) -> Callable:
    """Batched deterministic forward: ``(params, x) -> probs`` (host numpy).

    Cached per (model config, batch size) — flax modules hash by config — so
    repeated construction (e.g. ~80 retrain evaluations per active-learning
    run) reuses one jitted program instead of recompiling."""

    @jax.jit
    def fwd(params, xb):
        probs, _ = model.apply({"params": params}, xb, train=False)
        return probs

    def predict(params, x: np.ndarray) -> np.ndarray:
        outs = []
        for start in range(0, x.shape[0], batch_size):
            xb = jnp.asarray(x[start : start + batch_size])
            outs.append(np.asarray(fwd(params, xb)))
        return np.concatenate(outs, axis=0)

    return predict


def make_taps_fn(
    model, activation_layers, include_last_layer: bool = False, batch_size: int = 1024
) -> Callable:
    """Batched transparent forward returning the tapped layer outputs
    (cached per configuration; see ``make_predict_fn``).

    Equivalent of the reference's "transparent model"
    (reference: src/dnn_test_prio/handler_model.py:175-206): selects taps whose
    Keras layer index is in ``activation_layers`` (integers only — tuple
    entries are silently ignored, replicating handler_model.py:202), plus the
    final output if requested. Unconsumed taps are DCE'd by XLA.
    """
    return _make_taps_fn_cached(
        model, tuple(i for i in activation_layers if isinstance(i, int)),
        include_last_layer, batch_size,
    )


@lru_cache(maxsize=64)
def _make_taps_fn_cached(
    model, layer_ids: Tuple[int, ...], include_last_layer: bool, batch_size: int
) -> Callable:
    @jax.jit
    def fwd(params, xb):
        probs, taps = model.apply({"params": params}, xb, train=False)
        outs = [taps[i] for i in layer_ids]
        if include_last_layer:
            outs.append(probs)
        return outs

    def get_activations(params, x: np.ndarray, device: bool = False):
        """Tapped activations; ``device=True`` returns jax arrays (keeps the
        downstream metric kernels on device instead of host numpy)."""
        n = x.shape[0]
        chunks = []
        for start in range(0, n, batch_size):
            xb = jnp.asarray(x[start : start + batch_size])
            outs = fwd(params, xb)
            chunks.append(outs if device else [np.asarray(o) for o in outs])
        cat = jnp.concatenate if device else np.concatenate
        return [cat([c[i] for c in chunks], axis=0) for i in range(len(chunks[0]))]

    return get_activations


def evaluate_accuracy(model, params, x: np.ndarray, labels: np.ndarray, batch_size: int = 1024) -> float:
    """Top-1 accuracy of the model on (x, labels)."""
    predict = make_predict_fn(model, batch_size)
    probs = predict(params, x)
    return float(np.mean(np.argmax(probs, axis=1) == np.asarray(labels).flatten()))


class Trainer:
    """Reusable training harness: one jitted epoch program per (model, cfg),
    shared across arbitrarily many from-scratch trainings (the active-learning
    phase retrains ~80x per run with identical shapes — one compile total)."""

    def __init__(self, model, cfg: TrainConfig):
        self.model = model
        self.cfg = cfg
        self.tx = adam_like_keras(cfg.learning_rate)
        self._epoch_fn = make_epoch_fn(model, self.tx, cfg.batch_size)

    def train(self, x: np.ndarray, y_onehot: np.ndarray, rng, verbose: bool = False):
        """Train a fresh model (keras-fit semantics), returning its params."""
        cfg = self.cfg
        n = x.shape[0]
        n_train = n - int(n * cfg.validation_split)
        x_train = jnp.asarray(x[:n_train])
        y_train = jnp.asarray(y_onehot[:n_train])
        init_rng, epoch_rng = jax.random.split(rng)
        params = init_params(self.model, init_rng, x_train[:1])
        opt_state = self.tx.init(params)
        for epoch in range(cfg.epochs):
            epoch_rng, this_rng = jax.random.split(epoch_rng)
            params, opt_state, loss = self._epoch_fn(
                params, opt_state, x_train, y_train, this_rng
            )
            if verbose:
                logger.info(
                    "epoch %d/%d loss=%.4f", epoch + 1, cfg.epochs, float(loss)
                )
        return params


@lru_cache(maxsize=16)
def get_trainer(model, cfg: TrainConfig) -> Trainer:
    """Cached Trainer per (model config, train config)."""
    return Trainer(model, cfg)


def mc_dropout_votes(
    model, params, x: np.ndarray, n_samples: int, rng, batch_size: int = 256
) -> np.ndarray:
    """Class-vote counts over stochastic (dropout-active) forward passes.

    Used for the variation-ratio quantifier with DROPOUT_SAMPLE_SIZE samples
    (reference: src/dnn_test_prio/handler_model.py:7,151-161). The sample loop
    is a ``lax.scan`` accumulating one-hot argmax votes, so peak memory is one
    batch of activations regardless of sample count.
    """
    votes_fn = _make_votes_fn(model)
    n = x.shape[0]
    out = []
    for i, start in enumerate(range(0, n, batch_size)):
        chunk_rng = jax.random.fold_in(rng, i)
        rngs = jax.random.split(chunk_rng, n_samples)
        xb = jnp.asarray(x[start : start + batch_size])
        out.append(np.asarray(votes_fn(params, xb, rngs)))
    return np.concatenate(out, axis=0)


@lru_cache(maxsize=16)
def _make_votes_fn(model):
    @jax.jit
    def votes_fn(params, xb, rngs):
        def one_sample(counts, sample_rng):
            probs, _ = model.apply(
                {"params": params}, xb, train=True, rngs={"dropout": sample_rng}
            )
            votes = jnp.argmax(probs, axis=1)
            one_hot = jax.nn.one_hot(votes, probs.shape[1], dtype=jnp.int32)
            return counts + one_hot, None

        init = jnp.zeros((xb.shape[0], _num_classes(model)), dtype=jnp.int32)
        counts, _ = jax.lax.scan(one_sample, init, rngs)
        return counts

    return votes_fn


def _num_classes(model) -> int:
    return getattr(model, "num_classes", 10)

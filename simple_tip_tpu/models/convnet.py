"""Convolutional models for the MNIST / Fashion-MNIST / CIFAR-10 case studies.

Architectures match the reference's Keras models exactly (layer order, widths,
activations, initialization family):

- ``MnistConvNet``  (reference: src/dnn_test_prio/case_study_mnist.py:50-69):
  Conv 32 3x3 relu -> MaxPool 2x2 -> Conv 64 3x3 relu -> MaxPool 2x2 ->
  Flatten -> Dropout 0.5 -> Dense 10 softmax. Also used for Fashion-MNIST
  (case_study_fashion_mnist.py:29-48).
- ``Cifar10ConvNet`` (reference: src/dnn_test_prio/case_study_cifar10.py:33-57):
  Conv 32 -> MaxPool -> Conv 64 -> MaxPool -> Conv 64 -> Flatten -> Dense 64
  relu -> Dense 10 softmax. **No dropout** — MC-dropout (VR) is intentionally
  unavailable on CIFAR-10, as in the reference.

Tap indices follow the Keras ``model.layers`` numbering so the reference's
``SA_ACTIVATION_LAYERS``/``NC_ACTIVATION_LAYERS`` configs carry over verbatim.

``compute_dtype=jnp.bfloat16`` runs the conv/dense compute on the MXU's
native bfloat16 (parameters, softmax and emitted taps stay float32 — taps
feed host metric kernels and the softmax feeds uncertainty quantifiers, so
both keep full precision). Default ``None`` is exact float32 parity.
"""

from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp
from flax import linen as nn

# Keras Conv2D/Dense default kernel initializer.
glorot = nn.initializers.glorot_uniform()


class MnistConvNet(nn.Module):
    """LeNet-style convnet for MNIST/FMNIST; taps 0-3 are conv/pool outputs."""

    num_classes: int = 10
    dropout_rate: float = 0.5
    compute_dtype: Optional[Any] = None

    has_dropout = True
    # Keras layer indices usable as NC/SA taps.
    sa_layers = (3,)
    nc_layers = (0, 1, 2, 3)
    all_layers = (0, 1, 2, 3, 4, 5, 6)

    @nn.compact
    def __call__(self, x, train: bool = False) -> Tuple[jnp.ndarray, Dict[int, jnp.ndarray]]:
        dt = self.compute_dtype
        f32 = jnp.float32
        taps: Dict[int, jnp.ndarray] = {}
        if dt is not None:
            x = x.astype(dt)
        x = nn.relu(nn.Conv(32, (3, 3), padding="VALID", kernel_init=glorot, dtype=dt)(x))
        taps[0] = x.astype(f32)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        taps[1] = x.astype(f32)
        x = nn.relu(nn.Conv(64, (3, 3), padding="VALID", kernel_init=glorot, dtype=dt)(x))
        taps[2] = x.astype(f32)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        taps[3] = x.astype(f32)
        x = x.reshape((x.shape[0], -1))
        taps[4] = x.astype(f32)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        taps[5] = x.astype(f32)
        logits = nn.Dense(self.num_classes, kernel_init=glorot, dtype=dt)(x)
        probs = nn.softmax(logits.astype(f32))
        taps[6] = probs
        return probs, taps


class Cifar10ConvNet(nn.Module):
    """3-conv CNN for CIFAR-10; no stochastic layers (VR intentionally absent)."""

    num_classes: int = 10
    compute_dtype: Optional[Any] = None

    has_dropout = False
    sa_layers = (3,)
    nc_layers = (0, 1, 2, 3)
    all_layers = (0, 1, 2, 3, 4, 5, 6, 7)

    @nn.compact
    def __call__(self, x, train: bool = False) -> Tuple[jnp.ndarray, Dict[int, jnp.ndarray]]:
        dt = self.compute_dtype
        f32 = jnp.float32
        taps: Dict[int, jnp.ndarray] = {}
        if dt is not None:
            x = x.astype(dt)
        x = nn.relu(nn.Conv(32, (3, 3), padding="VALID", kernel_init=glorot, dtype=dt)(x))
        taps[0] = x.astype(f32)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        taps[1] = x.astype(f32)
        x = nn.relu(nn.Conv(64, (3, 3), padding="VALID", kernel_init=glorot, dtype=dt)(x))
        taps[2] = x.astype(f32)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        taps[3] = x.astype(f32)
        x = nn.relu(nn.Conv(64, (3, 3), padding="VALID", kernel_init=glorot, dtype=dt)(x))
        taps[4] = x.astype(f32)
        x = x.reshape((x.shape[0], -1))
        taps[5] = x.astype(f32)
        x = nn.relu(nn.Dense(64, kernel_init=glorot, dtype=dt)(x))
        taps[6] = x.astype(f32)
        logits = nn.Dense(self.num_classes, kernel_init=glorot, dtype=dt)(x)
        probs = nn.softmax(logits.astype(f32))
        taps[7] = probs
        return probs, taps

"""IMDB sentiment model: a small transformer (NOT an LSTM — see SURVEY.md
section 2.2 D13 note), matching the reference's Keras architecture
(reference: src/dnn_test_prio/case_study_imdb.py:48-182):

token+position embedding (vocab 2000, maxlen 100, dim 32) -> TransformerBlock
(MHA 2 heads with per-head key dim 32, FFN 32, dropout 0.1, post-LN) ->
GlobalAveragePooling1D -> Dropout 0.1 -> Dense 20 relu -> Dropout 0.1 ->
Dense 2 softmax.

Tap indices follow the Keras functional ``model.layers`` numbering
(0=input ... 7=softmax). The reference's NC config lists tuple-form taps into
embedding/FFN sublayers which its own membership test silently ignores
(handler_model.py:202 vs case_study_imdb.py:35-38); we replicate the
*effective* behavior: only integer taps 3 and 5 participate in NC.
"""

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh, PartitionSpec as P

glorot = nn.initializers.glorot_uniform()


def _keras_uniform(key, shape, dtype=jnp.float32):
    """Keras Embedding default initializer: U(-0.05, 0.05)."""
    return jax.random.uniform(key, shape, dtype, -0.05, 0.05)


class TokenAndPositionEmbedding(nn.Module):
    """Token embedding + learned position embedding (added)."""

    maxlen: int
    vocab_size: int
    embed_dim: int
    compute_dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x):
        positions = jnp.arange(x.shape[-1])
        dt = self.compute_dtype
        tok = nn.Embed(
            self.vocab_size, self.embed_dim, embedding_init=_keras_uniform, dtype=dt
        )(x.astype(jnp.int32))
        pos = nn.Embed(
            self.maxlen, self.embed_dim, embedding_init=_keras_uniform, dtype=dt
        )(positions)
        return tok + pos


class SequenceParallelSelfAttention(nn.Module):
    """Self-attention whose core runs sequence-parallel over a device mesh.

    Long-context path: Q/K/V projections are local; the attention core shards
    the sequence axis over ``seq_axis`` of ``sp_mesh`` using one of these
    exact strategies:

    - ``impl="ring"``: streaming-softmax ring — K/V blocks rotate via
      ppermute (parallel/ring_attention.py); no head-count constraint.
    - ``impl="ulysses"``: all-to-all head-scatter/seq-gather, dense local
      softmax, inverse all-to-all (parallel/ulysses_attention.py); requires
      ``num_heads %% mesh size == 0``.
    - ``impl="flash"``: single-device Pallas flash kernel (``sp_mesh`` must
      be None) — the score matrix streams through VMEM instead of
      materializing in HBM (ops/flash_attention.py).

    With ``sp_mesh=None`` (and impl != "flash") the same parameters run
    through the dense oracle core — enabling single-device use and
    equivalence testing.
    """

    num_heads: int
    qkv_features: int
    out_features: int
    sp_mesh: Optional[Mesh] = None
    seq_axis: str = "sp"
    impl: str = "ring"
    compute_dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x):
        from simple_tip_tpu.parallel.ring_attention import (
            ring_attention,
            ring_self_attention_reference,
        )

        head_dim = self.qkv_features // self.num_heads
        proj = functools.partial(
            nn.DenseGeneral,
            features=(self.num_heads, head_dim),
            kernel_init=glorot,
            dtype=self.compute_dtype,
        )
        q = proj(name="query")(x)
        k = proj(name="key")(x)
        v = proj(name="value")(x)
        if self.impl not in ("ring", "ulysses", "flash"):
            raise ValueError(
                f"unknown impl {self.impl!r}; use 'ring', 'ulysses' or 'flash'"
            )
        if self.impl == "flash" and self.sp_mesh is not None:
            raise ValueError(
                "impl='flash' is the single-device core; combine long "
                "sequences with a mesh via impl='ring' or 'ulysses' "
                "(ulysses uses the flash kernel as its local core on TPU)"
            )
        if self.sp_mesh is not None:
            n_dev = self.sp_mesh.shape[self.seq_axis]
            if self.impl == "ulysses":
                from simple_tip_tpu.parallel.ulysses_attention import (
                    check_ulysses_divisibility,
                    ulysses_attention,
                )

                check_ulysses_divisibility(x.shape[1], self.num_heads, n_dev)
                shard_fn = functools.partial(
                    ulysses_attention, axis_name=self.seq_axis
                )
            else:
                from simple_tip_tpu.parallel.ring_attention import (
                    check_ring_divisibility,
                )

                check_ring_divisibility(x.shape[1], n_dev)
                shard_fn = functools.partial(
                    ring_attention, axis_name=self.seq_axis, n_dev=n_dev
                )
            spec = P(None, self.seq_axis, None, None)
            core = jax.shard_map(
                shard_fn,
                mesh=self.sp_mesh,
                in_specs=(spec, spec, spec),
                out_specs=spec,
            )
            out = core(q, k, v)
        elif self.impl == "flash":
            from simple_tip_tpu.ops.flash_attention import (
                flash_attention,
                flash_available,
            )

            out = flash_attention(
                q,
                k,
                v,
                interpret=not flash_available(),
                compute_dtype=self.compute_dtype,
            )
        else:
            out = ring_self_attention_reference(q, k, v)
        return nn.DenseGeneral(
            features=self.out_features,
            axis=(-2, -1),
            kernel_init=glorot,
            name="out",
            dtype=self.compute_dtype,
        )(out)


class TransformerBlock(nn.Module):
    """Post-LN transformer encoder block, Keras-tutorial style.

    ``attention_impl``: "dense" (default, Keras-parity MHA), "ring"
    (sequence-parallel streaming-softmax ring over ``sp_mesh``), "ulysses"
    (sequence-parallel all-to-all head scatter over ``sp_mesh``), or "flash"
    (single-device Pallas VMEM-tiled kernel).
    """

    embed_dim: int
    num_heads: int
    ff_dim: int
    rate: float = 0.1
    attention_impl: str = "dense"
    sp_mesh: Optional[Mesh] = None
    seq_axis: str = "sp"
    compute_dtype: Optional[Any] = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        dt = self.compute_dtype
        # Keras MultiHeadAttention(key_dim=embed_dim) uses *per-head* dim
        # embed_dim => total qkv features = num_heads * embed_dim.
        if self.attention_impl not in ("dense", "ring", "ulysses", "flash"):
            raise ValueError(
                f"unknown attention_impl {self.attention_impl!r}; "
                "use 'dense', 'ring', 'ulysses' or 'flash'"
            )
        if self.attention_impl in ("ring", "ulysses", "flash"):
            attn = SequenceParallelSelfAttention(
                num_heads=self.num_heads,
                qkv_features=self.num_heads * self.embed_dim,
                out_features=self.embed_dim,
                sp_mesh=self.sp_mesh,
                seq_axis=self.seq_axis,
                impl=self.attention_impl,
                compute_dtype=dt,
            )(x)
        else:
            attn = nn.MultiHeadDotProductAttention(
                num_heads=self.num_heads,
                qkv_features=self.num_heads * self.embed_dim,
                out_features=self.embed_dim,
                kernel_init=glorot,
                dtype=dt,
            )(x, x)
        attn = nn.Dropout(self.rate, deterministic=not train)(attn)
        out1 = nn.LayerNorm(epsilon=1e-6, dtype=dt)(x + attn)
        ffn = nn.Dense(self.ff_dim, kernel_init=glorot, dtype=dt)(out1)
        ffn = nn.relu(ffn)
        ffn = nn.Dense(self.embed_dim, kernel_init=glorot, dtype=dt)(ffn)
        ffn = nn.Dropout(self.rate, deterministic=not train)(ffn)
        return nn.LayerNorm(epsilon=1e-6, dtype=dt)(out1 + ffn)


class ImdbTransformer(nn.Module):
    """2-class IMDB sentiment classifier with Keras-index taps.

    ``attention_impl="ring"`` or ``"ulysses"`` (+ ``sp_mesh``) switches the
    encoder block to sequence-parallel attention for long-context scaling
    (ppermute ring vs all-to-all head scatter); the default "dense" path is
    the reference-parity architecture.
    """

    vocab_size: int = 2000
    maxlen: int = 100
    embed_dim: int = 32
    num_heads: int = 2
    ff_dim: int = 32
    num_classes: int = 2
    attention_impl: str = "dense"
    sp_mesh: Optional[Mesh] = None
    seq_axis: str = "sp"
    compute_dtype: Optional[Any] = None

    has_dropout = True
    sa_layers = (5,)
    # Effective reference behavior: tuple-form entries ignored, ints kept.
    nc_layers = (3, 5)
    all_layers = (1, 2, 3, 4, 5, 6, 7)

    @nn.compact
    def __call__(self, x, train: bool = False) -> Tuple[jnp.ndarray, Dict[int, jnp.ndarray]]:
        dt = self.compute_dtype
        f32 = jnp.float32
        taps: Dict[int, jnp.ndarray] = {}
        h = TokenAndPositionEmbedding(
            self.maxlen, self.vocab_size, self.embed_dim, compute_dtype=dt
        )(x)
        taps[1] = h.astype(f32)
        h = TransformerBlock(
            self.embed_dim,
            self.num_heads,
            self.ff_dim,
            attention_impl=self.attention_impl,
            sp_mesh=self.sp_mesh,
            seq_axis=self.seq_axis,
            compute_dtype=dt,
        )(h, train)
        taps[2] = h.astype(f32)
        h = jnp.mean(h, axis=1)  # GlobalAveragePooling1D
        taps[3] = h.astype(f32)
        h = nn.Dropout(0.1, deterministic=not train)(h)
        taps[4] = h.astype(f32)
        h = nn.relu(nn.Dense(20, kernel_init=glorot, dtype=dt)(h))
        taps[5] = h.astype(f32)
        h = nn.Dropout(0.1, deterministic=not train)(h)
        taps[6] = h.astype(f32)
        logits = nn.Dense(self.num_classes, kernel_init=glorot, dtype=dt)(h)
        probs = nn.softmax(logits.astype(f32))
        taps[7] = probs
        return probs, taps

"""IMDB sentiment model: a small transformer (NOT an LSTM — see SURVEY.md
section 2.2 D13 note), matching the reference's Keras architecture
(reference: src/dnn_test_prio/case_study_imdb.py:48-182):

token+position embedding (vocab 2000, maxlen 100, dim 32) -> TransformerBlock
(MHA 2 heads with per-head key dim 32, FFN 32, dropout 0.1, post-LN) ->
GlobalAveragePooling1D -> Dropout 0.1 -> Dense 20 relu -> Dropout 0.1 ->
Dense 2 softmax.

Tap indices follow the Keras functional ``model.layers`` numbering
(0=input ... 7=softmax). The reference's NC config lists tuple-form taps into
embedding/FFN sublayers which its own membership test silently ignores
(handler_model.py:202 vs case_study_imdb.py:35-38); we replicate the
*effective* behavior: only integer taps 3 and 5 participate in NC.
"""

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

glorot = nn.initializers.glorot_uniform()


def _keras_uniform(key, shape, dtype=jnp.float32):
    """Keras Embedding default initializer: U(-0.05, 0.05)."""
    return jax.random.uniform(key, shape, dtype, -0.05, 0.05)


class TokenAndPositionEmbedding(nn.Module):
    """Token embedding + learned position embedding (added)."""

    maxlen: int
    vocab_size: int
    embed_dim: int

    @nn.compact
    def __call__(self, x):
        positions = jnp.arange(x.shape[-1])
        tok = nn.Embed(self.vocab_size, self.embed_dim, embedding_init=_keras_uniform)(
            x.astype(jnp.int32)
        )
        pos = nn.Embed(self.maxlen, self.embed_dim, embedding_init=_keras_uniform)(
            positions
        )
        return tok + pos


class TransformerBlock(nn.Module):
    """Post-LN transformer encoder block, Keras-tutorial style."""

    embed_dim: int
    num_heads: int
    ff_dim: int
    rate: float = 0.1

    @nn.compact
    def __call__(self, x, train: bool = False):
        # Keras MultiHeadAttention(key_dim=embed_dim) uses *per-head* dim
        # embed_dim => total qkv features = num_heads * embed_dim.
        attn = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads,
            qkv_features=self.num_heads * self.embed_dim,
            out_features=self.embed_dim,
            kernel_init=glorot,
        )(x, x)
        attn = nn.Dropout(self.rate, deterministic=not train)(attn)
        out1 = nn.LayerNorm(epsilon=1e-6)(x + attn)
        ffn = nn.Dense(self.ff_dim, kernel_init=glorot)(out1)
        ffn = nn.relu(ffn)
        ffn = nn.Dense(self.embed_dim, kernel_init=glorot)(ffn)
        ffn = nn.Dropout(self.rate, deterministic=not train)(ffn)
        return nn.LayerNorm(epsilon=1e-6)(out1 + ffn)


class ImdbTransformer(nn.Module):
    """2-class IMDB sentiment classifier with Keras-index taps."""

    vocab_size: int = 2000
    maxlen: int = 100
    embed_dim: int = 32
    num_heads: int = 2
    ff_dim: int = 32
    num_classes: int = 2

    has_dropout = True
    sa_layers = (5,)
    # Effective reference behavior: tuple-form entries ignored, ints kept.
    nc_layers = (3, 5)
    all_layers = (1, 2, 3, 4, 5, 6, 7)

    @nn.compact
    def __call__(self, x, train: bool = False) -> Tuple[jnp.ndarray, Dict[int, jnp.ndarray]]:
        taps: Dict[int, jnp.ndarray] = {}
        h = TokenAndPositionEmbedding(self.maxlen, self.vocab_size, self.embed_dim)(x)
        taps[1] = h
        h = TransformerBlock(self.embed_dim, self.num_heads, self.ff_dim)(h, train)
        taps[2] = h
        h = jnp.mean(h, axis=1)  # GlobalAveragePooling1D
        taps[3] = h
        h = nn.Dropout(0.1, deterministic=not train)(h)
        taps[4] = h
        h = nn.relu(nn.Dense(20, kernel_init=glorot)(h))
        taps[5] = h
        h = nn.Dropout(0.1, deterministic=not train)(h)
        taps[6] = h
        logits = nn.Dense(self.num_classes, kernel_init=glorot)(h)
        probs = nn.softmax(logits)
        taps[7] = probs
        return probs, taps

"""Model-centric utilities: predictions + uncertainties, activation walking.

TPU-native counterpart of the reference's ``BaseModel``
(reference: src/dnn_test_prio/handler_model.py:88-206). Differences by design:

- A model here is ``(flax module, params)``; the "transparent model" is not a
  separately-built graph but the same traced program with taps consumed
  (XLA DCE prunes the rest), see models/train.make_taps_fn.
- MC-dropout variation ratio runs DROPOUT_SAMPLE_SIZE stochastic passes as a
  ``lax.scan`` on device instead of 200 separate predict calls.
- Timing keeps the reference's record semantics: per-quantifier
  ``[setup, pred, quant, cam]`` with prediction time measured once and shared.
"""

import logging
from typing import Dict, Generator, List, Optional, Tuple

import jax
import numpy as np

from simple_tip_tpu.models.train import make_predict_fn, make_taps_fn, mc_dropout_votes
from simple_tip_tpu.ops.timer import Timer
from simple_tip_tpu.ops.uncertainty import POINT_PRED_QUANTIFIERS

DROPOUT_SAMPLE_SIZE = 200

logger = logging.getLogger(__name__)


class BaseModel:
    """Wraps (module, params) with prediction, uncertainty and AT utilities."""

    def __init__(
        self,
        model_def,
        params,
        activation_layers: Optional[List] = None,
        include_last_layer: bool = False,
        batch_size: int = 32,
    ):
        self.model_def = model_def
        self.params = params
        self.activation_layers = activation_layers
        self.include_last_layer = include_last_layer
        self.batch_size = batch_size
        self._predict_fn = None
        self._taps_fn = None

    # -- prediction + uncertainty --------------------------------------------

    def get_pred_and_uncertainty(
        self, x: np.ndarray, rng=None
    ) -> Tuple[np.ndarray, Dict[str, np.ndarray], Dict[str, List[float]]]:
        """Point predictions plus all applicable uncertainty quantifications.

        Returns ``(pred, {name: uncertainty}, {name: [setup, pred, quant, cam]})``
        with names matching the artifact contract: softmax, pcs,
        softmax_entropy, deep_gini, and VR when the model has dropout layers.
        """
        if self._predict_fn is None:
            self._predict_fn = make_predict_fn(self.model_def, self.batch_size)

        pred_timer = Timer()
        with pred_timer:
            probs = self._predict_fn(self.params, x)
            probs = np.asarray(probs)
        pred_time = pred_timer.get()

        uncertainties: Dict[str, np.ndarray] = {}
        times: Dict[str, List[float]] = {}
        pred = None
        for name, quantifier in POINT_PRED_QUANTIFIERS.items():
            q_timer = Timer()
            with q_timer:
                q_pred, unc = quantifier(probs)
            if pred is None:
                pred = np.asarray(q_pred)
            uncertainties[name] = np.asarray(unc)
            times[name] = [0, pred_time, q_timer.get(), 0]

        if getattr(self.model_def, "has_dropout", False):
            logger.info("Collecting MC-Dropout samples")
            if rng is None:
                rng = jax.random.PRNGKey(0)
            sampling_timer = Timer()
            with sampling_timer:
                counts = mc_dropout_votes(
                    self.model_def,
                    self.params,
                    x,
                    n_samples=DROPOUT_SAMPLE_SIZE,
                    rng=rng,
                    batch_size=max(self.batch_size, 128),
                )
            quant_timer = Timer()
            with quant_timer:
                majority_count = counts.max(axis=1)
                vr = 1.0 - majority_count / DROPOUT_SAMPLE_SIZE
            uncertainties["VR"] = vr
            times["VR"] = [
                0,
                sampling_timer.get(),
                quant_timer.get(),
                0,
            ]
        else:
            logger.warning(
                "No stochastic layers found in model. Skipping stochastic quantifiers."
            )

        return pred, uncertainties, times

    # -- activations ---------------------------------------------------------

    def _ensure_taps_fn(self):
        if self._taps_fn is None:
            if self.activation_layers is None:
                raise ValueError("No activation layers specified")
            self._taps_fn = make_taps_fn(
                self.model_def,
                self.activation_layers,
                include_last_layer=self.include_last_layer,
                batch_size=self.batch_size,
            )

    def get_activations(self, x: np.ndarray, device: bool = False) -> List[np.ndarray]:
        """Deterministic forward returning the tapped layer activations
        (``device=True`` keeps them as jax arrays for on-device consumers)."""
        self._ensure_taps_fn()
        return self._taps_fn(self.params, x, device=device)

    def walk_activations(
        self, x: np.ndarray, badge_size: Optional[int] = None, device: bool = False
    ) -> Generator[List[np.ndarray], None, None]:
        """Stream activations badge-by-badge over a potentially large dataset."""
        self._ensure_taps_fn()
        badge_size = badge_size or self.batch_size
        for start in range(0, x.shape[0], badge_size):
            yield self._taps_fn(self.params, x[start : start + badge_size], device=device)

"""Experiment engines: model handler, coverage/surprise workers, and the
prioritization / active-learning / activation-collection phases.

TPU-native counterpart of the reference's ``src/dnn_test_prio/`` (SURVEY.md
section 2.2), writing the identical filesystem artifact contract.
"""

"""Surprise-adequacy engine: fit the five tested SA variants on the training
activation traces, score every test set, and derive surprise-coverage CAM
prioritization orders.

What is protocol (reproduced from the reference experiment,
src/dnn_test_prio/handler_surprise.py:19-117, and pinned by
tests/test_reference_engine_parity.py): the five-variant registry with its
exact hyperparameters (DSA at 30% subsampling, per-class LSA/MDSA, per-class
MLSA with 3 mixture components, KMeans-clustered MDSA with k ∈ 2..5 at 30%
subsampling), train ATs + predictions collected in ONE forward pass over
``sa_layers`` + the output layer, 1000-bucket surprise-coverage profiles,
and the four-stage ``[setup, pred, quant, cam]`` time record where setup
includes the (shared) train-AT collection time.

What is this framework's own: the flow — each variant runs a
fit → score → SC-CAM pipeline per dataset (the reference mutates its result
dict across three separate passes), activations come from the jitted tap
forward of ``BaseModel``, and the SC bucket upper bound is the maximum
FINITE observed score: an LSA whose KDE degraded returns +inf for every
sample, and bucket edges up to inf would be all-NaN, silently voiding the
CAM (fix-with-note; non-finite scores simply land outside every bucket).
"""

import logging
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from simple_tip_tpu.engine.model_handler import BaseModel
from simple_tip_tpu.ops.prioritizers import cam
from simple_tip_tpu.ops.surprise import (
    DSA,
    LSA,
    MDSA,
    MLSA,
    MultiModalSA,
    SurpriseCoverageMapper,
)
from simple_tip_tpu.ops.timer import Timer

logger = logging.getLogger(__name__)

NUM_SC_BUCKETS = 1000

# {sa_name: (train_ats, train_preds) -> scorer} — the tested registry.
SA_VARIANTS: Dict[str, Callable] = {
    "dsa": lambda ats, preds: DSA(ats, preds, subsampling=0.3),
    "pc-lsa": lambda ats, preds: MultiModalSA.build_by_class(
        ats, preds, lambda a, _: LSA(a)
    ),
    "pc-mdsa": lambda ats, preds: MultiModalSA.build_by_class(
        ats, preds, lambda a, _: MDSA(a)
    ),
    "pc-mlsa": lambda ats, preds: MultiModalSA.build_by_class(
        ats, preds, lambda a, _: MLSA(a, num_components=3)
    ),
    "pc-mmdsa": lambda ats, preds: MultiModalSA.build_with_kmeans(
        ats, preds, lambda a, _: MDSA(a), potential_k=range(2, 6), subsampling=0.3
    ),
}

DatasetResult = Tuple[np.ndarray, np.ndarray, List[float]]
"""(sa_scores, sc_cam_order, [setup, pred, quant, cam] seconds)."""


def _sc_cam_order(sa_scores: np.ndarray) -> np.ndarray:
    """Coverage-additional order over 1000-bucket SC profiles, bounded by
    the max finite score (see module docstring)."""
    finite = np.asarray(sa_scores)[np.isfinite(sa_scores)]
    upper = float(finite.max()) if finite.size else 1.0
    profiles = SurpriseCoverageMapper(NUM_SC_BUCKETS, upper).get_coverage_profile(
        sa_scores
    )
    return np.fromiter(cam(sa_scores, profiles), dtype=np.int64)


class SurpriseHandler:
    """One fitted-per-run surprise engine shared by the prio and AL phases."""

    # Back-compat alias for the registry's historical name.
    TESTED_SA = SA_VARIANTS

    def __init__(
        self,
        model_def,
        params,
        sa_layers: List[int],
        training_dataset: np.ndarray,
        batch_size: int = 1024,
    ):
        self.sa_layers = list(sa_layers)
        self.base_model = BaseModel(
            model_def,
            params,
            activation_layers=self.sa_layers,
            include_last_layer=True,
            batch_size=batch_size,
        )
        self.train_at_timer = Timer()
        with self.train_at_timer:
            self.train_ats, self.train_pred = self._traces(training_dataset)

    def _traces(self, dataset: np.ndarray) -> Tuple[List[np.ndarray], np.ndarray]:
        """(tapped activations, argmax predictions) — one forward pass."""
        outs = self.base_model.get_activations(dataset)
        n_taps = sum(1 for layer in self.sa_layers if isinstance(layer, int))
        assert len(outs) == n_taps + 1, (len(outs), n_taps)
        return outs[:-1], np.argmax(outs[-1], axis=1)

    def evaluate_all(
        self,
        datasets: Dict[str, np.ndarray],
        dsa_badge_size: Optional[int] = None,
    ) -> Dict[str, Dict[str, DatasetResult]]:
        """``{sa_name: {ds_name: (scores, cam_order, times)}}`` for every
        (variant, dataset) pair."""
        logger.info("collecting test-set activation traces")
        traces: Dict[str, Tuple[List[np.ndarray], np.ndarray, float]] = {}
        for ds_name, dataset in datasets.items():
            with Timer() as pred_timer:
                ats, preds = self._traces(dataset)
            traces[ds_name] = (ats, preds, pred_timer.get())

        results: Dict[str, Dict[str, DatasetResult]] = {}
        for sa_name, build in SA_VARIANTS.items():
            logger.info("fitting %s", sa_name)
            with Timer() as fit_timer:
                scorer = build(self.train_ats, self.train_pred)
                if dsa_badge_size is not None and isinstance(scorer, DSA):
                    scorer.badge_size = dsa_badge_size
            setup_s = self.train_at_timer.get() + fit_timer.get()

            per_ds: Dict[str, DatasetResult] = {}
            for ds_name, (ats, preds, pred_s) in traces.items():
                logger.info("scoring %s on %s", sa_name, ds_name)
                with Timer() as quant_timer:
                    scores = scorer(ats, preds)
                with Timer() as cam_timer:
                    order = _sc_cam_order(scores)
                per_ds[ds_name] = (
                    scores,
                    order,
                    [setup_s, pred_s, quant_timer.get(), cam_timer.get()],
                )
            results[sa_name] = per_ds
        return results

"""Surprise-adequacy worker: fit the 5 tested SA variants on training ATs, then
score + surprise-coverage-CAM every test set.

Behavioral contract matches the reference's ``SurpriseHandler``
(reference: src/dnn_test_prio/handler_surprise.py:19-117): the TESTED_SA
registry (dsa with 30% subsample, pc-lsa, pc-mdsa, pc-mlsa with 3 components,
pc-mmdsa with KMeans k in 2..5 and 30% subsample), train ATs+predictions
collected in ONE forward pass over sa_layers + output, SC profiles with 1000
buckets upper-bounded by the max observed SA, and the per-variant
``[setup, pred, quant, cam]`` time records.
"""

import logging
from typing import Dict, List, Optional, Tuple, Union

import numpy as np

from simple_tip_tpu.engine.model_handler import BaseModel
from simple_tip_tpu.ops.prioritizers import cam
from simple_tip_tpu.ops.surprise import (
    DSA,
    LSA,
    MDSA,
    MLSA,
    MultiModalSA,
    SurpriseCoverageMapper,
)
from simple_tip_tpu.ops.timer import Timer

NUM_SC_BUCKETS = 1000

logger = logging.getLogger(__name__)


class SurpriseHandler:
    """Efficiently handles the tested surprise-adequacy instances."""

    TESTED_SA = {
        # Plain distance-based surprise adequacy
        "dsa": lambda x, y: DSA(x, y, subsampling=0.3),
        # Per-class likelihood surprise adequacy
        "pc-lsa": lambda x, y: MultiModalSA.build_by_class(x, y, lambda x, y: LSA(x)),
        # Per-class Mahalanobis-distance surprise adequacy
        "pc-mdsa": lambda x, y: MultiModalSA.build_by_class(x, y, lambda x, y: MDSA(x)),
        # Per-class multimodal likelihood surprise adequacy
        "pc-mlsa": lambda x, y: MultiModalSA.build_by_class(
            x, y, lambda x, y: MLSA(x, num_components=3)
        ),
        # Per-cluster (KMeans) Mahalanobis-distance surprise adequacy
        "pc-mmdsa": lambda x, y: MultiModalSA.build_with_kmeans(
            x, y, lambda x, y: MDSA(x), potential_k=range(2, 6), subsampling=0.3
        ),
    }

    def __init__(
        self,
        model_def,
        params,
        sa_layers: List[int],
        training_dataset: np.ndarray,
        batch_size: int = 1024,
    ):
        self.sa_layers = list(sa_layers)
        self.base_model = BaseModel(
            model_def,
            params,
            activation_layers=self.sa_layers,
            include_last_layer=True,
            batch_size=batch_size,
        )
        self.train_at_timer = Timer()
        with self.train_at_timer:
            self.train_ats, self.train_pred = self._acti_and_pred(training_dataset)

    def _acti_and_pred(
        self, dataset: np.ndarray
    ) -> Tuple[List[np.ndarray], np.ndarray]:
        """Activations and predictions in a single forward pass."""
        outputs = self.base_model.get_activations(dataset)
        assert len(outputs) == len([i for i in self.sa_layers if isinstance(i, int)]) + 1
        return outputs[:-1], np.argmax(outputs[-1], axis=1)

    def evaluate_all(
        self,
        datasets: Dict[str, np.ndarray],
        dsa_badge_size: Optional[int] = None,
    ) -> Dict[str, Dict[str, Tuple[np.ndarray, np.ndarray, List[float]]]]:
        """SA scores + SC-CAM orders for every (variant, dataset) pair.

        Returns ``{sa_name: {ds_name: (scores, cam_order, times)}}``.
        """
        res: Dict[str, Dict] = {}
        test_apt = {}

        logger.info("Collecting SA ATs")
        for ds_name, dataset in datasets.items():
            test_pred_timer = Timer()
            with test_pred_timer:
                test_ats, test_pred = self._acti_and_pred(dataset)
            test_apt[ds_name] = (test_ats, test_pred, test_pred_timer.get())

        for sa_name, sa_func in self.TESTED_SA.items():
            res[sa_name] = {}
            setup_timer = Timer()
            with setup_timer:
                logger.info("Creating %s instance", sa_name)
                sa = sa_func(self.train_ats, self.train_pred)
                if isinstance(sa, DSA) and dsa_badge_size is not None:
                    sa.badge_size = dsa_badge_size
            setup_time = self.train_at_timer.get() + setup_timer.get()

            for ds_name, (test_ats, test_pred, test_pred_time) in test_apt.items():
                sa_timer = Timer()
                with sa_timer:
                    logger.info("Calculating %s for %s", sa_name, ds_name)
                    sa_pred = sa(test_ats, test_pred)
                times = [setup_time, test_pred_time, sa_timer.get()]
                res[sa_name][ds_name] = (sa_pred, times)

        # CAM on surprise-coverage profiles
        for sa_name in self.TESTED_SA.keys():
            for ds_name in datasets.keys():
                sa_pred, times = res[sa_name][ds_name]
                cam_timer = Timer()
                with cam_timer:
                    # Upper bound chosen dynamically from the observed max —
                    # the FINITE max: LSA yields +inf for all samples when the
                    # KDE degrades to zero densities (ops/kde.py "failing
                    # silently" mode), and linspace(0, inf) would produce
                    # all-NaN bucket thresholds. Non-finite SA values then
                    # simply fall outside every bucket.
                    finite = np.asarray(sa_pred)[np.isfinite(sa_pred)]
                    upper = float(finite.max()) if finite.size else 1.0
                    coverage_mapper = SurpriseCoverageMapper(NUM_SC_BUCKETS, upper)
                    coverage_profiles = coverage_mapper.get_coverage_profile(sa_pred)
                    cam_order = [i for i in cam(sa_pred, coverage_profiles)]
                cam_order = np.array(cam_order)
                times.append(cam_timer.get())
                res[sa_name][ds_name] = (sa_pred, cam_order, times)

        return res

"""Surprise-adequacy engine: fit the five tested SA variants on the training
activation traces, score every test set, and derive surprise-coverage CAM
prioritization orders.

What is protocol (reproduced from the reference experiment,
src/dnn_test_prio/handler_surprise.py:19-117, and pinned by
tests/test_reference_engine_parity.py): the five-variant registry with its
exact hyperparameters (DSA at 30% subsampling, per-class LSA/MDSA, per-class
MLSA with 3 mixture components, KMeans-clustered MDSA with k ∈ 2..5 at 30%
subsampling), train ATs + predictions collected in ONE forward pass over
``sa_layers`` + the output layer, 1000-bucket surprise-coverage profiles,
and the four-stage ``[setup, pred, quant, cam]`` time record where setup
includes the (shared) train-AT collection time.

What is this framework's own: the flow — each variant runs a
fit → score → SC-CAM pipeline per dataset (the reference mutates its result
dict across three separate passes), activations come from the jitted tap
forward of ``BaseModel``, and the SC bucket upper bound is the maximum
FINITE observed score: an LSA whose KDE degraded returns +inf for every
sample, and bucket edges up to inf would be all-NaN, silently voiding the
CAM (fix-with-note; non-finite scores simply land outside every bucket).

Fit-path performance layer (engine/sa_prep.py — HOST_PHASE.json measured
~243 s of the 536 s per-run prio host tail in SA setup):

- the train ATs are flattened and by-class partitioned ONCE
  (``SharedTrainPrep``), shared across the per-class variants, with the
  shared cost debited into each consumer's setup record (the same
  time-debit scheme ``CoverageWorker`` uses for its aggregate statistics);
- independent per-modal / candidate-k fits fan over a bounded process pool
  (``TIP_SA_POOL``), seeded so the results are bit-identical to serial;
- while variant *i* scores (device-heavy for DSA), variant *i+1* fits on
  host — a bounded two-stage pipeline (``TIP_SA_PIPELINE``);
- fitted scorers persist in a disk cache (``TIP_SA_CACHE_DIR``) keyed by
  (case study, model id, sa_layers, train fingerprint), so the AL phase
  and ``run_scheduler`` restarts reuse prio-phase fits across processes.
  On a fully-warm cache the train-AT forward pass is skipped entirely; a
  cache hit records its load time as setup (the fit genuinely did not
  happen — logged per variant).
"""

import logging
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from simple_tip_tpu import obs
from simple_tip_tpu.engine.model_handler import BaseModel
from simple_tip_tpu.engine.sa_prep import (
    FitPool,
    SAFitCache,
    SharedTrainPrep,
    VariantFitter,
    pipeline_enabled,
    pool_size,
    variant_fanout_enabled,
)
from simple_tip_tpu.ops.prioritizers import cam
from simple_tip_tpu.ops.surprise import (
    DSA,
    LSA,
    MDSA,
    MLSA,
    MultiModalSA,
    SurpriseCoverageMapper,
)
from simple_tip_tpu.ops.timer import Timer

logger = logging.getLogger(__name__)

NUM_SC_BUCKETS = 1000

# {sa_name: (train_ats, train_preds) -> scorer} — the tested registry.
# ``VariantFitter`` (engine/sa_prep.py) is the shared-prep/parallel
# incarnation of these constructors; bit-parity between the two fit paths
# is pinned by tests/test_sa_prep.py.
SA_VARIANTS: Dict[str, Callable] = {
    "dsa": lambda ats, preds: DSA(ats, preds, subsampling=0.3),
    "pc-lsa": lambda ats, preds: MultiModalSA.build_by_class(
        ats, preds, lambda a, _: LSA(a)
    ),
    "pc-mdsa": lambda ats, preds: MultiModalSA.build_by_class(
        ats, preds, lambda a, _: MDSA(a)
    ),
    "pc-mlsa": lambda ats, preds: MultiModalSA.build_by_class(
        ats, preds, lambda a, _: MLSA(a, num_components=3)
    ),
    "pc-mmdsa": lambda ats, preds: MultiModalSA.build_with_kmeans(
        ats, preds, lambda a, _: MDSA(a), potential_k=range(2, 6), subsampling=0.3
    ),
}

DatasetResult = Tuple[np.ndarray, np.ndarray, List[float]]
"""(sa_scores, sc_cam_order, [setup, pred, quant, cam] seconds)."""

PreparedScorer = Tuple[str, object, float]
"""(sa_name, fitted scorer, setup seconds attributed to it)."""


def _sc_cam_order(sa_scores: np.ndarray) -> np.ndarray:
    """Coverage-additional order over 1000-bucket SC profiles, bounded by
    the max finite score (see module docstring)."""
    finite = np.asarray(sa_scores)[np.isfinite(sa_scores)]
    upper = float(finite.max()) if finite.size else 1.0
    profiles = SurpriseCoverageMapper(NUM_SC_BUCKETS, upper).get_coverage_profile(
        sa_scores
    )
    return np.fromiter(cam(sa_scores, profiles), dtype=np.int64)


class SurpriseHandler:
    """One fitted-per-run surprise engine shared by the prio and AL phases.

    ``case_study`` / ``model_id`` namespace the disk fit cache; without
    them the cache still works keyed purely on the train fingerprint.
    Train-AT collection is lazy: a fully-warm cache never pays the
    training-set forward pass.
    """

    # Back-compat alias for the registry's historical name.
    TESTED_SA = SA_VARIANTS

    def __init__(
        self,
        model_def,
        params,
        sa_layers: List[int],
        training_dataset: np.ndarray,
        batch_size: int = 1024,
        case_study: Optional[str] = None,
        model_id: Optional[int] = None,
    ):
        self.sa_layers = list(sa_layers)
        self.params = params
        self.training_dataset = training_dataset
        self.case_study = case_study
        self.model_id = model_id
        self.base_model = BaseModel(
            model_def,
            params,
            activation_layers=self.sa_layers,
            include_last_layer=True,
            batch_size=batch_size,
        )
        self.train_at_timer = Timer()
        self.train_ats: Optional[List[np.ndarray]] = None
        self.train_pred: Optional[np.ndarray] = None
        self._prep: Optional[SharedTrainPrep] = None
        self._fitter: Optional[VariantFitter] = None
        self._cache: Optional[SAFitCache] = None
        self._cache_resolved = False

    def _traces(self, dataset: np.ndarray) -> Tuple[List[np.ndarray], np.ndarray]:
        """(tapped activations, argmax predictions) — one forward pass."""
        outs = self.base_model.get_activations(dataset)
        n_taps = sum(1 for layer in self.sa_layers if isinstance(layer, int))
        assert len(outs) == n_taps + 1, (len(outs), n_taps)
        return outs[:-1], np.argmax(outs[-1], axis=1)

    def _ensure_cache(self) -> Optional[SAFitCache]:
        """Resolve the fit cache once (fingerprinting hashes params+data)."""
        if not self._cache_resolved:
            self._cache_resolved = True
            self._cache = SAFitCache.from_env(
                self.case_study,
                self.model_id,
                self.params,
                self.training_dataset,
                self.sa_layers,
            )
        return self._cache

    def _ensure_fitter(self) -> VariantFitter:
        """Collect train traces + shared prep on first (cache-missing) fit."""
        if self._fitter is None:
            with self.train_at_timer:
                self.train_ats, self.train_pred = self._traces(self.training_dataset)
            self._prep = SharedTrainPrep(self.train_ats, self.train_pred)
            self._fitter = VariantFitter(self._prep, FitPool(pool_size()))
        return self._fitter

    def _prepare_one(self, sa_name: str, dsa_badge_size: Optional[int]) -> PreparedScorer:
        """Fitted scorer for one variant: cache load, else shared-prep fit.

        Setup seconds follow the reference contract on the fit path
        (train-AT collection + shared-prep debit + own fit); a cache hit
        records its load time (the work genuinely did not happen). The
        cache store itself is bus bookkeeping (like ``_persist``) and is
        not part of the setup record. The whole preparation is one obs
        span (``sa_fit``) stamped with the variant and cache outcome.
        """
        with obs.span("sa_fit", variant=sa_name) as span:
            cache = self._ensure_cache()
            if cache is not None:
                load_timer = Timer()
                with load_timer:
                    scorer = cache.load(sa_name)
                if scorer is not None:
                    logger.info(
                        "sa-fit cache HIT for %s (%s)", sa_name, cache.describe(sa_name)
                    )
                    span.set(cached=True, setup_s=load_timer.get())
                    if dsa_badge_size is not None and isinstance(scorer, DSA):
                        scorer.badge_size = dsa_badge_size
                    return sa_name, scorer, load_timer.get()
            fitter = self._ensure_fitter()
            logger.info("fitting %s", sa_name)
            with Timer() as fit_timer:
                scorer = fitter.build(sa_name)
            setup_s = (
                self.train_at_timer.get()
                + self._prep.debit_for(sa_name)
                + fit_timer.get()
            )
            span.set(cached=False, setup_s=setup_s)
            if cache is not None:
                cache.store(sa_name, scorer)
            if dsa_badge_size is not None and isinstance(scorer, DSA):
                scorer.badge_size = dsa_badge_size
            return sa_name, scorer, setup_s

    def _prepared_fanout(
        self, dsa_badge_size: Optional[int]
    ) -> Iterator[PreparedScorer]:
        """All variants at once: load what the cache has, fan the missing
        WHOLE-variant fits over the process pool, yield in registry order.

        Setup accounting matches ``_prepare_one``: hits record their load
        time; fits record train-AT collection + shared-prep debit + the
        fit's own wall (a pooled worker's wall includes its in-worker prep
        rebuild — the parent's debit is charged exactly once per variant,
        never double-counted by the worker).
        """
        names = list(SA_VARIANTS)
        cache = self._ensure_cache()
        prepared: Dict[str, PreparedScorer] = {}
        missing: List[str] = []
        for name in names:
            scorer = None
            load_timer = Timer()
            if cache is not None:
                with load_timer:
                    scorer = cache.load(name)
            if scorer is not None:
                logger.info(
                    "sa-fit cache HIT for %s (%s)", name, cache.describe(name)
                )
                with obs.span("sa_fit", variant=name, fanout=True) as span:
                    span.set(cached=True, setup_s=load_timer.get())
                prepared[name] = (name, scorer, load_timer.get())
            else:
                missing.append(name)
        if missing:
            fitter = self._ensure_fitter()
            logger.info("fan-out fitting %s", ", ".join(missing))
            built = fitter.build_variants(missing)
            for name in missing:
                scorer, fit_s = built[name]
                setup_s = (
                    self.train_at_timer.get()
                    + self._prep.debit_for(name)
                    + fit_s
                )
                with obs.span("sa_fit", variant=name, fanout=True) as span:
                    span.set(cached=False, setup_s=setup_s)
                if cache is not None:
                    cache.store(name, scorer)
                prepared[name] = (name, scorer, setup_s)
        for name in names:
            sa_name, scorer, setup_s = prepared[name]
            if dsa_badge_size is not None and isinstance(scorer, DSA):
                scorer.badge_size = dsa_badge_size
            yield sa_name, scorer, setup_s

    def _prepared_scorers(
        self, dsa_badge_size: Optional[int]
    ) -> Iterator[PreparedScorer]:
        """Yield fitted scorers in registry order, optionally pipelined.

        With whole-variant fan-out on (``TIP_SA_FANOUT``; auto = when the
        fit pool has more than one worker), all five fits dispatch to the
        pool at once instead of riding the two-stage pipeline. Otherwise,
        with the pipeline on, variant *i+1* fits (or cache-loads) in a
        single background thread while the caller scores variant *i* —
        a bounded two-stage pipeline; the fits themselves stay in
        registry order, so timing records and results are unaffected.
        """
        names = list(SA_VARIANTS)
        if variant_fanout_enabled() and len(names) >= 2:
            yield from self._prepared_fanout(dsa_badge_size)
            return
        if not pipeline_enabled() or len(names) < 2:
            for name in names:
                yield self._prepare_one(name, dsa_badge_size)
            return
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=1, thread_name_prefix="sa-fit") as ex:
            fut = ex.submit(self._prepare_one, names[0], dsa_badge_size)
            for i in range(len(names)):
                item = fut.result()
                if i + 1 < len(names):
                    fut = ex.submit(self._prepare_one, names[i + 1], dsa_badge_size)
                yield item

    def evaluate_all(
        self,
        datasets: Dict[str, np.ndarray],
        dsa_badge_size: Optional[int] = None,
    ) -> Dict[str, Dict[str, DatasetResult]]:
        """``{sa_name: {ds_name: (scores, cam_order, times)}}`` for every
        (variant, dataset) pair."""
        logger.info("collecting test-set activation traces")
        traces: Dict[str, Tuple[List[np.ndarray], np.ndarray, float]] = {}
        for ds_name, dataset in datasets.items():
            with Timer() as pred_timer:
                ats, preds = self._traces(dataset)
            traces[ds_name] = (ats, preds, pred_timer.get())

        results: Dict[str, Dict[str, DatasetResult]] = {}
        try:
            for sa_name, scorer, setup_s in self._prepared_scorers(dsa_badge_size):
                per_ds: Dict[str, DatasetResult] = {}
                for ds_name, (ats, preds, pred_s) in traces.items():
                    logger.info("scoring %s on %s", sa_name, ds_name)
                    # Named timers mirror the quant/cam segments into the
                    # obs trace while keeping the reference timing record.
                    with Timer(name="sa_score", variant=sa_name, ds=ds_name) as quant_timer:
                        scores = scorer(ats, preds)
                    with Timer(name="sa_cam", variant=sa_name, ds=ds_name) as cam_timer:
                        order = _sc_cam_order(scores)
                    per_ds[ds_name] = (
                        scores,
                        order,
                        [setup_s, pred_s, quant_timer.get(), cam_timer.get()],
                    )
                results[sa_name] = per_ds
        finally:
            if self._fitter is not None:
                self._fitter.pool.close()
        return results

"""Shared-preparation, fit-parallelism and persistence for the SA engine.

``HOST_PHASE.json`` locates ~243 s of the 536 s per-run test-prio host tail
in surprise-adequacy *setup* (pc-mlsa 91.9 s, pc-mmdsa 75.6 s, pc-mdsa
50.9 s, pc-lsa 12.9 s, dsa 11.8 s) — pure host work that serializes across
all 100 runs no matter how fast the chip is. Three structural facts make it
attackable (Podracer's lesson, PAPERS.md: keep host preparation pipelined
against device work rather than letting either idle):

1. **The prep is redundant.** Each per-class variant independently
   re-flattens the train ATs and re-partitions them by predicted class.
   ``SharedTrainPrep`` computes the flatten and the by-class partition
   (index arrays + per-class AT views) ONCE, shared by pc-lsa / pc-mdsa /
   pc-mlsa (pc-mmdsa and dsa share the flatten). The shared cost is
   attributed to each consuming variant's ``[setup, pred, quant, cam]``
   record via the same time-debit scheme ``CoverageWorker`` uses for its
   shared aggregate statistics (engine/coverage_handler.py), so the
   reference's timing contract is preserved.
2. **The fits are embarrassingly parallel.** The ~10 per-class constructors
   of each per-class variant, pc-mmdsa's per-cluster MDSA fits, and the
   KMeans candidate-k fits are independent seeded computations.
   ``FitPool`` fans them over a bounded spawn-based process pool
   (``TIP_SA_POOL``); every fit is seeded, so the results are
   bit-identical to the serial path (pinned by tests/test_sa_prep.py).
3. **The fits are re-run needlessly.** The "fitted once, shared by the
   prio and AL phases" claim only held within one process, and
   ``run_scheduler`` spawns a fresh interpreter per phase. ``SAFitCache``
   persists fitted scorers on disk keyed by (case study, model id,
   sa_layers, train-set fingerprint), so the AL phase and scheduler
   restarts/requeues reuse the prio-phase fits instead of refitting.

Module import stays jax-free on purpose: the pool's spawned workers import
this module, and host-side sklearn/numpy fits must never pay (or wedge on)
an accelerator-backend initialization.
"""

import hashlib
import logging
import os
import pickle
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from simple_tip_tpu import obs
from simple_tip_tpu.resilience import RetryPolicy, faults
from simple_tip_tpu.utils.artifacts_io import atomic_write_bytes
from simple_tip_tpu.ops.surprise import (
    DSA,
    LSA,
    MDSA,
    MLSA,
    MultiModalSA,
    _by_class_discriminator,
    _class_predictions,
    _flatten_layers,
    _flatten_predictions,
    _KmeansDiscriminator,
    resolved_cluster_backend,
)
from simple_tip_tpu.ops.timer import Timer

logger = logging.getLogger(__name__)

#: Bump when the cache entry layout or any fit hyperparameter baked into the
#: registry changes; stale-version entries are treated as misses.
CACHE_FORMAT_VERSION = "sa-fit-cache-v1"

# Per-modal constructors by picklable kind-name (the pool ships kind strings,
# never closures). Must mirror the modal lambdas of the tested registry
# (engine/surprise_handler.SA_VARIANTS); parity is pinned by test_sa_prep.
_MODAL_KINDS: Dict[str, Callable] = {
    "lsa": lambda acts, preds: LSA(acts),
    "mdsa": lambda acts, preds: MDSA(acts),
    "mlsa3": lambda acts, preds: MLSA(acts, num_components=3),
}

#: Per-class modal kind of each by-class registry variant.
BY_CLASS_MODAL = {"pc-lsa": "lsa", "pc-mdsa": "mdsa", "pc-mlsa": "mlsa3"}


def _fit_modal_task(task):
    """Fit ONE modal SA instance (runs in a pool worker or inline).

    ``task`` = (modal_id, kind, activations, predictions); returns
    (modal_id, fitted SA). Top-level so spawn can pickle it.
    """
    modal_id, kind, acts, preds = task
    return modal_id, _MODAL_KINDS[kind](acts, preds)


def _pool_worker_init(env: Dict[str, str]) -> None:
    """Pool-worker initializer: pin the resolved env before any fit runs.

    Pins ``TIP_CLUSTER_BACKEND`` to the PARENT's resolved choice (a worker
    re-resolving ``auto`` would import jax and probe a possibly-dead
    tunnel) and ``JAX_PLATFORMS=cpu`` as a belt-and-braces guard — pooled
    fits are host-side sklearn/numpy by policy (see ``pool_size``).
    """
    os.environ.update(env)


def pool_size() -> int:
    """Bounded fit-pool size from ``TIP_SA_POOL`` (≤1 disables the pool).

    ``auto`` (default): 1 on hosts with ≤2 cores (spawn + pickling overhead
    would exceed the win — measured single-core host, SCALING.md), else
    ``min(8, cpu_count - 1)`` so the pool never starves the scoring/device
    thread. An explicit integer forces that size.
    """
    raw = os.environ.get("TIP_SA_POOL", "auto").strip().lower()
    if raw in ("", "auto"):
        cores = os.cpu_count() or 1
        return 1 if cores <= 2 else min(8, cores - 1)
    try:
        return max(1, int(raw))
    except ValueError:
        raise ValueError(f"TIP_SA_POOL={raw!r} not recognized (auto or an int)")


def pipeline_enabled() -> bool:
    """Whether ``evaluate_all`` overlaps variant *i*'s scoring with variant
    *i+1*'s host fit (``TIP_SA_PIPELINE``, default on; ``0``/``off`` disables)."""
    raw = os.environ.get("TIP_SA_PIPELINE", "auto").strip().lower()
    if raw in ("", "auto", "1", "on"):
        return True
    if raw in ("0", "off"):
        return False
    raise ValueError(f"TIP_SA_PIPELINE={raw!r} not recognized (auto, 1, 0)")


def variant_fanout_enabled() -> bool:
    """Whether whole VARIANTS (not just their modals) fan out over the pool
    (``TIP_SA_FANOUT``; ``auto`` = on exactly when ``pool_size() > 1``)."""
    raw = os.environ.get("TIP_SA_FANOUT", "auto").strip().lower()
    if raw in ("", "auto"):
        return pool_size() > 1
    if raw in ("1", "on"):
        return True
    if raw in ("0", "off"):
        return False
    raise ValueError(f"TIP_SA_FANOUT={raw!r} not recognized (auto, 1, 0)")


def sa_cache_max_bytes() -> Optional[int]:
    """Size cap for the sa_fit_cache dir from ``TIP_SA_CACHE_MAX_BYTES``.

    Same grammar as ``TIP_OBS_MAX_BYTES`` (obs/tracer.py): a plain byte
    count or a ``k``/``m``/``g``-suffixed size; empty / ``0`` / ``off`` /
    ``unlimited`` / ``none`` means uncapped (None). LSA/MDSA pickles carry
    d² covariance/precision matrices, so a long-lived shared cache dir
    grows without bound unless swept.
    """
    raw = os.environ.get("TIP_SA_CACHE_MAX_BYTES", "").strip().lower()
    if not raw:
        return None
    if raw in ("0", "off", "unlimited", "none"):
        return None
    mult = 1
    if raw[-1] in ("k", "m", "g"):
        mult = {"k": 1024, "m": 1024**2, "g": 1024**3}[raw[-1]]
        raw = raw[:-1]
    try:
        return int(float(raw) * mult)
    except ValueError:
        raise ValueError(
            f"TIP_SA_CACHE_MAX_BYTES={raw!r} not recognized (bytes, or k/m/g suffix)"
        )


class FitPool:
    """Bounded spawn-based process pool for independent seeded SA fits.

    ``spawn`` (never ``fork``) follows the repo-wide policy
    (parallel/run_scheduler.py): a forked child could inherit initialized
    backend/tunnel state. Workers only ever run host-side sklearn/numpy
    fits, so their startup cost is an interpreter + numpy/sklearn import,
    not a jax init. Any pool-level failure (a worker OOM-killed, a broken
    pipe) degrades to the serial in-process path with a warning — the pool
    is an optimization, never a correctness dependency.
    """

    def __init__(self, processes: int):
        self.processes = processes
        self._executor = None

    def _ensure(self):
        from concurrent.futures import ProcessPoolExecutor
        import multiprocessing as mp

        if self._executor is None:
            env = {
                "TIP_CLUSTER_BACKEND": resolved_cluster_backend(),
                "JAX_PLATFORMS": "cpu",
            }
            self._executor = ProcessPoolExecutor(
                max_workers=self.processes,
                mp_context=mp.get_context("spawn"),
                initializer=_pool_worker_init,
                initargs=(env,),
            )
        return self._executor

    def map(self, fn: Callable, tasks: Sequence) -> List:
        """``[fn(t) for t in tasks]`` across the pool, order-preserving;
        falls back to the serial path if the pool breaks."""
        import multiprocessing as mp

        # run_scheduler workers are daemonic and may not spawn children;
        # inside one, the run-level parallelism already owns the cores.
        if (
            self.processes <= 1
            or len(tasks) <= 1
            or mp.current_process().daemon
        ):
            return [fn(t) for t in tasks]
        try:
            return list(self._ensure().map(fn, tasks))
        except Exception as e:  # noqa: BLE001 — any pool failure degrades to serial
            logger.warning("SA fit pool failed (%r); refitting serially", e)
            self.close()
            return [fn(t) for t in tasks]

    def close(self) -> None:
        """Shut the pool down (idempotent)."""
        if self._executor is not None:
            self._executor.shutdown(wait=False, cancel_futures=True)
            self._executor = None


class SharedTrainPrep:
    """Flatten + by-class partition of the train ATs, computed once.

    ``flatten_debit`` covers the flatten + prediction validation every
    variant previously paid inside its own fit; ``partition_debit``
    additionally covers the by-class index arrays + per-class AT views the
    three per-class variants each rebuilt. ``debit_for`` returns the share
    a variant's setup record owes (CoverageWorker's time-debit scheme).
    """

    def __init__(self, train_ats, train_pred):
        flat_timer = Timer()
        with flat_timer:
            self.flat = _flatten_layers(train_ats)
            self.pred = _class_predictions(_flatten_predictions(train_pred))
        part_timer = Timer()
        with part_timer:
            self.class_ids = np.unique(self.pred)
            self.class_views: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
            for c in self.class_ids:
                mask = self.pred == c
                self.class_views[int(c)] = (self.flat[mask], self.pred[mask])
        self.flatten_debit = flat_timer.get()
        self.partition_debit = part_timer.get()

    def debit_for(self, sa_name: str) -> float:
        """Shared-prep seconds attributable to ``sa_name``'s setup record."""
        if sa_name in BY_CLASS_MODAL:
            return self.flatten_debit + self.partition_debit
        return self.flatten_debit


def _fit_variant_task(task):
    """Fit ONE whole registry variant (runs in a pool worker or inline).

    ``task`` = (sa_name, flat train ATs, flat class predictions); returns
    (sa_name, fitted scorer, fit wall seconds). The worker rebuilds its own
    ``SharedTrainPrep`` from the shipped flat arrays (flatten is idempotent
    on an already-flat single layer) and runs a serial fit — every fit is
    seeded, so the result is bit-identical to the in-process path.
    Top-level so spawn can pickle it.
    """
    import time

    sa_name, flat, pred = task
    t0 = time.perf_counter()
    prep = SharedTrainPrep([flat], pred)
    scorer = VariantFitter(prep, FitPool(1)).build(sa_name)
    return sa_name, scorer, time.perf_counter() - t0


def _poolable_variant(sa_name: str) -> bool:
    """Whether a whole-variant fit may run in a spawn worker.

    dsa / pc-lsa / pc-mdsa are pure host numpy/scipy fits; pc-mlsa and
    pc-mmdsa involve GMM/KMeans fits that run on the device when the
    resolved cluster backend is jax — pooling those would silently change
    numerics vs the in-process device path, so they stay in the parent.
    """
    if sa_name in ("dsa", "pc-lsa", "pc-mdsa"):
        return True
    return resolved_cluster_backend() == "sklearn"


def estimate_variant_fit_bytes(sa_name: str, n: int, d: int) -> int:
    """Worst-case worker working-set estimate for one variant fit.

    Every worker ships the f32 (n, d) train matrix and rebuilds the
    by-class partition (~2 more transient copies). On top of that: LSA's
    KDE whitens an f64 copy (dims capped ~300 by the variance filter),
    MDSA/MMDSA factor d² f64 covariance/precision matrices, MLSA holds
    per-component responsibilities (~3 more n·d f32 blocks at 3
    components). The profile only needs to be the right order of
    magnitude: it sizes the fan-out, it does not gate correctness.
    """
    base = 3 * n * d * 4
    if sa_name in ("pc-lsa",):
        return base + n * min(d, 300) * 8 + 3 * 300 * 300 * 8
    if sa_name in ("pc-mdsa", "pc-mmdsa"):
        return base + 3 * d * d * 8
    if sa_name == "pc-mlsa":
        return base + 4 * n * d * 4
    return base + n * d * 4  # dsa keeps a reference copy for kNN


def mem_fraction() -> float:
    """The FitPool memory bound: fraction of available RAM the fan-out may
    budget (``TIP_SA_MEM_FRAC``, a planner knob; default 0.5, clamped to
    (0, 1]; a bad value warns and keeps the default, never crashes)."""
    raw = os.environ.get("TIP_SA_MEM_FRAC", "").strip()
    if not raw:
        return 0.5
    try:
        frac = float(raw)
    except ValueError:
        logging.getLogger(__name__).warning(
            "TIP_SA_MEM_FRAC=%r is not a number; using 0.5", raw
        )
        return 0.5
    return min(max(frac, 0.01), 1.0)


def fanout_workers(names: Sequence[str], n: int, d: int) -> int:
    """How many whole-variant fits may run at once within the memory budget
    (``mem_fraction()`` of available RAM; serial when psutil or the budget
    says no)."""
    cap = min(pool_size(), len(names))
    if cap <= 1:
        return 1
    try:
        import psutil

        budget = int(psutil.virtual_memory().available * mem_fraction())
    except Exception:  # noqa: BLE001 — no psutil: trust pool_size alone
        return cap
    per_variant = max(
        [estimate_variant_fit_bytes(s, n, d) for s in names] or [1]
    )
    return max(1, min(cap, budget // max(1, per_variant)))


class VariantFitter:
    """Builds every registry variant from one ``SharedTrainPrep``.

    Per-modal constructors (and the KMeans candidate-k fits, when the
    resolved cluster backend is sklearn) fan out over ``pool``; everything
    is seeded, so the result is bit-identical to the serial reference path
    (pinned by tests/test_sa_prep.py).
    """

    def __init__(self, prep: SharedTrainPrep, pool: Optional[FitPool] = None):
        self.prep = prep
        self.pool = pool or FitPool(1)

    def _poolable(self, kind: str) -> bool:
        # lsa/mdsa are pure host numpy/scipy; mlsa3 and the KMeans candidate
        # fits only when the resolved backend is sklearn — pooling the jnp
        # backend would move device fits onto worker CPUs and silently
        # change numerics vs the serial device path.
        if kind in ("lsa", "mdsa"):
            return True
        return resolved_cluster_backend() == "sklearn"

    def _fit_modals(self, kind: str, partitions) -> Dict[int, object]:
        tasks = [(int(m), kind, acts, preds) for m, (acts, preds) in partitions]
        mapper = self.pool.map if self._poolable(kind) else lambda f, t: [f(x) for x in t]
        return dict(mapper(_fit_modal_task, tasks))

    def build(self, sa_name: str):
        """Fit one registry variant; returns the fitted scorer (any
        ``dsa_badge_size`` override is the caller's concern — it is device
        chunking, not fitted state)."""
        prep = self.prep
        if sa_name == "dsa":
            return DSA(prep.flat, prep.pred, subsampling=0.3)
        if sa_name in BY_CLASS_MODAL:
            modal_sa = self._fit_modals(
                BY_CLASS_MODAL[sa_name],
                ((c, prep.class_views[int(c)]) for c in prep.class_ids),
            )
            return MultiModalSA(
                discriminator=_by_class_discriminator, modal_sa=modal_sa
            )
        if sa_name == "pc-mmdsa":
            kmeans_map = self.pool.map if self._poolable("kmeans") else None
            discriminator = _KmeansDiscriminator(
                training_data=prep.flat,
                potential_k=range(2, 6),
                subsampling=0.3,
                fit_map=kmeans_map,
            )
            modal_indexes = discriminator(prep.flat, prep.pred)
            modal_sa = self._fit_modals(
                "mdsa",
                (
                    (m, (prep.flat[modal_indexes == m], prep.pred[modal_indexes == m]))
                    for m in np.unique(modal_indexes)
                ),
            )
            return MultiModalSA(discriminator=discriminator, modal_sa=modal_sa)
        raise KeyError(f"unknown SA variant {sa_name!r}")

    def build_variants(self, names: Sequence[str]) -> Dict[str, Tuple[object, float]]:
        """Fit several variants, whole-variant fan-out over the pool.

        Poolable variants (``_poolable_variant``) ship as one task each to
        a memory-profiled worker count (``fanout_workers``); the rest fit
        serially in-process. Returns ``{sa_name: (scorer, fit_s)}`` where
        ``fit_s`` is the fit's own wall time (the worker's wall includes
        its prep rebuild — the parent's shared-prep debit is accounted
        separately by the caller, never double-counted here).
        """
        import time

        n, d = self.prep.flat.shape
        pooled = [s for s in names if _poolable_variant(s)]
        out: Dict[str, Tuple[object, float]] = {}
        workers = fanout_workers(pooled, n, d) if pooled else 1
        if workers > 1 and len(pooled) > 1:
            tasks = [(s, self.prep.flat, self.prep.pred) for s in pooled]
            fan_pool = FitPool(workers)
            try:
                for sa_name, scorer, fit_s in fan_pool.map(_fit_variant_task, tasks):
                    out[sa_name] = (scorer, fit_s)
            finally:
                fan_pool.close()
        else:
            pooled = []
        for sa_name in names:
            if sa_name in out:
                continue
            t0 = time.perf_counter()
            scorer = self.build(sa_name)
            out[sa_name] = (scorer, time.perf_counter() - t0)
        return out


def content_fingerprint(
    version: str, params, training_dataset, layers: Sequence, *tags: str
) -> str:
    """sha256 of one (model, train set, tap config) triple plus cache tags.

    Hash order is the stable contract every disk cache keys on: version
    string, ``repr(list(layers))``, each extra tag, then parameter leaves
    (shape/dtype/bytes) and the raw training array. Deliberately does NOT
    require a forward pass: a fully-warm cache must be able to skip
    train-AT collection entirely.
    """
    import jax

    h = hashlib.sha256()
    h.update(version.encode())
    h.update(repr(list(layers)).encode())
    for tag in tags:
        h.update(tag.encode())
    for leaf in jax.tree_util.tree_leaves(params):
        arr = np.asarray(leaf)
        h.update(str(arr.shape).encode() + str(arr.dtype).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    data = np.asarray(training_dataset)
    h.update(str(data.shape).encode() + str(data.dtype).encode())
    h.update(np.ascontiguousarray(data).tobytes())
    return h.hexdigest()


def train_fingerprint(params, training_dataset, sa_layers: Sequence) -> str:
    """SA-fit fingerprint: ``content_fingerprint`` tagged with the resolved
    cluster backend (it changes fitted estimators, so sklearn- and
    jax-resolved fits may never cross-hit)."""
    return content_fingerprint(
        CACHE_FORMAT_VERSION,
        params,
        training_dataset,
        sa_layers,
        resolved_cluster_backend(),
    )


class SAFitCache:
    """Disk-backed fitted-scorer cache for the five SA registry variants.

    One pickle per (case study, model id, fingerprint, variant) under
    ``TIP_SA_CACHE_DIR`` (default ``$TIP_ASSETS/sa_fit_cache``; ``off``
    disables, as does constructing with ``root=None``). Writes are atomic
    (tmp + rename, unique per pid) so concurrent scheduler workers can
    share one cache dir; loads verify the stored meta and treat ANY
    read/unpickle failure as a miss (refit overwrites the bad entry) — a
    corrupt cache can cost time, never correctness.
    """

    def __init__(self, root: str, case_study: str, model_ref: str, fingerprint: str):
        self.root = root
        self.case_study = case_study
        self.model_ref = model_ref
        self.fingerprint = fingerprint
        # Open-path hygiene: a writer killed between its tmp write and the
        # rename (artifact.write 'kill' fault, real power loss) leaks a
        # pid-unique tmp; sweep aged ones so restarts don't accrete litter.
        from simple_tip_tpu.utils.artifacts_io import sweep_orphan_tmp

        sweep_orphan_tmp(self.root)

    @classmethod
    def from_env(
        cls, case_study: Optional[str], model_id, params, training_dataset, sa_layers
    ) -> Optional["SAFitCache"]:
        """Cache handle per ``TIP_SA_CACHE_DIR`` policy, or None when off."""
        raw = os.environ.get("TIP_SA_CACHE_DIR", "").strip()
        if raw.lower() in ("off", "0"):
            return None
        if not raw:
            from simple_tip_tpu.config import output_folder

            raw = os.path.join(output_folder(), "sa_fit_cache")
        fp = train_fingerprint(params, training_dataset, sa_layers)
        return cls(
            root=raw,
            case_study=case_study or "default",
            model_ref="na" if model_id is None else str(model_id),
            fingerprint=fp,
        )

    def _path(self, sa_name: str) -> str:
        return os.path.join(
            self.root,
            f"{self.case_study}_{self.model_ref}_{self.fingerprint[:16]}"
            f"_{sa_name}.pkl",
        )

    def describe(self, sa_name: str) -> str:
        """Human-readable entry label for cache-hit/miss log lines."""
        return self._path(sa_name)

    @staticmethod
    def _read(path: str):
        """One read+unpickle attempt (retried for transient IO only)."""
        with open(path, "rb") as f:
            return pickle.load(f)

    def load(self, sa_name: str):
        """The cached fitted scorer, or None on miss/stale/corrupt entries.

        Transient IO errors (a briefly unavailable shared cache mount —
        NOT unpickle failures, which retrying cannot fix) are retried
        under the ``sa_cache`` scope of the unified policy before the
        entry degrades to a refit. The ``sa_cache.load`` fault seam lets
        the chaos suite corrupt the on-disk pickle first, driving the
        REAL corrupt-entry path rather than a mock of it.
        """
        path = self._path(sa_name)
        fault = faults.maybe_inject("sa_cache.load", variant=sa_name, path=path)
        if fault is not None and fault.kind == "corrupt":
            faults.corrupt_file(path)
        try:
            entry = RetryPolicy.from_env(
                scope="sa_cache", attempts=2, base_s=0.05, deadline_s=10.0
            ).call(
                self._read,
                path,
                transient=(OSError,),
                fatal=(FileNotFoundError,),
                describe=f"sa-fit cache read ({sa_name})",
            )
            meta = entry["meta"]
            if (
                meta["version"] != CACHE_FORMAT_VERSION
                or meta["variant"] != sa_name
                or meta["fingerprint"] != self.fingerprint
            ):
                logger.info("sa-fit cache STALE for %s (%s)", sa_name, path)
                obs.counter("sa_fit_cache.stale").inc()
                obs.event("sa_cache", variant=sa_name, outcome="stale")
                return None
            obs.counter("sa_fit_cache.hit").inc()
            obs.event("sa_cache", variant=sa_name, outcome="hit")
            try:
                os.utime(path)  # LRU recency: a hit entry is the last swept
            except OSError:
                pass
            return entry["scorer"]
        except FileNotFoundError:
            obs.counter("sa_fit_cache.miss").inc()
            obs.event("sa_cache", variant=sa_name, outcome="miss")
            return None
        except Exception as e:  # noqa: BLE001 — any corrupt entry degrades to refit
            logger.warning(
                "sa-fit cache entry corrupt for %s (%s: %r); refitting",
                sa_name,
                path,
                e,
            )
            obs.counter("sa_fit_cache.corrupt").inc()
            obs.event("sa_cache", variant=sa_name, outcome="corrupt")
            return None

    def store(self, sa_name: str, scorer) -> None:
        """Persist one fitted scorer (atomic; failures warn, never raise).

        The write rides ``atomic_write_bytes`` (tmp + fsync + rename), so
        a kill mid-store — the chaos suite injects one at the
        ``artifact.write`` seam — can never leave a torn entry at the
        final path: the next reader sees either the old entry or none.
        """
        path = self._path(sa_name)
        try:
            os.makedirs(self.root, exist_ok=True)
            entry = {
                "meta": {
                    "version": CACHE_FORMAT_VERSION,
                    "variant": sa_name,
                    "fingerprint": self.fingerprint,
                    "case_study": self.case_study,
                    "model_ref": self.model_ref,
                },
                "scorer": scorer,
            }
            atomic_write_bytes(path, pickle.dumps(entry, protocol=4))
            logger.info("sa-fit cache stored %s (%s)", sa_name, path)
            obs.counter("sa_fit_cache.store").inc()
            self._sweep(keep=path)
        except Exception as e:  # noqa: BLE001 — cache is an optimization only
            logger.warning("sa-fit cache store failed for %s (%r)", sa_name, e)

    def _sweep(self, keep: str) -> None:
        """Evict least-recently-used entries until the dir fits the
        ``TIP_SA_CACHE_MAX_BYTES`` cap (never the just-written ``keep``
        entry; concurrent-unlink races are benign misses)."""
        cap = sa_cache_max_bytes()
        if cap is None:
            return
        entries = []
        for name in os.listdir(self.root):
            if not name.endswith(".pkl"):
                continue
            full = os.path.join(self.root, name)
            try:
                st = os.stat(full)
            except OSError:
                continue
            entries.append((st.st_mtime, st.st_size, full))
        total = sum(size for _, size, _ in entries)
        keep = os.path.abspath(keep)
        for _, size, full in sorted(entries):
            if total <= cap:
                break
            if os.path.abspath(full) == keep:
                continue
            try:
                os.unlink(full)
            except OSError:
                continue
            total -= size
            logger.info("sa-fit cache evicted %s (cap %d bytes)", full, cap)
            obs.counter("sa_fit_cache.evict").inc()
            obs.event("sa_cache", outcome="evict", path=full)

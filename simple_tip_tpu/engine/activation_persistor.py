"""Activation-trace dump utility (the reference's post-paper ``at_collection``
phase, reference: src/dnn_test_prio/activation_persistor.py): every tapped
layer's activations plus labels, in badges of 100, to
``activations/{cs}/model_{id}/{ds}/layer_{i}/badge_{j}.npy``.

Warning from the reference applies here too: the full dump across all
models/datasets is *multiple terabytes*.
"""

import os
from typing import Tuple

import numpy as np

from simple_tip_tpu.config import output_folder
from simple_tip_tpu.engine.model_handler import BaseModel

BADGE_SIZE = 100


def _persist_badge(case_study, model_id, dataset, badge_id, activations, labels):
    path = os.path.join(
        output_folder(), "activations", case_study, f"model_{model_id}", dataset
    )
    for layer_i, layer_at in enumerate(activations):
        folder = os.path.join(path, f"layer_{layer_i}")
        os.makedirs(folder, exist_ok=True)
        np.save(os.path.join(folder, f"badge_{badge_id}.npy"), layer_at)
    labels_folder = os.path.join(path, "labels")
    os.makedirs(labels_folder, exist_ok=True)
    np.save(os.path.join(labels_folder, f"badge_{badge_id}.npy"), labels)


def persist(
    model_def,
    params,
    case_study: str,
    model_id: int,
    train_set: Tuple[np.ndarray, np.ndarray],
    test_nominal: Tuple[np.ndarray, np.ndarray],
    test_corrupted: Tuple[np.ndarray, np.ndarray],
) -> None:
    """Persist all layer activations of the model for the three datasets."""
    transparent_model = BaseModel(
        model_def,
        params,
        activation_layers=list(model_def.all_layers),
        include_last_layer=False,
        batch_size=BADGE_SIZE,
    )
    for ds, (x, y) in {
        "train": train_set,
        "test_nominal": test_nominal,
        "test_nominal_and_corrupted": test_corrupted,
    }.items():
        for badge_id, start in enumerate(range(0, x.shape[0], BADGE_SIZE)):
            badge_x = x[start : start + BADGE_SIZE]
            badge_y = y[start : start + BADGE_SIZE]
            activations = transparent_model.get_activations(badge_x)
            _persist_badge(case_study, model_id, ds, badge_id, activations, badge_y)

"""Neuron-coverage worker: one pass of aggregate statistics over the training
set, then 12 configured coverage metrics with CAM orders per test set.

Behavioral contract matches the reference's ``CoverageWorker``
(reference: src/dnn_test_prio/handler_coverage.py:20-205), including the
metric configuration (NBC_0/0.5/1, SNAC_0/0.5/1, NAC_0/0.75, TKNC_1/2/3,
KMNC_2), the per-metric setup "time debits" for shared statistics, the
badge-streamed profile spill to temp .npy files (which bounds peak memory and
doubles as the restart point), and the CAM-order sanity check.
"""

import os
import secrets
import shutil
from typing import Callable, Dict, List, Tuple

import numpy as np

from simple_tip_tpu import obs
from simple_tip_tpu.config import output_folder
from simple_tip_tpu.engine.model_handler import BaseModel
from simple_tip_tpu.ops.coverage import (
    KMNC,
    NAC,
    NBC,
    SNAC,
    TKNC,
    CoverageMethod,
    make_fused_profile_fn,
)
from simple_tip_tpu.ops.prioritizers import cam_order
from simple_tip_tpu.ops.timer import Timer

PROFILE_BADGE_SIZE = 512


def _cam_from_packed(scores: np.ndarray, packed: np.ndarray, bit_len: int) -> np.ndarray:
    """CAM order from packed profiles, backend-selected via ``TIP_CAM_BACKEND``:

    - ``native`` (default on host-resident profiles): the C++ popcount kernel
      (6.6x the reference's loop at 20k x 4096, SCALING.md).
    - ``device``: ``cam_order_device`` — the greedy phase as an on-device
      ``lax.while_loop`` popcount sweep. The profiles here are host-resident
      (the badge pass spills/accumulates on host to bound memory), so this
      pays one upload; it wins only when the device is otherwise idle and the
      profile matrix is large — measure before defaulting to it (SCALING.md).
    - ``auto``: native, falling back to the pure-python path.
    """
    backend = os.environ.get("TIP_CAM_BACKEND", "auto").strip().lower()
    if backend not in ("auto", "native", "device", "python"):
        raise ValueError(
            f"TIP_CAM_BACKEND={backend!r} not recognized "
            f"(one of: auto, native, device, python)"
        )
    if backend == "device":
        profiles = np.unpackbits(packed, axis=1, count=bit_len).astype(bool)
        from simple_tip_tpu.ops.prioritizers import cam_order_device

        return cam_order_device(scores, profiles)
    if backend == "python":
        profiles = np.unpackbits(packed, axis=1, count=bit_len).astype(bool)
        return cam_order(scores, profiles)
    try:
        from simple_tip_tpu.ops.native import cam_order_packed

        return cam_order_packed(scores, packed, bit_len)
    except (ImportError, OSError):
        if backend == "native":
            # explicit request must not silently degrade to the slow path
            raise
        profiles = np.unpackbits(packed, axis=1, count=bit_len).astype(bool)
        return cam_order(scores, profiles)


class CoverageWorker:
    """Efficiently handles the 12 configured neuron-coverage instances.

    ``spill``: where test-set profiles live between the badge pass and CAM.
    "memory" keeps them in host RAM (no disk I/O — the TPU-native default
    when RAM allows), "disk" reproduces the reference's temp-npy spill,
    "auto" picks by available memory.
    """

    def __init__(
        self, base_model: BaseModel, training_set: np.ndarray, spill: str = "auto"
    ):
        from simple_tip_tpu.ops.stats import DeviceAggregateStatisticsCollector

        self.base_model = base_model
        self.metrics: Dict[str, CoverageMethod] = {}
        self.setup_times: Dict[str, float] = {}
        self.training_set = training_set
        self.spill = spill
        self._mem_profiles: Dict[str, list] = {}
        self._mem_scores: Dict[str, list] = {}
        self._fused_fn = None
        self._bit_len = None
        # Random token avoids temp-dir collisions between concurrent runs.
        self.temp_random = str(secrets.token_urlsafe(16))

        # The train-stats pass is a pure function of (params, train set, tap
        # layers) but was recomputed by every scheduler process; the disk
        # cache amortizes it to once per study. On a hit, every consuming
        # metric's debit is the LOAD time (the same full-debit-per-metric
        # accounting the recompute path uses), and the train walk is skipped
        # entirely.
        from simple_tip_tpu.engine.coverage_stats_cache import CoverageStatsCache

        stats_cache = CoverageStatsCache.from_env(
            base_model.params, training_set, base_model.activation_layers
        )
        self.stats_cache_outcome = "off" if stats_cache is None else "miss"
        cached_stats = None
        load_timer = Timer()
        if stats_cache is not None:
            with load_timer:
                cached_stats = stats_cache.load()

        if cached_stats is not None:
            self.stats_cache_outcome = "hit"
            mins, maxs, std = cached_stats
            with obs.span(
                "coverage.train_stats_pass", samples=len(training_set)
            ) as span:
                span.set(cached=True, load_s=round(load_timer.get(), 6))
            nbc_debit = snac_debit = kmnc_debit = load_timer.get()
        else:
            agg_stats = DeviceAggregateStatisticsCollector()
            with obs.span(
                "coverage.train_stats_pass", samples=len(training_set)
            ) as span:
                span.set(cached=False)
                pred_timer = Timer(start=True)
                for activations in base_model.walk_activations(
                    training_set, badge_size=PROFILE_BADGE_SIZE, device=True
                ):
                    pred_timer.stop()
                    agg_stats.track(activations)
                    pred_timer.start()
                pred_timer.stop()

            mins, maxs, std = agg_stats.get()
            if stats_cache is not None:
                stats_cache.store((mins, maxs, std))

            nbc_debit = (
                agg_stats.min_timer.get()
                + agg_stats.max_timer.get()
                + pred_timer.get()
                + agg_stats.welford_timer.get()
            )
            snac_debit = (
                agg_stats.welford_timer.get()
                + agg_stats.max_timer.get()
                + pred_timer.get()
            )
            kmnc_debit = (
                agg_stats.min_timer.get()
                + agg_stats.max_timer.get()
                + pred_timer.get()
            )
        for scaler in (0, 0.5, 1):
            self._add_metric(
                f"NBC_{scaler}",
                lambda s=scaler: NBC(mins=mins, maxs=maxs, stds=std, scaler=s),
                time_debit=nbc_debit,
            )

        for scaler in (0, 0.5, 1):
            self._add_metric(
                f"SNAC_{scaler}",
                lambda s=scaler: SNAC(maxs=maxs, stds=std, scaler=s),
                time_debit=snac_debit,
            )

        self._add_metric("NAC_0", lambda: NAC(cov_threshold=0.0))
        self._add_metric("NAC_0.75", lambda: NAC(cov_threshold=0.75))

        for k in (1, 2, 3):
            self._add_metric(f"TKNC_{k}", lambda kk=k: TKNC(top_neurons=kk))

        # KMNC_1000/KMNC_10000 from the DeepGini paper are too expensive; the
        # reference (and we) use KMNC_2 instead.
        self._add_metric(
            "KMNC_2", lambda: KMNC(mins, maxs, sections=2), time_debit=kmnc_debit
        )

    def evaluate_all(
        self, test_dataset: np.ndarray, test_dataset_id
    ) -> Tuple[Dict[str, List[float]], Dict[str, np.ndarray], Dict[str, List[int]]]:
        """All coverages + CAM orders for one test set.

        Returns ``(times, scores, cam_orders)`` with times =
        ``[setup, pred, quant, cam]`` per metric.
        """
        times, all_scores, cam_orders = {}, {}, {}
        for metric_name, setup_time in self.setup_times.items():
            times[metric_name] = [setup_time, 0.0, 0.0]

        with obs.span("coverage.profiles", ds=str(test_dataset_id)):
            self._prepare_profiles(test_dataset, ds_id=test_dataset_id, times=times)
        for metric_id in self.metrics.keys():
            scores, packed, bit_len = self._load_prepared_profile(
                metric_id=metric_id, ds_id=test_dataset_id, delete=True
            )
            all_scores[metric_id] = scores

            timer = Timer(name="coverage.cam", metric=metric_id, ds=str(test_dataset_id))
            with timer:
                cam_orders[metric_id] = list(
                    _cam_from_packed(scores, packed, bit_len)
                )
            times[metric_id].append(timer.get())
            self._cam_sanity_check(cam_orders[metric_id], scores)
            del packed
        return times, all_scores, cam_orders

    def _get_temp_path(self, metric_id: str) -> str:
        return os.path.join(
            output_folder(), ".tmp", f"{self.temp_random}-prepared-profiles", metric_id
        )

    @staticmethod
    def _cam_sanity_check(cam_order, scores):
        assert (
            len(cam_order) == len(set(cam_order)) == scores.shape[0]
        ), "CAM order is not unique or not complete"

    def _add_metric(
        self,
        metric_id: str,
        metric_supplier: Callable[[], CoverageMethod],
        time_debit: float = 0.0,
    ):
        timer = Timer()
        with timer:
            self.metrics[metric_id] = metric_supplier()
        self.setup_times[metric_id] = time_debit + timer.get()
        # The shared-stats debit scheme made auditable: each metric's setup
        # record = its own constructor time + its share of the one stats pass.
        obs.event(
            "coverage.debit",
            metric=metric_id,
            debit_s=round(time_debit, 6),
            own_s=round(timer.get(), 6),
        )

    def _timed_activation_walk(self, test_dataset: np.ndarray):
        # device=True: profiles are computed by the jnp kernels on-device and
        # only the packed results are pulled to host. The walk badge is larger
        # than the reference's prediction badge — on TPU, per-dispatch latency
        # dominates tiny badges.
        activations_generator = self.base_model.walk_activations(
            test_dataset, badge_size=PROFILE_BADGE_SIZE, device=True
        )
        while True:
            try:
                timer = Timer()
                with timer:
                    activations = next(activations_generator)
                yield activations, timer.get()
            except StopIteration:
                return

    def _resolve_spill(self, test_dataset: np.ndarray) -> str:
        if self.spill != "auto":
            return self.spill
        try:
            import psutil

            available = psutil.virtual_memory().available
        except ImportError:  # pragma: no cover
            return "disk"
        # Rough per-sample profile footprint across all configured metrics:
        # one bool per (neuron, section).
        sample = self.base_model.get_activations(test_dataset[:1])
        neurons = sum(int(np.prod(a.shape[1:])) for a in sample)
        sections = {"NBC": 2, "KMNC": 2}
        per_sample_bits = sum(
            neurons * sections.get(mid.split("_")[0], 1) for mid in self.metrics
        )
        estimate = per_sample_bits // 8 * test_dataset.shape[0]
        return "memory" if estimate * 2 < available else "disk"

    def _prepare_profiles(self, test_dataset: np.ndarray, ds_id, times):
        """One fused device dispatch per badge computes ALL metrics' scores and
        bit-packed profiles; packed bytes (8x smaller than bool) accumulate in
        RAM or spill to disk."""
        mode = self._resolve_spill(test_dataset)
        self._mem_profiles = {m: [] for m in self.metrics}
        self._mem_scores = {m: [] for m in self.metrics}
        if self._fused_fn is None:
            self._fused_fn, self._bit_len = make_fused_profile_fn(self.metrics)
        if mode == "disk":
            for metric_id in self.metrics.keys():
                shutil.rmtree(self._get_temp_path(metric_id), ignore_errors=True)
                os.makedirs(os.path.join(self._get_temp_path(metric_id), f"{ds_id}-scores"))
                os.makedirs(os.path.join(self._get_temp_path(metric_id), f"{ds_id}-profiles"))

        for b, (activations, pred_time) in enumerate(
            self._timed_activation_walk(test_dataset)
        ):
            timer = Timer()
            with timer:
                fused_out = self._fused_fn(activations)
                fused_out = {
                    mid: (np.asarray(s), np.asarray(p))
                    for mid, (s, p) in fused_out.items()
                }
            quant_time = timer.get() / len(self.metrics)
            for metric_id, (s, p) in fused_out.items():
                times[metric_id][1] += pred_time
                times[metric_id][2] += quant_time
                if mode == "memory":
                    self._mem_scores[metric_id].append(s)
                    self._mem_profiles[metric_id].append(p)
                else:
                    np.save(
                        os.path.join(
                            self._get_temp_path(metric_id), f"{ds_id}-scores", f"{b}.npy"
                        ),
                        s,
                    )
                    np.save(
                        os.path.join(
                            self._get_temp_path(metric_id), f"{ds_id}-profiles", f"{b}.npy"
                        ),
                        p,
                    )

    @staticmethod
    def _concatenate_arrays_in_folder(folder: str) -> np.ndarray:
        files = sorted(
            (f for f in os.listdir(folder) if f.endswith(".npy")),
            key=lambda f: int(f.split(".")[0]),
        )
        arrays = [np.load(os.path.join(folder, f)) for f in files]
        return np.concatenate(arrays, axis=0)

    def _load_prepared_profile(self, metric_id: str, ds_id, delete: bool = True):
        """Returns (scores, packed_profiles, bit_len)."""
        if self._mem_profiles.get(metric_id):
            scores = np.concatenate(self._mem_scores[metric_id], axis=0)
            packed = np.concatenate(self._mem_profiles[metric_id], axis=0)
            if delete:
                self._mem_scores[metric_id] = []
                self._mem_profiles[metric_id] = []
            return scores, packed, self._bit_len(metric_id)
        folder = self._get_temp_path(metric_id)
        scores = self._concatenate_arrays_in_folder(os.path.join(folder, f"{ds_id}-scores"))
        packed = self._concatenate_arrays_in_folder(
            os.path.join(folder, f"{ds_id}-profiles")
        )
        if delete:
            shutil.rmtree(folder, ignore_errors=True)
        return scores, packed, self._bit_len(metric_id)

"""Test-prioritization experiment phase for one model run.

Behavioral contract matches the reference (reference:
src/dnn_test_prio/eval_prioritization.py): per run, evaluate fault predictors
(uncertainty quantifiers) on nominal+ood, then the 12 neuron-coverage configs,
then the 5 surprise-adequacy variants, persisting every score / CAM order /
misclassification mask / time record under the load-bearing file-naming
contract ``priorities/{cs}_{ds}_{model}_{type}.npy`` parsed downstream by
underscore-splitting.
"""

import os
import pickle
from typing import Dict, List, Optional

import jax
import numpy as np

from simple_tip_tpu import obs
from simple_tip_tpu.config import subdir
from simple_tip_tpu.engine.coverage_handler import CoverageWorker
from simple_tip_tpu.engine.model_handler import BaseModel
from simple_tip_tpu.engine.surprise_handler import SurpriseHandler
from simple_tip_tpu.utils.artifacts_io import atomic_write_bytes


def _persist(case_study: str, dataset_id: str, data_type: str, model_id: int, data):
    """Store one artifact array on the filesystem bus."""
    np.save(
        os.path.join(
            subdir("priorities"),
            f"{case_study}_{dataset_id}_{model_id}_{data_type}.npy",
        ),
        np.asarray(data),
    )


def _persist_times_multiple_metrics(
    case_study: str, dataset_id: str, model_id: int, data: Dict[str, List[float]]
):
    # File-per-metric so nothing is lost on partial re-run.
    for metric, times in data.items():
        _persist_times(case_study, dataset_id, model_id, metric, times)


def _persist_times(
    case_study: str, dataset_id: str, model_id: int, metric: str, data: List[float]
):
    path = os.path.join(
        subdir("times"), f"{case_study}_{dataset_id}_{model_id}_{metric}"
    )
    # atomic like every other prio-path writer: a reader (or a resumed run)
    # can never observe a torn pickle from a killed worker
    atomic_write_bytes(path, pickle.dumps(data))


def load(case_study: str, dataset_id: str, data_type: str, model_id: int) -> np.ndarray:
    """Load one artifact array from the filesystem bus."""
    return np.load(
        os.path.join(
            subdir("priorities"),
            f"{case_study}_{dataset_id}_{model_id}_{data_type}.npy",
        )
    )


def evaluate(
    model_id: int,
    case_study: str,
    model_def,
    params,
    training_dataset: np.ndarray,
    nominal_test_dataset: np.ndarray,
    nominal_test_labels: np.ndarray,
    ood_test_dataset: np.ndarray,
    ood_test_labels: np.ndarray,
    nc_activation_layers: List,
    sa_activation_layers: List[int],
    dsa_badge_size: Optional[int] = None,
    batch_size: int = 32,
) -> None:
    """Run the test-prioritization experiments for one trained model."""
    from simple_tip_tpu.engine.run_program import fused_chain_enabled

    if fused_chain_enabled():
        # one AOT-compiled chain program replaces the fault-predictor and
        # neuron-coverage phases; surprise adequacy stays per-phase (its
        # variant fits are host sklearn estimators, not XLA-loweable)
        with obs.span("prio.fused_chain", model_id=model_id):
            _eval_fused_chain(
                case_study,
                model_def,
                params,
                model_id,
                nc_activation_layers,
                nominal_test_dataset,
                nominal_test_labels,
                ood_test_dataset,
                ood_test_labels,
                training_dataset,
                batch_size,
            )
        with obs.span("prio.surprise", model_id=model_id):
            _eval_surprise(
                case_study,
                model_def,
                params,
                model_id,
                sa_activation_layers,
                nominal_test_dataset,
                ood_test_dataset,
                training_dataset,
                dsa_badge_size=dsa_badge_size,
            )
        return
    with obs.span("prio.fault_predictors", model_id=model_id, ds="nominal"):
        _eval_fault_predictors(
            case_study,
            model_def,
            params,
            model_id,
            nominal_test_dataset,
            nominal_test_labels,
            "nominal",
            batch_size,
        )
    with obs.span("prio.fault_predictors", model_id=model_id, ds="ood"):
        _eval_fault_predictors(
            case_study,
            model_def,
            params,
            model_id,
            ood_test_dataset,
            ood_test_labels,
            "ood",
            batch_size,
        )
    with obs.span("prio.neuron_coverage", model_id=model_id):
        _eval_neuron_coverage(
            case_study,
            model_def,
            params,
            model_id,
            nc_activation_layers,
            nominal_test_dataset,
            ood_test_dataset,
            training_dataset,
            batch_size,
        )
    with obs.span("prio.surprise", model_id=model_id):
        _eval_surprise(
            case_study,
            model_def,
            params,
            model_id,
            sa_activation_layers,
            nominal_test_dataset,
            ood_test_dataset,
            training_dataset,
            dsa_badge_size=dsa_badge_size,
        )


def evaluate_group(
    model_ids: List[int],
    case_study: str,
    model_def,
    params_loader,
    training_dataset: np.ndarray,
    nominal_test_dataset: np.ndarray,
    nominal_test_labels: np.ndarray,
    ood_test_dataset: np.ndarray,
    ood_test_labels: np.ndarray,
    nc_activation_layers: List,
    sa_activation_layers: List[int],
    dsa_badge_size: Optional[int] = None,
    batch_size: int = 32,
    group_size: Optional[int] = None,
) -> None:
    """Grouped test-prioritization walk: G models per chain dispatch.

    ``params_loader(model_id) -> params`` pulls member checkpoints;
    ``model_ids`` is chunked into groups of ``group_size``
    (``TIP_CHAIN_GROUP`` by default), each scored by ONE
    ``GroupChainRunner`` so a badge costs one dispatch for the whole group.
    While group i walks its badges, group i+1's stacked weights are ALREADY
    in flight to the device (``GroupChainRunner.stage`` — ``device_put`` is
    asynchronous), so weight upload overlaps badge scoring: the double
    buffer. The per-member artifact set persisted is byte-identical to what
    per-model ``evaluate`` writes (parity-pinned); surprise adequacy stays
    per-member (host sklearn fits, not XLA-loweable).
    """
    from simple_tip_tpu.engine.run_program import GroupChainRunner, chain_group_size

    g_size = int(group_size or chain_group_size())
    ids = list(model_ids)
    groups = [ids[i : i + g_size] for i in range(0, len(ids), g_size)]

    def _load(group):
        return [params_loader(mid) for mid in group]

    params = _load(groups[0])
    staged = GroupChainRunner.stage(params, g_size)
    for gi, group in enumerate(groups):
        cur_params, cur_staged = params, staged
        if gi + 1 < len(groups):
            params = _load(groups[gi + 1])
            staged = GroupChainRunner.stage(params, g_size)
        with obs.span(
            "prio.group_chain", model_ids=list(group), group_size=g_size
        ):
            _eval_fused_chain_group(
                case_study,
                model_def,
                list(zip(group, cur_params)),
                nc_activation_layers,
                nominal_test_dataset,
                nominal_test_labels,
                ood_test_dataset,
                ood_test_labels,
                training_dataset,
                batch_size,
                group_size=g_size,
                staged_params=cur_staged,
            )
        for model_id, member_params in zip(group, cur_params):
            with obs.span("prio.surprise", model_id=model_id):
                _eval_surprise(
                    case_study,
                    model_def,
                    member_params,
                    model_id,
                    sa_activation_layers,
                    nominal_test_dataset,
                    ood_test_dataset,
                    training_dataset,
                    dsa_badge_size=dsa_badge_size,
                )


def _eval_fused_chain_group(
    case_study,
    model_def,
    members,
    nc_layers,
    nominal_test_dataset,
    nominal_test_labels,
    ood_test_dataset,
    ood_test_labels,
    training_dataset,
    batch_size,
    group_size=None,
    staged_params=None,
):
    """``_eval_fused_chain`` for one member group: one runner scores every
    member per badge, then fans results out to the IDENTICAL per-model
    artifact set (same writers, same file contract — parity-pinned)."""
    from simple_tip_tpu.engine.run_program import GroupChainRunner

    runner = GroupChainRunner(
        model_def,
        [p for _, p in members],
        training_dataset,
        nc_layers,
        batch_size=batch_size,
        group_size=group_size,
        staged_params=staged_params,
    )
    datasets = {
        "nominal": (nominal_test_dataset, nominal_test_labels),
        "ood": (ood_test_dataset, ood_test_labels),
    }
    for ds_type, (ds, labels) in datasets.items():
        results = runner.evaluate_dataset(
            ds, rngs=[jax.random.PRNGKey(mid) for mid, _ in members]
        )
        labels_flat = np.asarray(labels).flatten()
        for (model_id, _), result in zip(members, results):
            is_misclassified = result["pred"] != labels_flat
            _persist(
                case_study, ds_type, "is_misclassified", model_id, is_misclassified
            )
            _persist_times_multiple_metrics(
                case_study, ds_type, model_id, result["unc_times"]
            )
            for unc_id, unc in result["uncertainties"].items():
                _persist(case_study, ds_type, f"uncertainty_{unc_id}", model_id, unc)
            _persist_times_multiple_metrics(
                case_study, ds_type, model_id, result["cov_times"]
            )
            for metric_id, score in result["scores"].items():
                _persist(case_study, ds_type, f"{metric_id}_scores", model_id, score)
            for metric_id, order in result["cam_orders"].items():
                _persist(
                    case_study,
                    ds_type,
                    f"{metric_id}_cam_order",
                    model_id,
                    np.array(order),
                )


def _eval_surprise(
    case_study,
    model_def,
    params,
    model_id,
    layers,
    nominal_test_dataset,
    ood_test_dataset,
    training_dataset,
    dsa_badge_size: Optional[int] = None,
):
    sa_worker = SurpriseHandler(
        model_def,
        params,
        sa_layers=layers,
        training_dataset=training_dataset,
        case_study=case_study,
        model_id=model_id,
    )
    results = sa_worker.evaluate_all(
        datasets={"nominal": nominal_test_dataset, "ood": ood_test_dataset},
        dsa_badge_size=dsa_badge_size,
    )
    for metric, values in results.items():
        for dataset, (sa, cam_order, times) in values.items():
            _persist_times(case_study, dataset, model_id, metric, times)
            _persist(case_study, dataset, f"{metric}_scores", model_id, sa)
            _persist(case_study, dataset, f"{metric}_cam_order", model_id, cam_order)


def _eval_neuron_coverage(
    case_study,
    model_def,
    params,
    model_id,
    layers,
    nominal_test_dataset,
    ood_test_dataset,
    training_dataset,
    batch_size,
):
    nc_worker = CoverageWorker(
        base_model=BaseModel(
            model_def, params, activation_layers=layers, batch_size=batch_size
        ),
        training_set=training_dataset,
    )
    for name, ds in {"nominal": nominal_test_dataset, "ood": ood_test_dataset}.items():
        times, scores, cam_orders = nc_worker.evaluate_all(ds, name)
        _persist_times_multiple_metrics(case_study, name, model_id, times)
        for metric_id, score in scores.items():
            _persist(case_study, name, f"{metric_id}_scores", model_id, score)
        for metric_id, order in cam_orders.items():
            _persist(case_study, name, f"{metric_id}_cam_order", model_id, np.array(order))


def _eval_fused_chain(
    case_study,
    model_def,
    params,
    model_id,
    nc_layers,
    nominal_test_dataset,
    nominal_test_labels,
    ood_test_dataset,
    ood_test_labels,
    training_dataset,
    batch_size,
):
    """Fused-dispatch replacement for fault predictors + neuron coverage.

    Persists the IDENTICAL artifact set the two per-phase functions write
    (is_misclassified, uncertainty_{id}, {metric}_scores, {metric}_cam_order,
    per-metric times), from one compiled chain dispatch per badge plus one
    rank dispatch per metric. CAM orders are byte-identical to the per-phase
    reference; uncertainty VALUES may differ from the host-numpy quantifiers
    by float ULPs (XLA vs numpy log rounding) with identical ordering —
    downstream consumers depend only on the ordering (see ops/uncertainty.py).
    """
    from simple_tip_tpu.engine.run_program import FusedChainRunner

    runner = FusedChainRunner(
        model_def,
        params,
        training_dataset,
        nc_layers,
        batch_size=batch_size,
    )
    datasets = {
        "nominal": (nominal_test_dataset, nominal_test_labels),
        "ood": (ood_test_dataset, ood_test_labels),
    }
    for ds_type, (ds, labels) in datasets.items():
        result = runner.evaluate_dataset(ds, rng=jax.random.PRNGKey(model_id))
        is_misclassified = result["pred"] != np.asarray(labels).flatten()
        _persist(case_study, ds_type, "is_misclassified", model_id, is_misclassified)
        _persist_times_multiple_metrics(
            case_study, ds_type, model_id, result["unc_times"]
        )
        for unc_id, unc in result["uncertainties"].items():
            _persist(case_study, ds_type, f"uncertainty_{unc_id}", model_id, unc)
        _persist_times_multiple_metrics(
            case_study, ds_type, model_id, result["cov_times"]
        )
        for metric_id, score in result["scores"].items():
            _persist(case_study, ds_type, f"{metric_id}_scores", model_id, score)
        for metric_id, order in result["cam_orders"].items():
            _persist(
                case_study, ds_type, f"{metric_id}_cam_order", model_id, np.array(order)
            )


def _eval_fault_predictors(
    case_study, model_def, params, model_id, ds, labels, ds_type, batch_size
):
    base_model = BaseModel(model_def, params, activation_layers=None, batch_size=batch_size)
    pred, uncertainties, times = base_model.get_pred_and_uncertainty(
        ds, rng=jax.random.PRNGKey(model_id)
    )
    is_misclassified = pred != np.asarray(labels).flatten()
    _persist(case_study, ds_type, "is_misclassified", model_id, is_misclassified)
    _persist_times_multiple_metrics(case_study, ds_type, model_id, times)
    for unc_id, unc in uncertainties.items():
        _persist(case_study, ds_type, f"uncertainty_{unc_id}", model_id, unc)
